#!/bin/bash
#
# Smoke test against a DEPLOYED wva-tpu controller (reference
# Makefile:239-262 test-e2e-smoke): applies a VariantAutoscaling + dummy
# Deployment, waits for the controller to resolve the target and write
# status, then asserts the wva_desired_replicas series appears on the
# controller's /metrics endpoint.
#
# Requires: kubectl with KUBECONFIG pointing at the cluster where
# `make deploy-wva-tpu-emulated-on-kind` ran.

set -euo pipefail

KUBECTL="${KUBECTL:-kubectl}"
WVA_NS="${WVA_NS:-wva-tpu-system}"
LLMD_NS="${LLMD_NS:-llm-d-inference}"
RELEASE_NAME="${RELEASE_NAME:-wva-tpu}"
TIMEOUT="${TIMEOUT:-180}"
VA_NAME="smoke-llama-v5e"

RED='\033[0;31m'; GREEN='\033[0;32m'; NC='\033[0m'
fail() { echo -e "${RED}[smoke] FAIL:${NC} $*" >&2; cleanup || true; exit 1; }
ok()   { echo -e "${GREEN}[smoke]${NC} $*"; }

PF_PID=""
cleanup() {
    [[ -n "$PF_PID" ]] && kill "$PF_PID" 2>/dev/null || true
    "$KUBECTL" -n "$LLMD_NS" delete variantautoscaling "$VA_NAME" \
        deployment "$VA_NAME" --ignore-not-found=true >/dev/null 2>&1 || true
}
trap cleanup EXIT

# 1. Controller up?
"$KUBECTL" -n "$WVA_NS" get deployment >/dev/null \
    || fail "cannot reach namespace $WVA_NS"
"$KUBECTL" -n "$WVA_NS" wait --for=condition=Available --timeout="${TIMEOUT}s" \
    deployment -l app.kubernetes.io/name=wva-tpu \
    || fail "controller deployment not Available"
ok "controller deployment Available"

# 2. Dummy workload + VA
"$KUBECTL" create namespace "$LLMD_NS" --dry-run=client -o yaml | "$KUBECTL" apply -f -
cat <<EOF | "$KUBECTL" apply -f -
apiVersion: apps/v1
kind: Deployment
metadata:
  name: $VA_NAME
  namespace: $LLMD_NS
  labels: {app: $VA_NAME}
spec:
  replicas: 1
  selector: {matchLabels: {app: $VA_NAME}}
  template:
    metadata:
      labels: {app: $VA_NAME}
    spec:
      containers:
        - name: srv
          image: registry.k8s.io/pause:3.9
          args: ["--max-num-batched-tokens=8192", "--max-num-seqs=256"]
---
apiVersion: wva.tpu.llmd.ai/v1alpha1
kind: VariantAutoscaling
metadata:
  name: $VA_NAME
  namespace: $LLMD_NS
  labels:
    inference.optimization/acceleratorName: v5e-8
spec:
  scaleTargetRef:
    apiVersion: apps/v1
    kind: Deployment
    name: $VA_NAME
  modelID: smoke/llama-3.1-8b
  variantCost: "8.0"
EOF
ok "applied dummy Deployment + VariantAutoscaling"

# 3. Wait for the controller to resolve the scale target (status written).
deadline=$((SECONDS + TIMEOUT))
until "$KUBECTL" -n "$LLMD_NS" get variantautoscaling "$VA_NAME" \
        -o jsonpath='{.status.conditions[?(@.type=="TargetResolved")].status}' \
        2>/dev/null | grep -q True; do
    [[ $SECONDS -lt $deadline ]] || fail "TargetResolved condition never became True"
    sleep 2
done
ok "VA TargetResolved=True"

# 4. wva_desired_replicas visible on /metrics (through the metrics Service).
PORT="${SMOKE_LOCAL_PORT:-18443}"
"$KUBECTL" -n "$WVA_NS" port-forward "service/$RELEASE_NAME-metrics-service" \
    "$PORT:8443" >/dev/null 2>&1 &
PF_PID=$!
sleep 2
deadline=$((SECONDS + TIMEOUT))
while true; do
    metrics="$(curl -sk "https://127.0.0.1:$PORT/metrics" 2>/dev/null \
        || curl -s "http://127.0.0.1:$PORT/metrics" 2>/dev/null || true)"
    if echo "$metrics" | grep -q "wva_desired_replicas{.*variant_name=\"$VA_NAME\""; then
        ok "wva_desired_replicas emitted for $VA_NAME"
        break
    fi
    [[ $SECONDS -lt $deadline ]] || fail "wva_desired_replicas for $VA_NAME never appeared on /metrics"
    sleep 3
done

ok "SMOKE PASSED"
