#!/usr/bin/env python
"""Cluster-free smoke test: the SAME assertions as deploy/e2e/smoke.sh, but
with every process boundary faked over real sockets.

Proves the deploy pipeline up to the image-build boundary (the environment
has no docker/kind): the controller runs as the Dockerfile's entrypoint
(``python -m wva_tpu``) in a subprocess, talks to a FakeAPIServer over HTTP
for list/watch/status-patch, collects saturated metrics from a
FakePrometheusServer over HTTP, and must emit a scale-up decision on its
real /metrics endpoint:

1. controller subprocess starts, /healthz + /readyz go 200;
2. a VariantAutoscaling + Deployment + Ready pods exist; pods report
   kv_cache_usage 0.85 / queue depth 8 (saturated);
3. wva_desired_replicas{variant_name="llama-v5e"} >= 2 appears on /metrics
   — the full collect -> analyze -> decide -> emit loop ran;
4. SIGTERM exits 0 (leader release / clean shutdown).

Reference analogue: Makefile:239-262 test-e2e-smoke against a kind cluster.
"""

from __future__ import annotations

import os
import re
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO_ROOT)

from wva_tpu.api import ObjectMeta, VariantAutoscaling, VariantAutoscalingSpec  # noqa: E402
from wva_tpu.api.v1alpha1 import CrossVersionObjectReference  # noqa: E402
from wva_tpu.collector.source import TimeSeriesDB  # noqa: E402
from wva_tpu.emulator.prom_server import FakePrometheusServer  # noqa: E402
from wva_tpu.k8s import (  # noqa: E402
    ConfigMap,
    Container,
    Deployment,
    DeploymentStatus,
    FakeCluster,
    Pod,
    PodStatus,
    PodTemplateSpec,
    ResourceRequirements,
)
from wva_tpu.k8s.fake_apiserver import FakeAPIServer  # noqa: E402

NS = "llm-d-inference"
SYSTEM_NS = "wva-tpu-system"
MODEL = "meta-llama/Llama-3.1-8B"
VARIANT = "llama-v5e"
TIMEOUT = float(os.environ.get("SMOKE_TIMEOUT", "90"))

SATURATION_CM = """\
analyzerName: ""
kvCacheThreshold: 0.80
queueLengthThreshold: 5
kvSpareTrigger: 0.10
queueSpareTrigger: 3
enableLimiter: false
"""


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def build_world() -> tuple[FakeCluster, TimeSeriesDB]:
    cluster = FakeCluster()
    tsdb = TimeSeriesDB()

    cluster.create(ConfigMap(
        metadata=ObjectMeta(name="wva-saturation-scaling-config",
                            namespace=SYSTEM_NS),
        data={"default": SATURATION_CM}))

    replicas = 1
    cluster.create(Deployment(
        metadata=ObjectMeta(name=VARIANT, namespace=NS),
        replicas=replicas,
        selector={"app": "llama"},
        template=PodTemplateSpec(
            labels={"app": "llama"},
            containers=[Container(
                name="srv",
                args=["--max-num-batched-tokens=8192", "--max-num-seqs=256"],
                resources=ResourceRequirements(
                    requests={"google.com/tpu": "8"}))]),
        status=DeploymentStatus(replicas=replicas, ready_replicas=replicas)))
    cluster.create(VariantAutoscaling(
        metadata=ObjectMeta(
            name=VARIANT, namespace=NS,
            labels={"inference.optimization/acceleratorName": "v5e-8"}),
        spec=VariantAutoscalingSpec(
            scale_target_ref=CrossVersionObjectReference(name=VARIANT),
            model_id=MODEL, variant_cost="8.0")))
    for i in range(replicas):
        cluster.create(Pod(
            metadata=ObjectMeta(
                name=f"{VARIANT}-{i}", namespace=NS, labels={"app": "llama"},
                owner_references=[{"kind": "Deployment", "name": VARIANT}]),
            status=PodStatus(phase="Running", ready=True,
                             pod_ip=f"10.0.0.{i}")))
        pod_labels = {"pod": f"{VARIANT}-{i}", "namespace": NS,
                      "model_name": MODEL}
        # Saturated: kv 0.85 > 0.80 threshold, queue 8 > 5 threshold.
        tsdb.add_sample("vllm:kv_cache_usage_perc", pod_labels, 0.85)
        tsdb.add_sample("vllm:num_requests_waiting", pod_labels, 8)
        tsdb.add_sample("vllm:cache_config_info",
                        {**pod_labels, "num_gpu_blocks": "4096",
                         "block_size": "32"}, 1.0)
    return cluster, tsdb


def restamp(db: TimeSeriesDB) -> None:
    """Re-stamp every seeded series with the current wall clock so the
    collector's staleness windows keep passing while the smoke runs."""
    for i in range(1):
        pod_labels = {"pod": f"{VARIANT}-{i}", "namespace": NS,
                      "model_name": MODEL}
        db.add_sample("vllm:kv_cache_usage_perc", pod_labels, 0.85)
        db.add_sample("vllm:num_requests_waiting", pod_labels, 8)
        db.add_sample("vllm:cache_config_info",
                      {**pod_labels, "num_gpu_blocks": "4096",
                       "block_size": "32"}, 1.0)


def fetch(url: str, timeout: float = 2.0) -> tuple[int, str]:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


def main() -> int:
    import json
    import tempfile

    cluster, tsdb = build_world()
    apiserver = FakeAPIServer(cluster).start()
    prom = FakePrometheusServer(tsdb, refresh=restamp).start()
    print(f"[smoke-local] fake apiserver at {apiserver.url}, "
          f"fake prometheus at {prom.url}")

    mport, hport = free_port(), free_port()
    with tempfile.TemporaryDirectory() as tmp:
        kubeconfig = os.path.join(tmp, "kubeconfig")
        with open(kubeconfig, "w") as f:
            json.dump({
                "current-context": "smoke",
                "contexts": [{"name": "smoke", "context":
                              {"cluster": "smoke", "user": "smoke"}}],
                "clusters": [{"name": "smoke",
                              "cluster": {"server": apiserver.url}}],
                "users": [{"name": "smoke", "user": {}}],
            }, f)
        env = {**os.environ,
               "KUBECONFIG": kubeconfig,
               "PROMETHEUS_BASE_URL": prom.url,
               "POD_NAMESPACE": SYSTEM_NS,
               "GLOBAL_OPT_INTERVAL": "2s",
               "JAX_PLATFORMS": "cpu"}
        env.pop("PALLAS_AXON_POOL_IPS", None)
        proc = subprocess.Popen(
            [sys.executable, "-m", "wva_tpu",
             "--metrics-bind-address", f"127.0.0.1:{mport}",
             "--health-probe-bind-address", f"127.0.0.1:{hport}",
             "-v", "2"],
            cwd=REPO_ROOT, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        rc = 1
        try:
            # 1. health + readiness
            deadline = time.time() + TIMEOUT
            while time.time() < deadline:
                try:
                    if fetch(f"http://127.0.0.1:{hport}/healthz")[0] == 200:
                        break
                except (urllib.error.URLError, OSError):
                    time.sleep(0.3)
            else:
                raise AssertionError("healthz never came up")
            status, _ = fetch(f"http://127.0.0.1:{hport}/readyz")
            assert status == 200, "readyz not 200 after bootstrap"
            print("[smoke-local] healthz/readyz OK")

            # 2+3. scale-up decision visible on /metrics
            pattern = re.compile(
                r'wva_desired_replicas\{[^}]*variant_name="%s"[^}]*\}\s+'
                r'([0-9.e+]+)' % re.escape(VARIANT))
            desired = None
            while time.time() < deadline:
                _, body = fetch(f"http://127.0.0.1:{mport}/metrics")
                m = pattern.search(body)
                if m and float(m.group(1)) >= 2:
                    desired = float(m.group(1))
                    break
                time.sleep(1.0)
            assert desired is not None, \
                "wva_desired_replicas >= 2 never appeared on /metrics"
            print(f"[smoke-local] scale-up decision emitted: "
                  f"wva_desired_replicas={desired}")

            # VA status written through the REST path too. The status PUT
            # is asynchronous relative to the gauge (the engine emits
            # metrics, then writes status; retries/conflict-refetch can add
            # latency under load), so poll with its OWN deadline instead of
            # one racy read — the shared deadline may already be consumed
            # by the gauge poll, which would skip this loop entirely.
            deadline = time.time() + 15
            alloc = None
            while time.time() < deadline:
                va = cluster.get("VariantAutoscaling", NS, VARIANT)
                alloc = va.status.desired_optimized_alloc
                if alloc is not None and alloc.num_replicas >= 2:
                    break
                time.sleep(0.5)
            assert alloc is not None and alloc.num_replicas >= 2, \
                f"VA status not updated: {alloc}"
            print(f"[smoke-local] VA status desired_optimized_alloc="
                  f"{alloc.num_replicas} accel={alloc.accelerator}")

            # 4. clean shutdown
            proc.send_signal(signal.SIGTERM)
            rc_proc = proc.wait(timeout=20)
            assert rc_proc == 0, f"controller exited {rc_proc}"
            print("[smoke-local] clean SIGTERM shutdown (rc=0)")
            print("[smoke-local] SMOKE PASSED")
            rc = 0
        except AssertionError as e:
            print(f"[smoke-local] FAIL: {e}", file=sys.stderr)
            if proc.poll() is None:
                proc.kill()
            out = proc.stdout.read() if proc.stdout else ""
            print("---- controller output ----", file=sys.stderr)
            print(out[-8000:], file=sys.stderr)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
            apiserver.shutdown()
            prom.shutdown()
    return rc


if __name__ == "__main__":
    sys.exit(main())
