#!/bin/bash
#
# wva-tpu deployment script: image build -> kind load -> chart install.
# Env-driven like the reference's deploy/install.sh; invoked by the
# Makefile targets deploy-wva-tpu-emulated-on-kind /
# undeploy-wva-tpu-emulated-on-kind (reference Makefile:107-118).
#
# Renders the chart with helm when available, falling back to the bundled
# subset renderer (python -m wva_tpu.utils.helmlite) + kubectl apply so the
# pipeline works on machines without a helm binary.

set -euo pipefail

RED='\033[0;31m'; GREEN='\033[0;32m'; BLUE='\033[0;34m'; NC='\033[0m'
info()  { echo -e "${BLUE}[install]${NC} $*"; }
ok()    { echo -e "${GREEN}[install]${NC} $*"; }
fail()  { echo -e "${RED}[install]${NC} $*" >&2; exit 1; }

# Tools
KIND="${KIND:-kind}"
KUBECTL="${KUBECTL:-kubectl}"
HELM="${HELM:-helm}"
DOCKER="${DOCKER:-docker}"
PYTHON="${PYTHON:-python}"

# Configuration
REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
IMG="${IMG:-ghcr.io/llm-d/wva-tpu:v0.3.0}"
CLUSTER_NAME="${CLUSTER_NAME:-kind-wva-tpu-cluster}"
CREATE_CLUSTER="${CREATE_CLUSTER:-false}"
CLUSTER_NODES="${CLUSTER_NODES:-3}"
CLUSTER_TPU_PROFILE="${CLUSTER_TPU_PROFILE:-v5e}"
WVA_NS="${WVA_NS:-wva-tpu-system}"
LLMD_NS="${LLMD_NS:-llm-d-inference}"
RELEASE_NAME="${RELEASE_NAME:-wva-tpu}"
NAMESPACE_SCOPED="${NAMESPACE_SCOPED:-false}"
VALUES_FILE="${VALUES_FILE:-$REPO_ROOT/charts/wva-tpu/values.yaml}"
CHART_DIR="${CHART_DIR:-$REPO_ROOT/charts/wva-tpu}"
PROMETHEUS_URL="${PROMETHEUS_URL:-http://prometheus-k8s.monitoring.svc:9090}"
SKIP_BUILD="${SKIP_BUILD:-false}"
DELETE_CLUSTER="${DELETE_CLUSTER:-false}"

IMG_REPO="${IMG%:*}"
IMG_TAG="${IMG##*:}"

have() { command -v "$1" >/dev/null 2>&1; }

render_chart() {
    # Render to stdout with either helm or the bundled subset renderer.
    local common_sets=(
        "wva.image.repository=$IMG_REPO"
        "wva.image.tag=$IMG_TAG"
        "wva.imagePullPolicy=IfNotPresent"
        "wva.namespaceScoped=$NAMESPACE_SCOPED"
        "wva.prometheus.baseURL=$PROMETHEUS_URL"
        "llmd.namespace=$LLMD_NS"
    )
    if have "$HELM"; then
        local args=(template "$RELEASE_NAME" "$CHART_DIR" -n "$WVA_NS"
                    --include-crds -f "$VALUES_FILE")
        for s in "${common_sets[@]}"; do args+=(--set "$s"); done
        "$HELM" "${args[@]}"
    else
        info "no helm binary; rendering with python -m wva_tpu.utils.helmlite"
        local args=("$CHART_DIR" --release "$RELEASE_NAME" -n "$WVA_NS"
                    --include-crds -f "$VALUES_FILE")
        for s in "${common_sets[@]}"; do args+=(--set "$s"); done
        (cd "$REPO_ROOT" && "$PYTHON" -m wva_tpu.utils.helmlite "${args[@]}")
    fi
}

undeploy() {
    info "Undeploying $RELEASE_NAME from namespace $WVA_NS"
    if have "$HELM" && "$HELM" status "$RELEASE_NAME" -n "$WVA_NS" >/dev/null 2>&1; then
        "$HELM" uninstall "$RELEASE_NAME" -n "$WVA_NS"
    else
        render_chart | "$KUBECTL" delete -f - --ignore-not-found=true
    fi
    "$KUBECTL" delete namespace "$WVA_NS" --ignore-not-found=true
    if [[ "$DELETE_CLUSTER" == "true" ]]; then
        KIND="$KIND" CLUSTER_NAME="$CLUSTER_NAME" \
            "$REPO_ROOT/deploy/kind-emulator/teardown.sh"
    fi
    ok "Undeploy complete"
}

deploy() {
    have "$KUBECTL" || fail "kubectl not found"

    # 1. Cluster (optional)
    if [[ "$CREATE_CLUSTER" == "true" ]]; then
        have "$KIND" || fail "kind not found (CREATE_CLUSTER=true)"
        KIND="$KIND" KUBECTL="$KUBECTL" CLUSTER_NAME="$CLUSTER_NAME" \
            "$REPO_ROOT/deploy/kind-emulator/setup.sh" \
            -n "$CLUSTER_NODES" -p "$CLUSTER_TPU_PROFILE"
    fi

    # 2. Image build + load
    if [[ "$SKIP_BUILD" != "true" ]]; then
        have "$DOCKER" || fail "docker not found (set SKIP_BUILD=true to use a pre-pushed image)"
        info "Building $IMG"
        "$DOCKER" build -t "$IMG" "$REPO_ROOT"
        if have "$KIND" && "$KIND" get clusters 2>/dev/null | grep -qx "$CLUSTER_NAME"; then
            info "Loading $IMG into kind cluster $CLUSTER_NAME"
            "$KIND" load docker-image "$IMG" --name "$CLUSTER_NAME"
        fi
    fi

    # 3. Namespaces
    "$KUBECTL" create namespace "$WVA_NS" --dry-run=client -o yaml | "$KUBECTL" apply -f -
    "$KUBECTL" create namespace "$LLMD_NS" --dry-run=client -o yaml | "$KUBECTL" apply -f -

    # 4. Chart install (CRDs included)
    if have "$HELM"; then
        info "Installing chart with helm"
        local args=(upgrade --install "$RELEASE_NAME" "$CHART_DIR" -n "$WVA_NS"
                    -f "$VALUES_FILE"
                    --set "wva.image.repository=$IMG_REPO"
                    --set "wva.image.tag=$IMG_TAG"
                    --set "wva.imagePullPolicy=IfNotPresent"
                    --set "wva.namespaceScoped=$NAMESPACE_SCOPED"
                    --set "wva.prometheus.baseURL=$PROMETHEUS_URL"
                    --set "llmd.namespace=$LLMD_NS")
        "$HELM" "${args[@]}"
    else
        info "Installing chart with the bundled renderer + kubectl apply"
        render_chart | "$KUBECTL" apply -f -
    fi

    # 5. Wait for rollout
    info "Waiting for controller rollout"
    "$KUBECTL" -n "$WVA_NS" rollout status deployment -l app.kubernetes.io/name=wva-tpu --timeout=180s \
        || "$KUBECTL" -n "$WVA_NS" rollout status "deployment/$RELEASE_NAME-controller-manager" --timeout=180s

    ok "wva-tpu deployed. Smoke test with: make test-e2e-smoke"
}

if [[ "${1:-}" == "--undeploy" ]]; then
    undeploy
else
    deploy
fi
