#!/bin/bash
# Destroy the fake-TPU kind cluster created by setup.sh
# (reference deploy/kind-emulator teardown path, Makefile:102-105).
set -euo pipefail

KIND="${KIND:-kind}"
cluster_name="${CLUSTER_NAME:-kind-wva-tpu-cluster}"

if "$KIND" get clusters 2>/dev/null | grep -qx "$cluster_name"; then
    "$KIND" delete cluster --name "$cluster_name"
    echo "Deleted kind cluster $cluster_name"
else
    echo "Cluster $cluster_name not found; nothing to do"
fi
