#!/bin/bash
# Fake-TPU kind cluster: patches GKE TPU node labels and google.com/tpu
# allocatable onto plain kind nodes so discovery, the slice limiter, and the
# e2e suites behave exactly as on real TPU node pools.
#
# TPU port of the reference's GPU emulator (deploy/kind-emulator/setup.sh
# there patches <vendor>.com/gpu labels + status.capacity; :144-262). The
# label schema here matches wva_tpu/constants/labels.py and
# wva_tpu/discovery/tpu.py: cloud.google.com/gke-tpu-accelerator,
# cloud.google.com/gke-tpu-topology, allocatable["google.com/tpu"].

set -euo pipefail

DEFAULT_CLUSTER_NAME="kind-wva-tpu-cluster"
DEFAULT_NODES=3
DEFAULT_PROFILE="v5e"          # v5e | v5p | v6e | mix
DEFAULT_K8S_VERSION="v1.32.0"

cluster_name="${CLUSTER_NAME:-$DEFAULT_CLUSTER_NAME}"
nodes="${NODES:-$DEFAULT_NODES}"
profile="${TPU_PROFILE:-$DEFAULT_PROFILE}"
k8s_version="${K8S_VERSION:-$DEFAULT_K8S_VERSION}"
enable_scale_to_zero="${ENABLE_SCALE_TO_ZERO:-true}"

usage() {
    cat <<EOF
Usage: $0 [OPTIONS]
  -c NAME     Cluster name (default: $DEFAULT_CLUSTER_NAME)
  -n NODES    Worker nodes (default: $DEFAULT_NODES)
  -p PROFILE  TPU profile: v5e, v5p, v6e, mix (default: $DEFAULT_PROFILE)
              - v5e: every node a ct5lp-hightpu-8t host (8 chips, 2x4)
              - v5p: every node a 4-chip v5p host (2x2x1)
              - v6e: every node an 8-chip v6e host (2x4)
              - mix: rotate v5e-8 / v5p-4 / v6e-8 per node (limiter tests)
  -k VERSION  Kubernetes version (default: $DEFAULT_K8S_VERSION)
  -h          Show help
EOF
}

while getopts "c:n:p:k:h" opt; do
    case $opt in
        c) cluster_name="$OPTARG" ;;
        n) nodes="$OPTARG" ;;
        p) profile="$OPTARG" ;;
        k) k8s_version="$OPTARG" ;;
        h) usage; exit 0 ;;
        *) usage; exit 1 ;;
    esac
done

# Generated config lives in a temp file so a user's own kind-config.yaml in
# the cwd is never overwritten or deleted.
kind_config="$(mktemp -t kind-wva-tpu-config.XXXXXX.yaml)"
cleanup() { rm -f "$kind_config" || true; }
trap cleanup EXIT

# ------------------------------------------------------------------
# 1. kind cluster (control plane + N workers, HPAScaleToZero optional)
# ------------------------------------------------------------------
make_kind_config() {
    cat > "$kind_config" <<EOF
kind: Cluster
apiVersion: kind.x-k8s.io/v1alpha4
nodes:
  - role: control-plane
EOF
    if [[ "$enable_scale_to_zero" == "true" ]]; then
        cat >> "$kind_config" <<EOF
    kubeadmConfigPatches:
      - |
        kind: ClusterConfiguration
        apiServer:
          extraArgs:
            feature-gates: HPAScaleToZero=true
EOF
    fi
    for ((i = 0; i < nodes; i++)); do
        echo "  - role: worker" >> "$kind_config"
    done
}

# ------------------------------------------------------------------
# 2. per-profile label + capacity schema
#    (accelerator label, topology, chips per host)
# ------------------------------------------------------------------
node_schema() {
    local idx=$1
    case "$profile" in
        v5e) echo "tpu-v5-lite-podslice 2x4 8 ct5lp-hightpu-8t" ;;
        v5p) echo "tpu-v5p-slice 2x2x1 4 ct5p-hightpu-4t" ;;
        v6e) echo "tpu-v6e-slice 2x4 8 ct6e-standard-8t" ;;
        mix)
            case $((idx % 3)) in
                0) echo "tpu-v5-lite-podslice 2x4 8 ct5lp-hightpu-8t" ;;
                1) echo "tpu-v5p-slice 2x2x1 4 ct5p-hightpu-4t" ;;
                2) echo "tpu-v6e-slice 2x4 8 ct6e-standard-8t" ;;
            esac ;;
        *) echo "unknown profile: $profile" >&2; exit 1 ;;
    esac
}

# ------------------------------------------------------------------
# 3. patch nodes: GKE TPU labels + google.com/tpu allocatable
#    (kubectl patch --subresource=status, like the reference :256-262)
# ------------------------------------------------------------------
patch_nodes() {
    local idx=0
    for node in $(kubectl get nodes -o name | grep -v control-plane); do
        read -r accel topology chips machine <<< "$(node_schema $idx)"
        node_name="${node#node/}"
        echo ">> $node_name: $accel topology=$topology chips=$chips"
        kubectl label "$node" \
            "cloud.google.com/gke-tpu-accelerator=$accel" \
            "cloud.google.com/gke-tpu-topology=$topology" \
            "cloud.google.com/gke-nodepool=tpu-pool-$((idx % 3))" \
            "node.kubernetes.io/instance-type=$machine" \
            --overwrite
        kubectl patch "$node" --subresource=status --type=merge -p "{
            \"status\": {
                \"capacity\":    {\"google.com/tpu\": \"$chips\"},
                \"allocatable\": {\"google.com/tpu\": \"$chips\"}
            }
        }"
        idx=$((idx + 1))
    done
}

main() {
    command -v kind >/dev/null || { echo "kind not found" >&2; exit 1; }
    command -v kubectl >/dev/null || { echo "kubectl not found" >&2; exit 1; }

    if kind get clusters 2>/dev/null | grep -qx "$cluster_name"; then
        echo "Cluster $cluster_name exists; reusing"
    else
        make_kind_config
        kind create cluster --name "$cluster_name" \
            --image "kindest/node:$k8s_version" --config "$kind_config"
    fi
    kubectl config use-context "kind-$cluster_name"
    patch_nodes
    echo "Fake-TPU cluster ready. Verify with:"
    echo "  kubectl get nodes -L cloud.google.com/gke-tpu-accelerator,cloud.google.com/gke-tpu-topology"
}

main
