#!/usr/bin/env python3
"""North-star benchmark (BASELINE.md): Llama-3.1-8B on JetStream v5e-8 slices
under ramped load, 1 -> N slices, measuring p99-TTFT SLO attainment and
scale-up latency.

Two policies run through the SAME emulated world (serving simulator, fake
kubelet with slice-provisioning delay, HPA emulator):

- baseline: the reference's shipped defaults — V1 percentage analyzer, 30s
  engine tick, HPA stabilization 240s up/down (charts/workload-variant-
  autoscaler/README.md:11-20).
- ours: the TPU build's defaults — V2 token-capacity analyzer (anticipates
  demand from the scheduler queue and pending-replica supply) with faster HPA
  windows, which V2's transition blocking + anticipated-supply math make safe
  against flapping.

Prints ONE JSON line:
  {"metric": ..., "value": <ours p99-TTFT SLO attainment>, "unit": ...,
   "vs_baseline": <ours / baseline>, "detail": {...}}
"""

from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, ".")

from wva_tpu.emulator import (  # noqa: E402
    EmulationHarness,
    HPAParams,
    ServingParams,
    VariantSpec,
    ramp,
)
from wva_tpu.interfaces import SaturationScalingConfig  # noqa: E402

MODEL = "meta-llama/Llama-3.1-8B"
SLO_TTFT_SECONDS = 1.0
RAMP_SECONDS = 300.0
HOLD_SECONDS = 1500.0
PEAK_RATE = 90.0  # req/s at peak — needs ~5 v5e-8 slices
STARTUP_SECONDS = 120.0  # slice provisioning + model load


def run_policy(name: str) -> dict:
    if name == "baseline":
        sat_cfg = SaturationScalingConfig()  # V1 defaults
        hpa = HPAParams()  # chart defaults: 240s stabilization
        engine_interval = 30.0
    else:
        sat_cfg = SaturationScalingConfig(
            analyzer_name="saturation",
            # Size scale-up for the demand that will exist when a new slice
            # becomes ready (slice provisioning + model load).
            anticipation_horizon_seconds=STARTUP_SECONDS,
            # Clamp desired to whole-slice inventory so unplaceable replicas
            # never sit pending.
            enable_limiter=True)
        sat_cfg.apply_defaults()
        hpa = HPAParams(stabilization_up_seconds=10.0,
                        stabilization_down_seconds=120.0,
                        sync_period_seconds=10.0)
        engine_interval = 10.0

    spec = VariantSpec(
        name="llama-v5e", model_id=MODEL, accelerator="v5e-8",
        chips_per_replica=8, cost=10.0, initial_replicas=1,
        serving=ServingParams(engine="jetstream"),
        load=ramp(4.0, PEAK_RATE, RAMP_SECONDS, hold=HOLD_SECONDS),
        hpa=hpa,
    )
    harness = EmulationHarness(
        [spec],
        saturation_config=sat_cfg,
        nodepools=[("v5e-pool", "v5e", "2x4", 8)],
        startup_seconds=STARTUP_SECONDS,
        engine_interval=engine_interval,
    )

    max_replicas = {"v": 1}
    first_scale_up = {"t": None}
    ready_at_peak = {"t": None}

    def watch(h: EmulationHarness, t: float) -> None:
        reps = h.replicas_of("llama-v5e")
        if reps > 1 and first_scale_up["t"] is None:
            first_scale_up["t"] = t
        if reps > max_replicas["v"]:
            max_replicas["v"] = reps
        ready = h.ready_replicas_of("llama-v5e")
        if ready >= 4 and ready_at_peak["t"] is None:
            ready_at_peak["t"] = t

    harness.run(RAMP_SECONDS + HOLD_SECONDS, on_step=watch)

    sim = harness.sim_of_model(MODEL)
    measure_since = harness.start_time  # whole run, ramp included
    now = harness.clock.now()
    attainment = sim.slo_attainment(SLO_TTFT_SECONDS, since=measure_since)
    p99 = sim.ttft_percentile(99.0, since=measure_since, now=now)
    p50 = sim.ttft_percentile(50.0, since=measure_since, now=now)
    return {
        "slo_attainment": attainment,
        "p50_ttft_s": round(p50, 3),
        "p99_ttft_s": round(p99, 3),
        "scale_up_decision_latency_s": first_scale_up["t"],
        "time_to_4_ready_slices_s": ready_at_peak["t"],
        "peak_slices": max_replicas["v"],
        "chips_peak": max_replicas["v"] * 8,
        "requests_served": int(sum(
            r.success_total for r in sim._replicas.values())),
    }


def main() -> None:
    t0 = time.time()
    baseline = run_policy("baseline")
    ours = run_policy("ours")
    wall = time.time() - t0

    value = ours["slo_attainment"]
    base = baseline["slo_attainment"]
    vs_baseline = value / base if base > 0 else float("inf")

    print(json.dumps({
        "metric": "p99_ttft_slo_attainment_ramped_1_to_N_v5e8",
        "value": round(value, 4),
        "unit": "fraction_of_requests_meeting_1s_TTFT_SLO",
        "vs_baseline": round(vs_baseline, 3),
        "detail": {
            "ours": ours,
            "baseline": baseline,
            "scenario": {
                "model": MODEL, "engine": "jetstream",
                "ramp": f"4->{PEAK_RATE} req/s over {RAMP_SECONDS:.0f}s",
                "hold_s": HOLD_SECONDS, "slo_ttft_s": SLO_TTFT_SECONDS,
                "slice_startup_s": STARTUP_SECONDS,
            },
            "bench_wall_seconds": round(wall, 1),
        },
    }))


if __name__ == "__main__":
    main()
