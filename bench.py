#!/usr/bin/env python3
"""North-star benchmark (BASELINE.md): Llama-3.1-8B on JetStream v5e-8 slices
under ramped load, 1 -> N slices, measuring p99-TTFT SLO attainment and
scale-up latency — plus a device microbenchmark of the flagship compiled
computation (the batched JAX queueing solver).

FOUR policies run through the SAME emulated world (serving simulator, fake
kubelet with slice-provisioning delay, HPA emulator), so the reported gain
decomposes honestly:

- baseline       — the reference's shipped defaults: V1 percentage analyzer,
                   30s engine tick, HPA stabilization 240s up/down
                   (charts/workload-variant-autoscaler/README.md:11-20).
- baseline-fast  — the SAME V1 analyzer with OUR intervals (10s engine tick,
                   10s/120s HPA windows): isolates interval tuning from
                   analyzer improvements. vs_baseline is quoted against the
                   STRONGER of the two baselines.
- ours           — the SLO path: the batched JAX queueing-model analyzer
                   (analyzerName "slo") sizes replicas against the 1s-TTFT
                   SLO directly, with demand-trend anticipation sized to the
                   slice-provisioning horizon and whole-slice limiting —
                   with ORACLE calibration (profiles fitted to the sim,
                   exact declared ramp slope): the framework's ceiling.
- ours-realistic — the SAME SLO path under operator-grade inputs: alpha/beta/
                   gamma start 2x off, the online EKF tuner is LIVE to walk
                   them in, and the declared burst slope is HALF the true
                   ramp slope. This is the number an adopter should expect.

The WORLD is stochastic (seeded, reproducible): request arrivals are a
Poisson process and request sizes draw from a 3-component token mixture,
so instantaneous-rate excursions and length variance exist — p99 genuinely
differs from p50, and burst headroom is absorbing real bursts, not a
deterministic fluid. Load is a full trapezoid: warm hold -> 300s ramp ->
peak hold -> 300s descent -> 300s base tail, and every policy reports the
integral chip-seconds over the measured window alongside attainment, so
over-provisioning cannot hide (the cost axis of BASELINE.md's north star).

Metrics are split by phase: overall (headline: ramp onset through the tail),
ramp window, steady state, and descent — the ramp tail is a
provisioning-physics cost (120s slice startup against a 300s ramp) and must
be visible, not hidden in an average.

The solver microbench jits ``size_batch`` over 1k/8k candidate batches on
the default JAX platform (the real TPU chip under the driver) and reports
compile time, execute time, candidates/s, and the speedup over the scalar
per-candidate facade (the reference solves one candidate at a time:
pkg/analyzer/queueanalyzer.go:127-258) — for both bisection backends (XLA
fori_loop and the fused Pallas kernel), quoting the best.

``detail.variant_choice`` adds the cost axis (BASELINE config 4): the same
ramp served by a v5e-8+v5p-8 fleet under the cost-aware path vs a
v5p-only fleet, reporting SLO attainment and integrated cost per 1k
requests for each.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, ".")

from wva_tpu.emulator import (  # noqa: E402
    EmulationHarness,
    HPAParams,
    ServingParams,
    VariantSpec,
    ramp,
    trapezoid,
)
from wva_tpu.interfaces import SaturationScalingConfig  # noqa: E402

MODEL = "meta-llama/Llama-3.1-8B"
SLO_TTFT_SECONDS = 1.0
# Warm pre-ramp hold at the base rate: the autoscaler observes steady base
# load before the surge arrives, like any production controller that has
# been running longer than one ramp. Rounds 1-3 started the controller
# COLD at ramp onset, which made every request in [capacity-crossing,
# first-landing] (~t=51..130s) a mathematically certain miss: no decision
# made after t=0 can land a slice before t=120. Measurement windows are
# unchanged — they start at RAMP ONSET, so the warm hold adds no
# easy-to-serve requests to the denominator; it only lets steady-state
# policies (e.g. ``headroomReplicas``) take effect before the surge, which
# is exactly what they are for. All policies get the same warm hold.
WARMUP_SECONDS = 180.0
RAMP_SECONDS = 300.0
HOLD_SECONDS = 1200.0
DOWN_SECONDS = 300.0  # descent back to base — scale-down is measured, not cut
TAIL_SECONDS = 300.0  # base-rate tail after the descent
BASE_RATE = 4.0  # req/s during the warm hold and at ramp onset
PEAK_RATE = 90.0  # req/s at peak — needs ~5 v5e-8 slices
STARTUP_SECONDS = 120.0  # slice provisioning + model load

# The stochastic world (seeded -> byte-for-byte reproducible): Poisson
# arrivals + this request-size mixture (weight, in_tokens, out_tokens).
# Weighted mean ~ (512, 253), matching the profile fit's operating point,
# so the mixture adds VARIANCE (short chat turns vs long-context requests),
# not a mean shift the static profiles never saw.
# WVA_BENCH_SEED overrides for robustness sweeps (PERF.md records
# 1/7/99 giving 1.000/1.000/0.9999 headline attainment).
STOCHASTIC_SEED = int(os.environ.get("WVA_BENCH_SEED", "20260730"))
TOKEN_MIXTURE = ((0.50, 256, 128), (0.35, 640, 320), (0.15, 1064, 512))

# ours-realistic miscalibration: profiles start this factor off true.
MISCAL_FACTOR = 2.0

# The serving world's iteration-time law (alpha_ms, beta_ms, gamma_ms):
# the emulator runs batch-aware latency physics T(n) = alpha + n*(beta*tc +
# gamma*tm) — the SAME law the analyzer's queueing model assumes
# (queue_model.py _iteration_time, reference queueanalyzer.go:261-280), so
# "oracle" profiles are genuinely oracle and the EKF tuner's 2x-off
# recovery is a fair identification problem, not curve-fitting against a
# foreign model class. At max batch 96 with (512, 256) tokens this gives
# ~20ms ITL and ~18.6 req/s per-replica capacity — the same operating
# point as the fixed-latency sim the earlier rounds benched against.
PROFILE_ALPHA_MS = 18.0
PROFILE_BETA = 0.00267
PROFILE_GAMMA = 0.00002
TRUE_PARMS = (PROFILE_ALPHA_MS, PROFILE_BETA, PROFILE_GAMMA)
V5P_PARMS = (PROFILE_ALPHA_MS / 2, PROFILE_BETA / 2, PROFILE_GAMMA / 2)

FAST_HPA = dict(stabilization_up_seconds=10.0,
                stabilization_down_seconds=120.0,
                sync_period_seconds=10.0)


import contextlib  # noqa: E402


@contextlib.contextmanager
def _arrival_rate_window(window: str = "30s"):
    """The TPU build's fast metrics pipeline pairing (chart: 10s scrape +
    30s window). The window is baked into the query registration at
    harness construction, so wrap construction in this context."""
    os.environ["WVA_SLO_ARRIVAL_RATE_WINDOW"] = window
    try:
        yield
    finally:
        os.environ.pop("WVA_SLO_ARRIVAL_RATE_WINDOW", None)


def _slo_config_data(model_id: str = MODEL, profiles=None,
                     miscal: float = 1.0, tuner_enabled: bool = False):
    from wva_tpu.analyzers.queueing import PerfProfile, ServiceParms, TargetPerf
    from wva_tpu.config.slo import SLOConfigData, ServiceClass

    if profiles is None:
        profiles = [PerfProfile(
            model_id=model_id, accelerator="v5e-8",
            service_parms=ServiceParms(alpha=PROFILE_ALPHA_MS * miscal,
                                       beta=PROFILE_BETA * miscal,
                                       gamma=PROFILE_GAMMA * miscal),
            max_batch_size=96, max_queue_size=384)]
    return SLOConfigData(
        service_classes=[ServiceClass(
            name="premium", priority=1,
            model_targets={model_id: TargetPerf(
                target_ttft_ms=SLO_TTFT_SECONDS * 1000.0)})],
        profiles=profiles,
        tuner_enabled=tuner_enabled)


def _bench_trace_path(policy: str) -> str | None:
    """WVA_BENCH_TRACE=path opts the bench into decision-trace recording:
    each policy's run spills to ``<path-root>.<policy><ext>`` (one harness
    per policy, so one golden trace per policy), replayable offline with
    ``python -m wva_tpu replay``."""
    base = os.environ.get("WVA_BENCH_TRACE")
    if not base:
        return None
    root, ext = os.path.splitext(base)
    path = f"{root}.{policy}{ext or '.jsonl'}"
    if os.path.exists(path):
        os.remove(path)  # spill appends; a rerun must not double the trace
    return path


def run_policy(name: str) -> dict:
    slo_names = ("ours", "ours-realistic")
    if name == "baseline":
        # V1 defaults; the reference has no scale-from-N fast path, so it is
        # disabled for both baselines to keep the comparison honest.
        sat_cfg = SaturationScalingConfig(fast_path_enabled=False)
        hpa = HPAParams()  # chart defaults: 240s stabilization
        engine_interval = 30.0
    elif name == "baseline-fast":
        # Ablation: the reference analyzer with OUR intervals. Separates
        # interval tuning (config anyone could apply) from analyzer gains.
        sat_cfg = SaturationScalingConfig(fast_path_enabled=False)
        hpa = HPAParams(**FAST_HPA)
        engine_interval = 10.0
    else:  # ours / ours-realistic
        true_slope = (PEAK_RATE - BASE_RATE) / RAMP_SECONDS
        sat_cfg = SaturationScalingConfig(
            analyzer_name="slo",
            # Size scale-up for the demand that will exist when a new slice
            # becomes ready (slice provisioning + model load + decision lag).
            anticipation_horizon_seconds=STARTUP_SECONDS + 30.0,
            # Burst insurance, derived not guessed: the scenario's declared
            # worst-credible ramp is (90-4)/300 req/s^2; the analyzer
            # stands slope x horizon spare capacity — exactly the demand
            # that can arrive during the provisioning blackout. (N+1
            # headroomReplicas remains as the floor for models without a
            # declared ramp shape.) ours-realistic declares only HALF the
            # true slope — an operator's guess, not the scenario's answer
            # key — and must cover the rest from trend anticipation.
            burst_slope_rps=(true_slope if name == "ours"
                             else true_slope / 2.0),
            headroom_replicas=1,
            # Clamp desired to whole-slice inventory so unplaceable replicas
            # never sit pending.
            enable_limiter=True,
            # Scale-from-N fast path (on by default) + immediate scale-up
            # actuation: with a 120s provisioning horizon, waiting out HPA
            # sync + stabilization is pure added backlog.
            fast_actuation=True)
        sat_cfg.apply_defaults()
        hpa = HPAParams(**FAST_HPA)
        # A tick is one batched solver call (~ms) + a handful of PromQL
        # queries; 5s polling is cheap for the decision loop, and with the
        # trend fed at the fast-path cadence the first sized scale-up lands
        # one trend-span (~10s) into the ramp.
        engine_interval = 5.0

    spec = VariantSpec(
        name="llama-v5e", model_id=MODEL, accelerator="v5e-8",
        chips_per_replica=8, cost=10.0, initial_replicas=1,
        serving=ServingParams(engine="jetstream",
                              token_mixture=TOKEN_MIXTURE,
                              latency_parms=TRUE_PARMS),
        load=trapezoid(BASE_RATE, PEAK_RATE, RAMP_SECONDS, HOLD_SECONDS,
                       DOWN_SECONDS, tail=TAIL_SECONDS,
                       delay=WARMUP_SECONDS),
        hpa=hpa,
    )
    with _arrival_rate_window() if name in slo_names \
            else contextlib.nullcontext():
        harness = EmulationHarness(
            [spec],
            saturation_config=sat_cfg,
            nodepools=[("v5e-pool", "v5e", "2x4", 8)],
            startup_seconds=STARTUP_SECONDS,
            engine_interval=engine_interval,
            stochastic_seed=STOCHASTIC_SEED,
            trace_path=_bench_trace_path(name),
        )
    if name == "ours":
        harness.config.update_slo_config(_slo_config_data())
    elif name == "ours-realistic":
        # Operator-grade calibration: profiles 2x off true, with the EKF
        # tuner live to walk them toward the observed TTFT/ITL telemetry.
        harness.config.update_slo_config(_slo_config_data(
            miscal=MISCAL_FACTOR, tuner_enabled=True))

    max_replicas = {"v": 1}
    base_replicas = {"v": 1}  # replicas as of ramp onset (post-warmup)
    first_scale_up = {"t": None}
    ready_at_peak = {"t": None}
    chip_seconds = {"v": 0.0}  # integral of allocated chips, post-warmup
    last_t = {"v": None}  # previous on_step time: the integral's real dt

    def watch(h: EmulationHarness, t: float) -> None:
        reps = h.replicas_of("llama-v5e")
        if t < WARMUP_SECONDS:
            base_replicas["v"] = reps
        elif reps > base_replicas["v"] and first_scale_up["t"] is None:
            # First RAMP-driven scale-up, relative to ramp onset (warm-hold
            # steady-state sizing, e.g. the headroom floor, is not it).
            first_scale_up["t"] = t - WARMUP_SECONDS
        if reps > max_replicas["v"]:
            max_replicas["v"] = reps
        if t >= WARMUP_SECONDS:
            # Integrate over the harness's ACTUAL step size (measured from
            # consecutive on_step times): a non-default run(dt=...) must
            # scale chip-seconds, not silently assume 1s steps.
            dt = t - last_t["v"] if last_t["v"] is not None else 0.0
            chip_seconds["v"] += reps * spec.chips_per_replica * dt
        last_t["v"] = t
        ready = h.ready_replicas_of("llama-v5e")
        if ready >= 4 and ready_at_peak["t"] is None and t >= WARMUP_SECONDS:
            ready_at_peak["t"] = t - WARMUP_SECONDS

    harness.run(WARMUP_SECONDS + RAMP_SECONDS + HOLD_SECONDS
                + DOWN_SECONDS + TAIL_SECONDS, on_step=watch)

    sim = harness.sim_of_model(MODEL)
    # ALL measurement starts at ramp onset — the warm hold is excluded from
    # every window so it cannot pad attainment.
    start = harness.start_time + WARMUP_SECONDS
    now = harness.clock.now()
    # Phase split: the ramp window covers the ramp itself plus one full
    # provisioning horizon (decisions made during the ramp land then);
    # steady state runs to the start of the descent; descent covers the
    # ramp-down and the base tail (where scale-down happens).
    ramp_end = start + RAMP_SECONDS + STARTUP_SECONDS
    descent_start = start + RAMP_SECONDS + HOLD_SECONDS
    overall = {
        "slo_attainment": sim.slo_attainment(SLO_TTFT_SECONDS, since=start),
        "p50_ttft_s": round(sim.ttft_percentile(50.0, since=start, now=now), 3),
        "p99_ttft_s": round(sim.ttft_percentile(99.0, since=start, now=now), 3),
    }
    ramp_phase = {
        "slo_attainment": sim.slo_attainment(
            SLO_TTFT_SECONDS, since=start, until=ramp_end),
        "p99_ttft_s": round(sim.ttft_percentile(
            99.0, since=start, now=now, until=ramp_end), 3),
    }
    steady = {
        "slo_attainment": sim.slo_attainment(
            SLO_TTFT_SECONDS, since=ramp_end, until=descent_start),
        "p99_ttft_s": round(sim.ttft_percentile(
            99.0, since=ramp_end, now=now, until=descent_start), 3),
    }
    descent = {
        "slo_attainment": sim.slo_attainment(
            SLO_TTFT_SECONDS, since=descent_start),
        "p99_ttft_s": round(sim.ttft_percentile(
            99.0, since=descent_start, now=now), 3),
        "slices_at_end": harness.replicas_of("llama-v5e"),
    }
    def _rounded(d: dict) -> dict:
        return {k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in d.items()}

    out = {
        **_rounded(overall),
        "ramp_phase": _rounded(ramp_phase),
        "steady_state": _rounded(steady),
        "descent": _rounded(descent),
        "scale_up_decision_latency_s": first_scale_up["t"],
        "time_to_4_ready_slices_s": ready_at_peak["t"],
        "peak_slices": max_replicas["v"],
        "chips_peak": max_replicas["v"] * 8,
        "chip_seconds": int(chip_seconds["v"]),
        "requests_served": sim.completed_total,
    }
    if name == "ours-realistic":
        # Auditability of the headline claim: the record carries the EKF's
        # actual identification trajectory — the 2x-off start, where it
        # ended, the ground truth, and the NIS rejection rate.
        prof = harness.manager.engine.slo_analyzer.profiles.get(
            MODEL, "v5e-8", namespace=harness.namespace)
        tuners = harness.manager.engine.slo_tuner._tuners
        stats = next(iter(tuners.values())) if tuners else None
        sp = prof.service_parms if prof is not None else None
        out["tuner"] = {
            "initial_parms": {"alpha": PROFILE_ALPHA_MS * MISCAL_FACTOR,
                              "beta": PROFILE_BETA * MISCAL_FACTOR,
                              "gamma": PROFILE_GAMMA * MISCAL_FACTOR},
            "final_parms": ({"alpha": round(sp.alpha, 4),
                             "beta": round(sp.beta, 6),
                             "gamma": round(sp.gamma, 7)}
                            if sp is not None else None),
            "true_parms": {"alpha": TRUE_PARMS[0], "beta": TRUE_PARMS[1],
                           "gamma": TRUE_PARMS[2]},
            "steps": stats.steps if stats else 0,
            "nis_rejected": stats.rejected if stats else 0,
            "profile_source": getattr(prof, "source", None),
        }
    return out


MIXTRAL = "mistralai/Mixtral-8x7B-Instruct-v0.1"


def variant_choice_bench() -> dict:
    """BASELINE config 4 (Mixtral variant choice): one model served by
    v5e-8 (cheap) AND v5p-8 (2x faster per replica, 3x the cost). The
    cost-aware path must serve the same ramp within SLO at materially
    lower cost than a v5p-only fleet — the cost axis the headline
    attainment metric doesn't capture. Cost and the request denominator
    both cover the post-warm window (ramp + hold; 1s steps)."""
    from wva_tpu.analyzers.queueing import PerfProfile, ServiceParms

    warm, ramp_s, hold = 120.0, 300.0, 480.0
    peak = 60.0
    profiles = [
        PerfProfile(model_id=MIXTRAL, accelerator="v5e-8",
                    service_parms=ServiceParms(alpha=PROFILE_ALPHA_MS,
                                               beta=PROFILE_BETA,
                                               gamma=PROFILE_GAMMA),
                    max_batch_size=96, max_queue_size=384),
        PerfProfile(model_id=MIXTRAL, accelerator="v5p-8",
                    service_parms=ServiceParms(alpha=PROFILE_ALPHA_MS / 2,
                                               beta=PROFILE_BETA / 2,
                                               gamma=PROFILE_GAMMA / 2),
                    max_batch_size=96, max_queue_size=384),
    ]

    def run(variants):
        sat_cfg = SaturationScalingConfig(
            analyzer_name="slo",
            anticipation_horizon_seconds=STARTUP_SECONDS + 30.0,
            burst_slope_rps=(peak - BASE_RATE) / ramp_s,
            enable_limiter=True, fast_actuation=True)
        sat_cfg.apply_defaults()
        with _arrival_rate_window():
            harness = EmulationHarness(
                variants, saturation_config=sat_cfg,
                nodepools=[("v5e-pool", "v5e", "2x4", 8),
                           ("v5p-pool", "v5p", "2x4", 8)],
                startup_seconds=STARTUP_SECONDS, engine_interval=5.0,
                stochastic_seed=STOCHASTIC_SEED)
        harness.config.update_slo_config(
            _slo_config_data(MIXTRAL, profiles))
        cost = {"v": 0.0}
        served_at_warm = {"v": None}

        def total_served(h):
            return h.sim_of_model(MIXTRAL).completed_total

        def watch(h, t):
            if t >= warm:
                if served_at_warm["v"] is None:
                    served_at_warm["v"] = total_served(h)
                cost["v"] += sum(h.replicas_of(s.name) * s.cost
                                 for s in variants)  # cost-units x 1s steps

        harness.run(warm + ramp_s + hold, on_step=watch)
        sim = harness.sim_of_model(MIXTRAL)
        start = harness.start_time + warm
        # Numerator and denominator cover the SAME post-warm window.
        served = int(total_served(harness) - (served_at_warm["v"] or 0))
        return {
            "slo_attainment": round(
                sim.slo_attainment(SLO_TTFT_SECONDS, since=start), 4),
            "cost_unit_seconds": round(cost["v"], 0),
            "cost_per_1k_requests": round(cost["v"] / max(served, 1) * 1000, 1),
            "replicas_end": {s.name: harness.replicas_of(s.name)
                             for s in variants},
        }

    hpa = HPAParams(**FAST_HPA)
    load = ramp(BASE_RATE, peak, ramp_s, hold=hold, delay=warm)
    v5e = VariantSpec(name="mixtral-v5e", model_id=MIXTRAL,
                      accelerator="v5e-8", chips_per_replica=8, cost=8.0,
                      initial_replicas=1,
                      serving=ServingParams(engine="jetstream",
                                            latency_parms=TRUE_PARMS),
                      load=load, hpa=hpa)
    v5p_spec = dict(model_id=MIXTRAL, accelerator="v5p-8",
                    chips_per_replica=8, cost=24.0,
                    serving=ServingParams(engine="jetstream",
                                          latency_parms=V5P_PARMS),
                    hpa=hpa)
    v5p_variant = VariantSpec(name="mixtral-v5p", initial_replicas=0,
                              load=None, **v5p_spec)
    ours = run([v5e, v5p_variant])
    v5p_only = run([VariantSpec(name="mixtral-v5p", initial_replicas=1,
                                load=load, **v5p_spec)])
    savings = 1.0 - (ours["cost_per_1k_requests"]
                     / max(v5p_only["cost_per_1k_requests"], 1e-9))
    return {"ours": ours, "v5p_only": v5p_only,
            "cost_savings_frac": round(savings, 3),
            "scenario": {"model": MIXTRAL,
                         "ramp": f"{BASE_RATE:.0f}->{peak:.0f} req/s over "
                                 f"{ramp_s:.0f}s, hold {hold:.0f}s",
                         # Derived from the specs — the metadata can't lie.
                         "costs_per_replica": {
                             v5e.accelerator: v5e.cost,
                             v5p_variant.accelerator: v5p_variant.cost}}}


LLAMA70B = "meta-llama/Llama-3-70B"


def multihost_bench() -> dict:
    """BASELINE config 3: Llama-3-70B on multi-host v5e-16 slices
    (LeaderWorkerSet, 2 hosts x 8 chips scaling atomically — a replica is
    ready only when BOTH hosts are). Measures SLO attainment and 1->N
    whole-slice scale-up latency under the SLO path with burst
    insurance, the multi-host counterpart of the headline scenario."""
    from wva_tpu.analyzers.queueing import PerfProfile, ServiceParms

    warm, ramp_s, hold = 120.0, 300.0, 480.0
    peak = 40.0
    sat_cfg = SaturationScalingConfig(
        analyzer_name="slo",
        anticipation_horizon_seconds=STARTUP_SECONDS + 30.0,
        burst_slope_rps=(peak - BASE_RATE) / ramp_s,
        enable_limiter=True, fast_actuation=True)
    sat_cfg.apply_defaults()
    spec = VariantSpec(
        name="llama70b-v5e16", model_id=LLAMA70B, accelerator="v5e-16",
        chips_per_replica=8,  # per host
        hosts_per_slice=2, cost=16.0, initial_replicas=1,
        serving=ServingParams(engine="jetstream", latency_parms=TRUE_PARMS),
        load=ramp(BASE_RATE, peak, ramp_s, hold=hold, delay=warm),
        hpa=HPAParams(**FAST_HPA))
    with _arrival_rate_window():
        harness = EmulationHarness(
            [spec], saturation_config=sat_cfg,
            # "4x4" = 16 chips = 2 x 8-chip hosts per slice -> variant
            # v5e-16 (the slice limiter allocates whole slices per
            # variant, so the pool topology must derive the SAME variant
            # the VA is labeled with — "4x8" would be v5e-32 and leave
            # zero placeable slices).
            nodepools=[("v5e-pool", "v5e", "4x4", 8)],
            startup_seconds=STARTUP_SECONDS, engine_interval=5.0,
            stochastic_seed=STOCHASTIC_SEED)
    harness.config.update_slo_config(_slo_config_data(
        LLAMA70B, [PerfProfile(
            model_id=LLAMA70B, accelerator="v5e-16",
            service_parms=ServiceParms(alpha=PROFILE_ALPHA_MS,
                                       beta=PROFILE_BETA,
                                       gamma=PROFILE_GAMMA),
            max_batch_size=96, max_queue_size=384)]))
    ready_3 = {"t": None}
    peak_groups = {"v": 1}

    def watch(h, t):
        ready = h.ready_replicas_of(spec.name)
        if ready >= 3 and ready_3["t"] is None and t >= warm:
            ready_3["t"] = t - warm
        peak_groups["v"] = max(peak_groups["v"], h.replicas_of(spec.name))

    harness.run(warm + ramp_s + hold, on_step=watch)
    sim = harness.sim_of_model(LLAMA70B)
    start = harness.start_time + warm
    lws = harness.cluster.get("LeaderWorkerSet", harness.namespace, spec.name)
    # The whole-group invariant, actually verified: count pods the LWS
    # owns and compare against groups x hosts (restating replicas*2 would
    # report the invariant as holding even when pods are orphaned).
    owned_pods = sum(
        1 for p in harness.cluster.list("Pod", namespace=harness.namespace)
        if any(r.get("kind") == "LeaderWorkerSet" and r.get("name") == spec.name
               for r in p.metadata.owner_references))
    chips_per_slice = spec.chips_per_replica * spec.hosts_per_slice
    return {
        "slo_attainment": round(
            sim.slo_attainment(SLO_TTFT_SECONDS, since=start), 4),
        "time_to_3_ready_slices_s": ready_3["t"],
        "peak_slices": peak_groups["v"],
        "chips_peak": peak_groups["v"] * chips_per_slice,
        "pods_per_slice": spec.hosts_per_slice,
        "whole_group_invariant_holds": (
            owned_pods == lws.status.replicas * spec.hosts_per_slice),
        "scenario": {"model": LLAMA70B, "accelerator": "v5e-16 (LWS, 2 hosts)",
                     "ramp": f"{BASE_RATE:.0f}->{peak:.0f} req/s over "
                             f"{ramp_s:.0f}s, hold {hold:.0f}s"},
    }


GEMMA = "google/gemma-7b"


def multi_model_bench() -> dict:
    """BASELINE config 5 (multi-model + service classes): Llama-3.1-8B
    (premium, priority 1) and Gemma-7B (standard, priority 10) share ONE
    v5e pool sized too small for both — 5 slices against a fleet that wants
    ~8 at peak. The greedy fleet solver (fleet/solver.py, reference
    pkg/core/serviceclass.go priority semantics) allocates in priority
    order: premium must hold its SLO through the contention while standard
    degrades gracefully to its min-replica floor instead of collapsing.
    Stochastic world, same seed discipline as the headline."""
    from wva_tpu.analyzers.queueing import PerfProfile, ServiceParms, TargetPerf
    from wva_tpu.config.slo import SLOConfigData, ServiceClass

    warm, ramp_s, hold = 120.0, 300.0, 600.0
    peak_each = 45.0  # per model; combined demand ~8 slices vs 5 available
    pool_slices = 5
    sat_cfg = SaturationScalingConfig(
        analyzer_name="slo", optimizer_name="global",
        anticipation_horizon_seconds=STARTUP_SECONDS + 30.0,
        burst_slope_rps=(peak_each - BASE_RATE) / ramp_s,
        enable_limiter=True,
        # The fleet-wide assignment runs on the engine tick; the fast path
        # is a single-model shortcut and stays off in global mode (mirrors
        # tests/test_emulator_e2e_contention.py).
        fast_path_enabled=False)
    sat_cfg.apply_defaults()
    hpa = HPAParams(**FAST_HPA)
    load = ramp(BASE_RATE, peak_each, ramp_s, hold=hold, delay=warm)
    serving = ServingParams(engine="jetstream", token_mixture=TOKEN_MIXTURE,
                            latency_parms=TRUE_PARMS)
    specs = [
        VariantSpec(name="llama-v5e", model_id=MODEL, accelerator="v5e-8",
                    chips_per_replica=8, cost=8.0, initial_replicas=1,
                    serving=serving, load=load, hpa=hpa),
        VariantSpec(name="gemma-v5e", model_id=GEMMA, accelerator="v5e-8",
                    chips_per_replica=8, cost=8.0, initial_replicas=1,
                    serving=serving, load=load, hpa=hpa),
    ]

    def profile(model_id):
        return PerfProfile(
            model_id=model_id, accelerator="v5e-8",
            service_parms=ServiceParms(alpha=PROFILE_ALPHA_MS,
                                       beta=PROFILE_BETA,
                                       gamma=PROFILE_GAMMA),
            max_batch_size=96, max_queue_size=384)

    with _arrival_rate_window():
        harness = EmulationHarness(
            specs, saturation_config=sat_cfg,
            nodepools=[("v5e-pool", "v5e", "2x4", pool_slices)],
            startup_seconds=STARTUP_SECONDS, engine_interval=5.0,
            stochastic_seed=STOCHASTIC_SEED)
    harness.config.update_slo_config(SLOConfigData(
        service_classes=[
            ServiceClass(name="premium", priority=1,
                         model_targets={MODEL: TargetPerf(
                             target_ttft_ms=SLO_TTFT_SECONDS * 1000.0)}),
            ServiceClass(name="standard", priority=10,
                         model_targets={GEMMA: TargetPerf(
                             target_ttft_ms=SLO_TTFT_SECONDS * 1000.0)}),
        ],
        profiles=[profile(MODEL), profile(GEMMA)]))

    harness.run(warm + ramp_s + hold)
    start = harness.start_time + warm
    now = harness.clock.now()

    def measure(model_id, variant):
        sim = harness.sim_of_model(model_id)
        return {
            "slo_attainment": round(
                sim.slo_attainment(SLO_TTFT_SECONDS, since=start), 4),
            "p99_ttft_s": round(
                sim.ttft_percentile(99.0, since=start, now=now), 3),
            "replicas_end": harness.replicas_of(variant),
        }

    return {
        "contended": {"premium": measure(MODEL, "llama-v5e"),
                      "standard": measure(GEMMA, "gemma-v5e")},
        "scenario": {
            "models": {MODEL: "premium (priority 1)",
                       GEMMA: "standard (priority 10)"},
            "pool": f"{pool_slices} v5e-8 slices (fleet wants ~8 at peak)",
            "ramp": f"{BASE_RATE:.0f}->{peak_each:.0f} req/s EACH over "
                    f"{ramp_s:.0f}s, hold {hold:.0f}s",
        },
    }


def _drain_decision_bus():
    """The DecisionCache/DecisionTrigger bus is process-global: every
    bench section leaves it as clean as it found it, or later sections
    would drain this section's stale triggers into their own (clean)
    worlds."""
    from wva_tpu.engines import common as engines_common

    engines_common.DecisionCache.clear()
    while not engines_common.DecisionTrigger.empty():
        engines_common.DecisionTrigger.get_nowait()


def _build_tick_world(n_models: int, variants_per_model: int,
                      informer: bool = True, incremental: bool = True,
                      zero_copy: bool = True, fp_delta: bool = True,
                      sharding: int = 0, fused: bool = True,
                      spans: bool = True):
    """The shared 48-model/96-VA in-memory fleet world for the tick
    benches (`make bench-tick` / `make bench-tick-quiet`): FakeCluster +
    TSDB + fully wired manager on the SLO analyzer path, with a ``feed``
    hook that refreshes every model's gauge/counter samples. ``informer``/
    ``incremental`` map to WVA_INFORMER / WVA_INCREMENTAL so the honest
    pre-change levers build in the same process."""
    from wva_tpu.analyzers.queueing import PerfProfile, ServiceParms, TargetPerf
    from wva_tpu.api import (
        ObjectMeta,
        VariantAutoscaling,
        VariantAutoscalingSpec,
    )
    from wva_tpu.api.v1alpha1 import CrossVersionObjectReference
    from wva_tpu.collector.source import TimeSeriesDB
    from wva_tpu.config import new_test_config
    from wva_tpu.config.slo import SLOConfigData, ServiceClass
    from wva_tpu.engines import common as engines_common
    from wva_tpu.k8s import (
        Container,
        Deployment,
        DeploymentStatus,
        FakeCluster,
        Pod,
        PodStatus,
        PodTemplateSpec,
        ResourceRequirements,
    )
    from wva_tpu.main import build_manager
    from wva_tpu.utils import FakeClock

    ns = "bench"
    accels = ["v5e-8", "v5p-8"]

    _drain_decision_bus()
    clock = FakeClock(start=200_000.0)
    cluster = FakeCluster(clock=clock)
    tsdb = TimeSeriesDB(clock=clock)
    cfg = new_test_config()
    cfg.infrastructure.informer = informer
    cfg.infrastructure.incremental = incremental
    # WVA_ZERO_COPY lever: build_manager applies it process-wide from the
    # config, so the honest copy-on-read mode must flow through here.
    cfg.infrastructure.zero_copy = zero_copy
    # WVA_FP_DELTA lever (versioned fingerprint plane): off restores the
    # recomputed per-tick fingerprint — the honest pre-change lever.
    cfg.infrastructure.fp_delta = fp_delta
    # WVA_FUSED lever (one-jitted-program decision plane): off restores
    # the staged per-stage dispatches — the honest pre-change lever.
    cfg.infrastructure.fused = fused
    # WVA_SHARDING lever (sharded active-active engine): >0 splits the
    # engine into that many consistent-hash shard workers with the fleet
    # merge on top (docs/design/sharding.md); build_manager wires the
    # whole plane from config, exactly like a real deployment.
    if sharding:
        from wva_tpu.config.config import ShardingConfig

        cfg.set_sharding(ShardingConfig(enabled=True, shards=sharding))
    # WVA_SPANS lever (obs plane): off builds NO recorder — the honest
    # zero-cost baseline for `make bench-spans`.
    if not spans:
        from wva_tpu.config.config import ObsConfig

        cfg.set_obs(ObsConfig(spans=False))
    sat = SaturationScalingConfig(analyzer_name="slo")
    sat.apply_defaults()
    cfg.update_saturation_config({"default": sat})

    classes, profiles = [], []
    for i in range(n_models):
        model = f"org/bench-model-{i:03d}"
        classes.append(ServiceClass(
            name=f"c{i:03d}", priority=1,
            model_targets={model: TargetPerf(target_ttft_ms=1000.0)}))
        for v in range(variants_per_model):
            accel = accels[v % len(accels)]
            name = f"b{i:03d}-{accel}"
            profiles.append(PerfProfile(
                model_id=model, accelerator=accel,
                service_parms=ServiceParms(
                    alpha=PROFILE_ALPHA_MS / (v + 1),
                    beta=PROFILE_BETA / (v + 1),
                    gamma=PROFILE_GAMMA / (v + 1)),
                max_batch_size=96, max_queue_size=384))
            cluster.create(Deployment(
                metadata=ObjectMeta(name=name, namespace=ns),
                replicas=1, selector={"app": name},
                template=PodTemplateSpec(
                    labels={"app": name},
                    containers=[Container(
                        name="srv",
                        args=["--max-num-batched-tokens=8192",
                              "--max-num-seqs=256"],
                        resources=ResourceRequirements(
                            requests={"google.com/tpu": "8"}))]),
                status=DeploymentStatus(replicas=1, ready_replicas=1)))
            cluster.create(VariantAutoscaling(
                metadata=ObjectMeta(
                    name=name, namespace=ns,
                    labels={"inference.optimization/acceleratorName":
                            accel}),
                spec=VariantAutoscalingSpec(
                    scale_target_ref=CrossVersionObjectReference(
                        name=name),
                    model_id=model, variant_cost=str(8.0 * (v + 1)))))
            cluster.create(Pod(
                metadata=ObjectMeta(
                    name=f"{name}-0", namespace=ns,
                    labels={"app": name},
                    owner_references=[{"kind": "Deployment",
                                       "name": name}]),
                status=PodStatus(phase="Running", ready=True,
                                 pod_ip=f"10.1.{i}.{v + 1}")))

    def feed(now):
        """Fresh gauge + counter samples so KV collection and the
        arrival-rate rate() window always have data."""
        for i in range(n_models):
            model = f"org/bench-model-{i:03d}"
            for v in range(variants_per_model):
                accel = accels[v % len(accels)]
                pod = {"pod": f"b{i:03d}-{accel}-0", "namespace": ns,
                       "model_name": model}
                tsdb.add_sample("vllm:kv_cache_usage_perc", pod,
                                0.35, timestamp=now)
                tsdb.add_sample("vllm:num_requests_waiting", pod,
                                1, timestamp=now)
                tsdb.add_sample("vllm:cache_config_info",
                                {**pod, "num_gpu_blocks": "4096",
                                 "block_size": "32"}, 1.0, timestamp=now)
                # Monotone counter at ~4 req/s per pod.
                tsdb.add_sample("vllm:request_success_total", pod,
                                4.0 * (now - 199_000.0), timestamp=now)

    # Two samples a window apart so rate() is live from the first tick.
    feed(clock.now() - 30.0)
    feed(clock.now())
    mgr = build_manager(cluster, cfg, clock=clock, tsdb=tsdb)
    mgr.setup()
    mgr.config.update_slo_config(SLOConfigData(
        service_classes=classes, profiles=profiles))
    return mgr, cluster, clock, feed


def tick_scale_bench(n_models: int = 48, variants_per_model: int = 2,
                     measured_ticks: int = 15,
                     fleet_workers: int | None = None) -> dict:
    """Fleet-scale tick microbench (``make bench-tick``): 48 models / 96 VAs
    on the in-memory stack (FakeCluster + TSDB), SLO analyzer path.

    Two configurations run the SAME world:

    - **fleet** — the shipped fast path: tick-scoped snapshot (one LIST per
      kind), bounded per-model analysis pool, and ONE batched solver
      dispatch for every model's candidates.
    - **serial** — the pre-change loop shape, reproduced via the engine's
      compat levers: per-VA GETs (snapshot off), serial per-model analysis
      (workers 1), one solver dispatch per model (batching off).

    Reports tick p50/p99 wall latency and K8s-API requests per tick for
    both, plus the speedup. The world is deterministic (FakeClock, fixed
    series), so the numbers measure the control loop, not noise.
    """
    import statistics

    from wva_tpu.engines import common as engines_common

    def run_mode(snapshot: bool, workers: int | None, batching: bool,
                 indexed_tsdb: bool = True) -> dict:
        # This bench measures the ANALYSIS pipeline, so dirty-set skipping
        # is off in every mode — the feed's flat gauge values would let
        # fingerprints skip most measured ticks and the "fleet" numbers
        # would quietly stop measuring analysis at all (the quiet-tick
        # claim lives in tick_quiet_bench). The serial/legacy lever also
        # turns the informer off so its per-VA GETs really hit the
        # cluster, reproducing the pre-informer request shape.
        mgr, cluster, clock, feed = _build_tick_world(
            n_models, variants_per_model,
            informer=indexed_tsdb, incremental=False)
        eng = mgr.engine
        eng.tick_snapshot_enabled = snapshot
        if workers is not None:
            eng.analysis_workers = workers
        eng.solver_batching = batching
        if not indexed_tsdb:
            # Reproduce the pre-change metrics substrate too: full-store
            # scans per selector, a fresh parse per query string, the
            # pre-ring read path (copy-under-one-lock + linear window
            # scans), and per-model query fan-out (grouped collection off)
            # — PRs 2 and 3 added these levers alongside the engine ones,
            # so the honest baseline turns them all off.
            prom_api = mgr.source_registry.get("prometheus").api
            prom_api.engine.db.use_name_index = False
            prom_api.engine.db.legacy_reads = True
            prom_api.engine.cache_asts = False
            eng.grouped_collection = False
        for _ in range(3):  # warm: jit compile + caches out of the timings
            eng.optimize()
            clock.advance(5.0)
            feed(clock.now())
        walls = []
        reads = {}
        for _ in range(measured_ticks):
            cluster.reset_request_counts()
            t0 = time.perf_counter()
            eng.optimize()
            walls.append(time.perf_counter() - t0)
            for (verb, kind), c in cluster.request_counts().items():
                if verb in ("get", "list"):
                    key = f"{verb}:{kind}"
                    reads[key] = reads.get(key, 0) + c
            clock.advance(5.0)
            feed(clock.now())
        mgr.shutdown()
        walls.sort()
        per_tick_reads = {k: round(v / measured_ticks, 2)
                          for k, v in sorted(reads.items())}
        return {
            "tick_p50_ms": round(statistics.median(walls) * 1000.0, 2),
            "tick_p99_ms": round(
                walls[min(len(walls) - 1,
                          int(len(walls) * 0.99))] * 1000.0, 2),
            "api_reads_per_tick": per_tick_reads,
            "api_reads_per_tick_total": round(
                sum(per_tick_reads.values()), 1),
        }

    # fleet = the SHIPPED configuration on this stack: workers resolve by
    # the auto rule (serial against the in-memory backend — pure-Python
    # work gains nothing from threads under the GIL; pooled against HTTP
    # Prometheus, where collection is I/O-bound). fleet_pooled shows the
    # pool's GIL tax on this CPU-bound substrate for transparency.
    fleet = run_mode(snapshot=True, workers=fleet_workers, batching=True)
    pooled = run_mode(snapshot=True, workers=8, batching=True)
    serial = run_mode(snapshot=False, workers=1, batching=False,
                      indexed_tsdb=False)
    # The DecisionCache/DecisionTrigger bus is process-global: leave it as
    # clean as build_world() found it, or the policy runs that follow in a
    # full `make bench` would drain this bench's stale triggers into their
    # own (clean) worlds.
    _drain_decision_bus()
    return {
        "models": n_models,
        "variant_autoscalings": n_models * variants_per_model,
        "measured_ticks": measured_ticks,
        "fleet": fleet,
        "fleet_pooled_8_workers": pooled,
        "serial_pre_change": serial,
        "tick_p50_speedup": round(
            serial["tick_p50_ms"] / max(fleet["tick_p50_ms"], 1e-9), 2),
        "tick_p99_speedup": round(
            serial["tick_p99_ms"] / max(fleet["tick_p99_ms"], 1e-9), 2),
        # With the informer on, fleet reads/tick are exactly 0; a ratio
        # against zero is meaningless, so report the absolute reads
        # eliminated instead.
        "api_reads_reduction": (round(
            serial["api_reads_per_tick_total"]
            / fleet["api_reads_per_tick_total"], 1)
            if fleet["api_reads_per_tick_total"]
            else serial["api_reads_per_tick_total"]),
        "levers": {
            "fleet": "snapshot + indexed TSDB + grouped collection +"
                     " cross-model solver batching (auto workers: serial on"
                     " the in-memory backend, pooled against HTTP"
                     " Prometheus)",
            "serial_pre_change":
                "per-VA GETs, serial models, per-model solver dispatch,"
                " per-model query fan-out, unindexed copy-under-lock TSDB"
                " scans (the seed tick)",
        },
    }


def tick_quiet_bench(n_models: int = 48, variants_per_model: int = 2,
                     measured_ticks: int = 24,
                     quiet_warm_ticks: int = 16) -> dict:
    """Steady-state quiet-tick microbench (``make bench-tick-quiet``): the
    48-model fleet with NO demand or spec changes between ticks — the
    shape a production fleet spends most of its life in.

    Three configurations run the same world in the same process:

    - **incremental** — the shipped path: watch-backed informer (zero LIST
      requests per tick) + dirty-set fingerprints (zero clean models
      analyzed per tick; the periodic WVA_RESYNC_TICKS full pass stays on,
      so its cost is included honestly).
    - **informer_only** — informer on, incremental off: every tick still
      analyzes every model but LISTs nothing.
    - **per_tick_list** — both off: the PR-2 baseline (one LIST per kind
      per tick, full analysis) — the honest lever.

    Reports tick p50/p99 wall latency, K8s-API reads per tick, and models
    analyzed per tick. "Quiet" is the realistic steady state: metrics ARE
    scraped fresh every tick (new sample timestamps) but their VALUES are
    constant — flat gauges, a linearly increasing request counter (so
    rate() is constant). The fingerprint hashes (labels, value) only, so
    live-but-unchanged scrapes skip; quiet warmup ticks let the
    rate()/max_over_time windows settle onto the steady values first.
    """
    import statistics

    from wva_tpu.engines import common as engines_common

    def run_mode(informer: bool, incremental: bool,
                 zero_copy: bool = True, fp_delta: bool = True) -> dict:
        from wva_tpu.utils import freeze as frz

        # The object-plane lever is process-global (build_manager applies
        # it from the world's config); restore the shipped default after
        # the mode.
        try:
            mgr, cluster, clock, feed = _build_tick_world(
                n_models, variants_per_model,
                informer=informer, incremental=incremental,
                zero_copy=zero_copy, fp_delta=fp_delta)
            eng = mgr.engine
            for _ in range(3 + quiet_warm_ticks):  # jit + caches + memos +
                eng.optimize()                     # window settling
                clock.advance(5.0)
                feed(clock.now())
            walls, reads, analyzed, copies = [], {}, 0, []
            phase_sums: dict[str, float] = {}
            for _ in range(measured_ticks):
                cluster.reset_request_counts()
                t0 = time.perf_counter()
                eng.optimize()
                walls.append(time.perf_counter() - t0)
                analyzed += eng.last_tick_stats["analyzed"]
                copies.append(eng.last_tick_object_copies)
                for phase, sec in eng.last_tick_phase_seconds.items():
                    phase_sums[phase] = phase_sums.get(phase, 0.0) + sec
                for (verb, kind), c in cluster.request_counts().items():
                    if verb in ("get", "list"):
                        key = f"{verb}:{kind}"
                        reads[key] = reads.get(key, 0) + c
                clock.advance(5.0)
                feed(clock.now())  # fresh scrapes, unchanged values
            mgr.shutdown()
        finally:
            frz.set_zero_copy(True)
        walls.sort()
        per_tick_reads = {k: round(v / measured_ticks, 2)
                          for k, v in sorted(reads.items())}
        return {
            "tick_p50_ms": round(statistics.median(walls) * 1000.0, 2),
            "tick_p99_ms": round(
                walls[min(len(walls) - 1,
                          int(len(walls) * 0.99))] * 1000.0, 2),
            "api_reads_per_tick": per_tick_reads,
            "api_reads_per_tick_total": round(
                sum(per_tick_reads.values()), 1),
            "lists_per_tick": round(sum(
                v for k, v in per_tick_reads.items()
                if k.startswith("list:")), 2),
            "models_analyzed_per_tick": round(analyzed / measured_ticks, 2),
            # Per-phase wall time (wva_tick_phase_seconds): mean ms per
            # tick spent in prepare | fingerprint | analyze | apply.
            "phase_ms_mean": {
                k: round(v * 1000.0 / measured_ticks, 2)
                for k, v in sorted(phase_sums.items())},
            # K8s object copies per tick (wva_tick_object_copies): ~0 at
            # steady state on the zero-copy plane — every copy marks an
            # actual status write, not a read.
            "object_copies_per_tick_p50": float(
                statistics.median(copies)),
            "object_copies_per_tick_max": float(max(copies)),
        }

    incremental = run_mode(informer=True, incremental=True)
    # The fingerprint-plane honest lever: same shipped configuration with
    # WVA_FP_DELTA off — per-tick fingerprint RECOMPUTATION restored
    # (sorted (labels, value) tuples per model per template, full K8s
    # walks), byte-identical clean/dirty dynamics.
    fp_recompute = run_mode(informer=True, incremental=True,
                            fp_delta=False)
    informer_only = run_mode(informer=True, incremental=False)
    baseline = run_mode(informer=False, incremental=False)
    # The object-plane honest lever: the SAME shipped configuration with
    # WVA_ZERO_COPY off — deep-copy-on-read restored everywhere
    # (FakeCluster, informer store, snapshot fill/read-out), byte-identical
    # decisions (tests/test_object_plane.py).
    copy_on_read = run_mode(informer=True, incremental=True,
                            zero_copy=False)
    _drain_decision_bus()
    return {
        "models": n_models,
        "variant_autoscalings": n_models * variants_per_model,
        "measured_ticks": measured_ticks,
        "quiet_warm_ticks": quiet_warm_ticks,
        "incremental": incremental,
        "fp_recompute": fp_recompute,
        "informer_only": informer_only,
        "per_tick_list_baseline": baseline,
        "copy_on_read": copy_on_read,
        "quiet_tick_p50_speedup": round(
            baseline["tick_p50_ms"]
            / max(incremental["tick_p50_ms"], 1e-9), 2),
        "fp_delta_p50_speedup": round(
            fp_recompute["tick_p50_ms"]
            / max(incremental["tick_p50_ms"], 1e-9), 2),
        "object_plane_p50_speedup": round(
            copy_on_read["tick_p50_ms"]
            / max(incremental["tick_p50_ms"], 1e-9), 2),
        "api_reads_reduction": round(
            baseline["api_reads_per_tick_total"]
            / max(incremental["api_reads_per_tick_total"], 1e-9), 1)
        if incremental["api_reads_per_tick_total"] else float(
            baseline["api_reads_per_tick_total"]),
        "levers": {
            "incremental": "WVA_INFORMER + WVA_INCREMENTAL + WVA_FP_DELTA "
                           "on (shipped; includes the periodic resync "
                           "tick's cost)",
            "fp_recompute": "shipped config with WVA_FP_DELTA off: "
                            "per-tick fingerprint recomputation restored",
            "informer_only": "watch store on, dirty-set off: zero LISTs, "
                             "full analysis",
            "per_tick_list_baseline": "both off: one LIST per kind per "
                                      "tick + full analysis (the PR-2 "
                                      "shape)",
            "copy_on_read": "shipped config with WVA_ZERO_COPY off: "
                            "deep-copy-on-read restored everywhere (the "
                            "pre-object-plane shape)",
        },
    }


def fingerprint_scale_sweep(models=(48, 144, 480, 1000, 2000),
                            variants_per_model: int = 2,
                            measured_ticks: int = 13,
                            quiet_warm_ticks: int = 13) -> dict:
    """Fleet-growth sweep for the versioned fingerprint plane (`make
    bench-tick-quiet`, BENCH_LOCAL detail.fingerprint_plane): the SHIPPED
    quiet-tick configuration at 1x / 3x / 10x / ~42x fleet size, with
    per-phase wall time. The claim under test: the per-model fingerprint
    cost stays flat as the fleet grows (versions + memos replace per-model
    recomputation); the residual growth is the shared fleet-wide metric
    queries (O(series), charged once per template per tick — a real
    Prometheus pays the same cost server-side) and the per-VA apply
    phase (batched since the shard plane PR). The 2000-model point is the
    single-engine ceiling the sharded plane (`make bench-shard`,
    detail.shard_plane) divides across workers."""
    import statistics

    from wva_tpu.engines import common as engines_common

    out: dict[str, dict] = {}
    for n in models:
        mgr, cluster, clock, feed = _build_tick_world(n, variants_per_model)
        eng = mgr.engine
        for _ in range(3 + quiet_warm_ticks):
            eng.optimize()
            clock.advance(5.0)
            feed(clock.now())
        walls: list[float] = []
        phase_sums: dict[str, float] = {}
        for _ in range(measured_ticks):
            t0 = time.perf_counter()
            eng.optimize()
            walls.append(time.perf_counter() - t0)
            for phase, sec in eng.last_tick_phase_seconds.items():
                phase_sums[phase] = phase_sums.get(phase, 0.0) + sec
            # Fresh same-value scrapes between measured ticks — the same
            # honest quiet definition as tick_quiet_bench: write-versions
            # move every tick, so the STRICT reuse tier is off and the
            # sweep measures the shipped value-version path, not a
            # no-scrape world.
            clock.advance(5.0)
            feed(clock.now())
        walls.sort()
        out[str(n)] = {
            "models": n,
            "variant_autoscalings": n * variants_per_model,
            "tick_p50_ms": round(statistics.median(walls) * 1000.0, 2),
            "phase_ms_mean": {
                k: round(v * 1000.0 / measured_ticks, 2)
                for k, v in sorted(phase_sums.items())},
        }
        mgr.shutdown()
        _drain_decision_bus()
    lo, hi = str(models[0]), str(models[-1])
    growth = round(out[hi]["tick_p50_ms"]
                   / max(out[lo]["tick_p50_ms"], 1e-9), 2)
    fp_growth = round(
        out[hi]["phase_ms_mean"].get("fingerprint", 0.0)
        / max(out[lo]["phase_ms_mean"].get("fingerprint", 1e-9), 1e-9), 2)
    return {
        "sweep": out,
        "fleet_growth": round(models[-1] / models[0], 1),
        "tick_p50_growth": growth,
        "fingerprint_phase_growth": fp_growth,
        "per_model_fingerprint_us": {
            k: round(v["phase_ms_mean"].get("fingerprint", 0.0)
                     * 1000.0 / v["models"], 2)
            for k, v in out.items()},
    }


def analyze_plane_bench(models=(48, 480, 1000, 2000, 4000),
                        variants_per_model: int = 2,
                        measured_ticks: int = 7,
                        warm_ticks: int = 3) -> dict:
    """Fused decision-plane sweep (``make bench-analyze``, BENCH_LOCAL
    ``detail.fused_plane``): the SLO analyze phase at 1x/10x/~21x/~42x/
    ~83x fleet size with WVA_FUSED on vs off, measuring

    - **device dispatches per tick** (utils.dispatch deltas around each
      engine tick) — the tentpole's headline: the fused path launches
      ONE dispatch per analyzing tick (sizing + forecast fits + gather
      fused), the staged path one per stage;
    - **analyze-phase p50 ms** (``wva_tick_phase_seconds{phase=analyze}``
      via ``engine.last_tick_phase_seconds``) — which also exposes,
      honestly, how much of the phase is Python finalize/optimizer/
      enforcer vs device work at each scale.

    Every tick analyzes every model (incremental off, the tick_scale
    discipline): a fingerprint-skipped model launches nothing, so quiet
    ticks would measure the skip plane, not the decision plane."""
    import statistics

    from wva_tpu import fused as fused_mod
    from wva_tpu.engines import common as engines_common
    from wva_tpu.utils import dispatch as dispatch_counter

    out: dict[str, dict] = {}
    for n in models:
        point: dict[str, dict] = {}
        for label, fused_on in (("fused", True), ("staged", False)):
            # Per-run memo reset: each measured configuration pays its own
            # first-solve tick, so points are independent of run order.
            fused_mod.clear_solve_memo()
            mgr, cluster, clock, feed = _build_tick_world(
                n, variants_per_model, incremental=False, fused=fused_on)
            eng = mgr.engine
            for _ in range(warm_ticks):
                eng.optimize()
                clock.advance(5.0)
                feed(clock.now())
            analyze_ms: list[float] = []
            dispatches: list[int] = []
            for _ in range(measured_ticks):
                d0 = dispatch_counter.count()
                eng.optimize()
                dispatches.append(dispatch_counter.count() - d0)
                analyze_ms.append(
                    eng.last_tick_phase_seconds.get("analyze", 0.0)
                    * 1000.0)
                clock.advance(5.0)
                feed(clock.now())
            mgr.shutdown()
            _drain_decision_bus()
            point[label] = {
                "analyze_p50_ms": round(
                    statistics.median(analyze_ms), 2),
                "dispatches_per_tick": round(
                    sum(dispatches) / len(dispatches), 2),
            }
        point["models"] = n
        point["analyze_p50_speedup"] = round(
            point["staged"]["analyze_p50_ms"]
            / max(point["fused"]["analyze_p50_ms"], 1e-9), 2)
        out[str(n)] = point
    return {
        "sweep": out,
        "host_breakdown": _host_stage_breakdown(
            1000, variants_per_model, measured_ticks, warm_ticks),
        "levers": {
            "fused": "WVA_FUSED on (shipped): one fused dispatch per "
                     "analyzing tick",
            "staged": "WVA_FUSED off: one dispatch per stage (batched "
                      "sizing + forecast fit), byte-identical decisions",
            "host_breakdown": "per-stage host ms at 1000 models, fused "
                              "on: WVA_VEC_DECIDE on (vec: fleet-wide "
                              "row arithmetic) vs off (loop: per-model "
                              "Python), trace off so trace_materialize "
                              "shows the deferred-steps win",
        },
    }


def _host_stage_breakdown(n_models: int, variants_per_model: int,
                          measured_ticks: int, warm_ticks: int) -> dict:
    """Vec-vs-loop A/B of the decision stage's host time
    (``engine.last_tick_stage_seconds``): finalize / optimize / enforce /
    trace-materialize p50 ms per tick at ``n_models`` models, fused on.
    The enforce row is where the loop form's O(models x decisions)
    rescans show; trace_materialize is ~0 either way because these
    worlds run with the flight recorder off."""
    import statistics

    from wva_tpu import fused as fused_mod

    out: dict[str, object] = {"models": n_models}
    for label, vec in (("vec", True), ("loop", False)):
        fused_mod.clear_solve_memo()
        mgr, cluster, clock, feed = _build_tick_world(
            n_models, variants_per_model, incremental=False, fused=True)
        eng = mgr.engine
        eng.vec_decide = vec
        for _ in range(warm_ticks):
            eng.optimize()
            clock.advance(5.0)
            feed(clock.now())
        stages: dict[str, list[float]] = {}
        analyze_ms: list[float] = []
        for _ in range(measured_ticks):
            eng.optimize()
            for k, v in eng.last_tick_stage_seconds.items():
                stages.setdefault(k, []).append(v * 1000.0)
            analyze_ms.append(
                eng.last_tick_phase_seconds.get("analyze", 0.0) * 1000.0)
            clock.advance(5.0)
            feed(clock.now())
        mgr.shutdown()
        _drain_decision_bus()
        row = {f"{k}_p50_ms": round(statistics.median(v), 3)
               for k, v in sorted(stages.items())}
        row["analyze_p50_ms"] = round(statistics.median(analyze_ms), 2)
        out[label] = row
    vec_row, loop_row = out["vec"], out["loop"]
    out["stage_speedups"] = {
        k: round(loop_row[k] / max(vec_row[k], 1e-9), 2)
        for k in ("finalize_p50_ms", "optimize_p50_ms", "enforce_p50_ms")
        if k in vec_row and k in loop_row}
    return out


def analyze_smoke() -> dict:
    """ANALYZE_SMOKE=1 CI shape (mirrors SHARD_SMOKE/SWEEP_SMOKE):
    asserts the decision plane's two hard contracts on a small changing
    world instead of measuring latency —

    1. exactly **1.0 device dispatches per analyzing tick** on the
       fused path (solve-memo hit ticks dispatch the forecast fits,
       miss ticks the full program — either way one dispatch);
    2. **WVA_VEC_DECIDE=off byte-identical statuses** at every tick
       (the vectorized finalize/optimize/enforce passes vs the
       per-model loops).
    """
    from wva_tpu import fused as fused_mod
    from wva_tpu.blackbox.schema import encode
    from wva_tpu.utils import dispatch as dispatch_counter

    n_models, warm_ticks, measured_ticks = 24, 2, 5

    def run(vec: bool):
        fused_mod.clear_solve_memo()
        mgr, cluster, clock, feed = _build_tick_world(
            n_models, 2, incremental=False, fused=True)
        eng = mgr.engine
        eng.vec_decide = vec
        for _ in range(warm_ticks):
            eng.optimize()
            clock.advance(5.0)
            feed(clock.now())
        snaps: list[str] = []
        dispatches: list[int] = []
        for _ in range(measured_ticks):
            d0 = dispatch_counter.count()
            eng.optimize()
            dispatches.append(dispatch_counter.count() - d0)
            snap = {
                f"{va.metadata.namespace}/{va.metadata.name}":
                    encode(va.status)
                for va in cluster.list("VariantAutoscaling",
                                       namespace="bench")}
            snaps.append(json.dumps(snap, sort_keys=True))
            clock.advance(5.0)
            feed(clock.now())
        mgr.shutdown()
        _drain_decision_bus()
        return snaps, dispatches

    vec_snaps, vec_dispatches = run(True)
    loop_snaps, _ = run(False)
    per_tick = sum(vec_dispatches) / len(vec_dispatches)
    assert per_tick == 1.0, \
        f"fused analyze tick: expected 1.0 dispatches/tick, got {per_tick}"
    assert vec_snaps == loop_snaps, \
        "WVA_VEC_DECIDE=off statuses diverged from the vectorized path"
    return {"smoke": True, "models": n_models,
            "measured_ticks": measured_ticks,
            "dispatches_per_tick": per_tick,
            "vec_off_byte_identical": True}


def analyze_main() -> None:
    """`make bench-analyze`: the fused decision-plane sweep, merged into
    BENCH_LOCAL.json detail.fused_plane, one JSON line on stdout.
    `--smoke` (ANALYZE_SMOKE=1) runs the short CI assertion shape (24
    models; 1.0 dispatches/tick + vec-off byte-equality, no latency
    sweep, no BENCH_LOCAL merge)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.time()
    if "--smoke" in sys.argv:
        record = analyze_smoke()
        record["bench_wall_seconds"] = round(time.time() - t0, 1)
        print(json.dumps({
            "metric": "analyze_smoke_dispatches_per_tick",
            "value": record["dispatches_per_tick"],
            "unit": "dispatches_per_tick",
            "detail": record,
        }))
        return
    record = analyze_plane_bench()
    record["bench_wall_seconds"] = round(time.time() - t0, 1)
    _merge_bench_local("fused_plane", record)
    k1 = "1000" if "1000" in record["sweep"] else \
        max(record["sweep"], key=int)
    print(json.dumps({
        "metric": "fused_analyze_phase_1000_models",
        "value": record["sweep"][k1]["fused"]["analyze_p50_ms"],
        "unit": "ms_p50_per_tick",
        "vs_baseline": record["sweep"][k1]["analyze_p50_speedup"],
        "dispatches_per_tick":
            record["sweep"][k1]["fused"]["dispatches_per_tick"],
        "detail": record,
    }))


def collect_scale_bench(n_models: int = 48, measured_ticks: int = 10,
                        readers: int = 8) -> dict:
    """Metrics-plane microbench (``make bench-collect``), two axes
    (docs/design/metrics-plane.md):

    1. **Backend queries per tick** — a 48-model in-memory fleet tick with
       grouped collection ON vs OFF, counted by the source's backend query
       counters (not estimated): O(templates) vs O(models x templates).
    2. **In-memory TSDB query latency under 8 concurrent readers** — the
       ring-buffer read path (striped locks + bisect zero-copy windows) vs
       the honest pre-change lever (``legacy_reads``: copy-under-one-lock
       plus linear window scans with per-sample objects).
    """
    import statistics
    import threading

    from wva_tpu.collector.source import (
        InMemoryPromAPI,
        PrometheusSource,
        RefreshSpec,
        SourceRegistry,
        TimeSeriesDB,
    )
    from wva_tpu.collector.registration import (
        register_saturation_queries,
        register_scale_to_zero_queries,
        register_slo_queries,
    )
    from wva_tpu.collector.source.grouped import GroupedMetricsView
    from wva_tpu.collector.source.promql import PromQLEngine
    from wva_tpu.utils import FakeClock

    ns = "bench"

    def build_db(retention_filled: float = 3600.0, step: float = 5.0):
        """48 models x 2 pods with a counter + gauges, retention fully
        populated so range windows pay realistic scan costs."""
        clock = FakeClock(start=200_000.0)
        db = TimeSeriesDB(clock=clock)
        now = clock.now()
        for i in range(n_models):
            model = f"org/bench-model-{i:03d}"
            for v in range(2):
                pod = {"pod": f"b{i:03d}-{v}", "namespace": ns,
                       "model_name": model}
                t = now - retention_filled
                while t <= now:
                    db.add_sample("vllm:request_success_total", pod,
                                  4.0 * (t - 190_000.0), timestamp=t)
                    t += step
                db.add_sample("vllm:kv_cache_usage_perc", pod, 0.4,
                              timestamp=now)
                db.add_sample("vllm:num_requests_waiting", pod, 1,
                              timestamp=now)
                db.add_sample("vllm:cache_config_info",
                              {**pod, "num_gpu_blocks": "4096",
                               "block_size": "32"}, 1.0, timestamp=now)
        return db, clock

    # --- axis 1: backend queries per tick (grouped ON vs OFF) ---

    def queries_per_tick(grouped: bool) -> dict:
        db, clock = build_db(retention_filled=120.0)
        registry = SourceRegistry()
        src = PrometheusSource(InMemoryPromAPI(db), clock=clock)
        registry.register("prometheus", src)
        register_saturation_queries(registry)
        register_scale_to_zero_queries(registry)
        register_slo_queries(registry)
        # One "tick" = the replica-collection queries every model refreshes
        # (the engine's per-model collection surface, driven directly so
        # the axis isolates the metrics plane from K8s/analyzer costs).
        replica_queries = [
            "kv_cache_usage", "queue_length", "cache_config_info",
            "serving_config_info", "avg_output_tokens", "avg_input_tokens",
            "prefix_cache_hit_rate", "generate_backlog", "slots_used",
            "slots_available"]
        walls = []
        src.reset_query_counts()
        for _ in range(measured_ticks):
            view = GroupedMetricsView(src) if grouped else src
            t0 = time.perf_counter()
            for i in range(n_models):
                view.refresh(RefreshSpec(
                    queries=replica_queries,
                    params={"modelID": f"org/bench-model-{i:03d}",
                            "namespace": ns}))
            walls.append(time.perf_counter() - t0)
            clock.advance(5.0)
        total = src.backend_query_total()
        src.close()
        walls.sort()
        return {
            "backend_queries_per_tick": round(total / measured_ticks, 1),
            "collection_wall_p50_ms": round(
                statistics.median(walls) * 1000.0, 2),
        }

    grouped_on = queries_per_tick(grouped=True)
    grouped_off = queries_per_tick(grouped=False)

    # --- axis 2: TSDB query p50 under concurrent readers ---

    def tsdb_read_p50(legacy: bool) -> dict:
        db, clock = build_db(retention_filled=3600.0)
        db.legacy_reads = legacy
        now = clock.now()
        per_thread = 40
        latencies: list[list[float]] = [[] for _ in range(readers)]

        def read_loop(ti: int) -> None:
            engine = PromQLEngine(db)
            for j in range(per_thread):
                model = f"org/bench-model-{(ti * per_thread + j) % n_models:03d}"
                q = ('sum(rate(vllm:request_success_total{namespace="%s",'
                     'model_name="%s"}[1m]))' % (ns, model))
                t0 = time.perf_counter()
                engine.query(q, at=now)
                latencies[ti].append(time.perf_counter() - t0)

        threads = [threading.Thread(target=read_loop, args=(ti,))
                   for ti in range(readers)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        flat = sorted(x for lat in latencies for x in lat)
        return {
            "query_p50_ms": round(
                statistics.median(flat) * 1000.0, 3),
            "query_p99_ms": round(
                flat[min(len(flat) - 1, int(len(flat) * 0.99))] * 1000.0, 3),
            "total_wall_s": round(wall, 3),
            "queries": len(flat),
        }

    ring = tsdb_read_p50(legacy=False)
    legacy = tsdb_read_p50(legacy=True)

    return {
        "models": n_models,
        "measured_ticks": measured_ticks,
        "concurrent_readers": readers,
        "grouped_on": grouped_on,
        "grouped_off_per_model": grouped_off,
        "query_reduction": round(
            grouped_off["backend_queries_per_tick"]
            / max(grouped_on["backend_queries_per_tick"], 1e-9), 1),
        "tsdb_ring": ring,
        "tsdb_legacy_pre_change": legacy,
        "tsdb_p50_speedup": round(
            legacy["query_p50_ms"] / max(ring["query_p50_ms"], 1e-9), 2),
        "levers": {
            "grouped_off_per_model": "GroupedMetricsView bypassed: one "
                                     "backend query per (model, template)",
            "tsdb_legacy_pre_change": "TimeSeriesDB.legacy_reads: "
                                      "copy-under-one-lock + linear window "
                                      "scans (the pre-ring read path)",
        },
    }


def forecast_scale_bench(n_models: int = 48, measured_ticks: int = 30,
                         period: float = 600.0) -> dict:
    """Forecast-plane microbench: per-tick forecaster fit cost at fleet
    scale, batched (ONE padded jitted call across all models — the engine's
    production path) vs serial (one call per model — the pre-batching
    shape). Series are seeded diurnal cycles with distinct phases so every
    model's fit does real work; results are asserted equal so the speedup
    compares identical outputs."""
    import statistics

    from wva_tpu.emulator.loadgen import diurnal
    from wva_tpu.forecast import forecasters as fc
    from wva_tpu.forecast.history import DemandHistoryStore

    long_step = period / fc.SEASON_STEPS
    grid_step = 5.0
    store = DemandHistoryStore(window_seconds=long_step * fc.N_GRID,
                               fine_window_seconds=grid_step * fc.N_GRID,
                               long_gap_seconds=long_step / 2.0)
    t_end = 3000.0
    for m in range(n_models):
        load = diurnal(base_rate=4.0 + 2.0 * m / n_models, amplitude=10.0,
                       period=period, phase=period * m / n_models)
        for i in range(int(t_end / grid_step)):
            t = i * grid_step
            store.observe(f"ns|model-{m:03d}", t, load(t))

    def grids(now: float):
        out = []
        for m in range(n_models):
            w = store.windows(f"ns|model-{m:03d}")
            fine, nf = fc.resample(w[0], now, grid_step)
            longg, nl = fc.resample(w[1], now, long_step)
            out.append(fc.SeriesGrids(
                fine=fine, fine_valid=nf, long=longg, long_valid=nl,
                h_fine_steps=120.0 / grid_step,
                h_long_steps=120.0 / long_step,
                season_steps=fc.SEASON_STEPS))
        return out

    # Warm both compilation caches off the clock.
    warm = grids(t_end)
    fc.fit_batch(warm)
    fc.fit_serial(warm[:1])

    batched_ms, serial_ms = [], []
    for tick in range(measured_ticks):
        g = grids(t_end + tick * 15.0)
        t0 = time.perf_counter()
        b = fc.fit_batch(g)
        batched_ms.append((time.perf_counter() - t0) * 1000.0)
        t0 = time.perf_counter()
        s = fc.fit_serial(g)
        serial_ms.append((time.perf_counter() - t0) * 1000.0)
        assert b == s, "batched and serial fits diverged"

    p50 = statistics.median
    return {
        "n_models": n_models,
        "measured_ticks": measured_ticks,
        "forecasters": list(fc.FORECASTERS),
        "grid_columns": fc.N_GRID,
        "batched_fit_ms_p50": round(p50(batched_ms), 3),
        "serial_fit_ms_p50": round(p50(serial_ms), 3),
        "batched_speedup": round(p50(serial_ms) / max(p50(batched_ms), 1e-9),
                                 2),
        "outputs_identical": True,
    }


def solver_microbench() -> dict:
    """The flagship compiled computation on the default JAX platform (the
    real chip under the driver): batched SLO sizing throughput.

    Timing methodology: the repetition loop runs ON DEVICE (a jitted
    ``lax.fori_loop`` whose carry creates a data dependency between solves)
    and wall time is taken around a single host materialization, with the
    per-solve cost extracted from the SLOPE between two rep counts. Plain
    ``block_until_ready`` loops were measured returning before execution
    completes under the experimental axon TPU backend (0.03ms "per call"
    against XLA's own 4.9ms roofline estimate), so async-loop numbers are
    not trustworthy there; the slope method is immune to both that and the
    tunnel round-trip latency."""
    from functools import partial

    import jax
    import jax.numpy as jnp
    import numpy as np

    from wva_tpu.analyzers.queueing.params import ServiceParms
    from wva_tpu.analyzers.queueing.queue_model import (
        QueueAnalyzer,
        QueueConfig,
        RequestSize,
        TargetPerf,
        candidate_batch,
        size_batch,
    )

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(0)

    def batch(n):
        ks = rng.integers(512, 2048, n)
        cand = candidate_batch(
            alphas=rng.uniform(3.0, 30.0, n),
            betas=rng.uniform(0.001, 0.05, n),
            gammas=rng.uniform(0.00001, 0.002, n),
            avg_in=rng.uniform(128, 2048, n),
            avg_out=rng.uniform(64, 1024, n),
            max_batch=rng.integers(16, 256, n),
            k=ks)
        return (cand, jnp.full((n,), 1000.0, jnp.float32),
                jnp.full((n,), 50.0, jnp.float32),
                jnp.zeros((n,), jnp.float32))

    @partial(jax.jit, static_argnames=("reps", "impl"))
    def repeat_solve(cand, ttft, itl, tps, reps, impl):
        # Each solve's TTFT target depends on the previous solve's output
        # (value unchanged) -> the final transfer proves reps solves ran.
        def body(_, t):
            r = size_batch(cand, t, itl, tps, impl=impl)
            return ttft + 0.0 * r["max_rate_per_s"]
        t = jax.lax.fori_loop(0, reps, body, ttft)
        return size_batch(cand, t, itl, tps, impl=impl)["max_rate_per_s"]

    out: dict = {"platform": platform}
    # Slope needs two rep counts; CPU fallback runs ~13s/solve at C=8192,
    # so it gets the minimum spread while accelerators amortize more.
    reps_lo, reps_hi = (5, 25) if platform != "cpu" else (1, 3)
    # Both bisection backends: "xla" (lax.fori_loop) and "pallas" (the
    # fused Mosaic kernel keeping each tile's chain VMEM-resident across
    # all 48 iterations). The headline batch_{n} numbers quote the best;
    # per-impl results stay visible for the comparison. Only TPU compiles
    # the kernel natively (Mosaic); everywhere else size_batch routes
    # pallas through the interpreter — emulation timings, not a perf path.
    impls = ("xla", "pallas") if platform == "tpu" else ("xla",)
    batches = {n: batch(n) for n in (1024, 8192)}
    compile_s: dict = {}
    exec_best: dict = {}
    # Two sweeps spaced apart, keeping the best exec per (batch, impl):
    # the shared chip/tunnel has multi-minute contention windows that
    # slowed a full sweep ~20x in testing; contention only ever slows a
    # measurement, so min-over-sweeps estimates true capability (same
    # logic as the min-of-3 walls within a sweep).
    sweeps = 2 if platform == "tpu" else 1
    wall_best: dict = {}
    for sweep in range(sweeps):
        if sweep:
            time.sleep(20.0)
        for n, args in batches.items():
            for impl in impls:
                if (n, impl) not in compile_s:
                    t0 = time.perf_counter()
                    jax.block_until_ready(size_batch(*args, impl=impl))
                    compile_s[(n, impl)] = time.perf_counter() - t0
                for reps in (reps_lo, reps_hi):
                    np.asarray(repeat_solve(*args, reps=reps, impl=impl))
                    # min-of-3 per sweep; the cross-sweep min is taken on
                    # the WALLS (contention only ever inflates a wall),
                    # never on the slope — min of a signed difference
                    # would prefer a corrupted sweep whose reps_lo wall
                    # got inflated.
                    wall = min(
                        _timed(lambda: np.asarray(
                            repeat_solve(*args, reps=reps, impl=impl)))
                        for _ in range(3))
                    key = (n, impl, reps)
                    if key not in wall_best or wall < wall_best[key]:
                        wall_best[key] = wall
    for (n, impl), _cs in compile_s.items():
        exec_best[(n, impl)] = max(
            (wall_best[(n, impl, reps_hi)] - wall_best[(n, impl, reps_lo)])
            / (reps_hi - reps_lo),
            1e-9)  # guard: a pathological wall pair must not divide by <= 0
    for n in batches:
        per_impl = {}
        best = None
        for impl in impls:
            exec_s = exec_best[(n, impl)]
            per_impl[impl] = {
                "compile_s": round(compile_s[(n, impl)], 3),
                "execute_s": round(exec_s, 6),
                "candidates_per_s": int(n / exec_s),
            }
            if best is None or exec_s < best[1]:
                best = (impl, exec_s)
        out[f"batch_{n}"] = {**per_impl[best[0]], "impl": best[0],
                             "per_impl": per_impl, "sweeps": sweeps}

    # Scalar facade (one candidate at a time — the reference's solve shape,
    # pkg/analyzer/queueanalyzer.go:127-258) for the batching speedup.
    qa = QueueAnalyzer(
        QueueConfig(max_batch_size=96, max_queue_size=384,
                    service_parms=ServiceParms(alpha=18.0, beta=0.00267,
                                               gamma=0.00002)),
        RequestSize(avg_input_tokens=512, avg_output_tokens=256))
    qa.size(TargetPerf(target_ttft_ms=1000.0))  # warm-up: exclude the
    # facade's own shape-[1] compile from the timed loop (the batched
    # path's compile is reported separately too).
    t0 = time.perf_counter()
    scalar_n = 20
    for _ in range(scalar_n):
        qa.size(TargetPerf(target_ttft_ms=1000.0))
    scalar_per = (time.perf_counter() - t0) / scalar_n
    out["scalar_facade_per_candidate_s"] = round(scalar_per, 5)
    out["batched_speedup_vs_scalar_facade"] = int(
        scalar_per / (out["batch_8192"]["execute_s"] / 8192))
    out["note"] = (
        "scalar = this repo's Python one-candidate-per-call facade (the "
        "reference's solve shape, incl. per-call dispatch/sync overhead — "
        "dominated by host-device round trips on remote TPUs); batched = "
        "compile-once execute-many on the default JAX device, device-slope "
        "timed")
    return out


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _ensure_healthy_device(
        timeouts: tuple = (90.0, 120.0, 150.0, 180.0),
        retry_sleep: float = 20.0) -> dict:
    """Probe the default JAX backend in a SUBPROCESS; retry several times
    over ~10 minutes before giving up (a wedged remote TPU tunnel blocks
    indefinitely and is uninterruptible in-process, but tunnels also come
    back — round 3 lost its hardware capture to a single-probe-then-give-up
    policy). Only after every attempt fails does the run fall back to CPU so
    the driver always gets a result line. Runs before any in-process jax
    use, so the platform override still takes effect.

    Returns a record of what happened for the output JSON: which probe
    attempt succeeded (or that all failed), per-attempt outcome, and the
    platform path taken ("default" vs "cpu-fallback")."""
    import subprocess
    import sys as _sys

    probe = ("import jax, jax.numpy as jnp;"
             "print(float(jax.jit(lambda a:(a@a).sum())"
             "(jnp.ones((256,256)))))")
    # Escalating timeouts: first attempt covers a cold ~20-40s compile;
    # later ones give a flapping tunnel time to recover. Worst case
    # ~(90+120+150+180) + 3*20 = 600s before the CPU fallback.
    import tempfile

    record: dict = {"attempts": [], "platform_path": "default"}
    for i, probe_timeout in enumerate(timeouts):
        # Each attempt is a FRESH interpreter: backend registration (the
        # axon sitecustomize hook) happens at subprocess startup, so a
        # retry re-dials the tunnel from scratch rather than reusing a
        # wedged connection. Output goes to a FILE, not pipes: a tunnel
        # helper grandchild inheriting a pipe fd would keep communicate()
        # blocked past the timeout kill, wedging this function — the exact
        # failure the subprocess isolation exists to prevent.
        t0 = time.perf_counter()
        with tempfile.TemporaryFile() as outf:
            try:
                # start_new_session + killpg on timeout: the timeout kill
                # must reap the WHOLE process group, or a leaked tunnel
                # helper from attempt N holds the remote connection and
                # dooms attempts N+1.. to the same wedge.
                proc = subprocess.Popen([_sys.executable, "-c", probe],
                                        stdout=outf,
                                        stderr=subprocess.STDOUT,
                                        start_new_session=True)
                try:
                    rc = proc.wait(timeout=probe_timeout)
                except subprocess.TimeoutExpired:
                    import signal

                    try:
                        os.killpg(proc.pid, signal.SIGKILL)
                    except ProcessLookupError:
                        pass
                    proc.wait()
                    raise
                if rc != 0:
                    raise subprocess.CalledProcessError(rc, "probe")
            except (subprocess.TimeoutExpired,
                    subprocess.CalledProcessError) as e:
                outf.seek(0, os.SEEK_END)
                outf.seek(max(0, outf.tell() - 800))
                tail = outf.read().decode(errors="replace").strip()
                record["attempts"].append({
                    "outcome": type(e).__name__,
                    "timeout_s": probe_timeout,
                    "wall_s": round(time.perf_counter() - t0, 1),
                    "output_tail": tail[-400:]})
                fatal = isinstance(e, subprocess.CalledProcessError)
                will_retry = not fatal and i + 1 < len(timeouts)
                print(f"WARNING: backend probe {i + 1}/{len(timeouts)} "
                      f"failed ({type(e).__name__}); "
                      + ("retrying" if will_retry
                         else "falling back to CPU")
                      + (f"\n  probe output tail: {tail[-400:]}"
                         if tail else ""),
                      file=_sys.stderr)
                if fatal:
                    # Nonzero exit is deterministic (broken install /
                    # registration error), not a flapping tunnel —
                    # retrying just delays the inevitable fallback.
                    break
                if will_retry:
                    time.sleep(retry_sleep)
                continue
        record["attempts"].append({
            "outcome": "ok", "timeout_s": probe_timeout,
            "wall_s": round(time.perf_counter() - t0, 1)})
        return record

    record["platform_path"] = "cpu-fallback"
    # Env alone is not enough: jax snapshots JAX_PLATFORMS at import,
    # and this module's imports already pulled jax in. config.update
    # works any time before the first backend initialization.
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
    return record


def _merge_bench_local(key: str, value: dict) -> str:
    """Merge one section into BENCH_LOCAL.json without clobbering the full
    bench's record (the tick bench runs standalone via `make bench-tick`)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_LOCAL.json")
    full = {}
    try:
        with open(path) as f:
            full = json.load(f)
    except (OSError, ValueError):
        pass
    full.setdefault("detail", {})[key] = value
    with open(path, "w") as f:
        json.dump(full, f, indent=1)
    return path


def _count_spans(tree) -> int:
    if not isinstance(tree, dict):
        return 0
    return 1 + sum(_count_spans(c) for c in tree.get("children", ()))


def _find_span(tree, name: str):
    if not isinstance(tree, dict):
        return None
    if tree.get("name") == name:
        return tree
    for child in tree.get("children", ()):
        hit = _find_span(child, name)
        if hit is not None:
            return hit
    return None


def spans_bench(models=(48, 480), variants_per_model: int = 2,
                measured_ticks: int = 21, warm_ticks: int = 13) -> dict:
    """Obs-plane A/B (`make bench-spans`, BENCH_LOCAL detail.obs_plane):
    quiet-tick p50 with WVA_SPANS on vs off at 48 and 480 models. The
    off lever is asserted ZERO-cost structurally — no recorder object
    exists, `engine.spans is None`, every hook is one attribute read —
    and the on-lever overhead is recorded against the <3% target. Also
    asserts the acceptance shape: a 4-shard fleet tick yields ONE
    stitched span tree covering every shard worker plus the fleet
    merge."""
    import statistics

    # Off-lever zero cost is STRUCTURAL, asserted on its own world: with
    # WVA_SPANS=off no recorder object exists anywhere — every hot-path
    # hook degenerates to one attribute read.
    mgr, cluster, clock, feed = _build_tick_world(
        models[0], variants_per_model, spans=False)
    assert mgr.spans is None and mgr.engine.spans is None, \
        "WVA_SPANS=off must build no recorder"
    mgr.shutdown()
    _drain_decision_bus()

    out: dict[str, dict] = {}
    for n in models:
        # One world, lever toggled tick-by-tick: alternating the recorder
        # on the SAME warmed world cancels the world-level drift (cache
        # warmth, allocator state) that dwarfs the per-span cost when two
        # separate worlds are compared.
        mgr, cluster, clock, feed = _build_tick_world(
            n, variants_per_model, spans=True)
        eng = mgr.engine
        assert mgr.spans is not None and eng.spans is mgr.spans
        capacity = eng.capacity
        for _ in range(3 + warm_ticks):
            eng.optimize()
            clock.advance(5.0)
            feed(clock.now())
        walls: dict[bool, list[float]] = {True: [], False: []}
        spans_counts: list[int] = []
        for i in range(measured_ticks * 2):
            spans_on = i % 2 == 0
            eng.spans = mgr.spans if spans_on else None
            if capacity is not None:
                capacity.spans = mgr.spans if spans_on else None
            t0 = time.perf_counter()
            eng.optimize()
            wall = time.perf_counter() - t0
            if spans_on:
                spans_counts.append(_count_spans(mgr.spans.last_tree()))
            # Quiet-tick p50 means QUIET: the every-Nth resync tick
            # re-analyzes the whole fleet and — the resync period being
            # even — always lands in the same parity bucket, so keeping
            # it would bias one side of the A/B by the full-analysis
            # cost. (Span counts above still sample it: the resync tick
            # is the per-model span worst case.)
            if eng.last_tick_stats.get("analyzed", 0) <= n // 2:
                walls[spans_on].append(wall)
            clock.advance(5.0)
            feed(clock.now())
        eng.spans = mgr.spans
        per: dict[str, object] = {
            "spans_on": {"tick_p50_ms": round(
                statistics.median(walls[True]) * 1000.0, 2)},
            "spans_off": {"tick_p50_ms": round(
                statistics.median(walls[False]) * 1000.0, 2)},
            # min = the truly quiet tick; resync ticks analyze everything
            # and record one model span per analyzed model.
            "spans_per_quiet_tick": min(spans_counts),
            "spans_per_resync_tick": max(spans_counts),
        }
        on_ms = per["spans_on"]["tick_p50_ms"]
        off_ms = per["spans_off"]["tick_p50_ms"]
        per["overhead_pct"] = round(
            (on_ms - off_ms) / max(off_ms, 1e-9) * 100.0, 1)
        per["overhead_target_pct"] = 3.0
        per["target_met"] = bool(per["overhead_pct"] < 3.0)
        out[str(n)] = per
        mgr.shutdown()
        _drain_decision_bus()

    # Acceptance shape: ONE stitched fleet-tick span tree across a
    # 4-shard world — every shard worker's subtree grafted (span ids
    # namespaced sh<i>:s<j>) plus the fleet merge span.
    shards = 4
    mgr, cluster, clock, feed = _build_tick_world(
        48, variants_per_model, sharding=shards)
    eng = mgr.engine
    for _ in range(3):
        eng.optimize()
        clock.advance(5.0)
        feed(clock.now())
    tree = mgr.spans.last_tree()
    assert tree is not None and tree["name"] == "tick"
    worker_subtrees = [c for c in tree.get("children", ())
                       if c.get("name") == "shard_tick"]
    seen = sorted((c.get("attrs") or {}).get("shard", -1)
                  for c in worker_subtrees)
    assert seen == list(range(shards)), \
        f"stitched tree missing shard workers: {seen}"
    assert _find_span(tree, "fleet_merge") is not None, \
        "stitched tree missing the fleet merge span"
    out["stitched_4shard"] = {
        "shards": shards,
        "worker_subtrees": len(worker_subtrees),
        "fleet_merge_present": True,
        "total_spans": _count_spans(tree),
        "trace_id": tree.get("trace_id", ""),
    }
    mgr.shutdown()
    _drain_decision_bus()
    return out


def spans_main() -> None:
    """`make bench-spans`: spans-on vs spans-off quiet-tick A/B at 48 and
    480 models + the 4-shard stitched-trace assertion; merges
    detail.obs_plane into BENCH_LOCAL.json, one JSON line on stdout."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.time()
    result = spans_bench()
    result["bench_wall_seconds"] = round(time.time() - t0, 1)
    _merge_bench_local("obs_plane", result)
    print(json.dumps({
        "metric": "span_overhead_quiet_tick_48_models",
        "value": result["48"]["overhead_pct"],
        "unit": "pct_p50_overhead_spans_on_vs_off",
        "detail": result,
    }))


def tick_main() -> None:
    """`make bench-tick`: run ONLY the fleet-scale tick microbench (CPU
    JAX is fine — the measured quantity is control-loop latency), merge the
    record into BENCH_LOCAL.json, print one JSON line."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.time()
    tick = tick_scale_bench()
    tick["bench_wall_seconds"] = round(time.time() - t0, 1)
    _merge_bench_local("tick_scale", tick)
    print(json.dumps({
        "metric": "fleet_tick_latency_48_models_96_vas",
        "value": tick["fleet"]["tick_p50_ms"],
        "unit": "ms_p50_per_tick",
        "vs_baseline": tick["tick_p50_speedup"],
        "detail": tick,
    }))


def _models_arg(default: int | None = None) -> int | None:
    """--models N: fleet size override for the quiet-tick bench and the
    profiler (`make bench-tick-quiet MODELS=480` / `make bench-profile
    MODELS=480`)."""
    if "--models" in sys.argv:
        return int(sys.argv[sys.argv.index("--models") + 1])
    return default


def tick_quiet_main() -> None:
    """`make bench-tick-quiet`: steady-state quiet-tick microbench
    (incremental vs fp-recompute vs informer-only vs per-tick-LIST
    baseline, merged into BENCH_LOCAL.json detail.incremental_tick) plus
    the 48/144/480/2000 fleet-growth sweep (detail.fingerprint_plane),
    one JSON line. `--models N` overrides the mode-comparison fleet
    size."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.time()
    record = tick_quiet_bench(n_models=_models_arg(48))
    sweep = fingerprint_scale_sweep()
    record["bench_wall_seconds"] = round(time.time() - t0, 1)
    _merge_bench_local("incremental_tick", record)
    _merge_bench_local("fingerprint_plane", {
        "quiet_tick_p50_ms": record["incremental"]["tick_p50_ms"],
        "quiet_tick_p50_ms_fp_recompute":
            record["fp_recompute"]["tick_p50_ms"],
        "fp_delta_p50_speedup": record["fp_delta_p50_speedup"],
        "phase_ms_mean": record["incremental"]["phase_ms_mean"],
        "scale_sweep": sweep,
    })
    # Object-plane extract (docs/design/object-plane.md): the shipped
    # zero-copy path vs the SAME configuration with WVA_ZERO_COPY off
    # (deep-copy-on-read), plus the per-tick copy accounting.
    _merge_bench_local("object_plane", {
        "quiet_tick_p50_ms_zero_copy":
            record["incremental"]["tick_p50_ms"],
        "quiet_tick_p50_ms_copy_on_read":
            record["copy_on_read"]["tick_p50_ms"],
        "quiet_tick_p99_ms_zero_copy":
            record["incremental"]["tick_p99_ms"],
        "quiet_tick_p99_ms_copy_on_read":
            record["copy_on_read"]["tick_p99_ms"],
        "p50_speedup": record["object_plane_p50_speedup"],
        "object_copies_per_tick_p50":
            record["incremental"]["object_copies_per_tick_p50"],
        "object_copies_per_tick_max":
            record["incremental"]["object_copies_per_tick_max"],
    })
    print(json.dumps({
        "metric": "quiet_tick_latency_48_models_96_vas",
        "value": record["incremental"]["tick_p50_ms"],
        "unit": "ms_p50_per_tick",
        "vs_baseline": record["quiet_tick_p50_speedup"],
        "detail": record,
    }))


def collect_main() -> None:
    """`make bench-collect`: metrics-plane microbench only (backend
    queries/tick grouped ON vs OFF + in-memory TSDB p50 under concurrent
    readers), merged into BENCH_LOCAL.json, one JSON line on stdout."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.time()
    record = collect_scale_bench()
    record["bench_wall_seconds"] = round(time.time() - t0, 1)
    _merge_bench_local("collect_scale", record)
    print(json.dumps({
        "metric": "metrics_plane_backend_queries_per_tick_48_models",
        "value": record["grouped_on"]["backend_queries_per_tick"],
        "unit": "backend_queries_per_tick",
        "vs_baseline": record["query_reduction"],
        "detail": record,
    }))


def forecast_main() -> None:
    """`make bench-forecast` / `bench.py --forecast-only`: forecaster-fit
    cost per tick at 48 models, batched vs serial, merged into
    BENCH_LOCAL.json detail.forecast, one JSON line on stdout."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.time()
    record = forecast_scale_bench()
    record["bench_wall_seconds"] = round(time.time() - t0, 1)
    _merge_bench_local("forecast", record)
    print(json.dumps({
        "metric": "forecast_fit_ms_per_tick_48_models",
        "value": record["batched_fit_ms_p50"],
        "unit": "ms_p50_per_tick",
        "vs_baseline": record["batched_speedup"],
        "detail": record,
    }))


def capacity_storm_bench(n_models: int = 48, duration: float = 600.0,
                         engine_interval: float = 15.0) -> dict:
    """Elastic-capacity microbench (``make bench-capacity``): a 48-model
    fleet on a mixed on-demand + spot pool under a seeded preemption storm
    (bursty demand with correlated spot preemptions), FakeGkeProvisioner
    ordering replacements. Reports, per preemption event, the engine ticks
    until the fleet's total desired replicas re-converges to its
    pre-preemption level (time-to-reconverge), plus decisions/tick churn
    (variants whose desired target moved per tick) — the stability axis a
    capacity-plane regression shows up on first."""
    import statistics

    from wva_tpu.capacity.tiers import GKE_SPOT_NODE_LABEL
    from wva_tpu.config import new_test_config
    from wva_tpu.constants import WVA_DESIRED_REPLICAS
    from wva_tpu.emulator import (
        EmulationHarness,
        FakeGkeProvisioner,
        HPAParams,
        ServingParams,
        TierPolicy,
        VariantSpec,
        add_tpu_nodepool,
        preemption_storm,
    )
    from wva_tpu.engines import common as engines_common
    from wva_tpu.interfaces import SaturationScalingConfig

    profile, events = preemption_storm(
        base_rate=2.0, burst_rate=14.0, burst_duration=90.0,
        mean_gap=150.0, horizon=duration, seed=11,
        preemptions_per_burst=4, preemption_lag=20.0)
    specs = [VariantSpec(
        name=f"m{i:03d}-v5e", model_id=f"bench/model-{i:03d}",
        accelerator="v5e-8", chips_per_replica=8, cost=10.0,
        initial_replicas=1, serving=ServingParams(engine="jetstream"),
        load=profile,
        hpa=HPAParams(stabilization_up_seconds=10.0,
                      stabilization_down_seconds=60.0,
                      sync_period_seconds=10.0))
        for i in range(n_models)]
    harness = EmulationHarness(
        specs,
        saturation_config=SaturationScalingConfig(
            analyzer_name="saturation", enable_limiter=True),
        config=new_test_config(),
        nodepools=[("od-pool", "v5e", "2x4", n_models)],
        startup_seconds=30.0, engine_interval=engine_interval,
        stochastic_seed=20260804,
        provisioner=lambda cluster, clock: FakeGkeProvisioner(
            cluster, clock,
            tiers={"on_demand": TierPolicy(provision_delay_seconds=120.0),
                   "spot": TierPolicy(provision_delay_seconds=60.0,
                                      preemptible=True)},
            seed=3))
    add_tpu_nodepool(harness.cluster, "spot-pool", "v5e", "2x4",
                     n_models // 2,
                     extra_labels={GKE_SPOT_NODE_LABEL: "true"})
    harness.provisioner.schedule_preemptions(
        [(harness.start_time + t, k) for t, k in events])

    registry = harness.manager.registry
    names = [s.name for s in specs]

    def fleet_desired() -> dict[str, int]:
        out = {}
        for name in names:
            v = registry.get(WVA_DESIRED_REPLICAS, {
                "variant_name": name, "namespace": harness.namespace,
                "accelerator_type": "v5e-8"})
            out[name] = int(v or 0)
        return out

    churn: list[int] = []
    tick_walls: list[float] = []
    last = {"desired": {}, "total": 0}
    pending: dict[float, dict] = {}  # event t -> {"before", "ticks"}
    reconverge_ticks: dict[float, int] = {}
    orig = harness.manager.engine.optimize

    def tick_wrapper():
        t0 = time.perf_counter()
        orig()
        tick_walls.append(time.perf_counter() - t0)
        desired = fleet_desired()
        total = sum(desired.values())
        churn.append(sum(1 for n in names
                         if desired[n] != last["desired"].get(n, 0)))
        for et, st in list(pending.items()):
            st["ticks"] += 1
            if total >= st["before"]:
                reconverge_ticks[et] = st["ticks"]
                del pending[et]
        last["desired"] = desired
        last["total"] = total

    def on_step(h, t):
        now = h.clock.now()
        for et, _ in events:
            at = h.start_time + et
            if now < at <= now + 1.0 and et not in pending \
                    and et not in reconverge_ticks:
                pending[et] = {"before": last["total"], "ticks": 0}

    harness.manager.engine.executor.task = tick_wrapper
    harness.run(duration, on_step=on_step)
    harness.manager.shutdown()
    _drain_decision_bus()

    capman = harness.manager.engine.capacity
    ticks_list = sorted(reconverge_ticks.values())
    outcomes: dict[str, int] = {}
    for _, _, _, _, outcome in capman.request_log:
        outcomes[outcome] = outcomes.get(outcome, 0) + 1
    return {
        "n_models": n_models,
        "duration_s": duration,
        "engine_interval_s": engine_interval,
        "preemption_events": len(events),
        "preempted_slices":
            harness.provisioner.preempted_slices_total,
        "reconverge_ticks_per_event": ticks_list,
        "reconverge_ticks_p50": (statistics.median(ticks_list)
                                 if ticks_list else None),
        "reconverge_ticks_max": max(ticks_list) if ticks_list else None,
        "reconverge_unresolved": len(pending),
        "decision_churn_per_tick_mean": round(
            sum(churn) / max(len(churn), 1), 2),
        "decision_churn_per_tick_max": max(churn) if churn else 0,
        "tick_p50_ms": round(
            statistics.median(tick_walls) * 1000.0, 2) if tick_walls else 0,
        "provision_request_outcomes": dict(sorted(outcomes.items())),
    }


def capacity_main() -> None:
    """`make bench-capacity` / `bench.py --capacity-only`: preemption-storm
    reconvergence + decision churn at 48 models, merged into
    BENCH_LOCAL.json detail.capacity, one JSON line on stdout."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.time()
    record = capacity_storm_bench()
    record["bench_wall_seconds"] = round(time.time() - t0, 1)
    _merge_bench_local("capacity", record)
    print(json.dumps({
        "metric": "preemption_reconverge_ticks_48_models",
        "value": record["reconverge_ticks_p50"],
        "unit": "engine_ticks_p50_to_reconverge",
        "vs_baseline": record["reconverge_ticks_max"],
        "detail": record,
    }))


def chaos_storm_bench(n_models: int = 48, duration: float = 1200.0,
                      engine_interval: float = 15.0) -> dict:
    """Chaos soak (``make bench-chaos``): a 48-model fleet under seeded
    bursty demand with CORRELATED metrics-plane faults (blackouts, partial
    label-subset responses, 429 error rates, an apiserver storm every 4th
    burst — ``loadgen.chaos_storm``), run twice over the SAME world seed:
    input-health plane ON (shipped default) and OFF (pre-change behavior).

    Asserts the do-no-harm acceptance criteria on the ON run:

    - zero wrong-direction scale events: during a blackout or partial
      window, no variant whose window-start desired was healthy (>= 1)
      ever has its desired lowered (scale-to-zero included);
    - bounded recovery: within ``recovery_ticks`` (3) engine ticks of a
      faulted interval clearing, the health plane reports all-fresh with
      no active clamps — desired has reconverged to trusted values.

    The OFF run reports the same counters for honest comparison (partial
    responses are the killer there: a "successful" query missing half the
    pods halves the computed demand)."""
    import statistics

    from wva_tpu.config import new_test_config
    from wva_tpu.constants import WVA_DESIRED_REPLICAS
    from wva_tpu.emulator import (
        EmulationHarness,
        FaultPlan,
        HPAParams,
        ServingParams,
        VariantSpec,
        chaos_storm,
    )
    from wva_tpu.emulator.faults import (
        KIND_METRICS_BLACKOUT,
        KIND_METRICS_PARTIAL,
    )
    from wva_tpu.engines import common as engines_common

    from wva_tpu.interfaces import SaturationScalingConfig

    profile, windows = chaos_storm(
        base_rate=2.0, burst_rate=14.0, burst_duration=90.0,
        mean_gap=130.0, horizon=duration, seed=17,
        fault_lead=20.0, fault_duration=150.0)
    guarded = [(w.start, w.end, w.kind) for w in windows
               if w.kind in (KIND_METRICS_BLACKOUT, KIND_METRICS_PARTIAL)]
    # Maximal faulted intervals (any metrics fault), for recovery timing.
    spans: list[list[float]] = []
    for w in sorted(windows, key=lambda w: w.start):
        if spans and w.start <= spans[-1][1]:
            spans[-1][1] = max(spans[-1][1], w.end)
        else:
            spans.append([w.start, w.end])

    def run_world(health_on: bool) -> dict:
        specs = [VariantSpec(
            name=f"m{i:03d}-v5e", model_id=f"bench/model-{i:03d}",
            accelerator="v5e-8", chips_per_replica=8, cost=10.0,
            initial_replicas=1, serving=ServingParams(engine="jetstream"),
            load=profile,
            hpa=HPAParams(stabilization_up_seconds=10.0,
                          stabilization_down_seconds=60.0,
                          sync_period_seconds=10.0))
            for i in range(n_models)]
        harness = EmulationHarness(
            specs,
            saturation_config=SaturationScalingConfig(
                analyzer_name="saturation", enable_limiter=True),
            config=new_test_config(),
            nodepools=[("v5e-pool", "v5e", "2x4", n_models * 2)],
            startup_seconds=30.0, engine_interval=engine_interval,
            stochastic_seed=20260804,
            fault_plan=FaultPlan(list(windows), seed=17))
        engine = harness.manager.engine
        if not health_on:
            engine.health = None
        registry = harness.manager.registry
        names = [s.name for s in specs]
        model_of = {s.name: s.model_id for s in specs}
        prom_api = harness.manager.source_registry.get("prometheus").api

        def fleet_desired() -> dict[str, int]:
            return {name: int(registry.get(WVA_DESIRED_REPLICAS, {
                "variant_name": name, "namespace": harness.namespace,
                "accelerator_type": "v5e-8"}) or 0) for name in names}

        wrong_direction = 0
        scaled_to_zero = 0
        window_base: dict[tuple, dict[str, int]] = {}
        recovery: dict[float, int] = {}
        pending_recovery: dict[float, int] = {}
        last = {"desired": {}}
        orig = harness.manager.engine.optimize

        def in_guarded(t: float) -> tuple | None:
            for start, end, kind in guarded:
                if start <= t < end:
                    return (start, end, kind)
            return None

        def tick_wrapper():
            orig()
            now_rel = harness.clock.now() - harness.start_time
            desired = fleet_desired()
            span = in_guarded(now_rel)
            if span is not None:
                start, end, kind = span
                base = window_base.setdefault((start, end),
                                              dict(last["desired"]))
                nonlocal wrong_direction, scaled_to_zero
                for n in names:
                    if kind == KIND_METRICS_PARTIAL and model_of[n] not in \
                            getattr(prom_api, "dropped_models", ()):
                        # Partial windows thin a seeded series subset;
                        # models whose series all survived see COMPLETE
                        # fresh data and may legitimately scale down.
                        continue
                    if base.get(n, 0) >= 1 and desired[n] < base[n]:
                        wrong_direction += 1
                        if desired[n] == 0:
                            scaled_to_zero += 1
            for end in list(pending_recovery):
                pending_recovery[end] += 1
                health = harness.manager.engine.last_tick_health
                if not health or not any(health.values()):
                    recovery[end] = pending_recovery.pop(end)
            last["desired"] = desired

        def on_step(h, t):
            for start, end in spans:
                if end <= t < end + 1.0 and end not in recovery \
                        and end not in pending_recovery:
                    pending_recovery[end] = 0

        harness.manager.engine.executor.task = tick_wrapper
        harness.run(duration, on_step=on_step)
        injected = dict(getattr(
            harness.manager.source_registry.get("prometheus").api,
            "injected", {}))
        harness.manager.shutdown()
        _drain_decision_bus()
        ticks = sorted(recovery.values())
        return {
            "wrong_direction_events": wrong_direction,
            "scaled_to_zero_events": scaled_to_zero,
            "recovery_ticks_per_span": ticks,
            "recovery_ticks_max": max(ticks) if ticks else 0,
            "recovery_ticks_p50": (statistics.median(ticks)
                                   if ticks else None),
            "recovery_unresolved": len(pending_recovery),
            "faults_injected": injected,
        }

    on = run_world(health_on=True)
    off = run_world(health_on=False)
    assert on["wrong_direction_events"] == 0, (
        f"health plane allowed {on['wrong_direction_events']} "
        "wrong-direction scale events during blackout/partial windows")
    assert on["scaled_to_zero_events"] == 0
    assert on["recovery_unresolved"] == 0, "a faulted span never recovered"
    assert on["recovery_ticks_max"] <= 3, (
        f"recovery took {on['recovery_ticks_max']} ticks (> 3)")
    return {
        "n_models": n_models,
        "duration_s": duration,
        "engine_interval_s": engine_interval,
        "fault_windows": len(windows),
        "guarded_windows": len(guarded),
        "health_on": on,
        "health_off": off,
    }


def chaos_main() -> None:
    """`make bench-chaos` / `bench.py --chaos-only`: seeded 48-model chaos
    storm, health plane on vs off, merged into BENCH_LOCAL.json
    detail.chaos, one JSON line on stdout. Raises when the do-no-harm
    acceptance criteria fail."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.time()
    record = chaos_storm_bench()
    record["bench_wall_seconds"] = round(time.time() - t0, 1)
    _merge_bench_local("chaos", record)
    print(json.dumps({
        "metric": "chaos_wrong_direction_events_48_models",
        "value": record["health_on"]["wrong_direction_events"],
        "unit": "wrong_direction_scale_events_during_faults",
        "vs_baseline": record["health_off"]["wrong_direction_events"],
        "detail": record,
    }))


def failover_storm_bench(n_models: int = 48, duration: float = 1200.0,
                         engine_interval: float = 15.0,
                         checkpoint: bool = True, seed: int = 23) -> dict:
    """Crash-restart + leader-flap storm (``make bench-failover``): a
    48-model fleet under steady high load with TWO manager processes over
    one world (leader election on), a seeded schedule of process
    kill/restarts (mid-tick and between-tick, crash and clean) and
    voluntary leader flaps, plus one PARTIAL metrics window overlapping a
    restart (the amnesia trap: the rebooted process sees successful-
    looking queries missing half the pods).

    Asserts the resilience acceptance criteria:

    - zero wrong-direction scale events inside every restart/handover
      window (same detection as the chaos bench: a variant whose
      window-start desired was healthy never has it lowered);
    - zero dual-actuation: every actuation write (VA status, scale
      subresource) is attributed to (writer identity, lease epoch) via
      the per-process SeverableKubeClient boundary — no epoch has two
      writers, and no actuation ever carries a None epoch (a non-leader
      never writes);
    - post-restart reconvergence <= 5 engine ticks (boot ramp released,
      no clamps) for events outside fault windows;
    - ``checkpoint=False`` (WVA_CHECKPOINT=off) keeps the same
      zero-wrong-direction guarantee on the boot ramp alone.
    """
    from wva_tpu.config.loader import load as load_config
    from wva_tpu.emulator import (
        EmulationHarness,
        FaultPlan,
        FaultWindow,
        HPAParams,
        ServingParams,
        VariantSpec,
        trapezoid,
    )
    from wva_tpu.emulator.faults import (
        KIND_METRICS_PARTIAL,
        seeded_leader_flaps,
        seeded_restarts,
    )
    from wva_tpu.engines import common as engines_common
    from wva_tpu.interfaces import SaturationScalingConfig

    cfg = load_config(env={
        "PROMETHEUS_BASE_URL": "http://prometheus.test:9090",
        "LEADER_ELECT": "true",
        "WVA_CHECKPOINT": "true" if checkpoint else "off",
        "WVA_CHECKPOINT_INTERVAL": "4",
    })
    restarts = seeded_restarts(seed, horizon=duration, n=3)
    flaps = seeded_leader_flaps(seed + 1, horizon=duration, n=2)
    # One partial window straddling the SECOND restart: the rebooted
    # process must hold through data it cannot yet distrust.
    trap = FaultWindow(kind=KIND_METRICS_PARTIAL,
                       start=restarts[1].at - 30.0,
                       end=restarts[1].at + 120.0, drop_fraction=0.5)
    # Steady high load: desired replicas should NEVER legitimately drop,
    # so any drop inside a restart/handover window is wrong-direction by
    # construction.
    load = trapezoid(base_rate=6.0, peak_rate=6.0, ramp_up=1.0, hold=1e9,
                     ramp_down=1.0, tail=0.0, delay=0.0)
    specs = [VariantSpec(
        name=f"f{i:03d}-v5e", model_id=f"bench/fo-model-{i:03d}",
        accelerator="v5e-8", chips_per_replica=8, cost=10.0,
        initial_replicas=1, serving=ServingParams(engine="jetstream"),
        load=load,
        hpa=HPAParams(stabilization_up_seconds=10.0,
                      stabilization_down_seconds=60.0,
                      sync_period_seconds=10.0))
        for i in range(n_models)]
    harness = EmulationHarness(
        specs,
        saturation_config=SaturationScalingConfig(
            analyzer_name="saturation", enable_limiter=True),
        config=cfg, nodepools=[("v5e-pool", "v5e", "2x4", n_models * 2)],
        startup_seconds=30.0, engine_interval=engine_interval,
        stochastic_seed=20260804,
        fault_plan=FaultPlan([trap], seed=seed))
    harness.manager.elector.identity = "replica-a"
    harness.add_standby("replica-b")

    # --- dual-actuation ledger: every actuation write attributed to
    # (identity, lease epoch) through the per-process boundary ---
    actuations: list[tuple[str, str, object]] = []

    def attach_ledger(mgr, identity: str) -> None:
        boundary = mgr.process_boundary

        def on_write(verb, args, _mgr=mgr, _id=identity):
            if verb not in ("update_status", "patch_scale"):
                return
            actuations.append((_id, verb, _mgr.elector.fencing_token()))
        boundary.on_write = on_write

    attach_ledger(harness.manager, "replica-a")
    attach_ledger(harness.standbys[0], "replica-b")

    names = [s.name for s in specs]

    def leader():
        for m in harness._all_managers():
            if m.is_leader():
                return m
        return None

    def fleet_desired() -> dict[str, int]:
        # Durable VA status, NOT a per-process gauge registry: a freshly
        # restarted manager exports nothing until its first leading tick,
        # and reading its empty registry as desired=0 would count every
        # handover gap as a fleet-wide scale-down.
        return {va.metadata.name:
                va.status.desired_optimized_alloc.num_replicas
                for va in harness.cluster.variant_autoscalings(
                    namespace=harness.namespace)}

    # Event windows: [event, event + 5 ticks + handover allowance].
    window_span = 5 * engine_interval + 90.0
    events = sorted([(e.at, "restart", e) for e in restarts]
                    + [(t, "flap", None) for t in flaps])
    event_state: dict[float, dict] = {
        at: {"kind": kind, "base": None, "reconverged": None,
             "in_fault": trap.start <= at < trap.end}
        for at, kind, _ in events}
    wrong_direction = 0
    restart_count = {"n": 0}
    last_desired: dict[str, int] = {}

    def on_step(h, t):
        nonlocal wrong_direction
        for at, kind, ev in events:
            if at <= t < at + 1.0 and event_state[at]["base"] is None:
                event_state[at]["base"] = dict(last_desired)
                if kind == "restart":
                    restart_count["n"] += 1
                    if ev.mid_tick:
                        h.manager.engine.crash_before_apply = True
                        h.manager.engine.executor.tick()
                    ident = f"replica-a-r{restart_count['n']}"
                    h.restart_manager(release_lease=ev.clean, identity=ident)
                    attach_ledger(h.manager, ident)
                else:
                    lead = leader()
                    if lead is not None:
                        lead.elector.release()
        desired = fleet_desired()
        for at, st in event_state.items():
            if st["base"] is None:
                continue
            if at <= t < at + window_span:
                for n in names:
                    if st["base"].get(n, 0) >= 1 \
                            and desired.get(n, 0) < st["base"][n]:
                        wrong_direction += 1
            if st["reconverged"] is None and t > at + 5.0 \
                    and not st["in_fault"]:
                lead = leader()
                if lead is not None:
                    stats = lead.engine.last_tick_health
                    ticks = lead.engine._tick_seq
                    if ticks >= 1 and stats \
                            and not stats.get("boot_held") \
                            and not stats.get("clamped"):
                        st["reconverged"] = min(ticks, int(
                            (t - at) / engine_interval) + 1)
        last_desired.clear()
        last_desired.update(desired)

    harness.run(duration, on_step=on_step)
    harness.manager.shutdown()
    for m in harness.standbys:
        m.shutdown()
    _drain_decision_bus()

    # --- assertions ---
    by_epoch: dict[object, set[str]] = {}
    none_epoch_writes = 0
    for ident, verb, epoch in actuations:
        if epoch is None:
            none_epoch_writes += 1
        else:
            by_epoch.setdefault(epoch, set()).add(ident)
    dual = {e: sorted(ws) for e, ws in by_epoch.items() if len(ws) > 1}
    reconv = [st["reconverged"] for st in event_state.values()
              if st["reconverged"] is not None]
    handovers = len([1 for _, k, e in events
                     if k == "flap" or (e is not None and e.clean)])
    assert wrong_direction == 0, (
        f"{wrong_direction} wrong-direction scale events inside "
        "restart/handover windows")
    assert not dual, f"dual actuation: two writers in one epoch: {dual}"
    assert none_epoch_writes == 0, (
        f"{none_epoch_writes} actuations without a lease epoch "
        "(non-leader wrote)")
    assert reconv and max(reconv) <= 5, (
        f"post-restart reconvergence took {reconv} ticks (> 5)")
    return {
        "checkpoint": checkpoint,
        "restarts": [{"at": e.at, "mid_tick": e.mid_tick,
                      "clean": e.clean} for e in restarts],
        "leader_flaps": flaps,
        "handovers": handovers,
        "wrong_direction_events": wrong_direction,
        "dual_actuation_epochs": len(dual),
        "actuations_recorded": len(actuations),
        "epochs_seen": len(by_epoch),
        "reconverge_ticks": reconv,
        "reconverge_ticks_max": max(reconv) if reconv else None,
    }


def failover_main() -> None:
    """`make bench-failover` / `bench.py --failover-only`: seeded 48-model
    crash-restart + leader-flap storm, checkpoint on AND off over the same
    seed, merged into BENCH_LOCAL.json detail.failover, one JSON line.
    Raises when any resilience acceptance criterion fails. `--smoke` runs
    the short CI shape (12 models, 600s)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    smoke = "--smoke" in sys.argv
    n_models = 12 if smoke else 48
    duration = 600.0 if smoke else 1200.0
    t0 = time.time()
    on = failover_storm_bench(n_models=n_models, duration=duration,
                              checkpoint=True)
    off = failover_storm_bench(n_models=n_models, duration=duration,
                               checkpoint=False)
    record = {
        "n_models": n_models,
        "duration_s": duration,
        "checkpoint_on": on,
        "checkpoint_off": off,
        "bench_wall_seconds": round(time.time() - t0, 1),
    }
    if not smoke:
        _merge_bench_local("failover", record)
    print(json.dumps({
        "metric": "failover_wrong_direction_events_48_models",
        "value": on["wrong_direction_events"],
        "unit": "wrong_direction_scale_events_in_restart_windows",
        "vs_baseline": on["reconverge_ticks_max"],
        "detail": record,
    }))


def federation_storm_bench(models_per_region: int = 4,
                           duration: float = 900.0,
                           engine_interval: float = 15.0,
                           seed: int = 29,
                           faults: bool = True) -> dict:
    """Federated-fleet storm (``make bench-federation``): THREE emulated
    regions in lockstep (docs/design/federation.md) under follow-the-sun
    diurnal load, with a seeded regional spot-preemption storm in
    ``eu-west4`` and one FULL-REGION metrics blackout in ``us-east1``
    (every model's inputs go dark; the input-health plane freezes the
    region). The same seeded world runs fault-free for the baseline.

    Asserts the federation acceptance criteria on the faulted run:

    - zero global SLO-attainment loss vs the no-fault run (physics keep
      serving through a metrics blackout; the frozen region holds its
      footprint while the arbiter raises spill standby elsewhere);
    - zero wrong-direction scale events in the blacked-out region: no
      variant whose window-start desired was healthy (>= 1) ever has it
      lowered inside the blackout window;
    - spill actually happened (directives from the dark region landed in
      a healthy region) and reconverged: once the dark region's capture
      classifies healthy again, directives drain within 5 arbiter ticks
      (re-admission hysteresis is 3).
    """
    from wva_tpu.config import HealthConfig, new_test_config
    from wva_tpu.constants import WVA_DESIRED_REPLICAS
    from wva_tpu.emulator import (
        FakeGkeProvisioner,
        FaultPlan,
        FaultWindow,
        FederatedHarness,
        HPAParams,
        RegionSpec,
        ServingParams,
        TierPolicy,
        VariantSpec,
        add_tpu_nodepool,
        diurnal,
        preemption_storm,
        regional,
    )
    from wva_tpu.emulator.faults import KIND_METRICS_BLACKOUT

    regions = ("us-east1", "eu-west4", "asia-ne1")
    dark = "us-east1"
    stormy = "eu-west4"
    blackout = FaultWindow(kind=KIND_METRICS_BLACKOUT,
                          start=duration * 0.3, end=duration * 0.6)
    _, preemptions = preemption_storm(
        base_rate=2.0, burst_rate=10.0, burst_duration=90.0,
        mean_gap=200.0, horizon=duration, seed=seed,
        preemptions_per_burst=2, preemption_lag=20.0)

    def cfg():
        # Tightened health thresholds so the blackout freezes the region
        # well inside the window (the golden-trace discipline).
        c = new_test_config()
        c.set_health(HealthConfig(degraded_after_seconds=30.0,
                                  freeze_after_seconds=60.0,
                                  recovery_ticks=2))
        return c

    def specs(region_index: int) -> list:
        base = diurnal(base_rate=2.0, amplitude=8.0, period=600.0)
        load = regional(base, region_index, len(regions), period=600.0)
        return [VariantSpec(
            name=f"m{i:03d}-v5e", model_id=f"bench/fed-model-{i:03d}",
            accelerator="v5e-8", chips_per_replica=8, cost=10.0,
            initial_replicas=2, serving=ServingParams(engine="jetstream"),
            load=load,
            hpa=HPAParams(stabilization_up_seconds=10.0,
                          stabilization_down_seconds=60.0,
                          sync_period_seconds=10.0))
            for i in range(models_per_region)]

    def spot_provisioner(cluster, clock):
        return FakeGkeProvisioner(
            cluster, clock,
            tiers={"on_demand": TierPolicy(provision_delay_seconds=120.0),
                   "spot": TierPolicy(provision_delay_seconds=60.0,
                                      preemptible=True)},
            seed=seed)

    fh = FederatedHarness(
        [RegionSpec(
            name=name, variants=specs(i), config=cfg(),
            saturation_config=None,
            fault_plan=(FaultPlan([blackout], seed=seed)
                        if faults and name == dark else None),
            nodepools=[("v5e-pool", "v5e", "2x4", models_per_region * 3)],
            provisioner=spot_provisioner if name == stormy else None)
         for i, name in enumerate(regions)],
        namespace="inference", engine_interval=engine_interval,
        startup_seconds=30.0, stochastic_seed=20260807)
    from wva_tpu.capacity.tiers import GKE_SPOT_NODE_LABEL

    add_tpu_nodepool(fh.cluster(stormy).cluster, "spot-pool", "v5e", "2x4",
                     models_per_region,
                     extra_labels={GKE_SPOT_NODE_LABEL: "true"})
    if faults:
        fh.cluster(stormy).provisioner.schedule_preemptions(
            [(fh.start_time + t, k) for t, k in preemptions])

    names = [f"m{i:03d}-v5e" for i in range(models_per_region)]

    def region_desired(name: str) -> dict[str, int]:
        registry = fh.cluster(name).manager.registry
        return {n: int(registry.get(WVA_DESIRED_REPLICAS, {
            "variant_name": n, "namespace": "inference",
            "accelerator_type": "v5e-8"}) or 0) for n in names}

    wrong_direction = 0
    spill_events = 0
    spill_targets: set[str] = set()
    dark_base: dict[str, int] = {}
    plan_track = {"last_tick": 0, "healthy_tick": None,
                  "last_spill_tick": None, "window_seen": False}

    def on_step(h, t):
        nonlocal wrong_direction, spill_events
        if faults and blackout.start <= t < blackout.end:
            desired = region_desired(dark)
            if not dark_base:
                dark_base.update(desired)
            plan_track["window_seen"] = True
            for n in names:
                if dark_base.get(n, 0) >= 1 and desired[n] < dark_base[n]:
                    wrong_direction += 1
        plan = h.last_plan()
        if not plan or plan["tick"] == plan_track["last_tick"]:
            return
        plan_track["last_tick"] = plan["tick"]
        spills = [d for ds in plan.get("directives", {}).values()
                  for d in ds if dark in d.get("source_region", "")]
        if spills:
            spill_events += len(spills)
            spill_targets.update(d["target_region"] for d in spills)
            plan_track["last_spill_tick"] = plan["tick"]
        dark_state = plan.get("region_states", {}).get(dark, {})
        if (plan_track["window_seen"] and t >= blackout.end
                and plan_track["healthy_tick"] is None
                and dark_state.get("state") == "healthy"):
            plan_track["healthy_tick"] = plan["tick"]

    fh.run(duration, on_step=on_step)
    attainment = {}
    for name in regions:
        harness = fh.cluster(name)
        sims = list(harness.sims.values())
        attainment[name] = round(min(
            sim.slo_attainment(SLO_TTFT_SECONDS, since=harness.start_time)
            for sim in sims), 4)
        harness.manager.shutdown()
    _drain_decision_bus()
    global_attainment = round(min(attainment.values()), 4)

    record = {
        "regions": list(regions),
        "models_per_region": models_per_region,
        "duration_s": duration,
        "engine_interval_s": engine_interval,
        "blackout_window": [blackout.start, blackout.end],
        "preemption_events": len(preemptions),
        "slo_attainment_per_region": attainment,
        "slo_attainment_global": global_attainment,
        "wrong_direction_events_dark_region": wrong_direction,
        "spill_directive_events": spill_events,
        "spill_targets": sorted(spill_targets),
        "arbiter_region": fh.arbiter_region(),
    }
    if faults:
        assert wrong_direction == 0, (
            f"{wrong_direction} wrong-direction scale events in the "
            "blacked-out region")
        assert spill_events > 0, "blackout produced no spill directives"
        assert plan_track["healthy_tick"] is not None, (
            "dark region never classified healthy after the window")
        reconverge = max((plan_track["last_spill_tick"] or 0)
                        - plan_track["healthy_tick"] + 1, 0)
        record["spill_reconverge_arbiter_ticks"] = reconverge
        assert reconverge <= 5, (
            f"spill directives drained {reconverge} arbiter ticks after "
            "re-admission (> 5)")
    return record


def federation_main() -> None:
    """`make bench-federation` / `bench.py --federation-only`: 3-region
    federated storm (regional preemptions + full-region blackout) vs the
    same seeded world fault-free, merged into BENCH_LOCAL.json
    detail.federation, one JSON line. Raises when any federation
    acceptance criterion fails (zero global SLO-attainment loss, zero
    wrong-direction scale events in the dark region, spill + <=5-tick
    reconvergence). `--smoke` runs the short CI shape (2 models/region,
    600s)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    smoke = "--smoke" in sys.argv
    models = 2 if smoke else 4
    duration = 600.0 if smoke else 900.0
    t0 = time.time()
    faulted = federation_storm_bench(models_per_region=models,
                                     duration=duration, faults=True)
    baseline = federation_storm_bench(models_per_region=models,
                                      duration=duration, faults=False)
    loss = round(baseline["slo_attainment_global"]
                 - faulted["slo_attainment_global"], 4)
    assert loss <= 0.0, (
        f"global SLO attainment lost {loss} vs the no-fault run "
        f"({faulted['slo_attainment_global']} faulted vs "
        f"{baseline['slo_attainment_global']} clean)")
    record = {
        "faulted": faulted,
        "no_fault_baseline": baseline,
        "slo_attainment_loss": loss,
        "bench_wall_seconds": round(time.time() - t0, 1),
    }
    if not smoke:
        _merge_bench_local("federation", record)
    print(json.dumps({
        "metric": "federation_slo_attainment_loss_3_regions",
        "value": loss,
        "unit": "global_slo_attainment_delta_vs_no_fault",
        "vs_baseline": faulted["spill_directive_events"],
        "detail": record,
    }))


def main() -> None:
    t0 = time.time()
    device_probe = _ensure_healthy_device()
    tick_scale = tick_scale_bench()
    baseline = run_policy("baseline")
    baseline_fast = run_policy("baseline-fast")
    ours = run_policy("ours")
    ours_realistic = run_policy("ours-realistic")
    variant_choice = variant_choice_bench()
    multihost = multihost_bench()
    multi_model = multi_model_bench()
    solver = solver_microbench()
    wall = time.time() - t0

    # HEADLINE = ours-realistic: the operator-grade configuration (2x-off
    # profiles + live tuner + half-declared slope) under stochastic load.
    # "ours" (oracle calibration) is the ceiling and stays visible.
    value = ours_realistic["slo_attainment"]
    # Honest comparison: quote against the STRONGEST baseline.
    strongest = max(baseline["slo_attainment"],
                    baseline_fast["slo_attainment"])
    vs_baseline = value / strongest if strongest > 0 else float("inf")

    def _headline(p: dict) -> dict:
        return {"slo_attainment": p["slo_attainment"],
                "p50_ttft_s": p["p50_ttft_s"], "p99_ttft_s": p["p99_ttft_s"],
                "peak_slices": p["peak_slices"],
                "chip_seconds": p["chip_seconds"]}

    summary = {
        "metric": "p99_ttft_slo_attainment_ramped_1_to_N_v5e8_stochastic",
        "value": round(value, 4),
        "unit": "fraction_of_requests_meeting_1s_TTFT_SLO",
        "vs_baseline": round(vs_baseline, 3),
        # Bounded summary only — the full per-phase/per-section record goes
        # to BENCH_LOCAL.json so the driver's line capture always parses and
        # always contains the headline (round-4 capture truncated mid-detail
        # and lost the one number that mattered).
        "detail": {
            "ours_realistic": _headline(ours_realistic),
            "ours_oracle": _headline(ours),
            "baseline": _headline(baseline),
            "baseline_fast": _headline(baseline_fast),
            "variant_choice_cost_savings_frac":
                variant_choice["cost_savings_frac"],
            "multihost_attainment": multihost["slo_attainment"],
            "multi_model": {
                "premium_attainment":
                    multi_model["contended"]["premium"]["slo_attainment"],
                "standard_attainment":
                    multi_model["contended"]["standard"]["slo_attainment"],
            },
            "solver": {
                "platform": solver["platform"],
                "batch_8192_candidates_per_s":
                    solver["batch_8192"]["candidates_per_s"],
                "batch_8192_impl": solver["batch_8192"]["impl"],
            },
            "tick_scale": {
                "fleet_tick_p50_ms": tick_scale["fleet"]["tick_p50_ms"],
                "speedup_vs_serial": tick_scale["tick_p50_speedup"],
                "api_reads_reduction": tick_scale["api_reads_reduction"],
            },
            "world": "stochastic (seeded Poisson arrivals + token mixture)",
            "full_detail": "BENCH_LOCAL.json",
            "bench_wall_seconds": round(wall, 1),
        },
    }
    full = {
        **summary,
        "detail": {
            "ours_realistic": ours_realistic,
            "ours": ours,
            "baseline": baseline,
            "baseline_fast": baseline_fast,
            "variant_choice": variant_choice,
            "multihost": multihost,
            "multi_model": multi_model,
            "solver_microbench": solver,
            "tick_scale": tick_scale,
            "device_probe": device_probe,
            "scenario": {
                "model": MODEL, "engine": "jetstream",
                "warmup": f"{WARMUP_SECONDS:.0f}s at {BASE_RATE:.0f} req/s "
                          "(excluded "
                          "from all measurement windows)",
                "ramp": f"{BASE_RATE:.0f}->{PEAK_RATE} req/s over {RAMP_SECONDS:.0f}s",
                "hold_s": HOLD_SECONDS, "down_s": DOWN_SECONDS,
                "tail_s": TAIL_SECONDS, "slo_ttft_s": SLO_TTFT_SECONDS,
                "slice_startup_s": STARTUP_SECONDS,
                "stochastic_seed": STOCHASTIC_SEED,
                "token_mixture": [list(c) for c in TOKEN_MIXTURE],
                "ours_realistic": {
                    "profile_miscalibration_factor": MISCAL_FACTOR,
                    "tuner": "EKF live (NIS-gated, trust region)",
                    "declared_burst_slope": "half of true ramp slope"},
                "vs_baseline_quoted_against": (
                    "baseline-fast" if baseline_fast["slo_attainment"]
                    >= baseline["slo_attainment"] else "baseline"),
            },
            "bench_wall_seconds": round(wall, 1),
        },
    }
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_LOCAL.json"), "w") as f:
        json.dump(full, f, indent=1)
    # stdout is exactly ONE bounded line (~1KB): small enough that neither
    # head- nor tail-truncating captures can lose the headline, and
    # parseable as a whole. The unbounded record lives in BENCH_LOCAL.json.
    print(json.dumps(summary))


def shard_plane_bench(n_models: int = 480, shards: int = 4,
                      variants_per_model: int = 2,
                      measured_ticks: int = 8,
                      quiet_warm_ticks: int = 12) -> dict:
    """Sharded active-active engine bench (``make bench-shard``;
    docs/design/sharding.md): the 480-model quiet world run unsharded and
    as ``shards`` consistent-hash shard workers over ONE FakeCluster.

    Asserts the acceptance criteria outright:

    - fleet-wide decisions (all VA statuses) byte-identical between the
      sharded and unsharded runs at every measured tick boundary;
    - per-shard quiet-tick analysis p50 under 30 ms at 480 models / 4
      shards (the distributed wall time a process-per-shard deployment
      would pay);
    - one seeded shard crash rebalances with ZERO wrong-direction scale
      events and reconvergence (holds drained, statuses stable) within 5
      fleet ticks.
    """
    import statistics

    from wva_tpu.emulator.faults import seeded_shard_crashes
    from wva_tpu.engines import common as engines_common

    def statuses(cluster):
        return [json.dumps(va.status.to_dict(), sort_keys=True)
                for va in sorted(cluster.variant_autoscalings(),
                                 key=lambda v: (v.metadata.namespace,
                                                v.metadata.name))]

    def drain_globals():
        _drain_decision_bus()

    def run_world(shard_count: int, crash: bool = False) -> dict:
        mgr, cluster, clock, feed = _build_tick_world(
            n_models, variants_per_model, sharding=shard_count)
        eng = mgr.engine
        try:
            for _ in range(3 + quiet_warm_ticks):
                eng.optimize()
                clock.advance(5.0)
                feed(clock.now())
            walls, shard_walls, status_trail = [], [], []
            for _ in range(measured_ticks):
                t0 = time.perf_counter()
                eng.optimize()
                walls.append(time.perf_counter() - t0)
                if eng.shard_plane is not None \
                        and eng.shard_plane.last_worker_seconds:
                    # The distributed wall time: the SLOWEST shard's
                    # analysis (workers run concurrently as processes;
                    # the in-process plane drives them serially and
                    # times each).
                    shard_walls.append(
                        max(eng.shard_plane.last_worker_seconds.values()))
                status_trail.append(statuses(cluster))
                clock.advance(5.0)
                feed(clock.now())
            out = {
                "tick_p50_ms": round(
                    statistics.median(walls) * 1000.0, 2),
                "status_trail": status_trail,
            }
            if shard_walls:
                out["per_shard_analyze_p50_ms"] = round(
                    statistics.median(shard_walls) * 1000.0, 2)
                out["per_shard_analyze_max_ms"] = round(
                    max(shard_walls) * 1000.0, 2)
            if not crash:
                return out
            # --- seeded shard-crash rebalance (the sharded world only) ---
            event = seeded_shard_crashes(
                seed=42, horizon=1200.0, shards=shard_count, n=1)[0]
            pre = {va.metadata.name:
                   va.status.desired_optimized_alloc.num_replicas
                   for va in cluster.variant_autoscalings()}
            eng.shard_plane.kill_shard(event.shard,
                                       release_lease=event.clean)
            wrong = 0
            reconverged_at = None
            prev = None
            for tick in range(1, 9):
                eng.optimize()
                cur = {va.metadata.name:
                       va.status.desired_optimized_alloc.num_replicas
                       for va in cluster.variant_autoscalings()}
                wrong += sum(1 for k, v in cur.items() if v < pre[k])
                if (reconverged_at is None and prev == cur
                        and not eng.shard_plane.hold_keys()):
                    reconverged_at = tick
                prev = cur
                clock.advance(5.0)
                feed(clock.now())
            moved = eng.shard_plane.rebalance_total
            assert wrong == 0, \
                f"{wrong} wrong-direction scale events during rebalance"
            assert reconverged_at is not None and reconverged_at <= 5, \
                f"rebalance did not reconverge within 5 ticks " \
                f"(reconverged_at={reconverged_at})"
            out["crash"] = {
                "killed_shard": event.shard,
                "clean_death": event.clean,
                "models_rebalanced": moved,
                "wrong_direction_events": wrong,
                "reconverged_ticks": reconverged_at,
            }
            return out
        finally:
            mgr.shutdown()
            drain_globals()

    single = run_world(0)
    sharded = run_world(shards, crash=True)
    identical = single["status_trail"] == sharded["status_trail"]
    assert identical, \
        "sharded decisions diverged from the unsharded engine"
    if n_models >= 480 and shards >= 4:
        assert sharded["per_shard_analyze_p50_ms"] < 30.0, \
            f"per-shard quiet-tick p50 " \
            f"{sharded['per_shard_analyze_p50_ms']}ms >= 30ms"
    single.pop("status_trail")
    sharded.pop("status_trail")
    return {
        "models": n_models,
        "variant_autoscalings": n_models * variants_per_model,
        "shards": shards,
        "measured_ticks": measured_ticks,
        "single_engine": single,
        "sharded": sharded,
        "decisions_byte_identical": identical,
        "shard_speedup_distributed": round(
            single["tick_p50_ms"]
            / max(sharded["per_shard_analyze_p50_ms"], 1e-9), 2),
    }


def shard_scale_sweep(models=(480, 2000), shards: int = 4,
                      variants_per_model: int = 2,
                      measured_ticks: int = 5,
                      quiet_warm_ticks: int = 8) -> dict:
    """Single-engine vs ``shards``-shard quiet-tick times side by side at
    fleet scale — the 2000-model point ROADMAP item 1 asked for. The
    sharded column reports BOTH the in-process fleet tick (all shards
    driven serially + merge + apply: the single-binary cost) and the
    slowest shard's analysis time (the distributed wall a
    process-per-shard deployment pays)."""
    import statistics

    from wva_tpu.engines import common as engines_common

    def measure(n: int, shard_count: int) -> dict:
        mgr, cluster, clock, feed = _build_tick_world(
            n, variants_per_model, sharding=shard_count)
        eng = mgr.engine
        try:
            for _ in range(3 + quiet_warm_ticks):
                eng.optimize()
                clock.advance(5.0)
                feed(clock.now())
            walls, shard_walls = [], []
            phase_sums: dict[str, float] = {}
            for _ in range(measured_ticks):
                t0 = time.perf_counter()
                eng.optimize()
                walls.append(time.perf_counter() - t0)
                for phase, sec in eng.last_tick_phase_seconds.items():
                    phase_sums[phase] = phase_sums.get(phase, 0.0) + sec
                if eng.shard_plane is not None \
                        and eng.shard_plane.last_worker_seconds:
                    shard_walls.append(
                        max(eng.shard_plane.last_worker_seconds.values()))
                clock.advance(5.0)
                feed(clock.now())
            out = {
                "tick_p50_ms": round(
                    statistics.median(walls) * 1000.0, 2),
                "phase_ms_mean": {
                    k: round(v * 1000.0 / measured_ticks, 2)
                    for k, v in sorted(phase_sums.items())},
            }
            if shard_walls:
                out["per_shard_analyze_p50_ms"] = round(
                    statistics.median(shard_walls) * 1000.0, 2)
            return out
        finally:
            mgr.shutdown()
            _drain_decision_bus()

    out: dict[str, dict] = {}
    for n in models:
        out[str(n)] = {
            "models": n,
            "single_engine": measure(n, 0),
            f"sharded_{shards}": measure(n, shards),
        }
    return {"sweep": out, "shards": shards}


def shard_main() -> None:
    """`make bench-shard` / `bench.py --shard-only`: sharded-vs-unsharded
    byte-identity + per-shard latency + seeded rebalance assertions,
    plus the 480/2000-model single-vs-sharded sweep, merged into
    BENCH_LOCAL.json detail.shard_plane. `--smoke` (SHARD_SMOKE=1) runs
    the short two-shard CI shape (24 models, no 2000-point sweep)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    smoke = "--smoke" in sys.argv
    t0 = time.time()
    if smoke:
        record = shard_plane_bench(n_models=24, shards=2,
                                   measured_ticks=5, quiet_warm_ticks=8)
        sweep = None
    else:
        record = shard_plane_bench()
        sweep = shard_scale_sweep()
    record["bench_wall_seconds"] = round(time.time() - t0, 1)
    if sweep is not None:
        record["scale_sweep"] = sweep
        _merge_bench_local("shard_plane", record)
    print(json.dumps({
        "metric": "per_shard_quiet_tick_latency"
                  f"_{record['models']}_models_{record['shards']}_shards",
        "value": record["sharded"].get("per_shard_analyze_p50_ms"),
        "unit": "ms_p50_per_shard_tick",
        "vs_baseline": record["shard_speedup_distributed"],
        "detail": record,
    }))


def sweep_plane_bench(smoke: bool = False) -> dict:
    """The vectorized-sweep plane (docs/design/sweep.md): advance >=1024
    (seed x knob) emulated worlds in a handful of jitted scan dispatches,
    assert the dispatch budget (~1 per step at most; measured far under),
    quote the measured throughput against the per-world Python loop at
    batch 256, run the event-world fidelity gate, and emit the
    attainment-vs-cost frontier + a trust-gated recommendation."""
    from wva_tpu.emulator import loadgen
    from wva_tpu.sweep import knobs as kb
    from wva_tpu.sweep import search
    from wva_tpu.sweep.fidelity import fidelity_check
    from wva_tpu.sweep.world import (WorldParams, rate_table,
                                     run_world_python, run_worlds)
    from wva_tpu.utils import dispatch

    # The sweep scenario: the bench trapezoid's shape at a sweep scale.
    params = WorldParams(horizon_s=1200.0)
    prof = loadgen.trapezoid(4.0, 40.0, 300.0, 420.0, 180.0,
                             tail=120.0, delay=180.0)
    lam = rate_table([prof], params)
    grid = "smoke" if smoke else "default"
    n_train, n_holdout = (2, 3) if smoke else (32, 8)

    d0 = dispatch.count()
    t0 = time.time()
    report = search.run_sweep(params, lam, [MODEL], algo="grid",
                              grid=grid, n_train=n_train,
                              n_holdout=n_holdout, chunk=256)
    sweep_wall = time.time() - t0
    dispatches = dispatch.count() - d0
    worlds = report["worlds_evaluated"]
    holdout_worlds = 2 * n_holdout  # candidate + incumbent pairs
    steps = params.steps
    if not smoke:
        assert worlds >= 1024, \
            f"sweep bench must evaluate >=1024 worlds, got {worlds}"
    # The acceptance bound: ~1 device dispatch per step. Measured: one
    # dispatch per (chunk x whole horizon), so dispatches/steps is far
    # below 1 even counting the holdout pass.
    assert dispatches <= steps, \
        f"{dispatches} dispatches for a {steps}-step horizon"

    # Throughput vs the per-world Python loop: both sides receive the
    # SAME precomputed seeded inputs (arrival/fault tables are shared
    # scenario data, built once outside both timers); vectorized
    # per-world time from a fresh 256-world batch (steady-state: the
    # program is already compiled above), Python per-world time from a
    # sampled subset of the same batch.
    from wva_tpu.sweep.world import arrivals_table, fault_table
    train_seeds = report["seeds"]["train"]
    batch_points = (kb.grid_points(grid) * 256)[:256]
    batch_seeds = [train_seeds[i % len(train_seeds)] for i in range(256)]
    arr = arrivals_table(batch_seeds, lam, params)
    flt = fault_table(batch_seeds, lam.shape[0], params)
    t0 = time.time()
    run_worlds(params, batch_points, batch_seeds, lam, chunk=256,
               arrivals=arr, faults=flt)
    vec_per_world_s = (time.time() - t0) / 256.0
    n_py = 2 if smoke else 8
    t0 = time.time()
    for i in range(n_py):
        run_world_python(params, batch_points[i], lam, arr[i], flt[i])
    py_per_world_s = (time.time() - t0) / n_py
    speedup = py_per_world_s / max(vec_per_world_s, 1e-12)
    if not smoke:
        assert speedup >= 20.0, \
            f"vectorized sweep only {speedup:.1f}x vs Python loop"

    fidelity = fidelity_check()
    assert fidelity["within_tolerance"], (
        "fluid world outside fidelity tolerance: "
        f"attainment delta {fidelity['attainment_delta_abs']}, "
        f"chip-seconds rel {fidelity['chip_seconds_delta_rel']}")

    rec = report["recommendations"][MODEL]
    assert rec["applied_knobs"], "empty recommendation"
    assert rec["trust"]["evals"] >= 3 and rec["trust"]["trusted"], (
        f"recommendation failed the trust gate: {rec['trust']}")

    return {
        "grid": grid,
        "worlds_evaluated": worlds,
        "holdout_worlds": holdout_worlds,
        "horizon_steps": steps,
        "device_dispatches": dispatches,
        "dispatches_per_step": round(dispatches / steps, 4),
        "vectorized_per_world_ms": round(vec_per_world_s * 1000.0, 3),
        "python_loop_per_world_ms": round(py_per_world_s * 1000.0, 3),
        "python_loop_worlds_sampled": n_py,
        "speedup_vs_python_loop": round(speedup, 1),
        "sweep_wall_seconds": round(sweep_wall, 1),
        "fidelity": fidelity,
        "recommendation": {
            "model": MODEL,
            "applied_knobs": rec["applied_knobs"],
            "train_objective": rec["train_objective"],
            "trust": {k: rec["trust"][k]
                      for k in ("trusted", "evals", "ewma_regret",
                                "reason")},
        },
        "frontier": rec["frontier"],
    }


def sweep_main() -> None:
    """`make bench-sweep` / `bench.py --sweep-only`: the vectorized
    policy-sweep bench, merged into BENCH_LOCAL.json detail.sweep.
    `--smoke` (SWEEP_SMOKE=1) runs the short CI shape (smoke grid, 2
    train seeds) but still asserts the fidelity gate, the dispatch
    budget, and a non-empty trust-gated recommendation."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    smoke = "--smoke" in sys.argv
    t0 = time.time()
    record = sweep_plane_bench(smoke=smoke)
    record["bench_wall_seconds"] = round(time.time() - t0, 1)
    if not smoke:
        _merge_bench_local("sweep", record)
    print(json.dumps({
        "metric": f"vectorized_sweep_{record['worlds_evaluated']}_worlds"
                  "_vs_python_loop",
        "value": record["speedup_vs_python_loop"],
        "unit": "x_throughput_vs_per_world_python_loop",
        "vs_baseline": record["speedup_vs_python_loop"],
        "detail": record,
    }))


def profile_main() -> None:
    """`make bench-profile`: cProfile one quiet-tick bench run and dump the
    top-N hot call sites by cumulative time (the tool that found the
    deepcopy tax this round; PERF.md "profiling the tick"). Text goes to
    stdout; tune N with --top N."""
    import cProfile
    import io
    import pstats

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    top = 40
    if "--top" in sys.argv:
        top = int(sys.argv[sys.argv.index("--top") + 1])
    # --models N: profile at fleet scale (e.g. 480) so the next hot path
    # is found where it actually binds, not at the comfortable size.
    mgr, cluster, clock, feed = _build_tick_world(_models_arg(48), 2)
    eng = mgr.engine
    for _ in range(19):  # jit + caches + memos + window settling
        eng.optimize()
        clock.advance(5.0)
        feed(clock.now())
    profiler = cProfile.Profile()
    profiler.enable()
    for _ in range(8):
        eng.optimize()
        clock.advance(5.0)
        feed(clock.now())
    profiler.disable()
    mgr.shutdown()
    out = io.StringIO()
    stats = pstats.Stats(profiler, stream=out)
    stats.sort_stats("cumulative").print_stats(top)
    stats.sort_stats("tottime").print_stats(top)
    print(out.getvalue())


if __name__ == "__main__":
    if "--profile" in sys.argv:
        profile_main()
    elif "--tick-quiet-only" in sys.argv:
        tick_quiet_main()
    elif "--tick-only" in sys.argv:
        tick_main()
    elif "--analyze-only" in sys.argv:
        analyze_main()
    elif "--collect-only" in sys.argv:
        collect_main()
    elif "--forecast-only" in sys.argv:
        forecast_main()
    elif "--capacity-only" in sys.argv:
        capacity_main()
    elif "--chaos-only" in sys.argv:
        chaos_main()
    elif "--failover-only" in sys.argv:
        failover_main()
    elif "--federation-only" in sys.argv:
        federation_main()
    elif "--shard-only" in sys.argv:
        shard_main()
    elif "--spans-only" in sys.argv:
        spans_main()
    elif "--sweep-only" in sys.argv:
        sweep_main()
    else:
        main()
