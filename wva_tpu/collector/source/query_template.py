"""Named, parameter-validated query templates with PromQL-injection escaping
(reference ``internal/collector/source/query_template.go:36-153``).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

# Simple metric name (backends without PromQL: pod-scrape, EPP).
QUERY_TYPE_METRIC_NAME = "metric"
# Full PromQL with {{.param}} placeholders (Prometheus backend only).
QUERY_TYPE_PROMQL = "promql"


@dataclass
class QueryTemplate:
    name: str
    template: str
    type: str = QUERY_TYPE_PROMQL
    params: list[str] = field(default_factory=list)
    description: str = ""


class QueryList:
    """Per-source query registry."""

    def __init__(self) -> None:
        self._mu = threading.RLock()
        self._queries: dict[str, QueryTemplate] = {}

    def register(self, query: QueryTemplate) -> None:
        if not query.name:
            raise ValueError("query name is required")
        if not query.template:
            raise ValueError(f"query template is required for {query.name!r}")
        with self._mu:
            if query.name in self._queries:
                raise ValueError(f"query {query.name!r} already registered")
            self._queries[query.name] = query

    def register_if_absent(self, query: QueryTemplate) -> None:
        with self._mu:
            if query.name not in self._queries:
                self._queries[query.name] = query

    def get(self, name: str) -> QueryTemplate | None:
        with self._mu:
            return self._queries.get(name)

    def build(self, name: str, params: dict[str, str]) -> str:
        """Substitute {{.param}} placeholders after validating required params
        are present. Values must be pre-escaped by the caller when they come
        from user-controlled fields (see escape_promql_value)."""
        with self._mu:
            query = self._queries.get(name)
        if query is None:
            raise KeyError(f"query {name!r} not found")
        for p in query.params:
            if p not in params:
                raise ValueError(f"missing required parameter {p!r} for query {name!r}")
        result = query.template
        for key, value in params.items():
            result = result.replace("{{." + key + "}}", value)
        return result

    def names(self) -> list[str]:
        with self._mu:
            return sorted(self._queries)


def escape_promql_value(value: str) -> str:
    """Escape backslashes then quotes for safe PromQL label-matcher embedding."""
    return value.replace("\\", "\\\\").replace('"', '\\"')
