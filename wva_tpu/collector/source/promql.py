"""TSDB-lite + PromQL-subset evaluator.

The reference runs its e2e suites against a real Prometheus fed by a fake
inference server (SURVEY.md section 4). This module is the TPU build's
equivalent fidelity trick without a cluster: an in-memory time-series store
plus an evaluator for exactly the query shapes the autoscaler registers
(``internal/collector/registration/saturation.go:8-122``):

- aggregations:  sum | max | min | avg | count, with optional ``by (l1, l2)``
- range funcs:   rate | increase | max_over_time | avg_over_time
- selectors:     ``name{label="v",other!="w",re=~"x.*"}``
- binary ops:    vector / vector (label-matched), expr or expr
- literals:      numeric scalars

Prometheus semantics that matter for correctness are preserved: instant
lookback (5m), aggregation over an empty vector returns an EMPTY vector (not
0 — scale-to-zero safety depends on "no data" being distinguishable from 0),
division drops unmatched/zero-denominator series, and ``or`` keeps the right
side's series only when the left has no series with the same label set.

Storage is array-backed ring buffers per series (``array('d')`` timestamp +
value columns with a live-region offset): appends are O(1) amortized,
retention trims advance the offset instead of ``pop(0)``-ing objects, and
reads hand out :class:`SeriesWindow` views — bisect-sliced, zero-copy
snapshots — under striped per-series locks, so concurrent engine workers
never serialize on one store-wide mutex (docs/design/metrics-plane.md).
"""

from __future__ import annotations

import math
import re
import threading
from array import array
from bisect import bisect_left, bisect_right
from dataclasses import dataclass, field
from functools import lru_cache

from wva_tpu.utils.clock import SYSTEM_CLOCK, Clock

DEFAULT_LOOKBACK_SECONDS = 300.0
DEFAULT_RETENTION_SECONDS = 3600.0

_AGG_OPS = {"sum", "max", "min", "avg", "count"}
_RANGE_FUNCS = {"rate", "increase", "max_over_time", "avg_over_time"}

_DURATION_RE = re.compile(r"^(\d+(?:\.\d+)?)(ms|s|m|h|d)$")
_DURATION_UNITS = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


def parse_promql_duration(s: str) -> float:
    m = _DURATION_RE.match(s)
    if not m:
        raise PromQLError(f"invalid duration {s!r}")
    return float(m.group(1)) * _DURATION_UNITS[m.group(2)]


def format_promql_duration(seconds: float) -> str:
    """Render seconds as a Prometheus range duration (reference
    utils.FormatPrometheusDuration)."""
    if seconds <= 0:
        return "0s"
    if seconds < 1:
        return f"{int(math.ceil(seconds * 1000))}ms"
    if seconds % 3600 == 0:
        return f"{int(seconds // 3600)}h"
    if seconds % 60 == 0:
        return f"{int(seconds // 60)}m"
    return f"{int(math.ceil(seconds))}s"


class PromQLError(ValueError):
    pass


@dataclass
class Sample:
    timestamp: float
    value: float


@dataclass
class SeriesPoint:
    """One evaluated output series."""

    labels: dict[str, str]
    value: float
    timestamp: float


@dataclass
class TrackMeta:
    """Validity metadata for one tracked evaluation (``query_tracked``) —
    the substrate of the grouped view's execution reuse
    (docs/design/informer.md §versioned-fingerprints).

    ``expiry_strict``: with NO further appends to the involved metrics,
    the result is byte-identical until this time (earliest point any
    included sample can leave its range window / instant lookback).

    ``expiry_b`` + ``uniform``: with only value-UNCHANGING appends, the
    result's VALUES (not timestamps) are identical until ``expiry_b`` —
    valid only when ``uniform`` (every matched series was included with a
    uniform window; an excluded or mixed-value series could change the
    result set without a value-version bump, so it disables this tier).
    """

    expiry_strict: float = float("inf")
    expiry_b: float = float("inf")
    uniform: bool = True


class SeriesWindow:
    """Zero-copy view over one series' samples in ``[lo, hi)``.

    Holds references to the backing timestamp/value arrays plus bounds taken
    under the series lock. Appends after the snapshot only extend the arrays
    past ``hi``; compaction replaces the arrays on the series (this view
    keeps the old ones) — so the window is immutable without copying a
    single sample. Supports ``len``/indexing/iteration yielding
    :class:`Sample` for compatibility with list-of-samples consumers."""

    __slots__ = ("ts", "vals", "lo", "hi", "series")

    def __init__(self, ts, vals, lo: int, hi: int, series=None) -> None:
        self.ts = ts
        self.vals = vals
        self.lo = lo
        self.hi = hi
        # Backing _Series (non-legacy reads only): the anchor for the
        # delta-maintained range-function memo. None on legacy windows
        # and sub-windows of anonymous callers — evaluation then scans.
        self.series = series

    def __len__(self) -> int:
        return self.hi - self.lo

    def __getitem__(self, i: int) -> Sample:
        n = self.hi - self.lo
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        return Sample(self.ts[self.lo + i], self.vals[self.lo + i])

    def __iter__(self):
        for i in range(self.lo, self.hi):
            yield Sample(self.ts[i], self.vals[i])

    def latest_at_or_before(self, now: float) -> Sample | None:
        i = bisect_right(self.ts, now, self.lo, self.hi)
        if i <= self.lo:
            return None
        return Sample(self.ts[i - 1], self.vals[i - 1])

    def range_window(self, lo_ts: float, hi_ts: float) -> "SeriesWindow":
        """Sub-window of samples with ``lo_ts <= timestamp <= hi_ts``
        (bisect-sliced; no samples are touched)."""
        i = bisect_left(self.ts, lo_ts, self.lo, self.hi)
        j = bisect_right(self.ts, hi_ts, self.lo, self.hi)
        return SeriesWindow(self.ts, self.vals, i, j, series=self.series)


class _Series:
    """One series' column store: parallel timestamp/value arrays with a
    live-region start offset (the "ring"). Samples before ``start`` are
    retention-expired garbage awaiting compaction. The forecast plane's
    ``forecast/history.py`` ``RingColumns`` carries a twin of this layout
    and of ``_trim_locked``'s compaction heuristic (kept separate: its
    trim is per-ring-window on append, ours is store-retention under the
    stripe locks) — keep changes to the heuristic in sync.

    ``write_version`` is the store-wide monotonic stamp of this series'
    last append — the substrate of the versioned fingerprint plane
    (docs/design/informer.md §versioned-fingerprints): "no series of
    metric X stamped since T" plus the evaluation's validity bounds
    (:class:`TrackMeta`) prove a query over X evaluates identically."""

    __slots__ = ("labels", "ts", "vals", "start", "last_ts",
                 "write_version", "range_memo")

    def __init__(self, labels: dict[str, str]) -> None:
        self.labels = labels
        self.ts = array("d")
        self.vals = array("d")
        self.start = 0
        self.last_ts = float("-inf")
        self.write_version = 0
        # Delta-maintained range-function accumulators, keyed by
        # (func, window_len): (ts array ref, lo, hi, accumulator,
        # result). See _apply_range_func_delta — the rolling state that
        # makes a quiet series' rate/*_over_time evaluation free and a
        # live series' evaluation O(new samples) instead of O(window).
        # Entries are immutable tuples replaced atomically (GIL), so
        # concurrent readers race benignly.
        self.range_memo: dict[tuple, tuple] = {}

    def last_value_changed(self, value: float) -> bool:
        """Would appending ``value`` change this series' latest value?
        NaN-aware (NaN -> NaN is NOT a change): the per-name
        value-version must stay put under quiet re-scrapes of the same
        reading, including a stuck-NaN exporter."""
        n = len(self.vals)
        if n == 0:
            return True
        prev = self.vals[n - 1]
        if value != value and prev != prev:
            return False
        return value != prev


# Compiled-regex matcher cache: the registered query surface reuses a small
# fixed set of regex matchers, and compiling per evaluation dominated regex
# selector cost at fleet scale.
@lru_cache(maxsize=512)
def _compiled_re(pattern: str) -> "re.Pattern[str]":
    return re.compile(pattern)


class TimeSeriesDB:
    """Append-only store of samples keyed by full label set (incl __name__).

    Concurrency: one structure lock guards the series maps; sample appends
    and window snapshots take a striped per-series lock, so readers (the
    engine's analysis workers) and the emulator's ingest never contend on a
    single store-wide mutex. Timestamps per series are assumed
    non-decreasing (Prometheus rejects out-of-order appends; every producer
    here stamps a monotone clock)."""

    LOCK_STRIPES = 64
    # Time-gated global sweep: any ongoing ingest trims QUIESCENT series
    # too, so a series whose writes stopped cannot pin memory forever (the
    # old `len % 256` count gate never fired again once writes ceased).
    SWEEP_INTERVAL_SECONDS = 60.0
    # Compact a series' dead prefix once it dominates the array (amortized
    # O(1) per append; replaces the arrays so live zero-copy windows keep
    # their old snapshot).
    COMPACT_MIN_DEAD = 256

    def __init__(self, clock: Clock | None = None,
                 retention: float = DEFAULT_RETENTION_SECONDS) -> None:
        self.clock = clock or SYSTEM_CLOCK
        self.retention = retention
        self._mu = threading.Lock()
        self._stripes = [threading.Lock() for _ in range(self.LOCK_STRIPES)]
        self._series: dict[tuple, _Series] = {}
        # Metric-name index: __name__ -> series keys (insertion-ordered dict
        # so enumeration — and thus float-summation order in aggregations —
        # is deterministic). Every PromQL selector names its metric with an
        # equality matcher, so lookups touch only that metric's series — a
        # real Prometheus resolves selectors through its label index the
        # same way.
        self._by_name: dict[str, dict[tuple, None]] = {}
        # Per-metric-name write-versions: the store-wide monotonic counter
        # value of the last append to ANY series of that name (deletes
        # count too — a dropped series changes what a query can return).
        # Consumers (the grouped view's fingerprint plane) compare "max
        # version across the query's metric names" across ticks to prove
        # nothing was written — O(names) instead of O(series x samples).
        # _name_value_versions moves ONLY on value-CHANGING appends (and
        # first appends / drops): a quiet fleet re-scraping the same
        # readings every tick keeps it still, which is what lets the
        # fingerprint tier reuse uniform-window evaluations.
        self._ver_mu = threading.Lock()
        self._write_counter = 0
        self._name_versions: dict[str, int] = {}
        self._name_value_versions: dict[str, int] = {}
        self._last_sweep = float("-inf")
        # Compat levers for `make bench-tick` / `make bench-collect`:
        # - use_name_index=False reproduces the pre-index full-store scan;
        # - legacy_reads=True reproduces the pre-ring read path (one global
        #   lock held for the whole scan + a full copy of every matched
        #   series' samples), so the before/after numbers measure the real
        #   pre-change cost, not an already-optimized substrate.
        self.use_name_index = True
        self.legacy_reads = False
        # Delta-maintained range evaluation (ROADMAP item 1a): per-series
        # rolling accumulators make rate/*_over_time free for unchanged
        # windows and O(new samples) for appended ones, byte-identical to
        # the scanning evaluator (tests/test_promql.py). Off restores the
        # per-eval window scan.
        self.delta_range_eval = True
        # Introspection for the equality/cost tests: full window folds vs
        # suffix extensions vs memo hits since process start.
        self.range_scans = 0
        self.range_extends = 0
        self.range_hits = 0

    @staticmethod
    def _key(name: str, labels: dict[str, str]) -> tuple:
        return tuple(sorted({**labels, "__name__": name}.items()))

    def _lock_for(self, key: tuple) -> threading.Lock:
        return self._stripes[hash(key) % self.LOCK_STRIPES]

    def add_sample(self, name: str, labels: dict[str, str], value: float,
                   timestamp: float | None = None) -> None:
        ts = self.clock.now() if timestamp is None else timestamp
        key = self._key(name, labels)
        while True:
            s = self._series.get(key)
            if s is None:
                with self._mu:
                    s = self._series.get(key)
                    if s is None:
                        s = _Series({**labels, "__name__": name})
                        self._series[key] = s
                        self._by_name.setdefault(name, {})[key] = None
            with self._lock_for(key):
                # A concurrent sweep may have dropped this series between
                # the map read and taking the stripe lock; appending to the
                # orphaned object would silently lose the sample. Re-check
                # registration under the lock and retry (sweep only drops
                # fully-expired series, so one retry recreates it).
                if self._series.get(key) is not s:
                    continue
                value_changed = s.last_value_changed(value)
                s.ts.append(ts)
                s.vals.append(value)
                s.last_ts = ts
                s.write_version = self._bump_name_version(
                    name, value_changed)
                self._trim_locked(s, ts)
                break
        if ts - self._last_sweep >= self.SWEEP_INTERVAL_SECONDS:
            self.sweep(ts)

    set_gauge = add_sample  # gauges and counters are both just samples

    def _bump_name_version(self, name: str, value_changed: bool = True
                           ) -> int:
        # One store-wide lock for a 3-op critical section (int += and up
        # to two dict writes). Deliberately NOT striped: the version gate
        # is an equality compare, and lock-free/striped counters can lose
        # updates or publish out of order — a consumer could then read an
        # unchanged version across a real write and reuse a stale
        # evaluation. Correctness over a ~100ns uncontended lock.
        with self._ver_mu:
            self._write_counter += 1
            self._name_versions[name] = self._write_counter
            if value_changed:
                self._name_value_versions[name] = self._write_counter
            return self._write_counter

    def name_write_version(self, names) -> int:
        """Max write-version across ``names`` (0 = never written). Two
        equal reads bracket a window with NO appends/drops to any series
        of those metrics — the grouped fingerprint plane's evaluation-
        reuse gate (see :class:`~wva_tpu.collector.source.grouped.
        SliceVersionBook`)."""
        with self._ver_mu:
            return max((self._name_versions.get(n, 0) for n in names),
                       default=0)

    def name_value_version(self, names) -> int:
        """Like :meth:`name_write_version` but moved only by
        value-CHANGING appends (and series creation/drops): quiet
        re-scrapes of the same readings keep it still, letting the
        fingerprint tier reuse uniform-window evaluations whose VALUES
        provably did not move (timestamps may have — which is why only
        the timestamp-free fingerprint tier may use this gate)."""
        with self._ver_mu:
            return max((self._name_value_versions.get(n, 0)
                        for n in names), default=0)

    def _trim_locked(self, s: _Series, now: float) -> None:
        """Advance the live-region start past retention (O(1) amortized —
        each sample is stepped over at most once) and compact when the dead
        prefix dominates. Caller holds the series' stripe lock."""
        cutoff = now - self.retention
        ts = s.ts
        start = s.start
        n = len(ts)
        while start < n and ts[start] < cutoff:
            start += 1
        s.start = start
        if start >= self.COMPACT_MIN_DEAD and start * 2 >= n:
            s.ts = ts[start:]
            s.vals = s.vals[start:]
            s.start = 0

    def sweep(self, now: float | None = None) -> int:
        """Trim every series to retention and drop series fully expired
        (no live samples and no write within retention). Called
        opportunistically from ``add_sample`` on a time gate; safe to call
        explicitly. Returns the number of series dropped."""
        now = self.clock.now() if now is None else now
        with self._mu:
            if self._last_sweep >= now:
                return 0
            self._last_sweep = now
            items = list(self._series.items())
        dead: list[tuple] = []
        for key, s in items:
            with self._lock_for(key):
                self._trim_locked(s, now)
                if s.start >= len(s.ts) and now - s.last_ts > self.retention:
                    dead.append(key)
        dropped = 0
        with self._mu:
            for key in dead:
                s = self._series.get(key)
                if s is None:
                    continue
                with self._lock_for(key):
                    if s.start < len(s.ts):  # raced a fresh append: keep
                        continue
                    del self._series[key]
                    dropped += 1
                    name = s.labels.get("__name__", "")
                    keys = self._by_name.get(name)
                    if keys is not None:
                        keys.pop(key, None)
                        if not keys:
                            del self._by_name[name]
        return dropped

    def live_sample_count(self) -> int:
        """Total retained (live-region) samples — the memory-bound guard
        the trim regression tests assert against."""
        with self._mu:
            items = list(self._series.items())
        total = 0
        for key, s in items:
            with self._lock_for(key):
                total += len(s.ts) - s.start
        return total

    def drop_series(self, name: str, labels: dict[str, str]) -> None:
        """Remove a series entirely (e.g. pod deleted — Prometheus staleness)."""
        with self._mu:
            key = self._key(name, labels)
            dropped = self._series.pop(key, None)
            keys = self._by_name.get(name)
            if keys is not None:
                keys.pop(key, None)
                if not keys:
                    del self._by_name[name]
        if dropped is not None:
            # An in-lookback series vanishing changes query results without
            # any append; the write-version must say so.
            self._bump_name_version(name)

    def matching_series(self, matchers: list[tuple[str, str, str]]):
        """Series whose labels satisfy all (label, op, value) matchers, as
        ``(labels, SeriesWindow)`` pairs. The windows are zero-copy
        snapshots; concurrent appends/compactions never mutate them. The
        label dicts are the STORE's own (never mutated after series
        creation) handed out by reference — evaluator outputs are
        read-only by contract, and the per-series dict copy was a
        measurable slice of fleet-wide queries at scale. Callers that
        publish labels onward must copy (the HTTP parse path and demux
        already build their own dicts)."""
        if self.legacy_reads:
            return self._matching_series_legacy(matchers)
        name_val = None
        if self.use_name_index:
            for lbl, op, val in matchers:
                if lbl == "__name__" and op == "=":
                    name_val = val
                    break
        with self._mu:
            if name_val is not None:
                keys = self._by_name.get(name_val)
                entries = ([] if keys is None
                           else [(k, self._series[k]) for k in keys])
            else:
                entries = list(self._series.items())
        # Pre-split the matchers once per query instead of re-dispatching
        # _match per (series, matcher): equality tests become direct dict
        # compares inside the loop, and the name matcher the index
        # already satisfied is dropped. At fleet scale the scan visits
        # thousands of series per select — the per-series function-call
        # fan-out was a measurable slice of every fleet-wide evaluation.
        eq: list[tuple[str, str]] = []
        rest: list[tuple[str, str, str]] = []
        for lbl, op, val in matchers:
            if lbl == "__name__" and op == "=" and val == name_val:
                continue  # every indexed entry carries this name
            if op == "=":
                eq.append((lbl, val))
            else:
                rest.append((lbl, op, val))
        out = []
        for key, s in entries:
            labels = s.labels
            ok = True
            for lbl, val in eq:
                if labels.get(lbl, "") != val:
                    ok = False
                    break
            if not ok or (rest and not all(
                    _match(labels.get(lbl, ""), op, val)
                    for lbl, op, val in rest)):
                continue
            with self._lock_for(key):
                window = SeriesWindow(s.ts, s.vals, s.start, len(s.ts),
                                      series=s)
            out.append((labels, window))
        return out

    def _matching_series_legacy(self, matchers):
        """Pre-ring read path for honest benchmarking: the whole scan holds
        ONE lock (readers serialize) and every matched series' samples are
        materialized into a fresh copy."""
        with self._mu:
            out = []
            for key, s in self._series.items():
                labels = s.labels
                if not all(_match(labels.get(lbl, ""), op, val)
                           for lbl, op, val in matchers):
                    continue
                with self._lock_for(key):
                    window = SeriesWindow(s.ts[s.start:], s.vals[s.start:],
                                          0, len(s.ts) - s.start)
                out.append((dict(labels), window))
            return out


def _match(actual: str, op: str, expected: str) -> bool:
    if op == "=":
        return actual == expected
    if op == "!=":
        return actual != expected
    if op == "=~":
        return _compiled_re(expected).fullmatch(actual) is not None
    if op == "!~":
        return _compiled_re(expected).fullmatch(actual) is None
    raise PromQLError(f"unknown matcher op {op!r}")


# --- AST ---

@dataclass
class Selector:
    name: str
    matchers: list[tuple[str, str, str]] = field(default_factory=list)
    range_seconds: float = 0.0  # >0 -> range selector


@dataclass
class FuncCall:
    func: str
    arg: Selector


@dataclass
class Aggregation:
    op: str
    by: list[str]
    arg: object


@dataclass
class BinaryOp:
    op: str  # "/" or "or"
    left: object
    right: object


@dataclass
class NumberLiteral:
    value: float


# --- Lexer/parser (recursive descent over the subset grammar) ---

_TOKEN_RE = re.compile(
    r"""
    (?P<string>"(?:[^"\\]|\\.)*")
  | (?P<duration>\d+(?:\.\d+)?(?:ms|s|m|h|d)\b)
  | (?P<number>\d+(?:\.\d+)?)
  | (?P<ident>[a-zA-Z_:][a-zA-Z0-9_:]*)
  | (?P<op>=~|!~|!=|=|\{|\}|\(|\)|\[|\]|,|/)
  | (?P<ws>\s+)
    """,
    re.VERBOSE,
)


def _tokenize(text: str) -> list[tuple[str, str]]:
    tokens = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m:
            raise PromQLError(f"unexpected character {text[pos]!r} at {pos} in {text!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        tokens.append((kind, m.group()))
    return tokens


class _Parser:
    def __init__(self, text: str) -> None:
        self.text = text
        self.tokens = _tokenize(text)
        self.pos = 0

    def peek(self) -> tuple[str, str] | None:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self) -> tuple[str, str]:
        tok = self.peek()
        if tok is None:
            raise PromQLError(f"unexpected end of query: {self.text!r}")
        self.pos += 1
        return tok

    def expect(self, value: str) -> None:
        tok = self.next()
        if tok[1] != value:
            raise PromQLError(f"expected {value!r}, got {tok[1]!r} in {self.text!r}")

    def parse(self):
        expr = self.parse_or()
        if self.peek() is not None:
            raise PromQLError(f"trailing tokens at {self.peek()} in {self.text!r}")
        return expr

    def parse_or(self):
        left = self.parse_div()
        while True:
            tok = self.peek()
            if tok and tok[0] == "ident" and tok[1] == "or":
                self.next()
                left = BinaryOp("or", left, self.parse_div())
            else:
                return left

    def parse_div(self):
        left = self.parse_primary()
        while True:
            tok = self.peek()
            if tok and tok[1] == "/":
                self.next()
                left = BinaryOp("/", left, self.parse_primary())
            else:
                return left

    def parse_primary(self):
        tok = self.peek()
        if tok is None:
            raise PromQLError(f"unexpected end of query: {self.text!r}")
        if tok[1] == "(":
            self.next()
            inner = self.parse_or()
            self.expect(")")
            return inner
        if tok[0] == "number":
            self.next()
            return NumberLiteral(float(tok[1]))
        if tok[0] == "ident":
            name = tok[1]
            if name in _AGG_OPS:
                return self.parse_aggregation()
            if name in _RANGE_FUNCS:
                return self.parse_func()
            if name == "vector":
                # vector(scalar) — Prometheus's connectivity-check idiom
                # ("vector(1)"), used by the startup validation.
                self.next()
                self.expect("(")
                num = self.next()
                if num[0] != "number":
                    raise PromQLError(
                        f"vector() expects a number, got {num[1]!r}")
                self.expect(")")
                return NumberLiteral(float(num[1]))
            return self.parse_selector()
        raise PromQLError(f"unexpected token {tok[1]!r} in {self.text!r}")

    def parse_aggregation(self):
        op = self.next()[1]
        by: list[str] = []
        tok = self.peek()
        if tok and tok[0] == "ident" and tok[1] == "by":
            self.next()
            self.expect("(")
            while True:
                t = self.next()
                if t[0] != "ident":
                    raise PromQLError(f"expected label name, got {t[1]!r}")
                by.append(t[1])
                t = self.next()
                if t[1] == ")":
                    break
                if t[1] != ",":
                    raise PromQLError(f"expected , or ) in by-clause, got {t[1]!r}")
        self.expect("(")
        arg = self.parse_or()
        self.expect(")")
        return Aggregation(op, by, arg)

    def parse_func(self):
        func = self.next()[1]
        self.expect("(")
        sel = self.parse_selector()
        self.expect(")")
        if sel.range_seconds <= 0:
            raise PromQLError(f"{func}() requires a range selector in {self.text!r}")
        return FuncCall(func, sel)

    def parse_selector(self) -> Selector:
        tok = self.next()
        if tok[0] != "ident":
            raise PromQLError(f"expected metric name, got {tok[1]!r}")
        sel = Selector(name=tok[1])
        nxt = self.peek()
        if nxt and nxt[1] == "{":
            self.next()
            while True:
                t = self.next()
                if t[1] == "}":
                    break
                if t[0] != "ident":
                    raise PromQLError(f"expected label name, got {t[1]!r}")
                label = t[1]
                op = self.next()[1]
                if op not in ("=", "!=", "=~", "!~"):
                    raise PromQLError(f"bad matcher op {op!r}")
                val_tok = self.next()
                if val_tok[0] != "string":
                    raise PromQLError(f"expected quoted value, got {val_tok[1]!r}")
                value = val_tok[1][1:-1].replace('\\"', '"').replace("\\\\", "\\")
                sel.matchers.append((label, op, value))
                t2 = self.peek()
                if t2 and t2[1] == ",":
                    self.next()
        nxt = self.peek()
        if nxt and nxt[1] == "[":
            self.next()
            dur = self.next()
            if dur[0] not in ("duration", "number"):
                raise PromQLError(f"expected duration, got {dur[1]!r}")
            sel.range_seconds = parse_promql_duration(dur[1]) \
                if dur[0] == "duration" else float(dur[1])
            self.expect("]")
        return sel


def parse_query(text: str):
    return _Parser(text).parse()


# --- AST -> PromQL serialization (the grouped-collection rewriter's other
# half: transformed ASTs must round-trip to query strings any Prometheus —
# real or this subset engine — accepts) ---

def _escape_label_value(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"')


def to_promql(node) -> str:
    """Serialize a (possibly transformed) AST back to PromQL text. Inverse
    of :func:`parse_query` up to whitespace/duration normalization."""
    if isinstance(node, NumberLiteral):
        v = node.value
        return str(int(v)) if float(v).is_integer() else repr(v)
    if isinstance(node, Selector):
        out = node.name
        if node.matchers:
            body = ",".join(f'{lbl}{op}"{_escape_label_value(val)}"'
                            for lbl, op, val in node.matchers)
            out += "{" + body + "}"
        if node.range_seconds > 0:
            out += f"[{format_promql_duration(node.range_seconds)}]"
        return out
    if isinstance(node, FuncCall):
        return f"{node.func}({to_promql(node.arg)})"
    if isinstance(node, Aggregation):
        by = f" by ({', '.join(node.by)})" if node.by else ""
        return f"{node.op}{by} ({to_promql(node.arg)})"
    if isinstance(node, BinaryOp):
        def operand(child) -> str:
            text = to_promql(child)
            return f"({text})" if isinstance(child, BinaryOp) else text
        joiner = " or " if node.op == "or" else " / "
        return operand(node.left) + joiner + operand(node.right)
    raise PromQLError(f"cannot serialize node {node!r}")


# --- Evaluator ---

def _series_identity(labels: dict[str, str]) -> tuple:
    return tuple(sorted((k, v) for k, v in labels.items() if k != "__name__"))


class PromQLEngine:
    # Parsed-AST cache bound: the query surface is a fixed template set with
    # per-(model, namespace) substitutions, so steady state holds a few
    # hundred distinct strings per fleet; the bound only guards pathological
    # callers. ASTs are immutable after parse, so sharing is safe.
    AST_CACHE_BOUND = 4096

    def __init__(self, db: TimeSeriesDB,
                 lookback: float = DEFAULT_LOOKBACK_SECONDS) -> None:
        self.db = db
        self.lookback = lookback
        self._ast_mu = threading.Lock()
        self._ast_cache: dict[str, object] = {}
        # Compat lever for `make bench-tick` (see TimeSeriesDB.use_name_index).
        self.cache_asts = True
        # Per-thread min-included-instant-sample tracking for
        # query_tracked (the grouped view's execution-reuse expiry bound).
        self._track = threading.local()

    def _parse_cached(self, text: str):
        if not self.cache_asts:
            return parse_query(text)
        with self._ast_mu:
            node = self._ast_cache.get(text)
        if node is None:
            node = parse_query(text)
            with self._ast_mu:
                if len(self._ast_cache) >= self.AST_CACHE_BOUND:
                    self._ast_cache.clear()
                self._ast_cache[text] = node
        return node

    def query(self, text: str, at: float | None = None) -> list[SeriesPoint]:
        now = self.db.clock.now() if at is None else at
        # Re-tokenizing the same template-rendered string every engine tick
        # cost more than evaluating it at fleet scale; parse once per
        # distinct string.
        return self._eval(self._parse_cached(text), now)

    def query_tracked(self, text: str, at: float | None = None
                      ) -> tuple[list[SeriesPoint], TrackMeta]:
        """``query`` plus the evaluation's validity metadata (see
        :class:`TrackMeta`) — how long the result provably stays current
        without writes (strict) or with only value-unchanging re-scrapes
        (the fingerprint tier's gate)."""
        self.begin_tracking()
        try:
            points = self.query(text, at)
        finally:
            meta = self.end_tracking()
        return points, meta

    def begin_tracking(self) -> None:
        """Start validity tracking on this thread (see query_tracked;
        split out so callers routing through an instance-level ``query``
        wrapper can still track)."""
        self._track.meta = TrackMeta()
        self._track.active = True

    def end_tracking(self) -> TrackMeta:
        self._track.active = False
        return getattr(self._track, "meta", None) or TrackMeta()

    def _track_instant(self, ts: float) -> None:
        """One included instant sample: the result holds until it ages
        past the lookback (same-value re-appends only extend that, so the
        bound serves both tiers)."""
        if not getattr(self._track, "active", False):
            return
        meta = self._track.meta
        expiry = ts + self.lookback
        if expiry < meta.expiry_strict:
            meta.expiry_strict = expiry
        if expiry < meta.expiry_b:
            meta.expiry_b = expiry

    def _track_excluded(self) -> None:
        """A matched series was EXCLUDED (empty/thin window, lookback-
        stale): value-unchanging appends could revive it — changing the
        result set without a value-version bump — so the uniform tier is
        off for this evaluation."""
        if getattr(self._track, "active", False):
            self._track.meta.uniform = False

    def _track_range(self, func: str, window: "SeriesWindow",
                     window_len: float) -> None:
        """One included range window. Range-func results depend only on
        the in-window SAMPLE SET (the extrapolation math uses sample
        timestamps, never eval time), so with no appends the result holds
        until the first sample departs (strict). A uniform window's VALUE
        additionally survives same-value appends + departures until it
        thins below the func's minimum sample count (tier b)."""
        if not getattr(self._track, "active", False):
            return
        meta = self._track.meta
        ts, vals, lo, hi = window.ts, window.vals, window.lo, window.hi
        strict = ts[lo] + window_len
        if strict < meta.expiry_strict:
            meta.expiry_strict = strict
        if not meta.uniform:
            return
        final = vals[hi - 1]
        for i in range(lo, hi - 1):
            if vals[i] != final:
                meta.uniform = False
                return
        min_idx = hi - 2 if func in ("rate", "increase") else hi - 1
        b = ts[max(lo, min_idx)] + window_len
        if b < meta.expiry_b:
            meta.expiry_b = b

    def _eval(self, node, now: float) -> list[SeriesPoint]:
        if isinstance(node, NumberLiteral):
            return [SeriesPoint({}, node.value, now)]
        if isinstance(node, Selector):
            return self._eval_instant(node, now)
        if isinstance(node, FuncCall):
            return self._eval_range_func(node, now)
        if isinstance(node, Aggregation):
            return self._eval_agg(node, now)
        if isinstance(node, BinaryOp):
            return self._eval_binop(node, now)
        raise PromQLError(f"unknown node {node!r}")

    def _select(self, sel: Selector):
        matchers = [("__name__", "=", sel.name)] + sel.matchers
        return self.db.matching_series(matchers)

    def _eval_instant(self, sel: Selector, now: float) -> list[SeriesPoint]:
        if sel.range_seconds > 0:
            raise PromQLError(f"range selector {sel.name} needs a function")
        legacy = self.db.legacy_reads
        out = []
        for labels, window in self._select(sel):
            if legacy:
                # Pre-ring shape: linear scan with per-sample objects.
                latest = None
                for s in window:
                    if s.timestamp <= now:
                        latest = s
                    else:
                        break
            else:
                latest = window.latest_at_or_before(now)
            if latest is None or now - latest.timestamp > self.lookback:
                self._track_excluded()
                continue
            self._track_instant(latest.timestamp)
            out.append(SeriesPoint(labels, latest.value, latest.timestamp))
        return out

    def _eval_range_func(self, call: FuncCall, now: float) -> list[SeriesPoint]:
        window_len = call.arg.range_seconds
        legacy = self.db.legacy_reads
        out = []
        for labels, window in self._select(call.arg):
            if legacy:
                # Pre-ring shape: full linear scan over every retained
                # sample, materializing Sample objects for the window —
                # the read-path cost `make bench-collect` measures as the
                # honest before.
                samples = [s for s in window
                           if now - window_len <= s.timestamp <= now]
                if not samples:
                    continue
                val = _apply_range_func_samples(call.func, samples,
                                                window_len)
                last_ts = samples[-1].timestamp
            else:
                in_window = window.range_window(now - window_len, now)
                if not len(in_window):
                    self._track_excluded()
                    continue
                self._track_range(call.func, in_window, window_len)
                if self.db.delta_range_eval:
                    val = _apply_range_func_delta(call.func, in_window,
                                                  window_len, self.db)
                else:
                    val = _apply_range_func(call.func, in_window,
                                            window_len)
                last_ts = in_window.ts[in_window.hi - 1]
            if val is None:
                self._track_excluded()
                continue
            result_labels = {k: v for k, v in labels.items() if k != "__name__"}
            out.append(SeriesPoint(result_labels, val, last_ts))
        return out

    def _eval_agg(self, agg: Aggregation, now: float) -> list[SeriesPoint]:
        inputs = self._eval(agg.arg, now)
        if not inputs:
            return []  # Prometheus: aggregation over empty vector is empty
        # Group keys are the sorted (label, value) item tuples — built
        # directly from the PRE-sORTED by-label names, so the per-point
        # dict + sort the old shape paid at fleet scale is gone while the
        # key (and thus output ordering) stays byte-identical.
        by_sorted = sorted(agg.by)
        groups: dict[tuple, list[SeriesPoint]] = {}
        for point in inputs:
            labels = point.labels
            key = tuple((l, labels.get(l, "")) for l in by_sorted)
            groups.setdefault(key, []).append(point)
        out = []
        for key, points in sorted(groups.items()):
            values = [p.value for p in points]
            if agg.op == "sum":
                val = sum(values)
            elif agg.op == "max":
                val = max(values)
            elif agg.op == "min":
                val = min(values)
            elif agg.op == "avg":
                val = sum(values) / len(values)
            elif agg.op == "count":
                val = float(len(values))
            else:
                raise PromQLError(f"unknown aggregation {agg.op!r}")
            out.append(SeriesPoint(dict(key), val, max(p.timestamp for p in points)))
        return out

    def _eval_binop(self, node: BinaryOp, now: float) -> list[SeriesPoint]:
        left = self._eval(node.left, now)
        if node.op == "or":
            right = self._eval(node.right, now)
            if not right:
                # Common registered-template shape: "vllm_metric or
                # jetstream_metric" where one engine's family is entirely
                # absent — skip the fleet-sized identity-set build.
                return left
            left_ids = {_series_identity(p.labels) for p in left}
            return left + [p for p in right if _series_identity(p.labels) not in left_ids]
        if node.op == "/":
            right = self._eval(node.right, now)
            # scalar division
            if len(right) == 1 and not right[0].labels:
                divisor = right[0].value
                if divisor == 0:
                    return []
                return [SeriesPoint(p.labels, p.value / divisor, p.timestamp) for p in left]
            right_by_id = {_series_identity(p.labels): p for p in right}
            out = []
            for p in left:
                match = right_by_id.get(_series_identity(p.labels))
                if match is None or match.value == 0:
                    continue  # unmatched or div-by-zero series are dropped
                out.append(SeriesPoint(p.labels, p.value / match.value, p.timestamp))
            return out
        raise PromQLError(f"unknown binary op {node.op!r}")


def _fold_range_acc(func: str, vals, lo: int, hi: int) -> float:
    """Left fold of the range function's accumulator over ``[lo, hi)`` —
    operation-for-operation the same fold the scanning evaluator runs
    (sum / running max / positive-delta total), so a fold extended over
    an appended suffix is bitwise the fold recomputed from scratch."""
    if func == "max_over_time":
        m = vals[lo]
        for i in range(lo + 1, hi):
            v = vals[i]
            if v > m:
                m = v
        return m
    if func == "avg_over_time":
        total = 0.0
        for i in range(lo, hi):
            total += vals[i]
        return total
    # rate / increase: positive-delta accumulation with counter-reset
    # handling, exactly _apply_range_func's loop.
    total = 0.0
    prev = vals[lo]
    for i in range(lo + 1, hi):
        v = vals[i]
        delta = v - prev
        total += delta if delta >= 0 else v
        prev = v
    return total


def _extend_range_acc(func: str, vals, m_hi: int, hi: int,
                      acc: float) -> float:
    """Continue the fold from a memoized prefix ``[lo, m_hi)`` over the
    appended suffix ``[m_hi, hi)``. A left fold's partial result plus the
    remaining terms in order IS the full fold — no re-association, so
    the extension is exact (the byte-equality the lever test asserts)."""
    if func == "max_over_time":
        m = acc
        for i in range(m_hi, hi):
            v = vals[i]
            if v > m:
                m = v
        return m
    if func == "avg_over_time":
        total = acc
        for i in range(m_hi, hi):
            total += vals[i]
        return total
    total = acc
    prev = vals[m_hi - 1]
    for i in range(m_hi, hi):
        v = vals[i]
        delta = v - prev
        total += delta if delta >= 0 else v
        prev = v
    return total


def _range_result(func: str, acc: float, ts, lo: int, hi: int,
                  window_len: float) -> float | None:
    """Finish a range function from its accumulator: O(1) — everything
    else the scanning evaluator derives comes from the window's first/
    last timestamps and the sample count."""
    if func == "max_over_time":
        return acc
    if func == "avg_over_time":
        return acc / (hi - lo)
    if hi - lo < 2:
        return None
    span = ts[hi - 1] - ts[lo]
    if span <= 0:
        return None
    window_start = ts[hi - 1] - window_len
    interval = span / (hi - lo - 1)
    limit = interval * 1.1
    extend_start = min(max(ts[lo] - window_start, 0.0), limit)
    scaled = acc * ((span + extend_start) / span)
    return scaled / window_len if func == "rate" else scaled


def _apply_range_func_delta(func: str, window: SeriesWindow,
                            window_len: float, db: TimeSeriesDB
                            ) -> float | None:
    """Delta-maintained twin of :func:`_apply_range_func` (ROADMAP item
    1a): per-(series, func, window) rolling accumulators keyed to the in-
    window sample set. An unchanged window (quiet series) returns the
    memoized result with zero fold work; an appended window extends the
    fold over only the new samples; a window whose LEFT edge moved
    (samples expired out) rescans — the left fold cannot be un-folded
    exactly, and byte-equality with the scanning evaluator is the
    contract. The memo anchors on the backing array OBJECT (compaction
    replaces arrays, so a replaced ring can never alias a stale memo),
    holding the old array alive at most until the next evaluation
    refreshes the entry. Counters (range_hits/extends/scans) are test
    introspection, not synchronized."""
    s = window.series
    if s is None:
        db.range_scans += 1
        return _apply_range_func(func, window, window_len)
    ts, vals, lo, hi = window.ts, window.vals, window.lo, window.hi
    key = (func, window_len)
    memo = s.range_memo.get(key)
    acc = None
    if memo is not None and memo[0] is ts and memo[1] == lo:
        _ref, _lo, m_hi, m_acc, m_val = memo
        if m_hi == hi:
            db.range_hits += 1
            return m_val
        if hi > m_hi:
            db.range_extends += 1
            acc = _extend_range_acc(func, vals, m_hi, hi, m_acc)
    if acc is None:
        db.range_scans += 1
        acc = _fold_range_acc(func, vals, lo, hi)
    val = _range_result(func, acc, ts, lo, hi, window_len)
    if len(s.range_memo) >= 16:  # bound pathological window_len churn
        s.range_memo.clear()
    s.range_memo[key] = (ts, lo, hi, acc, val)
    return val


def _apply_range_func(func: str, window: SeriesWindow,
                      window_len: float) -> float | None:
    ts, vals, lo, hi = window.ts, window.vals, window.lo, window.hi
    if func == "max_over_time":
        return max(vals[i] for i in range(lo, hi))
    if func == "avg_over_time":
        return sum(vals[i] for i in range(lo, hi)) / (hi - lo)
    if func in ("rate", "increase"):
        if hi - lo < 2:
            return None
        # Counter-reset handling: accumulate positive deltas.
        total = 0.0
        prev = vals[lo]
        for i in range(lo + 1, hi):
            v = vals[i]
            delta = v - prev
            total += delta if delta >= 0 else v
            prev = v
        span = ts[hi - 1] - ts[lo]
        if span <= 0:
            return None
        # Prometheus-style bounded extrapolation: extend toward the window
        # edges by at most ~one sample interval per side, so a series younger
        # than the window isn't inflated to the full window.
        window_start = ts[hi - 1] - window_len  # eval time ~ last sample
        interval = span / (hi - lo - 1)
        limit = interval * 1.1
        extend_start = min(max(ts[lo] - window_start, 0.0), limit)
        scaled = total * ((span + extend_start) / span)
        return scaled / window_len if func == "rate" else scaled
    raise PromQLError(f"unknown range function {func!r}")


def _apply_range_func_samples(func: str, samples: list[Sample],
                              window: float) -> float | None:
    """Sample-list twin of :func:`_apply_range_func` — the pre-ring code
    path, kept only for the ``legacy_reads`` bench lever. Same math."""
    values = [s.value for s in samples]
    if func == "max_over_time":
        return max(values)
    if func == "avg_over_time":
        return sum(values) / len(values)
    if func in ("rate", "increase"):
        if len(samples) < 2:
            return None
        total = 0.0
        prev = samples[0].value
        for s in samples[1:]:
            delta = s.value - prev
            total += delta if delta >= 0 else s.value
            prev = s.value
        span = samples[-1].timestamp - samples[0].timestamp
        if span <= 0:
            return None
        window_start = samples[-1].timestamp - window
        interval = span / (len(samples) - 1)
        limit = interval * 1.1
        extend_start = min(max(samples[0].timestamp - window_start, 0.0), limit)
        scaled = total * ((span + extend_start) / span)
        return scaled / window if func == "rate" else scaled
    raise PromQLError(f"unknown range function {func!r}")
