"""No-op source (reference ``internal/collector/source/noop_source.go``)."""

from __future__ import annotations

from wva_tpu.collector.source.query_template import QueryList
from wva_tpu.collector.source.source import MetricResult, MetricsSource, RefreshSpec


class NoopSource(MetricsSource):
    def __init__(self) -> None:
        self._queries = QueryList()

    def query_list(self) -> QueryList:
        return self._queries

    def refresh(self, spec: RefreshSpec) -> dict[str, MetricResult]:
        return {}

    def get(self, query_name: str, params: dict[str, str]):
        return None
