"""Direct pod-scraping metrics source
(reference ``internal/collector/source/pod/pod_scraping_source.go:29-388``).

Discovers Ready pods behind the EPP Service's selector, scrapes each pod's
``/metrics`` with bounded concurrency, parses Prometheus text format, tags
every sample with ``pod`` and ``__name__`` labels, and aggregates everything
under the single query name ``all_metrics``.

The actual fetch is behind a ``PodMetricsFetcher`` so the emulation harness
can serve pod metrics in-process while production uses HTTP.
"""

from __future__ import annotations

import logging
import re
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Callable

from wva_tpu.collector.source.cache import MetricsCache
from wva_tpu.collector.source.query_template import (
    QUERY_TYPE_METRIC_NAME,
    QueryList,
    QueryTemplate,
)
from wva_tpu.collector.source.source import (
    MetricResult,
    MetricValue,
    MetricsSource,
    RefreshSpec,
)
from wva_tpu.k8s.client import KubeClient, NotFoundError
from wva_tpu.k8s.objects import Pod, Service
from wva_tpu.utils.clock import SYSTEM_CLOCK, Clock

log = logging.getLogger(__name__)

ALL_METRICS_QUERY = "all_metrics"
DEFAULT_SCRAPE_CONCURRENCY = 10
DEFAULT_SCRAPE_TIMEOUT_SECONDS = 5.0

# pod -> Prometheus text exposition (or raises)
PodMetricsFetcher = Callable[[Pod], str]

_METRIC_LINE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>[^\s]+)"
    r"(?:\s+(?P<ts>-?\d+))?$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus_text(text: str) -> list[tuple[str, dict[str, str], float]]:
    """Parse text exposition into (name, labels, value) tuples. HELP/TYPE
    comments and malformed lines are skipped."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _METRIC_LINE_RE.match(line)
        if not m:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        labels = {}
        if m.group("labels"):
            for lm in _LABEL_RE.finditer(m.group("labels")):
                labels[lm.group(1)] = (
                    lm.group(2).replace('\\"', '"').replace("\\n", "\n")
                    .replace("\\\\", "\\")
                )
        out.append((m.group("name"), labels, value))
    return out


def http_pod_fetcher(metrics_port: int, bearer_token: str = "",
                     timeout: float = DEFAULT_SCRAPE_TIMEOUT_SECONDS) -> PodMetricsFetcher:
    """Production fetcher: GET http://<podIP>:<port>/metrics."""

    def fetch(pod: Pod) -> str:
        if not pod.status.pod_ip:
            raise RuntimeError(f"pod {pod.metadata.name} has no IP")
        req = urllib.request.Request(
            f"http://{pod.status.pod_ip}:{metrics_port}/metrics")
        if bearer_token:
            req.add_header("Authorization", f"Bearer {bearer_token}")
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read().decode("utf-8", errors="replace")

    return fetch


class PodScrapingSource(MetricsSource):
    def __init__(
        self,
        client: KubeClient,
        service_name: str,
        service_namespace: str,
        fetcher: PodMetricsFetcher,
        *,
        max_concurrency: int = DEFAULT_SCRAPE_CONCURRENCY,
        cache_ttl: float = 30.0,
        clock: Clock | None = None,
    ) -> None:
        self.client = client
        self.service_name = service_name
        self.service_namespace = service_namespace
        self.fetcher = fetcher
        self.max_concurrency = max_concurrency
        self.clock = clock or SYSTEM_CLOCK
        self._cache = MetricsCache(ttl=cache_ttl, clock=self.clock)
        self._queries = QueryList()
        self._queries.register(QueryTemplate(
            name=ALL_METRICS_QUERY,
            type=QUERY_TYPE_METRIC_NAME,
            template="*",
            description="All metrics scraped from pods behind the EPP service",
        ))

    def query_list(self) -> QueryList:
        return self._queries

    def discover_pods(self) -> list[Pod]:
        """Ready pods matched by the Service's selector
        (reference :163-201)."""
        try:
            svc: Service = self.client.get(
                Service.KIND, self.service_namespace, self.service_name)
        except NotFoundError:
            log.debug("EPP service %s/%s not found",
                      self.service_namespace, self.service_name)
            return []
        if not svc.selector:
            return []
        pods = self.client.list(Pod.KIND, namespace=self.service_namespace,
                                label_selector=svc.selector)
        return [p for p in pods if p.is_ready()]

    def refresh(self, spec: RefreshSpec) -> dict[str, MetricResult]:
        collected_at = self.clock.now()
        pods = self.discover_pods()
        values: list[MetricValue] = []
        errors: list[str] = []

        def scrape(pod: Pod) -> tuple[Pod, str | None, str]:
            try:
                return pod, self.fetcher(pod), ""
            except Exception as e:  # noqa: BLE001 — per-pod isolation
                return pod, None, str(e)

        if pods:
            with ThreadPoolExecutor(
                    max_workers=min(self.max_concurrency, len(pods))) as pool:
                scraped = list(pool.map(scrape, pods))
        else:
            scraped = []

        for pod, text, err in scraped:
            if text is None:
                log.debug("scrape failed for pod %s: %s", pod.metadata.name, err)
                errors.append(f"{pod.metadata.name}: {err}")
                continue
            for name, labels, value in parse_prometheus_text(text):
                tagged = dict(labels)
                tagged["pod"] = pod.metadata.name
                tagged["__name__"] = name
                values.append(MetricValue(value=value, timestamp=collected_at,
                                          labels=tagged))

        result = MetricResult(
            query_name=ALL_METRICS_QUERY,
            values=values,
            collected_at=collected_at,
            error="" if (values or not errors) else "; ".join(errors),
        )
        if not result.has_error():
            self._cache.set(ALL_METRICS_QUERY, {}, result)
        return {ALL_METRICS_QUERY: result}

    def get(self, query_name: str, params: dict[str, str]):
        return self._cache.get(query_name, params)
