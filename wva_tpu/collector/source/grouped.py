"""Grouped per-tick metrics collection (docs/design/metrics-plane.md).

Every model used to issue its own ~10 templated Prometheus queries per
engine tick, so a 48-model fleet fired ~480 HTTP queries per 5s tick —
exactly the per-job fan-out Autopilot (Rzadca et al., EuroSys 2020)
collapses into shared signal collection. This module makes the metrics
plane O(query templates) per tick instead of O(models x templates):

- :func:`build_grouped_query` rewrites a registered per-model template into
  ONE fleet-wide query by parsing it (the bundled PromQL-subset parser),
  dropping the ``model_name="..."``/``namespace="..."`` equality matchers
  (replaced by ``label!=""`` presence guards so series without the label
  never leak in), adding those labels to every enclosing aggregation's
  ``by`` clause, and serializing the AST back to PromQL.

- :class:`GroupedMetricsView` is a tick-scoped :class:`MetricsSource` view
  over a :class:`~wva_tpu.collector.source.prometheus.PrometheusSource`:
  the first caller needing a template this tick executes the fleet-wide
  query once; its result is demultiplexed into per-(model, namespace)
  ``MetricResult`` slices that serve every other caller — and each slice is
  cached under the SAME per-model cache key the per-model path uses, so
  stale-serve-on-error semantics are preserved per model. Templates the
  rewriter cannot group, and templates a backend rejected, automatically
  fall back to the existing per-model refresh path.

Demux reproduces per-model evaluation byte-for-byte: group labels are
stripped from every output point, and for multi-branch queries (a
top-level ``a or b`` of aggregations, e.g. the scheduler flow-control
pair) ``or``-preference is applied per model over the stripped label
identity — a right-branch point survives only when no earlier branch
produced the same series for that model.
"""

from __future__ import annotations

import itertools
import logging
import threading
import urllib.error
from dataclasses import dataclass, field

from wva_tpu.collector.source.promql import (
    Aggregation,
    BinaryOp,
    FuncCall,
    NumberLiteral,
    PromQLError,
    Selector,
    parse_query,
    to_promql,
)
from wva_tpu.collector.source.query_template import (
    QUERY_TYPE_PROMQL,
    QueryTemplate,
    escape_promql_value,
)
from wva_tpu.collector.source.source import (
    PARAM_MODEL_ID,
    PARAM_NAMESPACE,
    MetricResult,
    MetricsSource,
    RefreshSpec,
)
from wva_tpu.utils.oncemap import OnceMap

log = logging.getLogger(__name__)

# Sentinel label values substituted for the per-model placeholders before
# parsing; the rewriter recognizes and removes the matchers carrying them.
MODEL_SENTINEL = "__wva_grouped_model__"
NS_SENTINEL = "__wva_grouped_namespace__"


class NotGroupableError(PromQLError):
    """The template's shape is outside the rewriter's rules; callers fall
    back to per-model collection."""


@dataclass(frozen=True)
class GroupedBranch:
    """Demux descriptor for one top-level aggregation branch: which output
    label carries the model id / namespace, and which labels to strip so
    the demuxed slice is byte-identical to the per-model result."""

    model_label: str
    ns_label: str  # "" when the template has no namespace dimension
    strip: tuple[str, ...]


@dataclass(frozen=True)
class GroupedQuery:
    promql: str
    branches: tuple[GroupedBranch, ...]
    has_namespace: bool
    # Versioned-fingerprint metadata (docs/design/informer.md
    # §versioned-fingerprints): the metric names the query selects. The
    # execution-reuse gate compares backend write/value versions across
    # exactly these names; the per-evaluation validity bounds (TrackMeta)
    # cover instant and range shapes alike.
    metric_names: tuple[str, ...] = ()


def _merge_pending(into: dict[str, str], kind: str, label: str) -> None:
    prev = into.get(kind)
    if prev is not None and prev != label:
        raise NotGroupableError(
            f"conflicting {kind} labels {prev!r} vs {label!r}")
    into[kind] = label


def _rewrite(node, scope_namespace: str = "",
             ) -> tuple[list[GroupedBranch], dict[str, str]]:
    """Transform ``node`` in place. Returns (branches absorbed by
    aggregations in this subtree, sentinel labels still pending an
    enclosing aggregation)."""
    if isinstance(node, NumberLiteral):
        # `vector(N)` parses into NumberLiteral, so serialization would
        # lose the vector() wrapper — and a bare scalar operand under `or`
        # is invalid PromQL on a real backend. Refuse; the template stays
        # per-model.
        raise NotGroupableError("scalar / vector() operand")
    if isinstance(node, Selector):
        pending: dict[str, str] = {}
        matchers: list[tuple[str, str, str]] = []
        for lbl, op, val in node.matchers:
            if val in (MODEL_SENTINEL, NS_SENTINEL):
                if op != "=":
                    raise NotGroupableError(
                        f"non-equality matcher {op!r} on grouped param")
                kind = "model" if val == MODEL_SENTINEL else "ns"
                _merge_pending(pending, kind, lbl)
                if kind == "ns" and scope_namespace:
                    # A namespace-scoped controller keeps its scope as an
                    # equality matcher — on a shared multi-tenant
                    # Prometheus the fleet-wide query must not aggregate
                    # every other tenant's series.
                    matchers.append((lbl, "=", scope_namespace))
                else:
                    # Presence guard: the dropped equality matcher also
                    # implied the label exists and is non-empty
                    # (Prometheus treats a missing label as ""), so series
                    # without it must stay out of the fleet-wide result.
                    matchers.append((lbl, "!=", ""))
            else:
                matchers.append((lbl, op, val))
        node.matchers = matchers
        return [], pending
    if isinstance(node, FuncCall):
        return _rewrite(node.arg, scope_namespace)
    if isinstance(node, Aggregation):
        branches, pending = _rewrite(node.arg, scope_namespace)
        if branches:
            # An aggregation ABOVE an already-grouped aggregation would
            # collapse the models back together; no registered template
            # nests aggregations, so bail to per-model collection.
            raise NotGroupableError("nested aggregation above a grouped one")
        if pending:
            model_label = pending.get("model")
            if model_label is None:
                raise NotGroupableError("namespace param without a model "
                                        "param under one aggregation")
            ns_label = pending.get("ns", "")
            group_labels = [model_label] + ([ns_label] if ns_label else [])
            for lbl in group_labels:
                if lbl not in node.by:
                    node.by.append(lbl)
            branches = [GroupedBranch(model_label, ns_label,
                                      tuple(group_labels))]
            pending = {}
        return branches, pending
    if isinstance(node, BinaryOp):
        left_branches, left_pending = _rewrite(node.left, scope_namespace)
        right_branches, right_pending = _rewrite(node.right, scope_namespace)
        merged = dict(left_pending)
        for kind, label in right_pending.items():
            _merge_pending(merged, kind, label)
        return left_branches + right_branches, merged
    raise NotGroupableError(f"unsupported node {node!r}")


def build_grouped_query(template: QueryTemplate,
                        extra_params: dict[str, str],
                        scope_namespace: str = "") -> GroupedQuery | None:
    """Rewrite one registered per-model template into its fleet-wide
    grouped form, or None when the template is outside the rewrite rules.
    ``extra_params`` are the template's non-model/namespace parameters
    (e.g. ``retentionPeriod``), substituted before parsing — the grouped
    query is memoized per distinct extra-param set. ``scope_namespace``
    (a namespace-scoped controller's watch namespace) is kept as an
    equality matcher instead of the fleet-wide presence guard."""
    if template.type != QUERY_TYPE_PROMQL:
        return None
    if PARAM_MODEL_ID not in template.params:
        return None
    text = template.template
    text = text.replace("{{." + PARAM_MODEL_ID + "}}", MODEL_SENTINEL)
    has_namespace = PARAM_NAMESPACE in template.params
    if has_namespace:
        text = text.replace("{{." + PARAM_NAMESPACE + "}}", NS_SENTINEL)
    for key, value in extra_params.items():
        text = text.replace("{{." + key + "}}", escape_promql_value(value))
    if "{{." in text:
        return None  # unsubstituted params left: not safely groupable
    try:
        ast = parse_query(text)
        branches, pending = _rewrite(ast, scope_namespace)
        if pending:
            raise NotGroupableError("model matcher outside any aggregation")
        if not branches:
            raise NotGroupableError("no model matcher found in template")
    except PromQLError as e:
        log.debug("template %s not groupable: %s", template.name, e)
        return None
    # Deduplicate identical branches (e.g. both sides of a division absorb
    # the same labels) while preserving or-preference order.
    seen: set[tuple[str, str]] = set()
    unique: list[GroupedBranch] = []
    for b in branches:
        if (b.model_label, b.ns_label) not in seen:
            seen.add((b.model_label, b.ns_label))
            unique.append(b)
    return GroupedQuery(promql=to_promql(ast), branches=tuple(unique),
                        has_namespace=has_namespace,
                        metric_names=_selector_names(ast) or ())


def _selector_names(node) -> tuple[str, ...] | None:
    """Metric names one transformed AST selects — the reuse-gate metadata
    on :class:`GroupedQuery`. None poisons the whole query (empty
    metric_names disables reuse): a node shape this walk does not
    understand must never UNDER-cover the version gate. Unreachable for
    today's groupable templates (_rewrite refuses every other shape)."""
    if isinstance(node, Selector):
        return (node.name,)
    if isinstance(node, (FuncCall, Aggregation)):
        return _selector_names(node.arg)
    if isinstance(node, BinaryOp):
        ln = _selector_names(node.left)
        rn = _selector_names(node.right)
        if ln is None or rn is None:
            return None
        return ln + tuple(n for n in rn if n not in ln)
    return None


def demux_points(gq: GroupedQuery, points, make_value):
    """Split one grouped result into per-(model, namespace) value lists.

    ``make_value(labels, point)`` builds the per-model output element from
    the stripped labels; point order within a slice follows branch order
    then backend order, matching per-model ``left or right`` evaluation.
    Returns ``{(model, namespace): [value, ...]}`` (namespace "" when the
    template has no namespace dimension)."""
    if len(gq.branches) == 1:
        # Single-branch fast path (most templates): no or-preference is
        # possible, so the per-point identity tuple and branch bookkeeping
        # are dead weight — demux straight into the output lists.
        branch = gq.branches[0]
        strip = branch.strip
        fast: dict[tuple[str, str], list] = {}
        for p in points:
            labels = p.labels
            model = labels.get(branch.model_label)
            if not model:
                continue
            ns = labels.get(branch.ns_label, "") if branch.ns_label else ""
            stripped = {k: v for k, v in labels.items() if k not in strip}
            fast.setdefault((model, ns), []).append(make_value(stripped, p))
        return fast
    assigned: dict[tuple[str, str], list[tuple[int, tuple, object]]] = {}
    for p in points:
        for bi, branch in enumerate(gq.branches):
            model = p.labels.get(branch.model_label)
            if not model:
                continue
            ns = p.labels.get(branch.ns_label, "") if branch.ns_label else ""
            stripped = {k: v for k, v in p.labels.items()
                        if k not in branch.strip}
            identity = tuple(sorted(stripped.items()))  # fp-lint: bounded
            # (one point's labels; multi-branch or-preference path only)
            assigned.setdefault((model, ns), []).append(
                (bi, identity, make_value(stripped, p)))
            break
    out: dict[tuple[str, str], list] = {}
    for key, entries in assigned.items():
        # Branch-major order (stable: backend order preserved within a
        # branch) — real Prometheus does not guarantee or-result ordering.
        entries.sort(key=lambda e: e[0])
        kept: list = []
        seen_earlier: set[tuple] = set()
        current: set[tuple] = set()
        last_branch = -1
        for bi, identity, value in entries:  # entries keep backend order
            if bi != last_branch:
                seen_earlier |= current
                current = set()
                last_branch = bi
            if identity in seen_earlier:
                continue  # or-preference: an earlier branch won this series
            current.add(identity)
            kept.append(value)
        out[key] = kept
    return out


def _canon_value(v):
    """NaN/Inf-canonicalized value for digests and fingerprints: NaN is
    not equal to itself, so a raw NaN in a fingerprint tuple makes the
    fingerprint never compare equal — the model would be pinned
    permanently dirty. Map non-finite floats to stable sentinels."""
    if isinstance(v, float):
        if v != v:
            return "NaN"
        if v == float("inf"):
            return "Inf"
        if v == float("-inf"):
            return "-Inf"
    return v


def _slice_digest(values) -> tuple:
    """Content digest of one demuxed per-model slice: order-insensitive
    (sorted (labels, value) pairs), timestamp-free, NaN-canonicalized.
    Bounded by the handful of series one model contributes to one
    template — never fleet-sized."""
    return tuple(sorted(  # fp-lint: bounded (one model's slice)
        (tuple(sorted(v.labels.items())), _canon_value(v.value))
        for v in values))


_EMPTY_DIGEST: tuple = ()

# One version book holds at most this many (spec, model) entries; past it
# the book resets wholesale (the counter keeps climbing, so every model
# re-dirties exactly once — the safe direction) instead of growing without
# bound on churning fleets.
_BOOK_MAX_ENTRIES = 65536


@dataclass
class _ExecMemo:
    """One memoized fleet-wide execution with two reuse tiers:

    - **strict** (collection-grade): unchanged backend write-version +
      before ``expiry_strict`` — the evaluation is byte-identical,
      timestamps included, so it may serve collectors.
    - **fingerprint-grade**: unchanged VALUE-version + ``uniform`` +
      before ``expiry_b`` — the result's values (hence slice digests and
      versions) are provably unchanged, but timestamps may have moved
      under same-value re-scrapes, so ONLY the timestamp-free
      fingerprint tier may consume it."""

    write_version: int
    value_version: int
    expiry_strict: float
    expiry_b: float
    uniform: bool
    slices: dict = field(default_factory=dict)  # (model, ns) -> [values]
    versions: dict = field(default_factory=dict)  # (model, ns) -> int


class SliceVersionBook:
    """Cross-tick slice-version store — the metrics half of the versioned
    fingerprint plane (``WVA_FP_DELTA``; docs/design/informer.md).

    Per (template, extras, scope) spec and per demuxed (model, namespace)
    slice it keeps the last content digest and a store-monotonic
    ``slice_version`` that bumps ONLY when the digest changes. The
    engine's dirty-set fingerprint then records the version (an int)
    instead of rebuilding and comparing the full sorted (labels, value)
    tuple per model per tick. Digests are stamped once per fleet-wide
    execution — inside the demux walk that already touches every slice —
    so a quiet tick's fingerprint work is O(templates) version lookups
    per model.

    The book also memoizes whole executions (``_ExecMemo``): backed by
    the ring-buffer TSDB's per-series write-versions and the
    evaluation's tracked validity bounds, an unchanged write-version
    proves byte-identical evaluation, so re-scrape-free quiet metrics
    skip the backend query entirely. Thread-safe; shared by engine ticks and the cache warmer."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._counter = itertools.count(1)
        # spec_key -> {(model, ns): (digest, version)}
        self._digests: dict[tuple, dict[tuple, tuple[tuple, int]]] = {}
        self._entries = 0
        self._exec_memo: dict[tuple, _ExecMemo] = {}
        # Introspection for tests/bench.
        self.reused_executions = 0

    def stamp(self, spec_key: tuple,
              digests: dict[tuple, tuple]) -> dict[tuple, int]:
        """Record this execution's slice digests; returns the slice
        versions (bumped only where the digest changed)."""
        with self._mu:
            book = self._digests.get(spec_key)
            if book is None:
                book = self._digests[spec_key] = {}
            out: dict[tuple, int] = {}
            for key, digest in digests.items():
                cur = book.get(key)
                if cur is not None and cur[0] == digest:
                    out[key] = cur[1]
                    continue
                version = next(self._counter)
                if cur is None:
                    self._entries += 1
                book[key] = (digest, version)
                out[key] = version
            if self._entries > _BOOK_MAX_ENTRIES:
                self._digests = {spec_key: book}
                self._entries = len(book)
                self._exec_memo.clear()
            return out

    def version_for(self, spec_key: tuple, slice_key: tuple,
                    digest: tuple) -> int:
        """Version of one slice given its current digest — the lazy path
        for models ABSENT from this tick's demux (their slice digest is
        the empty tuple, which must still version: present -> absent is a
        change)."""
        with self._mu:
            book = self._digests.get(spec_key)
            if book is None:
                book = self._digests[spec_key] = {}
            cur = book.get(slice_key)
            if cur is not None and cur[0] == digest:
                return cur[1]
            version = next(self._counter)
            if cur is None:
                self._entries += 1
            book[slice_key] = (digest, version)
            return version

    def note_execution(self, spec_key: tuple, memo: "_ExecMemo") -> None:
        with self._mu:
            self._exec_memo[spec_key] = memo

    def reusable(self, spec_key: tuple, write_version: int | None,
                 now: float) -> "_ExecMemo | None":
        """Strict (collection-grade) reuse: the backend write-version for
        the query's metric names is unchanged (no appends, no drops) and
        no included sample has left its window yet — the evaluation would
        be byte-identical, timestamps included."""
        if write_version is None:
            return None
        with self._mu:
            memo = self._exec_memo.get(spec_key)
            if (memo is None or memo.write_version != write_version
                    or now >= memo.expiry_strict):
                return None
            self.reused_executions += 1
            return memo

    def reusable_fp(self, spec_key: tuple, write_version: int | None,
                    value_version: int | None,
                    now: float) -> "_ExecMemo | None":
        """Fingerprint-grade reuse: strict reuse, OR value-version
        unchanged over a uniform evaluation before ``expiry_b`` — the
        result's VALUES provably did not move, so the memoized slice
        versions are current (timestamps may be stale, which the
        timestamp-free fingerprint never sees)."""
        memo = self.reusable(spec_key, write_version, now)
        if memo is not None:
            return memo
        if value_version is None:
            return None
        with self._mu:
            memo = self._exec_memo.get(spec_key)
            if (memo is None or not memo.uniform
                    or memo.value_version != value_version
                    or now >= memo.expiry_b):
                return None
            self.reused_executions += 1
            return memo

    def forget_execution(self, spec_key: tuple) -> None:
        with self._mu:
            self._exec_memo.pop(spec_key, None)


class GroupedMetricsView(MetricsSource):
    """Tick-scoped grouped-collection view over a PrometheusSource.

    Construct one per engine tick and hand it to every collector call site;
    it is thread-safe (the engine's analysis workers race into it), and the
    first worker to need a template runs the fleet-wide query while the
    rest wait on the per-template latch. Anything non-groupable delegates
    to the wrapped source unchanged, so disabling grouping is equivalent to
    bypassing the view entirely."""

    def __init__(self, source, scope_namespace: str = "",
                 versioned: bool = True, spans=None) -> None:
        self._source = source
        # Obs plane (WVA_SPANS): backend query + demux spans, recorded
        # under the engine's current tick tree. None = off (zero cost).
        self._spans = spans
        # Namespace-scoped controllers keep their watch namespace as an
        # equality matcher in the fleet-wide queries (shared-Prometheus
        # tenancy: never aggregate other tenants' series).
        self._scope_namespace = scope_namespace
        # Versioned fingerprint plane (WVA_FP_DELTA): stamp slice digests
        # into the source's SliceVersionBook during demux and allow
        # write-version-backed execution reuse. Off restores the
        # recomputed path byte-for-byte (the book is never touched).
        self._book = (getattr(source, "slice_book", None)
                      if versioned else None)
        # (name, extras, scope) -> demuxed {(model, ns): MetricResult} |
        # None when the grouped execution failed this tick (per-model
        # fallback).
        self._once = OnceMap()
        # slice_versions fast path: (name, extras) -> (versions | None,
        # spec_key, has_ns) resolved once per tick per template, so the
        # per-model fingerprint pays one dict hit instead of re-walking
        # template params / rewrite memo / execution latch per template.
        # Filled idempotently (engine thread only computes fingerprints,
        # but racing fills would agree anyway). _tpl_pre caches the
        # params-independent template preamble (param list, ns-ness,
        # extra-param names) per template name.
        self._vmap: dict[tuple, tuple] = {}
        self._tpl_pre: dict[str, tuple | None] = {}
        # Serving-tier plan memo (one view = one tick): the per-model
        # serve path used to re-walk template params and rebuild the
        # grouped query for every (model, template) pair — O(models *
        # templates) re-resolution per tick for plans that depend only on
        # the template and its non-model params. _plan_pre memoizes the
        # params-independent preamble (template, param list, ns-ness,
        # extra-param names; None = ungroupable template), _plan_gq the
        # resolved grouped query per (template, extras) — so a 1k-model
        # refresh pays one dict hit per serve instead of a full re-plan.
        self._plan_pre: dict[str, tuple | None] = {}
        self._plan_gq: dict[tuple, tuple] = {}

    # --- MetricsSource ---

    def query_list(self):
        return self._source.query_list()

    def get(self, query_name: str, params: dict[str, str]):
        return self._source.get(query_name, params)

    def slice_age_seconds(self, queries, params: dict[str, str],
                          ) -> float | None:
        """Input-health age probe, delegated to the wrapped source's
        per-model cache — the grouped demux refreshes exactly those
        entries, so the probe sees grouped and per-model collection
        identically."""
        return self._source.slice_age_seconds(queries, params)

    def refresh(self, spec: RefreshSpec) -> dict[str, MetricResult]:
        names = list(spec.queries) or self._source.query_list().names()
        results: dict[str, MetricResult] = {}
        passthrough: list[str] = []
        for name in names:
            served = self._serve_grouped(name, spec.params)
            if served is None:
                passthrough.append(name)
            else:
                results[name] = served
        if passthrough:
            results.update(self._source.refresh(
                RefreshSpec(queries=passthrough, params=dict(spec.params))))
        return results

    # --- grouped execution ---

    def _grouped_plan(self, name: str, params: dict[str, str]):
        """Shared precondition walk for grouped serving and fingerprint
        versioning: (template, model, ns, has_ns, gq, spec_key), or None
        to delegate to the per-model path. The exclusion rules are shared
        so the fingerprint's template coverage matches serving exactly.
        The params-independent legs (template resolution, grouped-query
        construction) are memoized per view — see ``_plan_pre``."""
        pre = self._plan_pre.get(name, False)
        if pre is False:
            template = self._source.query_list().get(name)
            if (template is None or template.type != QUERY_TYPE_PROMQL
                    or PARAM_MODEL_ID not in template.params):
                pre = None
            else:
                tp = template.params
                pre = (template, tuple(tp), PARAM_NAMESPACE in tp,
                       tuple(k for k in tp
                             if k not in (PARAM_MODEL_ID, PARAM_NAMESPACE)))
            self._plan_pre[name] = pre
        if pre is None:
            return None
        template, tparams, has_ns, extra_names = pre
        model = params.get(PARAM_MODEL_ID)
        if not model:
            return None
        for p in tparams:
            if p not in params:
                return None  # let the per-model path raise its usual error
        ns = params.get(PARAM_NAMESPACE, "") if has_ns else ""
        # Template-order extras dict (what grouped_query_for always saw);
        # the sorted tuple is both the memo key and the spec key.
        extras = {k: params[k] for k in extra_names}
        ekey = tuple(sorted(extras.items()))  # fp-lint: bounded
        hit = self._plan_gq.get((name, ekey))
        if hit is None:
            gq = self._source.grouped_query_for(name, extras,
                                                self._scope_namespace)
            key = (name, ekey, self._scope_namespace)
            hit = (gq, key)
            self._plan_gq[(name, ekey)] = hit
        gq, key = hit
        if gq is None:
            return None
        return template, model, ns, has_ns, gq, key

    def _serve_grouped(self, name: str,
                       params: dict[str, str]) -> MetricResult | None:
        """The per-model slice for ``params`` from this tick's fleet-wide
        result, or None to delegate to the per-model path."""
        plan = self._grouped_plan(name, params)
        if plan is None:
            return None
        _, model, ns, has_ns, gq, key = plan
        demuxed = self._demuxed(key, name, gq, params, has_ns)
        if demuxed is None:
            return None  # grouped execution failed: per-model fallback
        result = demuxed.get((model, ns))
        if result is None:
            # Same outcome the per-model query would produce: an empty
            # (but successful) result — cached under the per-model key so
            # a later backend outage stale-serves "no data", not ancient
            # data.
            result = MetricResult(query_name=name, values=[],
                                  collected_at=demuxed["__collected_at__"])
            self._source.store_demuxed_result(name, dict(params), result)
        return result

    def warm_fleet_queries(self, params: dict[str, str]) -> None:
        """Execute every groupable template's fleet-wide query into this
        tick view's memo (idempotent — later callers hit the OnceMap).
        The sharded fleet tick warms its SHARED view here before driving
        the shard workers, so the backend's share of the tick (the
        O(series) fleet-wide evaluation a real Prometheus computes
        server-side) is paid once at the fleet level instead of inside
        whichever worker happens to touch a template first. Serving and
        digest stamping are exactly what the first organic toucher would
        have done — decisions and fingerprints are byte-identical."""
        for name in self._source.query_list().names():
            try:
                self._serve_grouped(name, params)
            except Exception:  # noqa: BLE001 — warm failures re-surface
                # (or fall back per-model) on the organic serve path.
                log.debug("fleet warm failed for %s", name, exc_info=True)

    def slice_fingerprint(self, queries, params: dict[str, str]) -> tuple:
        """Digest of this tick's demuxed slices for ``params`` across
        ``queries`` — the metrics component of the engine's dirty-set
        fingerprint (docs/design/informer.md). Serving goes through the
        same memoized fleet-wide execution the collectors use, so
        fingerprinting costs zero extra backend queries on a tick that
        analyzes anything. Hashes (labels, value) only — never collection
        timestamps, which move every tick even when the data does not.
        Ungroupable / failed / param-incomplete templates are excluded
        (stably, so their absence cannot churn the digest). This is the
        RECOMPUTED path (``WVA_FP_DELTA=off``); the shipped path is
        :meth:`slice_versions`."""
        parts: list[tuple] = []
        for name in queries:
            template = self._source.query_list().get(name)
            if template is None:
                continue
            if any(p not in params for p in template.params):
                continue
            sliced = self._serve_grouped(name, params)
            if sliced is None:
                continue
            # _canon_value: a raw NaN here would make the fingerprint
            # never equal itself (NaN != NaN inside the tuple compare),
            # silently pinning the model permanently dirty.
            values = tuple(sorted(  # fp-lint: bounded (one model's slice)
                (tuple(sorted(v.labels.items())), _canon_value(v.value))
                for v in sliced.values))
            parts.append((name, values))
        return tuple(parts)

    def slice_versions(self, queries, params: dict[str, str]) -> tuple:
        """Delta-maintained twin of :meth:`slice_fingerprint`
        (``WVA_FP_DELTA``, default on): O(templates) version lookups per
        model instead of rebuilding sorted (labels, value) tuples. The
        versions come from the source's :class:`SliceVersionBook`,
        stamped once per fleet-wide execution inside :meth:`_execute`'s
        demux walk; a version moves iff the slice's content digest moved,
        so equality dynamics match the recomputed fingerprint exactly
        (asserted by the equivalence mode and the property test).
        Template exclusion rules are shared with serving via
        :meth:`_grouped_plan`, so coverage cannot diverge."""
        parts: list[tuple] = []
        model = params.get(PARAM_MODEL_ID)
        if not model:
            return ()
        for name in queries:
            pre = self._tpl_pre.get(name, False)
            if pre is False:
                template = self._source.query_list().get(name)
                if template is None:
                    pre = None
                else:
                    tp = template.params
                    pre = (tuple(tp), PARAM_NAMESPACE in tp,
                           tuple(k for k in tp
                                 if k not in (PARAM_MODEL_ID,
                                              PARAM_NAMESPACE)))
                self._tpl_pre[name] = pre
            if pre is None:
                continue
            tparams, has_ns, extra_names = pre
            if any(p not in params for p in tparams):
                continue
            extras_key = (() if not extra_names else
                          tuple(sorted(  # fp-lint: bounded (tpl params)
                              (k, params[k]) for k in extra_names)))
            ns = params.get(PARAM_NAMESPACE, "") if has_ns else ""
            mkey = (name, extras_key)
            hit = self._vmap.get(mkey)
            if hit is None:
                # First model asking for this template this tick: resolve
                # the grouped plan and run (or version-reuse) the ONE
                # fleet-wide execution; every later model pays a dict hit.
                plan = self._grouped_plan(name, params)
                if plan is None:
                    self._vmap[mkey] = hit = ("excluded", None, None)
                else:
                    _, _, _, _, gq, key = plan
                    vmap = self._fp_versions(key, name, gq, params, has_ns)
                    if vmap is None:
                        # Failed execution: excluded this tick, like the
                        # legacy path (not memoized as a terminal state —
                        # the OnceMap already pins the failure per tick).
                        hit = ("excluded", None, None)
                    else:
                        hit = ("ok", vmap, key)
                        self._vmap[mkey] = hit
            state, versions, key = hit
            if state == "excluded":
                continue
            version = versions.get((model, ns))
            if version is None:
                # Model absent from this tick's demux: its slice is empty,
                # which must still version (present -> absent is a change).
                # Written back into the (cross-tick, book-memoized)
                # versions map so later models — and later quiet ticks
                # reusing the same memo — pay a dict hit, not a book
                # lock round-trip.
                version = self._book.version_for(key, (model, ns),
                                                 _EMPTY_DIGEST)
                versions[(model, ns)] = version
            parts.append((name, version))
        return tuple(parts)

    def slice_versions_bulk(self, queries,
                            pairs: list[tuple[str, str]],
                            ) -> dict[tuple[str, str], tuple]:
        """Template-major bulk form of :meth:`slice_versions` for the
        engine's partition pass: resolves each template ONCE, then walks
        the fleet with one dict lookup per (model, namespace) — the
        per-model re-walk of template params/plan/latch state is hoisted
        out of the O(models) loop entirely. Exclusion rules and version
        values are identical to per-model slice_versions with
        ``{model, namespace}`` params (the fingerprint queries' only
        shape)."""
        out: dict[tuple[str, str], list] = {p: [] for p in pairs}
        if not pairs:
            return {}
        for name in queries:
            first_model, first_ns = pairs[0]
            params = {PARAM_MODEL_ID: first_model,
                      PARAM_NAMESPACE: first_ns}
            plan = self._grouped_plan(name, params)
            if plan is None:
                continue
            template, _, _, has_ns, gq, key = plan
            if any(p not in params for p in template.params):
                continue
            versions = self._fp_versions(key, name, gq, params, has_ns)
            if versions is None:
                continue
            book = self._book
            for pair in pairs:
                model, ns = pair
                slice_key = (model, ns if has_ns else "")
                version = versions.get(slice_key)
                if version is None:
                    # Absent slice = empty digest; written back so later
                    # models and later memo-reusing ticks pay a dict hit.
                    version = book.version_for(key, slice_key,
                                               _EMPTY_DIGEST)
                    versions[slice_key] = version
                out[pair].append((name, version))
        return {p: tuple(parts) for p, parts in out.items()}

    def _demuxed(self, key, name: str, gq: GroupedQuery,
                 params: dict[str, str], has_ns: bool):
        """Memoized fleet-wide execution + demux for one (template,
        extras, scope) this tick. Concurrent callers for the same key wait
        on a latch instead of issuing duplicate backend queries."""
        return self._once.get_or_compute(
            key, lambda: self._execute(name, gq, params, has_ns, key=key))

    def _fp_versions(self, key, name: str, gq: GroupedQuery,
                     params: dict[str, str], has_ns: bool):
        """Fingerprint-tier access to this tick's slice versions: serves
        from the fingerprint-grade execution memo (value-version gate)
        when possible — the memoized versions are then current even
        though timestamps may not be, which the timestamp-free
        fingerprint never reads. Falls through to the full (collection-
        grade) execution otherwise. Returns the versions map or None
        when the execution failed / the book is off."""
        fp_key = ("fp",) + key

        def compute():
            book = self._book
            if book is not None and gq.metric_names:
                write_v = self._source.backend_write_version(
                    gq.metric_names)
                value_v = self._source.backend_value_version(
                    gq.metric_names)
                memo = book.reusable_fp(key, write_v, value_v,
                                        self._source.clock.now())
                if memo is not None:
                    return memo.versions
            demuxed = self._demuxed(key, name, gq, params, has_ns)
            if demuxed is None:
                return None
            return demuxed.get("__versions__")

        return self._once.get_or_compute(fp_key, compute)

    def _execute(self, name: str, gq: GroupedQuery, params: dict[str, str],
                 has_ns: bool, key: tuple | None = None,
                 organic: bool = True):
        collected_at = self._source.clock.now()
        book = self._book if key is not None else None
        write_version = value_version = None
        if book is not None and gq.metric_names:
            # Captured BEFORE evaluation: a write racing the query makes
            # the memo conservatively stale (re-executes next tick), never
            # silently fresh.
            write_version = self._source.backend_write_version(
                gq.metric_names)
            value_version = self._source.backend_value_version(
                gq.metric_names)
            memo = book.reusable(key, write_version, collected_at)
            if memo is not None:
                # Provably byte-identical evaluation (no writes/drops to
                # the query's metrics, no sample left its window): skip
                # the backend query, re-emit the memoized slices under a
                # fresh collected_at.
                return self._emit_demuxed(name, params, has_ns,
                                          memo.slices, collected_at,
                                          versions=memo.versions, key=key,
                                          organic=organic)
        qspan = (self._spans.begin_span("backend_query", template=name)
                 if self._spans is not None else None)
        try:
            points, meta = self._source.execute_grouped_tracked(
                name, gq.promql)
        except Exception as e:  # noqa: BLE001 — grouped failure falls back
            if self._spans is not None:
                self._spans.end_span(qspan, outcome="fallback")
            log.debug("grouped query %s failed (%s); falling back to "
                      "per-model collection", name, e)
            if book is not None:
                book.forget_execution(key)
            # Only DETERMINISTIC rejections (the backend executed or
            # parsed the query and said no) pin the template per-model for
            # the retry window. A transient transport blip must fall back
            # for this tick only — pinning on a timeout would amplify load
            # ~models-fold against a recovering backend for 10 minutes.
            if _is_deterministic_rejection(e):
                self._source.note_grouped_rejection(name, e)
            return None
        slices = demux_points(gq, points, self._source.make_metric_value)
        versions = None
        if book is not None:
            # Stamp slice digests in the same pass that already walked
            # every slice; a version bumps only when its digest moved.
            versions = book.stamp(key, {
                slice_key: _slice_digest(values)
                for slice_key, values in slices.items()})
            if (write_version is not None and value_version is not None
                    and meta is not None):
                book.note_execution(key, _ExecMemo(
                    write_version=write_version,
                    value_version=value_version,
                    expiry_strict=meta.expiry_strict,
                    expiry_b=meta.expiry_b,
                    uniform=meta.uniform,
                    slices=dict(slices), versions=versions))
        if self._spans is not None:
            # One span covers query + demux + digest stamping — the
            # collector's whole backend round-trip for this template.
            self._spans.end_span(qspan, slices=len(slices))
        return self._emit_demuxed(name, params, has_ns, slices,
                                  collected_at, versions=versions, key=key,
                                  organic=organic)

    def _emit_demuxed(self, name: str, params: dict[str, str], has_ns: bool,
                      slices: dict, collected_at: float, versions=None,
                      key: tuple | None = None, organic: bool = True):
        """Build the tick's demuxed map from per-slice value lists and
        refresh the per-model stale-serve cache entries."""
        demuxed: dict = {"__collected_at__": collected_at}
        if versions is not None:
            demuxed["__versions__"] = versions
        for (model, ns), values in slices.items():
            result = MetricResult(query_name=name, values=values,
                                  collected_at=collected_at)
            demuxed[(model, ns)] = result
            # Per-model stale-serve parity: each demuxed slice lands in the
            # source's cache under the SAME key the per-model path uses, so
            # an outage next tick serves the per-model stale entry.
            slice_params = dict(params)
            slice_params[PARAM_MODEL_ID] = model
            if has_ns:
                slice_params[PARAM_NAMESPACE] = ns
            self._source.store_demuxed_result(name, slice_params, result)
        if organic and key is not None:
            # Remember the grouped spec ONCE per execution (not once per
            # served model) so the background cache warmer re-executes the
            # fleet-wide query between ticks. Warmer executions come
            # through warm_grouped_spec with organic=False and never
            # renew. The view's versioned flag rides along so a warm pass
            # replays it — with WVA_FP_DELTA off the warmer must behave
            # pre-change too (no stamping, no reuse).
            self._source.remember_grouped_spec(
                name, dict(key[1]), self._scope_namespace,
                versioned=self._book is not None)
        return demuxed


def _is_deterministic_rejection(e: Exception) -> bool:
    """Did the backend actually REJECT the grouped form (4xx / query
    error), as opposed to failing transiently (timeout, connection
    reset)?"""
    if isinstance(e, urllib.error.HTTPError):
        return 400 <= e.code < 500
    if isinstance(e, PromQLError):
        return True  # in-memory engine refused the query shape
    # HTTPPromAPI surfaces a 200-with-error payload ("status": "error",
    # e.g. errorType bad_data) as this RuntimeError: the backend parsed
    # and refused the query.
    return isinstance(e, RuntimeError) and "prometheus query failed" in str(e)


def warm_grouped_spec(source, name: str, extras: dict[str, str],
                      scope_namespace: str = "",
                      versioned: bool = True) -> bool:
    """Re-execute one remembered fleet-wide query and refresh every demuxed
    per-model cache slice — the cache warmer's grouped path (with grouped
    collection on, per-model specs never reach the warmer, so without this
    the stale-serve cache would decay to tick cadence). ``versioned``
    replays the engine view's WVA_FP_DELTA state: with the lever off the
    warm pass must not touch the version book either. Returns False when
    the template is no longer groupable or the backend failed."""
    template = source.query_list().get(name)
    if template is None:
        return False
    gq = source.grouped_query_for(name, extras, scope_namespace)
    if gq is None:
        return False
    view = GroupedMetricsView(source, scope_namespace=scope_namespace,
                              versioned=versioned)
    has_ns = PARAM_NAMESPACE in template.params
    key = (name, tuple(sorted(extras.items())),  # fp-lint: bounded
           scope_namespace)                      # (template params)
    return view._execute(name, gq, dict(extras), has_ns, key=key,
                         organic=False) is not None
