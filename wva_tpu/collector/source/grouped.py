"""Grouped per-tick metrics collection (docs/design/metrics-plane.md).

Every model used to issue its own ~10 templated Prometheus queries per
engine tick, so a 48-model fleet fired ~480 HTTP queries per 5s tick —
exactly the per-job fan-out Autopilot (Rzadca et al., EuroSys 2020)
collapses into shared signal collection. This module makes the metrics
plane O(query templates) per tick instead of O(models x templates):

- :func:`build_grouped_query` rewrites a registered per-model template into
  ONE fleet-wide query by parsing it (the bundled PromQL-subset parser),
  dropping the ``model_name="..."``/``namespace="..."`` equality matchers
  (replaced by ``label!=""`` presence guards so series without the label
  never leak in), adding those labels to every enclosing aggregation's
  ``by`` clause, and serializing the AST back to PromQL.

- :class:`GroupedMetricsView` is a tick-scoped :class:`MetricsSource` view
  over a :class:`~wva_tpu.collector.source.prometheus.PrometheusSource`:
  the first caller needing a template this tick executes the fleet-wide
  query once; its result is demultiplexed into per-(model, namespace)
  ``MetricResult`` slices that serve every other caller — and each slice is
  cached under the SAME per-model cache key the per-model path uses, so
  stale-serve-on-error semantics are preserved per model. Templates the
  rewriter cannot group, and templates a backend rejected, automatically
  fall back to the existing per-model refresh path.

Demux reproduces per-model evaluation byte-for-byte: group labels are
stripped from every output point, and for multi-branch queries (a
top-level ``a or b`` of aggregations, e.g. the scheduler flow-control
pair) ``or``-preference is applied per model over the stripped label
identity — a right-branch point survives only when no earlier branch
produced the same series for that model.
"""

from __future__ import annotations

import logging
import urllib.error
from dataclasses import dataclass

from wva_tpu.collector.source.promql import (
    Aggregation,
    BinaryOp,
    FuncCall,
    NumberLiteral,
    PromQLError,
    Selector,
    parse_query,
    to_promql,
)
from wva_tpu.collector.source.query_template import (
    QUERY_TYPE_PROMQL,
    QueryTemplate,
    escape_promql_value,
)
from wva_tpu.collector.source.source import (
    PARAM_MODEL_ID,
    PARAM_NAMESPACE,
    MetricResult,
    MetricsSource,
    RefreshSpec,
)
from wva_tpu.utils.oncemap import OnceMap

log = logging.getLogger(__name__)

# Sentinel label values substituted for the per-model placeholders before
# parsing; the rewriter recognizes and removes the matchers carrying them.
MODEL_SENTINEL = "__wva_grouped_model__"
NS_SENTINEL = "__wva_grouped_namespace__"


class NotGroupableError(PromQLError):
    """The template's shape is outside the rewriter's rules; callers fall
    back to per-model collection."""


@dataclass(frozen=True)
class GroupedBranch:
    """Demux descriptor for one top-level aggregation branch: which output
    label carries the model id / namespace, and which labels to strip so
    the demuxed slice is byte-identical to the per-model result."""

    model_label: str
    ns_label: str  # "" when the template has no namespace dimension
    strip: tuple[str, ...]


@dataclass(frozen=True)
class GroupedQuery:
    promql: str
    branches: tuple[GroupedBranch, ...]
    has_namespace: bool


def _merge_pending(into: dict[str, str], kind: str, label: str) -> None:
    prev = into.get(kind)
    if prev is not None and prev != label:
        raise NotGroupableError(
            f"conflicting {kind} labels {prev!r} vs {label!r}")
    into[kind] = label


def _rewrite(node, scope_namespace: str = "",
             ) -> tuple[list[GroupedBranch], dict[str, str]]:
    """Transform ``node`` in place. Returns (branches absorbed by
    aggregations in this subtree, sentinel labels still pending an
    enclosing aggregation)."""
    if isinstance(node, NumberLiteral):
        # `vector(N)` parses into NumberLiteral, so serialization would
        # lose the vector() wrapper — and a bare scalar operand under `or`
        # is invalid PromQL on a real backend. Refuse; the template stays
        # per-model.
        raise NotGroupableError("scalar / vector() operand")
    if isinstance(node, Selector):
        pending: dict[str, str] = {}
        matchers: list[tuple[str, str, str]] = []
        for lbl, op, val in node.matchers:
            if val in (MODEL_SENTINEL, NS_SENTINEL):
                if op != "=":
                    raise NotGroupableError(
                        f"non-equality matcher {op!r} on grouped param")
                kind = "model" if val == MODEL_SENTINEL else "ns"
                _merge_pending(pending, kind, lbl)
                if kind == "ns" and scope_namespace:
                    # A namespace-scoped controller keeps its scope as an
                    # equality matcher — on a shared multi-tenant
                    # Prometheus the fleet-wide query must not aggregate
                    # every other tenant's series.
                    matchers.append((lbl, "=", scope_namespace))
                else:
                    # Presence guard: the dropped equality matcher also
                    # implied the label exists and is non-empty
                    # (Prometheus treats a missing label as ""), so series
                    # without it must stay out of the fleet-wide result.
                    matchers.append((lbl, "!=", ""))
            else:
                matchers.append((lbl, op, val))
        node.matchers = matchers
        return [], pending
    if isinstance(node, FuncCall):
        return _rewrite(node.arg, scope_namespace)
    if isinstance(node, Aggregation):
        branches, pending = _rewrite(node.arg, scope_namespace)
        if branches:
            # An aggregation ABOVE an already-grouped aggregation would
            # collapse the models back together; no registered template
            # nests aggregations, so bail to per-model collection.
            raise NotGroupableError("nested aggregation above a grouped one")
        if pending:
            model_label = pending.get("model")
            if model_label is None:
                raise NotGroupableError("namespace param without a model "
                                        "param under one aggregation")
            ns_label = pending.get("ns", "")
            group_labels = [model_label] + ([ns_label] if ns_label else [])
            for lbl in group_labels:
                if lbl not in node.by:
                    node.by.append(lbl)
            branches = [GroupedBranch(model_label, ns_label,
                                      tuple(group_labels))]
            pending = {}
        return branches, pending
    if isinstance(node, BinaryOp):
        left_branches, left_pending = _rewrite(node.left, scope_namespace)
        right_branches, right_pending = _rewrite(node.right, scope_namespace)
        merged = dict(left_pending)
        for kind, label in right_pending.items():
            _merge_pending(merged, kind, label)
        return left_branches + right_branches, merged
    raise NotGroupableError(f"unsupported node {node!r}")


def build_grouped_query(template: QueryTemplate,
                        extra_params: dict[str, str],
                        scope_namespace: str = "") -> GroupedQuery | None:
    """Rewrite one registered per-model template into its fleet-wide
    grouped form, or None when the template is outside the rewrite rules.
    ``extra_params`` are the template's non-model/namespace parameters
    (e.g. ``retentionPeriod``), substituted before parsing — the grouped
    query is memoized per distinct extra-param set. ``scope_namespace``
    (a namespace-scoped controller's watch namespace) is kept as an
    equality matcher instead of the fleet-wide presence guard."""
    if template.type != QUERY_TYPE_PROMQL:
        return None
    if PARAM_MODEL_ID not in template.params:
        return None
    text = template.template
    text = text.replace("{{." + PARAM_MODEL_ID + "}}", MODEL_SENTINEL)
    has_namespace = PARAM_NAMESPACE in template.params
    if has_namespace:
        text = text.replace("{{." + PARAM_NAMESPACE + "}}", NS_SENTINEL)
    for key, value in extra_params.items():
        text = text.replace("{{." + key + "}}", escape_promql_value(value))
    if "{{." in text:
        return None  # unsubstituted params left: not safely groupable
    try:
        ast = parse_query(text)
        branches, pending = _rewrite(ast, scope_namespace)
        if pending:
            raise NotGroupableError("model matcher outside any aggregation")
        if not branches:
            raise NotGroupableError("no model matcher found in template")
    except PromQLError as e:
        log.debug("template %s not groupable: %s", template.name, e)
        return None
    # Deduplicate identical branches (e.g. both sides of a division absorb
    # the same labels) while preserving or-preference order.
    seen: set[tuple[str, str]] = set()
    unique: list[GroupedBranch] = []
    for b in branches:
        if (b.model_label, b.ns_label) not in seen:
            seen.add((b.model_label, b.ns_label))
            unique.append(b)
    return GroupedQuery(promql=to_promql(ast), branches=tuple(unique),
                        has_namespace=has_namespace)


def demux_points(gq: GroupedQuery, points, make_value):
    """Split one grouped result into per-(model, namespace) value lists.

    ``make_value(labels, point)`` builds the per-model output element from
    the stripped labels; point order within a slice follows branch order
    then backend order, matching per-model ``left or right`` evaluation.
    Returns ``{(model, namespace): [value, ...]}`` (namespace "" when the
    template has no namespace dimension)."""
    assigned: dict[tuple[str, str], list[tuple[int, tuple, object]]] = {}
    for p in points:
        for bi, branch in enumerate(gq.branches):
            model = p.labels.get(branch.model_label)
            if not model:
                continue
            ns = p.labels.get(branch.ns_label, "") if branch.ns_label else ""
            stripped = {k: v for k, v in p.labels.items()
                        if k not in branch.strip}
            identity = tuple(sorted(stripped.items()))
            assigned.setdefault((model, ns), []).append(
                (bi, identity, make_value(stripped, p)))
            break
    out: dict[tuple[str, str], list] = {}
    for key, entries in assigned.items():
        # Branch-major order (stable: backend order preserved within a
        # branch) — real Prometheus does not guarantee or-result ordering.
        entries.sort(key=lambda e: e[0])
        kept: list = []
        seen_earlier: set[tuple] = set()
        current: set[tuple] = set()
        last_branch = -1
        for bi, identity, value in entries:  # entries keep backend order
            if bi != last_branch:
                seen_earlier |= current
                current = set()
                last_branch = bi
            if identity in seen_earlier:
                continue  # or-preference: an earlier branch won this series
            current.add(identity)
            kept.append(value)
        out[key] = kept
    return out


class GroupedMetricsView(MetricsSource):
    """Tick-scoped grouped-collection view over a PrometheusSource.

    Construct one per engine tick and hand it to every collector call site;
    it is thread-safe (the engine's analysis workers race into it), and the
    first worker to need a template runs the fleet-wide query while the
    rest wait on the per-template latch. Anything non-groupable delegates
    to the wrapped source unchanged, so disabling grouping is equivalent to
    bypassing the view entirely."""

    def __init__(self, source, scope_namespace: str = "") -> None:
        self._source = source
        # Namespace-scoped controllers keep their watch namespace as an
        # equality matcher in the fleet-wide queries (shared-Prometheus
        # tenancy: never aggregate other tenants' series).
        self._scope_namespace = scope_namespace
        # (name, extras) -> demuxed {(model, ns): MetricResult} | None when
        # the grouped execution failed this tick (per-model fallback).
        self._once = OnceMap()

    # --- MetricsSource ---

    def query_list(self):
        return self._source.query_list()

    def get(self, query_name: str, params: dict[str, str]):
        return self._source.get(query_name, params)

    def refresh(self, spec: RefreshSpec) -> dict[str, MetricResult]:
        names = list(spec.queries) or self._source.query_list().names()
        results: dict[str, MetricResult] = {}
        passthrough: list[str] = []
        for name in names:
            served = self._serve_grouped(name, spec.params)
            if served is None:
                passthrough.append(name)
            else:
                results[name] = served
        if passthrough:
            results.update(self._source.refresh(
                RefreshSpec(queries=passthrough, params=dict(spec.params))))
        return results

    # --- grouped execution ---

    def _serve_grouped(self, name: str,
                       params: dict[str, str]) -> MetricResult | None:
        """The per-model slice for ``params`` from this tick's fleet-wide
        result, or None to delegate to the per-model path."""
        template = self._source.query_list().get(name)
        if template is None or template.type != QUERY_TYPE_PROMQL:
            return None
        if PARAM_MODEL_ID not in template.params:
            return None
        model = params.get(PARAM_MODEL_ID)
        if not model:
            return None
        for p in template.params:
            if p not in params:
                return None  # let the per-model path raise its usual error
        has_ns = PARAM_NAMESPACE in template.params
        ns = params.get(PARAM_NAMESPACE, "") if has_ns else ""
        extras = {k: params[k] for k in template.params
                  if k not in (PARAM_MODEL_ID, PARAM_NAMESPACE)}
        gq = self._source.grouped_query_for(name, extras,
                                            self._scope_namespace)
        if gq is None:
            return None
        key = (name, tuple(sorted(extras.items())))
        demuxed = self._demuxed(key, name, gq, params, has_ns)
        if demuxed is None:
            return None  # grouped execution failed: per-model fallback
        # Organic serve: remember the grouped spec so the background cache
        # warmer re-executes the fleet-wide query (refreshing EVERY
        # demuxed per-model slice) between ticks — the grouped twin of
        # _remember_spec on the per-model path. Warmer executions go
        # through warm_grouped_spec/_execute and never renew.
        self._source.remember_grouped_spec(name, extras,
                                           self._scope_namespace)
        result = demuxed.get((model, ns))
        if result is None:
            # Same outcome the per-model query would produce: an empty
            # (but successful) result — cached under the per-model key so
            # a later backend outage stale-serves "no data", not ancient
            # data.
            result = MetricResult(query_name=name, values=[],
                                  collected_at=demuxed["__collected_at__"])
            self._source.store_demuxed_result(name, dict(params), result)
        return result

    def slice_fingerprint(self, queries, params: dict[str, str]) -> tuple:
        """Digest of this tick's demuxed slices for ``params`` across
        ``queries`` — the metrics component of the engine's dirty-set
        fingerprint (docs/design/informer.md). Serving goes through the
        same memoized fleet-wide execution the collectors use, so
        fingerprinting costs zero extra backend queries on a tick that
        analyzes anything. Hashes (labels, value) only — never collection
        timestamps, which move every tick even when the data does not.
        Ungroupable / failed / param-incomplete templates are excluded
        (stably, so their absence cannot churn the digest)."""
        parts: list[tuple] = []
        for name in queries:
            template = self._source.query_list().get(name)
            if template is None:
                continue
            if any(p not in params for p in template.params):
                continue
            sliced = self._serve_grouped(name, params)
            if sliced is None:
                continue
            values = tuple(sorted(
                (tuple(sorted(v.labels.items())), v.value)
                for v in sliced.values))
            parts.append((name, values))
        return tuple(parts)

    def _demuxed(self, key, name: str, gq: GroupedQuery,
                 params: dict[str, str], has_ns: bool):
        """Memoized fleet-wide execution + demux for one (template, extras)
        this tick. Concurrent callers for the same key wait on a latch
        instead of issuing duplicate backend queries."""
        return self._once.get_or_compute(
            key, lambda: self._execute(name, gq, params, has_ns))

    def _execute(self, name: str, gq: GroupedQuery, params: dict[str, str],
                 has_ns: bool):
        collected_at = self._source.clock.now()
        try:
            points = self._source.execute_grouped(name, gq.promql)
        except Exception as e:  # noqa: BLE001 — grouped failure falls back
            log.debug("grouped query %s failed (%s); falling back to "
                      "per-model collection", name, e)
            # Only DETERMINISTIC rejections (the backend executed or
            # parsed the query and said no) pin the template per-model for
            # the retry window. A transient transport blip must fall back
            # for this tick only — pinning on a timeout would amplify load
            # ~models-fold against a recovering backend for 10 minutes.
            if _is_deterministic_rejection(e):
                self._source.note_grouped_rejection(name, e)
            return None
        slices = demux_points(gq, points, self._source.make_metric_value)
        demuxed: dict = {"__collected_at__": collected_at}
        for (model, ns), values in slices.items():
            result = MetricResult(query_name=name, values=values,
                                  collected_at=collected_at)
            demuxed[(model, ns)] = result
            # Per-model stale-serve parity: each demuxed slice lands in the
            # source's cache under the SAME key the per-model path uses, so
            # an outage next tick serves the per-model stale entry.
            slice_params = dict(params)
            slice_params[PARAM_MODEL_ID] = model
            if has_ns:
                slice_params[PARAM_NAMESPACE] = ns
            self._source.store_demuxed_result(name, slice_params, result)
        return demuxed


def _is_deterministic_rejection(e: Exception) -> bool:
    """Did the backend actually REJECT the grouped form (4xx / query
    error), as opposed to failing transiently (timeout, connection
    reset)?"""
    if isinstance(e, urllib.error.HTTPError):
        return 400 <= e.code < 500
    if isinstance(e, PromQLError):
        return True  # in-memory engine refused the query shape
    # HTTPPromAPI surfaces a 200-with-error payload ("status": "error",
    # e.g. errorType bad_data) as this RuntimeError: the backend parsed
    # and refused the query.
    return isinstance(e, RuntimeError) and "prometheus query failed" in str(e)


def warm_grouped_spec(source, name: str, extras: dict[str, str],
                      scope_namespace: str = "") -> bool:
    """Re-execute one remembered fleet-wide query and refresh every demuxed
    per-model cache slice — the cache warmer's grouped path (with grouped
    collection on, per-model specs never reach the warmer, so without this
    the stale-serve cache would decay to tick cadence). Returns False when
    the template is no longer groupable or the backend failed."""
    template = source.query_list().get(name)
    if template is None:
        return False
    gq = source.grouped_query_for(name, extras, scope_namespace)
    if gq is None:
        return False
    view = GroupedMetricsView(source, scope_namespace=scope_namespace)
    has_ns = PARAM_NAMESPACE in template.params
    return view._execute(name, gq, dict(extras), has_ns) is not None
