"""Metrics-source abstraction (reference ``internal/collector/source``)."""

from wva_tpu.collector.source.source import (
    PARAM_MODEL_ID,
    PARAM_NAMESPACE,
    PARAM_POD_FILTER,
    MetricResult,
    MetricValue,
    MetricsSource,
    RefreshSpec,
)
from wva_tpu.collector.source.query_template import (
    QUERY_TYPE_METRIC_NAME,
    QUERY_TYPE_PROMQL,
    QueryList,
    QueryTemplate,
    escape_promql_value,
)
from wva_tpu.collector.source.cache import CachedValue, MetricsCache, cache_key
from wva_tpu.collector.source.grouped import (
    GroupedMetricsView,
    GroupedQuery,
    build_grouped_query,
)
from wva_tpu.collector.source.registry import PROMETHEUS_SOURCE_NAME, SourceRegistry
from wva_tpu.collector.source.prometheus import (
    HTTPPromAPI,
    InMemoryPromAPI,
    PrometheusSource,
    parse_prometheus_response,
)
from wva_tpu.collector.source.promql import (
    PromQLEngine,
    PromQLError,
    SeriesPoint,
    TimeSeriesDB,
    format_promql_duration,
    parse_promql_duration,
)
from wva_tpu.collector.source.pod_scrape import (
    ALL_METRICS_QUERY,
    PodScrapingSource,
    http_pod_fetcher,
    parse_prometheus_text,
)
from wva_tpu.collector.source.pod_va_mapper import PodVAMapper
from wva_tpu.collector.source.noop import NoopSource

__all__ = [
    "PARAM_MODEL_ID",
    "PARAM_NAMESPACE",
    "PARAM_POD_FILTER",
    "MetricResult",
    "MetricValue",
    "MetricsSource",
    "RefreshSpec",
    "QUERY_TYPE_METRIC_NAME",
    "QUERY_TYPE_PROMQL",
    "QueryList",
    "QueryTemplate",
    "escape_promql_value",
    "CachedValue",
    "MetricsCache",
    "cache_key",
    "GroupedMetricsView",
    "GroupedQuery",
    "build_grouped_query",
    "PROMETHEUS_SOURCE_NAME",
    "SourceRegistry",
    "HTTPPromAPI",
    "InMemoryPromAPI",
    "PrometheusSource",
    "parse_prometheus_response",
    "PromQLEngine",
    "PromQLError",
    "SeriesPoint",
    "TimeSeriesDB",
    "format_promql_duration",
    "parse_promql_duration",
    "ALL_METRICS_QUERY",
    "PodScrapingSource",
    "http_pod_fetcher",
    "parse_prometheus_text",
    "PodVAMapper",
    "NoopSource",
]
