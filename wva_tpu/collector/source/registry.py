"""Named registry of MetricsSources
(reference ``internal/collector/source/registry.go:19-58``): "prometheus"
plus one pod-scraping source per InferencePool.
"""

from __future__ import annotations

import threading

from wva_tpu.collector.source.source import MetricsSource

PROMETHEUS_SOURCE_NAME = "prometheus"


class SourceRegistry:
    def __init__(self) -> None:
        self._mu = threading.RLock()
        self._sources: dict[str, MetricsSource] = {}

    def register(self, name: str, source: MetricsSource) -> None:
        if not name:
            raise ValueError("source name is required")
        with self._mu:
            if name in self._sources:
                raise ValueError(f"source {name!r} already registered")
            self._sources[name] = source

    def register_if_absent(self, name: str, source_factory) -> MetricsSource:
        """Atomic check-and-register; returns the winning source. The factory
        is only invoked when the name is free."""
        if not name:
            raise ValueError("source name is required")
        with self._mu:
            existing = self._sources.get(name)
            if existing is not None:
                return existing
            created = source_factory()
            self._sources[name] = created
            return created

    def get(self, name: str) -> MetricsSource | None:
        with self._mu:
            return self._sources.get(name)

    def unregister(self, name: str) -> None:
        with self._mu:
            self._sources.pop(name, None)

    def names(self) -> list[str]:
        with self._mu:
            return sorted(self._sources)
