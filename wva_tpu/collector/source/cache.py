"""TTL cache for query results
(reference ``internal/collector/source/{cache,cache_value}.go``).

Cleanup is opportunistic (on writes) plus an explicit ``cleanup()`` the owner
can call periodically — no background thread, so simulated-clock runs stay
deterministic.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from wva_tpu.collector.source.source import MetricResult
from wva_tpu.utils.clock import SYSTEM_CLOCK, Clock


@dataclass
class CachedValue:
    result: MetricResult
    cached_at: float

    def age(self, clock: Clock) -> float:
        return clock.now() - self.cached_at


def cache_key(query_name: str, params: dict[str, str]) -> str:
    """Key = query name + sorted params (reference cache_value.go)."""
    if not params:
        return query_name
    parts = [f"{k}={v}" for k, v in sorted(params.items())]
    return query_name + "?" + "&".join(parts)


class MetricsCache:
    def __init__(self, ttl: float = 30.0, cleanup_interval: float = 60.0,
                 clock: Clock | None = None) -> None:
        self.ttl = ttl
        self.cleanup_interval = cleanup_interval
        self.clock = clock or SYSTEM_CLOCK
        self._mu = threading.RLock()
        self._values: dict[str, CachedValue] = {}
        self._last_cleanup = self.clock.now()

    def set(self, query_name: str, params: dict[str, str], result: MetricResult) -> None:
        now = self.clock.now()
        with self._mu:
            self._values[cache_key(query_name, params)] = CachedValue(result, now)
            if now - self._last_cleanup >= self.cleanup_interval:
                self._cleanup_locked(now)

    def get(self, query_name: str, params: dict[str, str]) -> CachedValue | None:
        with self._mu:
            cached = self._values.get(cache_key(query_name, params))
            if cached is None:
                return None
            if cached.age(self.clock) > self.ttl:
                return None
            return cached

    def get_stale(self, query_name: str, params: dict[str, str],
                  max_age: float) -> CachedValue | None:
        """Entry lookup ignoring the TTL, bounded by ``max_age`` — the
        serve-stale-on-error path (a Prometheus blip should ride on the
        last good result rather than skip a whole analysis tick, up to the
        configured unavailable threshold)."""
        with self._mu:
            cached = self._values.get(cache_key(query_name, params))
            if cached is None or cached.age(self.clock) > max_age:
                return None
            return cached

    def peek(self, query_name: str, params: dict[str, str],
             ) -> CachedValue | None:
        """Entry lookup ignoring BOTH the TTL and the stale-serve bound —
        the input-health plane's age probe (how old is the newest data we
        could possibly be deciding on?). Never used to serve data."""
        with self._mu:
            return self._values.get(cache_key(query_name, params))

    def cleanup(self) -> int:
        """Evict expired entries; returns evicted count."""
        with self._mu:
            return self._cleanup_locked(self.clock.now())

    # Entries are kept past the TTL for get_stale's serve-on-error
    # fallback; plain get() still refuses anything > ttl. The retention
    # floor keeps the stale-serve window intact even under a tiny TTL —
    # callers that need a longer window (the unavailable threshold) set
    # min_retention accordingly.
    STALE_RETENTION_FACTOR = 20.0
    min_retention: float = 0.0

    def _cleanup_locked(self, now: float) -> int:
        bound = max(self.ttl * self.STALE_RETENTION_FACTOR,
                    self.min_retention)
        expired = [k for k, v in self._values.items()
                   if now - v.cached_at > bound]
        for k in expired:
            del self._values[k]
        self._last_cleanup = now
        return len(expired)

    def __len__(self) -> int:
        with self._mu:
            return len(self._values)
