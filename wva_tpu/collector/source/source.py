"""MetricsSource interface + result types
(reference ``internal/collector/source/source.go:14-130``).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field

from wva_tpu.utils.clock import SYSTEM_CLOCK, Clock

# Common parameter names (reference query_template.go:18-22).
PARAM_NAMESPACE = "namespace"
PARAM_MODEL_ID = "modelID"
PARAM_POD_FILTER = "podFilter"


@dataclass
class MetricValue:
    """A single sample with backend timestamp + labels."""

    value: float = 0.0
    timestamp: float = 0.0  # backend sample time; 0 = unknown
    labels: dict[str, str] = field(default_factory=dict)

    def age(self, clock: Clock = SYSTEM_CLOCK) -> float:
        return 0.0 if self.timestamp == 0 else clock.now() - self.timestamp

    def is_stale(self, threshold: float, clock: Clock = SYSTEM_CLOCK) -> bool:
        if self.timestamp == 0:
            return True
        return self.age(clock) > threshold


@dataclass
class MetricResult:
    """Result of one query: one value per returned series."""

    query_name: str = ""
    values: list[MetricValue] = field(default_factory=list)
    collected_at: float = 0.0
    error: str = ""

    def has_error(self) -> bool:
        return bool(self.error)

    def first_value(self) -> MetricValue:
        return self.values[0] if self.values else MetricValue()

    def oldest_timestamp(self) -> float:
        if not self.values:
            return 0.0
        return min(v.timestamp for v in self.values)

    def is_stale(self, threshold: float, clock: Clock = SYSTEM_CLOCK) -> bool:
        if not self.values:
            return True
        return any(v.is_stale(threshold, clock) for v in self.values)


@dataclass
class RefreshSpec:
    """Which queries to refresh with what parameters; empty = all registered."""

    queries: list[str] = field(default_factory=list)
    params: dict[str, str] = field(default_factory=dict)


class MetricsSource(abc.ABC):
    """A metrics backend: registered queries + refresh + cached reads."""

    @abc.abstractmethod
    def query_list(self):
        """The QueryList registry for this source."""

    @abc.abstractmethod
    def refresh(self, spec: RefreshSpec) -> dict[str, MetricResult]:
        """Execute queries (all registered if spec.queries empty), update the
        cache, return name -> result."""

    @abc.abstractmethod
    def get(self, query_name: str, params: dict[str, str]):
        """Cached value for (query, params) or None if absent/expired. The
        returned value must not be modified."""
