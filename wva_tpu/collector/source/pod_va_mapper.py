"""Pod -> owning VariantAutoscaling mapping
(reference ``internal/collector/source/pod_va_mapper.go:32-99``).

Walks pod ownerReferences up (ReplicaSet -> Deployment, or Deployment
directly) and resolves the VA through the scale-target index.
"""

from __future__ import annotations

import logging

from wva_tpu.api.v1alpha1 import VariantAutoscaling
from wva_tpu.indexers import Indexer
from wva_tpu.k8s.client import KubeClient, NotFoundError
from wva_tpu.k8s.objects import Pod

log = logging.getLogger(__name__)


class PodVAMapper:
    def __init__(self, client: KubeClient, indexer: Indexer) -> None:
        self.client = client
        self.indexer = indexer

    def deployment_for_pod(self, pod: Pod) -> str | None:
        """Owning Deployment name, walking Pod -> ReplicaSet -> Deployment."""
        for ref in pod.metadata.owner_references:
            kind = ref.get("kind", "")
            name = ref.get("name", "")
            if kind == "Deployment":
                return name
            if kind == "ReplicaSet":
                # K8s convention: ReplicaSet name = "<deployment>-<hash>".
                # Resolve through the stored ReplicaSet when present, else
                # strip the trailing hash segment.
                try:
                    rs = self.client.get("ReplicaSet", pod.metadata.namespace, name)
                    for rs_ref in rs.metadata.owner_references:
                        if rs_ref.get("kind") == "Deployment":
                            return rs_ref.get("name")
                except NotFoundError:
                    pass
                if "-" in name:
                    return name.rsplit("-", 1)[0]
        return None

    def va_for_pod(self, pod: Pod,
                   tracked_deployments: set[str] | None = None) -> VariantAutoscaling | None:
        """The VA whose scale target owns the pod, or None. When
        ``tracked_deployments`` is given, the deployment must be in it
        (reference :72-84)."""
        deploy_name = self.deployment_for_pod(pod)
        if not deploy_name:
            log.debug("pod %s has no Deployment owner", pod.metadata.name)
            return None
        if tracked_deployments is not None and deploy_name not in tracked_deployments:
            return None
        return self.indexer.find_va_for_deployment(deploy_name, pod.metadata.namespace)
