"""Pod -> owning VariantAutoscaling mapping
(reference ``internal/collector/source/pod_va_mapper.go:32-99``).

Walks pod ownerReferences up (ReplicaSet -> Deployment, or Deployment
directly) and resolves the VA through the scale-target index.
"""

from __future__ import annotations

import logging

from wva_tpu.api.v1alpha1 import CrossVersionObjectReference, VariantAutoscaling
from wva_tpu.indexers import Indexer
from wva_tpu.k8s.client import KubeClient, NotFoundError
from wva_tpu.k8s.objects import LeaderWorkerSet, Pod

log = logging.getLogger(__name__)


class PodVAMapper:
    def __init__(self, client: KubeClient, indexer: Indexer) -> None:
        self.client = client
        self.indexer = indexer

    def deployment_for_pod(self, pod: Pod) -> str | None:
        """Owning scale-target name, walking Pod -> ReplicaSet -> Deployment.
        Multi-host slice pods are owned by their LeaderWorkerSet directly
        (emulation convention); on a real cluster LWS interposes a per-group
        StatefulSet named "<lws>-<group>", resolved through the stored
        StatefulSet's owner or the trailing-segment strip."""
        for ref in pod.metadata.owner_references:
            kind = ref.get("kind", "")
            name = ref.get("name", "")
            if kind in ("Deployment", "LeaderWorkerSet"):
                return name
            if kind == "StatefulSet":
                try:
                    sts = self.client.get("StatefulSet", pod.metadata.namespace, name)
                    for sts_ref in sts.metadata.owner_references:
                        if sts_ref.get("kind") == LeaderWorkerSet.KIND:
                            return sts_ref.get("name")
                except NotFoundError:
                    pass
                return name.rsplit("-", 1)[0] if "-" in name else name
            if kind == "ReplicaSet":
                # K8s convention: ReplicaSet name = "<deployment>-<hash>".
                # Resolve through the stored ReplicaSet when present, else
                # strip the trailing hash segment.
                try:
                    rs = self.client.get("ReplicaSet", pod.metadata.namespace, name)
                    for rs_ref in rs.metadata.owner_references:
                        if rs_ref.get("kind") == "Deployment":
                            return rs_ref.get("name")
                except NotFoundError:
                    pass
                if "-" in name:
                    return name.rsplit("-", 1)[0]
        return None

    def va_for_pod(self, pod: Pod,
                   tracked_deployments: set[str] | None = None) -> VariantAutoscaling | None:
        """The VA whose scale target owns the pod, or None. When
        ``tracked_deployments`` is given, the deployment must be in it
        (reference :72-84)."""
        deploy_name = self.deployment_for_pod(pod)
        if not deploy_name:
            log.debug("pod %s has no Deployment owner", pod.metadata.name)
            return None
        if tracked_deployments is not None and deploy_name not in tracked_deployments:
            return None
        return self.va_for_scale_target_name(deploy_name, pod.metadata.namespace)

    def va_name_for_pod(self, pod: Pod,
                        tracked_deployments: set[str] | None = None,
                        ) -> str | None:
        """Like :meth:`va_for_pod` but resolves only the VA NAME from the
        index — zero API requests. The replica-metrics join runs once per
        pod per tick and consumes nothing but the name, so the full-object
        fetch there was one GET per pod per tick at fleet scale."""
        deploy_name = self.deployment_for_pod(pod)
        if not deploy_name:
            log.debug("pod %s has no Deployment owner", pod.metadata.name)
            return None
        if tracked_deployments is not None and deploy_name not in tracked_deployments:
            return None
        return self.va_name_for_scale_target_name(
            deploy_name, pod.metadata.namespace)

    def va_name_for_scale_target_name(self, name: str,
                                      namespace: str) -> str | None:
        """Index-only name resolution across the supported kinds (the
        Deployment key first, then LeaderWorkerSet)."""
        va_name = self.indexer.find_va_name_for_scale_target(
            CrossVersionObjectReference(kind="Deployment", name=name,
                                        api_version="apps/v1"), namespace)
        if va_name is None:
            va_name = self.indexer.find_va_name_for_scale_target(
                CrossVersionObjectReference(
                    kind=LeaderWorkerSet.KIND, name=name,
                    api_version=LeaderWorkerSet.API_VERSION),
                namespace)
        return va_name

    def va_for_scale_target_name(self, name: str,
                                 namespace: str) -> VariantAutoscaling | None:
        """Resolve a VA by scale-target NAME across the supported kinds:
        the Deployment index key first, then the LeaderWorkerSet key (the
        index is keyed namespace/apiVersion/kind/name). Layered on the
        name-only resolution so the kind-fallback chain exists once."""
        va_name = self.va_name_for_scale_target_name(name, namespace)
        if va_name is None:
            return None
        try:
            return self.client.get(VariantAutoscaling.kind, namespace, va_name)
        except NotFoundError:
            return None
