"""Prometheus metrics source
(reference ``internal/collector/source/prometheus/prometheus_source.go:40-322``).

The source executes registered PromQL queries against a ``PromAPI`` backend and
caches results with a TTL. Two backends:

- :class:`HTTPPromAPI` — real Prometheus over ``/api/v1/query`` (urllib, 10s
  timeout, bearer token), parsing vector/scalar/matrix with NaN -> 0.
- :class:`InMemoryPromAPI` — the TSDB-lite + PromQL-subset engine
  (:mod:`wva_tpu.collector.source.promql`), used by tests, the emulation
  harness, and bench.
"""

from __future__ import annotations

import json
import logging
import math
import ssl
import threading
import urllib.error
import urllib.parse
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Protocol

from wva_tpu.collector.source.cache import MetricsCache
from wva_tpu.collector.source.promql import PromQLEngine, SeriesPoint, TimeSeriesDB
from wva_tpu.collector.source.query_template import QueryList, escape_promql_value
from wva_tpu.collector.source.source import (
    MetricResult,
    MetricValue,
    MetricsSource,
    RefreshSpec,
)
from wva_tpu.config.types import CacheConfig
from wva_tpu.utils.clock import SYSTEM_CLOCK, Clock

log = logging.getLogger(__name__)

DEFAULT_QUERY_TIMEOUT_SECONDS = 10.0
DEFAULT_CACHE_TTL_SECONDS = 30.0


class PromAPI(Protocol):
    def query(self, promql: str) -> list[SeriesPoint]:
        """Evaluate an instant query; raises on backend errors."""


class InMemoryPromAPI:
    """PromAPI over the in-memory TSDB."""

    def __init__(self, db: TimeSeriesDB) -> None:
        self.db = db
        self.engine = PromQLEngine(db)

    def query(self, promql: str) -> list[SeriesPoint]:
        return self.engine.query(promql)

    # --- versioned fingerprint plane hooks (docs/design/informer.md) ---

    def write_version(self, names) -> int:
        """Max TSDB write-version across ``names`` — the grouped view's
        proof that nothing was written between two executions."""
        return self.db.name_write_version(names)

    def value_version(self, names) -> int:
        """Max TSDB value-version across ``names`` (moves only on
        value-changing appends) — the fingerprint tier's reuse gate."""
        return self.db.name_value_version(names)

    def query_tracked(self, promql: str):
        """(points, TrackMeta) — validity metadata bounding how long the
        result provably stays current without (value-changing) writes.
        Routed through ``self.query`` so instance-level wrappers (test
        fault injection) still intercept the evaluation."""
        self.engine.begin_tracking()
        try:
            points = self.query(promql)
        finally:
            meta = self.engine.end_tracking()
        return points, meta

    def lookback_seconds(self) -> float:
        return self.engine.lookback


class _ServerNameContext(ssl.SSLContext):
    """SSLContext that pins the SNI/verification hostname regardless of the
    URL host — the in-cluster pattern where Prometheus is reached through a
    Service IP while its certificate names the Service DNS (reference
    ``internal/utils/tls.go:28`` ServerName)."""

    server_name: str = ""

    def wrap_socket(self, *args, **kwargs):  # noqa: D102
        if self.server_name:
            kwargs["server_hostname"] = self.server_name
        return super().wrap_socket(*args, **kwargs)


class HTTPPromAPI:
    """PromAPI over a real Prometheus HTTP endpoint.

    TLS matches the reference's custom transport
    (``internal/utils/prometheus_transport.go:18-79`` +
    ``internal/utils/tls.go:21-70``): custom CA bundle, optional client
    certificate (mTLS), SNI server-name override, TLS >= 1.2, and an
    insecure-skip-verify escape hatch for dev clusters. ``token_path``
    reads the bearer token from a file PER QUERY, so rotated
    BoundServiceAccountToken projections are picked up without a restart
    (the reference reads the file once at startup,
    ``prometheus_transport.go:50-58``; documented divergence).

    Queries go as POST form-encoded bodies by default (real Prometheus
    accepts both verbs on ``/api/v1/query``): fleet-wide grouped queries
    with many ``or``-joined metric families can exceed practical URL
    limits as GET query strings. ``use_get=True`` restores GET for
    read-only proxies that reject POST (PROMETHEUS_USE_GET_QUERIES)."""

    def __init__(self, base_url: str, bearer_token: str = "",
                 timeout: float = DEFAULT_QUERY_TIMEOUT_SECONDS,
                 insecure_skip_verify: bool = False,
                 ca_cert_path: str = "",
                 client_cert_path: str = "", client_key_path: str = "",
                 server_name: str = "", token_path: str = "",
                 use_get: bool = False) -> None:
        self.base_url = base_url.rstrip("/")
        self.bearer_token = bearer_token
        self.token_path = token_path
        self.timeout = timeout
        self.use_get = use_get
        self._ssl_ctx = None
        if insecure_skip_verify:
            self._ssl_ctx = ssl.create_default_context()
            self._ssl_ctx.check_hostname = False
            self._ssl_ctx.verify_mode = ssl.CERT_NONE
        elif ca_cert_path or client_cert_path or server_name:
            ctx = _ServerNameContext(ssl.PROTOCOL_TLS_CLIENT)
            ctx.minimum_version = ssl.TLSVersion.TLSv1_2
            ctx.check_hostname = True
            ctx.verify_mode = ssl.CERT_REQUIRED
            if ca_cert_path:
                # Raises on unreadable/unparseable CA — fail fast at wiring
                # time, not on the first query (tls.go:40-49).
                ctx.load_verify_locations(cafile=ca_cert_path)
            else:
                ctx.load_default_certs()
            if client_cert_path and client_key_path:
                ctx.load_cert_chain(client_cert_path, client_key_path)
            ctx.server_name = server_name
            self._ssl_ctx = ctx

    @classmethod
    def from_config(cls, prom) -> "HTTPPromAPI":
        """Build from a ``config.PrometheusConfig`` — the single place the
        TLS/auth knob surface maps onto the transport, shared by runtime
        wiring and the startup validation probe. Raises ``OSError`` /
        ``ssl.SSLError`` on unreadable or unparseable certificate files
        (configuration errors surface at wiring time, not first query)."""
        return cls(
            prom.base_url,
            bearer_token=prom.bearer_token,
            token_path=prom.token_path,
            insecure_skip_verify=prom.insecure_skip_verify,
            ca_cert_path=prom.ca_cert_path,
            client_cert_path=prom.client_cert_path,
            client_key_path=prom.client_key_path,
            server_name=prom.server_name,
            use_get=getattr(prom, "use_get_queries", False))

    def _token(self) -> str:
        if self.bearer_token:
            return self.bearer_token
        if self.token_path:
            try:
                with open(self.token_path) as f:
                    return f.read().strip()
            except OSError as e:
                raise RuntimeError(
                    f"failed to read bearer token from {self.token_path}: {e}"
                ) from e
        return ""

    def query(self, promql: str) -> list[SeriesPoint]:
        # Capture the verb THIS request uses: concurrent queries race the
        # degrade flip below, and the retry guard must test what was
        # actually sent, not the since-mutated shared flag (or every
        # in-flight POST but the first would re-raise its 405).
        used_get = self.use_get
        try:
            payload = self._request(promql, use_get=used_get)
        except urllib.error.HTTPError as e:
            # A GET-only proxy (405/501 on POST) must not black out every
            # metric until an operator finds the knob: degrade this API
            # handle to GET permanently and retry. Oversized grouped
            # queries may then fail individually — the grouped-rejection
            # fallback handles those per template.
            if used_get or e.code not in (405, 501):
                raise
            if not self.use_get:
                log.warning("Prometheus rejected POST /api/v1/query (%d); "
                            "falling back to GET for all queries (set "
                            "PROMETHEUS_USE_GET_QUERIES=true to silence "
                            "this)", e.code)
                self.use_get = True
            payload = self._request(promql, use_get=True)
        if payload.get("status") != "success":
            raise RuntimeError(f"prometheus query failed: {payload.get('error')}")
        return parse_prometheus_response(payload.get("data") or {})

    def _request(self, promql: str, use_get: bool) -> dict:
        encoded = urllib.parse.urlencode({"query": promql})
        if use_get:
            req = urllib.request.Request(
                f"{self.base_url}/api/v1/query?{encoded}")
        else:
            req = urllib.request.Request(
                f"{self.base_url}/api/v1/query", method="POST",
                data=encoded.encode(),
                headers={"Content-Type":
                         "application/x-www-form-urlencoded"})
        token = self._token()
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        with urllib.request.urlopen(req, timeout=self.timeout,
                                    context=self._ssl_ctx) as resp:
            return json.loads(resp.read())


def parse_prometheus_response(data: dict) -> list[SeriesPoint]:
    """Parse vector/scalar/matrix results; NaN/Inf values become 0
    (reference prometheus_source.go:171-250)."""
    result_type = data.get("resultType", "")
    result = data.get("result", [])
    points: list[SeriesPoint] = []

    def _value(raw) -> float:
        try:
            v = float(raw)
        except (TypeError, ValueError):
            return 0.0
        return 0.0 if (math.isnan(v) or math.isinf(v)) else v

    if result_type == "vector":
        for series in result:
            ts, raw = series.get("value", [0, "0"])
            labels = dict(series.get("metric") or {})
            labels.pop("__name__", None)
            points.append(SeriesPoint(labels, _value(raw), float(ts)))
    elif result_type == "scalar":
        ts, raw = result if isinstance(result, list) else (0, "0")
        points.append(SeriesPoint({}, _value(raw), float(ts)))
    elif result_type == "matrix":
        for series in result:
            values = series.get("values") or []
            if not values:
                continue
            ts, raw = values[-1]  # latest sample of each series
            labels = dict(series.get("metric") or {})
            labels.pop("__name__", None)
            points.append(SeriesPoint(labels, _value(raw), float(ts)))
    else:
        raise RuntimeError(f"unsupported prometheus result type {result_type!r}")
    return points


class PrometheusSource(MetricsSource):
    """Executes registered queries (concurrently for HTTP backends), caches
    results keyed by (query, params).

    Also the substrate for grouped per-tick collection
    (:class:`~wva_tpu.collector.source.grouped.GroupedMetricsView`): it
    memoizes the grouped rewrite per template, executes fleet-wide queries
    with the same backend, tracks grouped-form rejections for automatic
    per-model fallback, and exposes the per-model cache so demuxed slices
    keep stale-serve semantics. ``query_counts()`` reports backend queries
    by template name — the honest measurement the bench-collect harness
    and the query-budget regression tests assert against."""

    # GroupedMetricsView only wraps sources that carry the grouped hooks.
    supports_grouped_collection = True
    # A backend that rejected a grouped form is retried after this long
    # (rejections are usually deterministic — proxy limits, unsupported
    # grouped shape — so hammering every tick is pure waste).
    GROUPED_REJECT_RETRY_SECONDS = 600.0

    def __init__(self, api: PromAPI, cache_config: CacheConfig | None = None,
                 clock: Clock | None = None, concurrent: bool | None = None) -> None:
        self.api = api
        self.clock = clock or SYSTEM_CLOCK
        cache_cfg = cache_config or CacheConfig(ttl=DEFAULT_CACHE_TTL_SECONDS)
        self.fetch_interval = cache_cfg.fetch_interval
        self._freshness = cache_cfg.freshness
        self._cache = MetricsCache(ttl=cache_cfg.ttl,
                                   cleanup_interval=cache_cfg.cleanup_interval,
                                   clock=self.clock)
        # A tiny TTL must not truncate the configured stale-serve window.
        self._cache.min_retention = self._freshness.unavailable_threshold
        # Recently refreshed (queries, params) specs, for the background
        # cache warmer (bounded LRU; entries expire when not re-seen).
        # Guarded by _specs_mu: engine threads remember specs while the
        # warmer thread iterates/expires them.
        self._recent_specs: dict[str, tuple[float, RefreshSpec]] = {}
        self._recent_bound = 256
        self._specs_mu = threading.Lock()
        # Guard: the warmer's own refreshes must not renew seen_at, or
        # specs for deleted consumers would be warmed forever. Thread-LOCAL
        # so only the warmer thread's refreshes are exempt — an organic
        # engine refresh running concurrently with a warming pass still
        # registers its spec (a shared bool would briefly disable
        # registration globally).
        self._warming = threading.local()
        # Eviction-warning rate limit: one warning (with a suppressed-count)
        # per SPEC_EXPIRY window, not one per eviction — a deployment with
        # more specs than the bound would otherwise warn on every refresh.
        self._last_evict_warn = float("-inf")
        self._evictions_since_warn = 0
        self._queries = QueryList()
        # In-memory backends are fast + deterministic: run sequentially.
        # Wrappers over an in-memory backend (the chaos fault injector)
        # declare themselves with a `sequential` attribute so simulated
        # worlds stay single-threaded-deterministic.
        if concurrent is None:
            concurrent = not (isinstance(api, InMemoryPromAPI)
                              or getattr(api, "sequential", False))
        self._concurrent = concurrent
        # One persistent query pool for the source's lifetime (created
        # lazily, torn down by close()). Constructing a fresh
        # ThreadPoolExecutor per refresh() spawned and joined up to 8
        # threads per call — at a 5s engine tick with per-model refreshes
        # that is hundreds of thread creations a minute for nothing.
        self._pool: ThreadPoolExecutor | None = None
        # Separate small pool for the cache warmer: warm tasks call
        # refresh(), whose per-query fan-out runs on the query pool above —
        # warming on that same pool could fill every slot with warm tasks
        # all blocked on their own inner fan-out (nested-pool deadlock).
        self._warm_pool_handle: ThreadPoolExecutor | None = None
        self._pool_mu = threading.Lock()
        # Backend query counters by template name ("grouped:<name>" for
        # fleet-wide grouped executions) — the measured quantity behind
        # the O(templates)-per-tick claim.
        self._qc_mu = threading.Lock()
        self._query_counts: dict[str, int] = {}
        # Versioned fingerprint plane (WVA_FP_DELTA; docs/design/
        # informer.md): cross-tick slice digests/versions + write-version-
        # gated execution memos, stamped by GroupedMetricsView during
        # demux.
        from wva_tpu.collector.source.grouped import SliceVersionBook

        self.slice_book = SliceVersionBook()
        # Grouped-rewrite memo ((name, extras) -> GroupedQuery | None) and
        # rejection clock per template name.
        self._grouped_mu = threading.Lock()
        self._grouped_cache: dict[tuple, object] = {}
        self._grouped_rejected_at: dict[str, float] = {}
        # Recently ORGANICALLY-served grouped specs, for the cache warmer
        # (the grouped twin of _recent_specs: with grouping on, per-model
        # specs never reach refresh(), so warming must re-execute the
        # fleet-wide queries instead). Guarded by _specs_mu; warming
        # executions never renew.
        self._grouped_specs: dict[tuple, tuple[float, str, dict, str]] = {}

    def query_list(self) -> QueryList:
        return self._queries

    def refresh(self, spec: RefreshSpec) -> dict[str, MetricResult]:
        names = spec.queries or self._queries.names()
        results: dict[str, MetricResult] = {}
        # Escape every param against PromQL label-matcher injection before
        # templating (reference prometheus_source.go:123).
        escaped_params = {k: escape_promql_value(v) for k, v in spec.params.items()}

        def run_one(name: str) -> MetricResult:
            collected_at = self.clock.now()
            try:
                promql = self._queries.build(name, escaped_params)
                self._note_query(name)
                points = self.api.query(promql)
            except Exception as e:  # noqa: BLE001 — per-query isolation
                # Serve-stale-on-error: a Prometheus blip rides on the last
                # good result (original collected_at intact, so freshness
                # classification downgrades it honestly) instead of
                # skipping a whole analysis tick. Bounded by the
                # unavailable threshold — too-old data is worse than none.
                cached = self._cache.get_stale(
                    name, spec.params, self._freshness.unavailable_threshold)
                if cached is not None:
                    log.debug("query %s failed (%s); serving cached result "
                              "(age %.0fs)", name, e, cached.age(self.clock))
                    return cached.result
                log.debug("query %s failed: %s", name, e)
                return MetricResult(query_name=name, collected_at=collected_at,
                                    error=str(e))
            values = [self.make_metric_value(dict(p.labels), p)
                      for p in points]
            result = MetricResult(query_name=name, values=values,
                                  collected_at=collected_at)
            # Cache only genuinely fresh query results — re-caching a
            # stale-served fallback would renew its age and let outage
            # data outlive the unavailable bound.
            self._cache.set(name, spec.params, result)
            return result

        if self._concurrent and len(names) > 1:
            for name, result in zip(names,
                                    self._query_pool().map(run_one, names)):
                results[name] = result
        else:
            for name in names:
                results[name] = run_one(name)

        self._remember_spec(names, spec.params)
        return results

    # Shared across every concurrent refresh() — the engine's analysis pool
    # (up to 8 workers) fans per-model refreshes onto this ONE pool, so it
    # must be sized for workers x per-refresh parallelism or it would
    # serialize exactly the I/O overlap the analysis pool exists to exploit
    # (the old per-call ThreadPoolExecutor gave each refresh its own 8).
    QUERY_POOL_WORKERS = 32

    def _query_pool(self) -> ThreadPoolExecutor:
        with self._pool_mu:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self.QUERY_POOL_WORKERS,
                    thread_name_prefix="prom-query")
            return self._pool

    # Warm tasks are refresh() calls whose own queries fan onto the query
    # pool; 8 concurrent specs keeps a fleet-scale warming pass well under
    # fetch_interval without monopolizing the query pool.
    WARM_POOL_WORKERS = 8

    def _warm_pool(self) -> ThreadPoolExecutor:
        with self._pool_mu:
            if self._warm_pool_handle is None:
                self._warm_pool_handle = ThreadPoolExecutor(
                    max_workers=self.WARM_POOL_WORKERS,
                    thread_name_prefix="prom-warm")
            return self._warm_pool_handle

    def close(self) -> None:
        """Shut down the persistent query + warm pools (source stop /
        process shutdown). Safe to call repeatedly; a later refresh() would
        lazily recreate them."""
        with self._pool_mu:
            pool, self._pool = self._pool, None
            warm, self._warm_pool_handle = self._warm_pool_handle, None
        if pool is not None:
            pool.shutdown(wait=False)
        if warm is not None:
            warm.shutdown(wait=False)

    # --- backend query accounting ---

    def _note_query(self, name: str) -> None:
        with self._qc_mu:
            self._query_counts[name] = self._query_counts.get(name, 0) + 1

    def query_counts(self) -> dict[str, int]:
        """Backend queries issued since the last reset, by template name
        (grouped executions count under ``grouped:<name>``)."""
        with self._qc_mu:
            return dict(self._query_counts)

    def backend_query_total(self) -> int:
        with self._qc_mu:
            return sum(self._query_counts.values())

    def reset_query_counts(self) -> None:
        with self._qc_mu:
            self._query_counts.clear()

    # --- grouped-collection substrate (GroupedMetricsView) ---

    def grouped_query_for(self, name: str, extra_params: dict[str, str],
                          scope_namespace: str = ""):
        """The memoized fleet-wide rewrite of template ``name`` for this
        extra-param set (and namespace scope), or None when not groupable /
        recently rejected."""
        from wva_tpu.collector.source.grouped import build_grouped_query

        with self._grouped_mu:
            rejected_at = self._grouped_rejected_at.get(name)
            if rejected_at is not None:
                if (self.clock.now() - rejected_at
                        < self.GROUPED_REJECT_RETRY_SECONDS):
                    return None
                del self._grouped_rejected_at[name]
        template = self._queries.get(name)
        if template is None:
            return None
        key = (name, tuple(sorted(extra_params.items())), scope_namespace)
        with self._grouped_mu:
            if key in self._grouped_cache:
                return self._grouped_cache[key]
        gq = build_grouped_query(template, extra_params,
                                 scope_namespace=scope_namespace)
        with self._grouped_mu:
            if len(self._grouped_cache) >= 1024:
                self._grouped_cache.clear()
            self._grouped_cache[key] = gq
        return gq

    def execute_grouped(self, name: str, promql: str):
        """One fleet-wide query straight through the backend (the view owns
        demux + caching); exceptions propagate to trigger fallback."""
        self._note_query(f"grouped:{name}")
        return self.api.query(promql)

    def execute_grouped_tracked(self, name: str, promql: str):
        """``execute_grouped`` returning ``(points, TrackMeta | None)``:
        validity metadata for execution reuse (None when the backend
        cannot track it — HTTP Prometheus)."""
        self._note_query(f"grouped:{name}")
        tracked = getattr(self.api, "query_tracked", None)
        if tracked is not None:
            return tracked(promql)
        return self.api.query(promql), None

    def backend_write_version(self, names) -> int | None:
        """Backend write-version across ``names`` (None = backend cannot
        prove write-quiescence, e.g. HTTP Prometheus — execution reuse is
        then disabled and every tick re-queries)."""
        fn = getattr(self.api, "write_version", None)
        return None if fn is None else fn(names)

    def backend_value_version(self, names) -> int | None:
        """Backend value-version across ``names`` (moves only on
        value-changing appends); None = unsupported backend."""
        fn = getattr(self.api, "value_version", None)
        return None if fn is None else fn(names)

    def remember_grouped_spec(self, name: str, extras: dict[str, str],
                              scope_namespace: str = "",
                              versioned: bool = True) -> None:
        """Record an organically-served grouped spec for the warmer (true
        LRU like _remember_spec; bounded by _recent_bound). ``versioned``
        records whether the serving view ran the fingerprint plane, so
        warm passes replay the same mode (WVA_FP_DELTA=off must be
        pre-change on the warmer path too)."""
        key = (name, tuple(sorted(extras.items())), scope_namespace)
        with self._specs_mu:
            self._grouped_specs.pop(key, None)
            self._grouped_specs[key] = (self.clock.now(), name,
                                        dict(extras), scope_namespace,
                                        versioned)
            while len(self._grouped_specs) > self._recent_bound:
                self._grouped_specs.pop(next(iter(self._grouped_specs)))

    def note_grouped_rejection(self, name: str, error: Exception) -> None:
        """Backend rejected the grouped form: pin this template to the
        per-model path for a while (retried after the rejection window)."""
        with self._grouped_mu:
            first = name not in self._grouped_rejected_at
            self._grouped_rejected_at[name] = self.clock.now()
        if first:
            log.warning("grouped query %s rejected by backend (%s); "
                        "falling back to per-model collection for %.0fs",
                        name, error, self.GROUPED_REJECT_RETRY_SECONDS)

    def store_demuxed_result(self, name: str, params: dict[str, str],
                             result: MetricResult) -> None:
        """Cache one demuxed per-model slice under the exact key the
        per-model refresh path uses, preserving stale-serve semantics."""
        self._cache.set(name, params, result)

    @staticmethod
    def make_metric_value(labels: dict[str, str], point) -> MetricValue:
        """SeriesPoint -> MetricValue with the NaN/Inf -> 0 guard, shared
        by the per-model and grouped demux paths so values are built
        identically."""
        v = point.value
        return MetricValue(
            value=0.0 if math.isnan(v) or math.isinf(v) else v,
            timestamp=point.timestamp, labels=labels)

    # Specs not re-seen for this long stop being warmed (a deleted VA's
    # queries must not be re-executed forever).
    SPEC_EXPIRY_SECONDS = 600.0

    def _remember_spec(self, names, params: dict[str, str]) -> None:
        if getattr(self._warming, "active", False):
            return
        key = "|".join(sorted(names)) + "||" + \
            "|".join(f"{k}={v}" for k, v in sorted(params.items()))
        with self._specs_mu:
            # True LRU: re-insert moves the key to the back, so eviction
            # drops the least-recently-SEEN spec (plain assignment would
            # keep the original insertion position and evict the hottest
            # spec first).
            self._recent_specs.pop(key, None)
            self._recent_specs[key] = (self.clock.now(),
                                       RefreshSpec(queries=list(names),
                                                   params=dict(params)))
            while len(self._recent_specs) > self._recent_bound:
                evicted = next(iter(self._recent_specs))
                self._recent_specs.pop(evicted, None)
                # No silent caps — but no log spam either: at steady state
                # above the bound EVERY refresh evicts, so aggregate into
                # one warning per expiry window.
                self._evictions_since_warn += 1
                now = self.clock.now()
                if now - self._last_evict_warn >= self.SPEC_EXPIRY_SECONDS:
                    log.warning(
                        "warm-spec LRU full (bound %d): %d eviction(s) since "
                        "last warning, latest %s — evicted specs lose "
                        "warming + stale-serve fallback; raise the bound if "
                        "this fleet legitimately has more specs",
                        self._recent_bound, self._evictions_since_warn,
                        evicted[:120])
                    self._last_evict_warn = now
                    self._evictions_since_warn = 0

    def background_fetch_once(self) -> int:
        """Re-execute recently seen refresh specs — per-model AND grouped
        fleet-wide ones (each grouped re-execution refreshes every demuxed
        per-model cache slice) — to keep the stale-serve cache alive
        (PROMETHEUS_METRICS_CACHE_FETCH_INTERVAL, reference cache fetch
        loop); expired specs are dropped. Returns the number of specs
        refreshed.

        Specs warm CONCURRENTLY (bounded warm pool) against HTTP backends:
        a serial walk at fleet scale could overrun ``fetch_interval`` and
        let the stale-serve cache silently decay. The warming flag is
        thread-local, so it is set inside each warm task — whichever pool
        thread runs it — and organic refreshes on those threads still
        register their specs."""
        now = self.clock.now()
        live: list[RefreshSpec] = []
        grouped_live: list[tuple[str, dict, str, bool]] = []
        with self._specs_mu:
            for key, (seen_at, spec) in list(self._recent_specs.items()):
                if now - seen_at > self.SPEC_EXPIRY_SECONDS:
                    self._recent_specs.pop(key, None)
                else:
                    live.append(spec)
            for key, (seen_at, name, extras, scope, versioned) in \
                    list(self._grouped_specs.items()):
                if now - seen_at > self.SPEC_EXPIRY_SECONDS:
                    self._grouped_specs.pop(key, None)
                else:
                    grouped_live.append((name, extras, scope, versioned))

        def warm_one(spec: RefreshSpec) -> None:
            self._warming.active = True
            try:
                self.refresh(spec)
            except Exception as e:  # noqa: BLE001 — warming must not crash
                log.debug("background fetch failed: %s", e)
            finally:
                self._warming.active = False

        def warm_grouped(item: tuple[str, dict, str, bool]) -> None:
            from wva_tpu.collector.source.grouped import warm_grouped_spec

            name, extras, scope, versioned = item
            try:
                warm_grouped_spec(self, name, extras, scope,
                                  versioned=versioned)
            except Exception as e:  # noqa: BLE001 — warming must not crash
                log.debug("grouped background fetch failed: %s", e)

        tasks = [(warm_one, s) for s in live] + \
            [(warm_grouped, g) for g in grouped_live]
        if self._concurrent and len(tasks) > 1:
            list(self._warm_pool().map(lambda t: t[0](t[1]), tasks))
        else:
            for fn, arg in tasks:
                fn(arg)
        return len(tasks)

    def start_background_fetch(self, stop) -> "threading.Thread | None":
        """Spawn the cache warmer when fetch_interval > 0 (0 disables)."""
        if self.fetch_interval <= 0:
            return None

        def loop():
            while not stop.wait(self.fetch_interval):
                self.background_fetch_once()

        t = threading.Thread(target=loop, name="prometheus-cache-fetch",
                             daemon=True)
        t.start()
        return t

    def get(self, query_name: str, params: dict[str, str]):
        return self._cache.get(query_name, params)

    def slice_age_seconds(self, queries, params: dict[str, str],
                          ) -> float | None:
        """Input-health probe: age of the OLDEST cached entry among
        ``queries`` for these params, ignoring TTL and the stale-serve
        bound. A healthy tick re-caches every slice (directly or through
        the grouped demux), so the age collapses to ~0; during an outage
        refresh() stale-serves WITHOUT re-caching, so the age grows
        monotonically — exactly the quantity the degraded/blackout ladder
        classifies. None = nothing cached (never collected, or the entry
        aged past the retention sweep — the monitor keeps its own
        last-good clock so None never resets an outage)."""
        now = self.clock.now()
        ages = [now - entry.cached_at
                for name in queries
                for entry in (self._cache.peek(name, params),)
                if entry is not None]
        return max(ages) if ages else None
