"""Query registration for the saturation + scale-to-zero pipelines."""

from wva_tpu.collector.registration.saturation import (
    QUERY_AVG_INPUT_TOKENS,
    QUERY_AVG_OUTPUT_TOKENS,
    QUERY_CACHE_CONFIG_INFO,
    QUERY_GENERATE_BACKLOG,
    QUERY_KV_CACHE_USAGE,
    QUERY_PREFIX_CACHE_HIT_RATE,
    QUERY_QUEUE_LENGTH,
    QUERY_SCHEDULER_QUEUE_BYTES,
    QUERY_SCHEDULER_QUEUE_SIZE,
    QUERY_SERVING_CONFIG_INFO,
    QUERY_SLOTS_AVAILABLE,
    QUERY_SLOTS_USED,
    register_saturation_queries,
)
from wva_tpu.collector.registration.scale_to_zero import (
    PARAM_RETENTION_PERIOD,
    QUERY_MODEL_REQUEST_COUNT,
    collect_model_request_count,
    register_scale_to_zero_queries,
)
from wva_tpu.collector.registration.slo import (
    QUERY_ARRIVAL_RATE,
    QUERY_AVG_ITL,
    QUERY_AVG_TTFT,
    collect_optimizer_metrics,
    register_slo_queries,
)

__all__ = [
    "QUERY_AVG_INPUT_TOKENS",
    "QUERY_AVG_OUTPUT_TOKENS",
    "QUERY_CACHE_CONFIG_INFO",
    "QUERY_GENERATE_BACKLOG",
    "QUERY_KV_CACHE_USAGE",
    "QUERY_PREFIX_CACHE_HIT_RATE",
    "QUERY_QUEUE_LENGTH",
    "QUERY_SCHEDULER_QUEUE_BYTES",
    "QUERY_SCHEDULER_QUEUE_SIZE",
    "QUERY_SERVING_CONFIG_INFO",
    "QUERY_SLOTS_AVAILABLE",
    "QUERY_SLOTS_USED",
    "register_saturation_queries",
    "PARAM_RETENTION_PERIOD",
    "QUERY_MODEL_REQUEST_COUNT",
    "collect_model_request_count",
    "register_scale_to_zero_queries",
    "QUERY_ARRIVAL_RATE",
    "QUERY_AVG_ITL",
    "QUERY_AVG_TTFT",
    "collect_optimizer_metrics",
    "register_slo_queries",
]
