"""Saturation query registration
(reference ``internal/collector/registration/saturation.go:8-122``).

Every per-pod query merges the vLLM-TPU and JetStream metric families with a
PromQL ``or`` so one pipeline serves both engines: vLLM-TPU emits the same
``vllm:*`` names as CUDA vLLM, JetStream emits ``jetstream_*`` gauges. The
merge is per-series — a pod only ever exposes one family, so ``or`` acts as a
per-pod fallback, not a mixing operator.
"""

from __future__ import annotations

from wva_tpu.collector.source.query_template import QueryTemplate
from wva_tpu.collector.source.registry import PROMETHEUS_SOURCE_NAME, SourceRegistry
from wva_tpu.collector.source.source import PARAM_MODEL_ID, PARAM_NAMESPACE

# Saturation queries (per-pod peaks over 1m windows).
QUERY_KV_CACHE_USAGE = "kv_cache_usage"
QUERY_QUEUE_LENGTH = "queue_length"

# V2 token-capacity queries.
QUERY_CACHE_CONFIG_INFO = "cache_config_info"
QUERY_SERVING_CONFIG_INFO = "serving_config_info"
QUERY_AVG_OUTPUT_TOKENS = "avg_output_tokens"
QUERY_AVG_INPUT_TOKENS = "avg_input_tokens"
QUERY_PREFIX_CACHE_HIT_RATE = "prefix_cache_hit_rate"

# JetStream disaggregated-serving queries.
QUERY_GENERATE_BACKLOG = "generate_backlog"
QUERY_SLOTS_USED = "slots_used"
QUERY_SLOTS_AVAILABLE = "slots_available"

# Scheduler flow-control queries (model-level).
QUERY_SCHEDULER_QUEUE_SIZE = "scheduler_queue_size"
QUERY_SCHEDULER_QUEUE_BYTES = "scheduler_queue_bytes"

_NS_MODEL = '{namespace="{{.namespace}}",model_name="{{.modelID}}"}'


def register_saturation_queries(source_registry: SourceRegistry) -> None:
    src = source_registry.get(PROMETHEUS_SOURCE_NAME)
    if src is None:
        return
    registry = src.query_list()

    registry.register(QueryTemplate(
        name=QUERY_KV_CACHE_USAGE,
        template=(
            f"max by (pod) (max_over_time(vllm:kv_cache_usage_perc{_NS_MODEL}[1m])"
            f" or max_over_time(jetstream_kv_cache_utilization{_NS_MODEL}[1m]))"
        ),
        params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
        description="Peak HBM KV-cache utilization per pod (0.0-1.0) over last minute",
    ))

    registry.register(QueryTemplate(
        name=QUERY_QUEUE_LENGTH,
        template=(
            f"max by (pod) (max_over_time(vllm:num_requests_waiting{_NS_MODEL}[1m])"
            f" or max_over_time(jetstream_prefill_backlog_size{_NS_MODEL}[1m]))"
        ),
        params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
        description="Peak waiting-request / prefill-backlog depth per pod over last minute",
    ))

    # --- V2 token-capacity queries ---

    registry.register(QueryTemplate(
        name=QUERY_CACHE_CONFIG_INFO,
        template=(
            "max by (pod, num_gpu_blocks, block_size) "
            f"(vllm:cache_config_info{_NS_MODEL})"
        ),
        params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
        description="vLLM KV cache configuration per pod (labels carry block counts)",
    ))

    registry.register(QueryTemplate(
        name=QUERY_SERVING_CONFIG_INFO,
        template=(
            "max by (pod, max_concurrent_decodes, max_target_length, tokens_per_slot) "
            f"(jetstream_serving_config_info{_NS_MODEL})"
        ),
        params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
        description="JetStream serving configuration per pod (labels carry slot budget)",
    ))

    registry.register(QueryTemplate(
        name=QUERY_AVG_OUTPUT_TOKENS,
        template=(
            "max by (pod) ("
            f"rate(vllm:request_generation_tokens_sum{_NS_MODEL}[5m])"
            f" / rate(vllm:request_generation_tokens_count{_NS_MODEL}[5m])"
            f" or rate(jetstream_request_output_length_sum{_NS_MODEL}[5m])"
            f" / rate(jetstream_request_output_length_count{_NS_MODEL}[5m]))"
        ),
        params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
        description="Average output tokens per completed request (5m rate)",
    ))

    registry.register(QueryTemplate(
        name=QUERY_AVG_INPUT_TOKENS,
        template=(
            "max by (pod) ("
            f"rate(vllm:request_prompt_tokens_sum{_NS_MODEL}[5m])"
            f" / rate(vllm:request_prompt_tokens_count{_NS_MODEL}[5m])"
            f" or rate(jetstream_request_input_length_sum{_NS_MODEL}[5m])"
            f" / rate(jetstream_request_input_length_count{_NS_MODEL}[5m]))"
        ),
        params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
        description="Average input tokens per completed request (5m rate)",
    ))

    registry.register(QueryTemplate(
        name=QUERY_PREFIX_CACHE_HIT_RATE,
        template=(
            "max by (pod) ("
            f"rate(vllm:prefix_cache_hits{_NS_MODEL}[5m])"
            f" / rate(vllm:prefix_cache_queries{_NS_MODEL}[5m]))"
        ),
        params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
        description="Prefix cache hit rate per pod (0.0-1.0, 5m rate; vLLM only)",
    ))

    # --- JetStream disaggregated-serving extensions ---

    registry.register(QueryTemplate(
        name=QUERY_GENERATE_BACKLOG,
        template=(
            f"max by (pod) (max_over_time(jetstream_generate_backlog_size{_NS_MODEL}[1m]))"
        ),
        params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
        description="Peak decode-slot backlog per pod over last minute (JetStream)",
    ))

    registry.register(QueryTemplate(
        name=QUERY_SLOTS_USED,
        template=f"max by (pod) (jetstream_slots_used{_NS_MODEL})",
        params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
        description="Concurrent decode slots in use per pod (JetStream)",
    ))

    registry.register(QueryTemplate(
        name=QUERY_SLOTS_AVAILABLE,
        template=f"max by (pod) (jetstream_slots_available{_NS_MODEL})",
        params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
        description="Free concurrent decode slots per pod (JetStream)",
    ))

    # --- Scheduler flow-control (model-level; no namespace label upstream) ---

    registry.register(QueryTemplate(
        name=QUERY_SCHEDULER_QUEUE_SIZE,
        template=(
            'sum(inference_extension_flow_control_queue_size{target_model_name="{{.modelID}}"})'
            ' or sum(inference_extension_flow_control_queue_size'
            '{model_name="{{.modelID}}",target_model_name=""})'
        ),
        params=[PARAM_MODEL_ID],
        description="Total requests queued in scheduler flow control for this model",
    ))

    registry.register(QueryTemplate(
        name=QUERY_SCHEDULER_QUEUE_BYTES,
        template=(
            'sum(inference_extension_flow_control_queue_bytes{target_model_name="{{.modelID}}"})'
            ' or sum(inference_extension_flow_control_queue_bytes'
            '{model_name="{{.modelID}}",target_model_name=""})'
        ),
        params=[PARAM_MODEL_ID],
        description="Total bytes queued in scheduler flow control for this model",
    ))
