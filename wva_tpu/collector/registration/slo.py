"""SLO-analyzer telemetry queries: model-level arrival rate and observed
latencies.

The reference's inferno path consumed the same shape through
``interfaces.OptimizerMetrics`` (``internal/interfaces/metrics_collector.go:
12-24``, arrival rate in req/min). Queries accept both vLLM-TPU (``vllm:*``)
and JetStream metric families, like the saturation registrations.
"""

from __future__ import annotations

import logging
import math
from dataclasses import dataclass

from wva_tpu.collector.source.query_template import QueryTemplate
from wva_tpu.collector.source.registry import PROMETHEUS_SOURCE_NAME, SourceRegistry
from wva_tpu.collector.source.source import (
    PARAM_MODEL_ID,
    PARAM_NAMESPACE,
    MetricsSource,
    RefreshSpec,
)
from wva_tpu.interfaces.allocation import OptimizerMetrics

log = logging.getLogger(__name__)

QUERY_ARRIVAL_RATE = "model_arrival_rate"

# Rate window for the arrival-rate query. During a ramp the measured rate is
# ~half a window stale, and with slices taking minutes to provision, 30s less
# telemetry lag is 30s less backlog to drain — but rate() needs >=2 samples
# in the window, so the window must stay >= 2x the Prometheus scrape
# interval. Default 1m tolerates the common 30s scrape; deployments scraping
# at 15s or faster (our chart's default) should set 30s.
ARRIVAL_RATE_WINDOW_ENV = "WVA_SLO_ARRIVAL_RATE_WINDOW"
DEFAULT_ARRIVAL_RATE_WINDOW = "1m"


def arrival_rate_window() -> str:
    import os
    import re

    raw = os.environ.get(ARRIVAL_RATE_WINDOW_ENV,
                         DEFAULT_ARRIVAL_RATE_WINDOW)
    return raw if re.fullmatch(r"\d+[smh]", raw) else DEFAULT_ARRIVAL_RATE_WINDOW


def arrival_rate_window_seconds() -> float:
    """The arrival-rate window as seconds (consumed by the demand-trend
    spin-up gate)."""
    raw = arrival_rate_window()
    return float(raw[:-1]) * {"s": 1.0, "m": 60.0, "h": 3600.0}[raw[-1]]


QUERY_AVG_TTFT = "model_avg_ttft"
QUERY_AVG_ITL = "model_avg_itl"

# Per-pod latency-rate companions to the model-level means. Observed TTFT/ITL
# averaged model-wide is a blend across accelerator types, useless for tuning
# per-accelerator performance profiles; grouping the histogram sum/count
# rates ``by (pod)`` lets the engine join each pod's latency contribution to
# its accelerator (pod -> VA -> accelerator, the same join the replica
# collector performs) and rebuild an exact per-accelerator mean:
# sum(sum-rates of the type's pods) / sum(count-rates of the type's pods).
QUERY_POD_TTFT_SUM_RATE = "model_pod_ttft_sum_rate"
QUERY_POD_TTFT_COUNT_RATE = "model_pod_ttft_count_rate"
QUERY_POD_ITL_SUM_RATE = "model_pod_itl_sum_rate"
QUERY_POD_ITL_COUNT_RATE = "model_pod_itl_count_rate"
QUERY_POD_ARRIVAL_RATE = "model_pod_arrival_rate"
QUERY_POD_ARRIVAL_RATE_FAST = "model_pod_arrival_rate_fast"

# Short-window companion to the arrival-rate query. During a ramp the
# long-window rate lags the true rate by ~half a window; the fast window
# tracks it closely, so the collector reports max(long, fast). With a scrape
# interval above the fast window the query simply returns no data and the
# long window stands alone (rate() needs >=2 samples) — strictly additive.
QUERY_ARRIVAL_RATE_FAST = "model_arrival_rate_fast"
FAST_ARRIVAL_RATE_WINDOW = "10s"

_NS_MODEL = '{namespace="{{.namespace}}",model_name="{{.modelID}}"}'


def register_slo_queries(source_registry: SourceRegistry) -> None:
    src = source_registry.get(PROMETHEUS_SOURCE_NAME)
    if src is None:
        log.debug("Prometheus source not registered; skipping SLO queries")
        return
    ql = src.query_list()
    window = arrival_rate_window()
    ql.register_if_absent(QueryTemplate(
        name=QUERY_ARRIVAL_RATE,
        template=(
            f"sum(rate(vllm:request_success_total{_NS_MODEL}[{window}])"
            f" or rate(jetstream_request_success_total{_NS_MODEL}[{window}]))"
        ),
        params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
        description=f"Model request arrival (completion) rate over {window}",
    ))
    ql.register_if_absent(QueryTemplate(
        name=QUERY_ARRIVAL_RATE_FAST,
        template=(
            f"sum(rate(vllm:request_success_total{_NS_MODEL}"
            f"[{FAST_ARRIVAL_RATE_WINDOW}])"
            f" or rate(jetstream_request_success_total{_NS_MODEL}"
            f"[{FAST_ARRIVAL_RATE_WINDOW}]))"
        ),
        params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
        description=("Model request completion rate over "
                     f"{FAST_ARRIVAL_RATE_WINDOW} (ramp tracking)"),
    ))
    ql.register_if_absent(QueryTemplate(
        name=QUERY_AVG_TTFT,
        template=(
            f"sum({_latency_rates(_TTFT_SUM_METRICS)})"
            f" / sum({_latency_rates(_TTFT_COUNT_METRICS)})"
        ),
        params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
        description="Observed mean TTFT (s) over 5m",
    ))
    ql.register_if_absent(QueryTemplate(
        name=QUERY_AVG_ITL,
        template=(
            f"sum({_latency_rates(_ITL_SUM_METRICS)})"
            f" / sum({_latency_rates(_ITL_COUNT_METRICS)})"
        ),
        params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
        description="Observed mean inter-token latency (s) over 5m",
    ))
    _register_pod_latency_queries(ql)


# Histogram series names by engine family. JetStream's exporter names its
# latency histograms without a unit suffix (jetstream_time_to_first_token ->
# _sum/_count); some deployments re-export them with the Prometheus-idiomatic
# ``_seconds`` infix, so both spellings are accepted via ``or``.
_TTFT_SUM_METRICS = ("vllm:time_to_first_token_seconds_sum",
                     "jetstream_time_to_first_token_sum",
                     "jetstream_time_to_first_token_seconds_sum")
_TTFT_COUNT_METRICS = ("vllm:time_to_first_token_seconds_count",
                       "jetstream_time_to_first_token_count",
                       "jetstream_time_to_first_token_seconds_count")
_ITL_SUM_METRICS = ("vllm:time_per_output_token_seconds_sum",
                    "jetstream_time_per_output_token_sum",
                    "jetstream_time_per_output_token_seconds_sum")
_ITL_COUNT_METRICS = ("vllm:time_per_output_token_seconds_count",
                      "jetstream_time_per_output_token_count",
                      "jetstream_time_per_output_token_seconds_count")


def _latency_rates(metrics: tuple[str, ...], window: str = "5m") -> str:
    return " or ".join(f"rate({m}{_NS_MODEL}[{window}])" for m in metrics)


def _register_pod_latency_queries(ql) -> None:
    pod_queries = {
        QUERY_POD_TTFT_SUM_RATE: (
            _TTFT_SUM_METRICS, "Per-pod TTFT sum rate (s/s) over 5m"),
        QUERY_POD_TTFT_COUNT_RATE: (
            _TTFT_COUNT_METRICS, "Per-pod TTFT sample rate (1/s) over 5m"),
        QUERY_POD_ITL_SUM_RATE: (
            _ITL_SUM_METRICS, "Per-pod ITL sum rate (s/s) over 5m"),
        QUERY_POD_ITL_COUNT_RATE: (
            _ITL_COUNT_METRICS, "Per-pod ITL sample rate (1/s) over 5m"),
    }
    for name, (metrics, desc) in pod_queries.items():
        ql.register_if_absent(QueryTemplate(
            name=name,
            template=f"sum by (pod) ({_latency_rates(metrics)})",
            params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
            description=desc,
        ))
    # Long + fast arrival windows, mirroring the model-wide pair: during a
    # ramp the long window under-reports by ~half a window, so the
    # per-accelerator collector takes max(long, fast) per pod too.
    for name, window in ((QUERY_POD_ARRIVAL_RATE, arrival_rate_window()),
                         (QUERY_POD_ARRIVAL_RATE_FAST,
                          FAST_ARRIVAL_RATE_WINDOW)):
        ql.register_if_absent(QueryTemplate(
            name=name,
            template=(
                f"sum by (pod) (rate(vllm:request_success_total{_NS_MODEL}"
                f"[{window}])"
                f" or rate(jetstream_request_success_total{_NS_MODEL}"
                f"[{window}]))"
            ),
            params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
            description=f"Per-pod request completion rate over {window}",
        ))


def collect_optimizer_metrics(
    metrics_source: MetricsSource, model_id: str, namespace: str,
) -> OptimizerMetrics | None:
    """Model-level rate/latency telemetry; None when the arrival rate is
    unavailable (latencies are optional — used only by the tuner)."""
    params = {PARAM_MODEL_ID: model_id, PARAM_NAMESPACE: namespace}
    try:
        results = metrics_source.refresh(RefreshSpec(
            queries=[QUERY_ARRIVAL_RATE, QUERY_ARRIVAL_RATE_FAST,
                     QUERY_AVG_TTFT, QUERY_AVG_ITL],
            params=params))
    except Exception as e:  # noqa: BLE001
        log.debug("optimizer metrics unavailable for %s: %s", model_id, e)
        return None

    def first_value(name: str) -> float | None:
        result = results.get(name)
        if result is None or result.has_error():
            return None
        for v in result.values:
            if math.isfinite(v.value):
                return float(v.value)
        return None

    rate = first_value(QUERY_ARRIVAL_RATE)
    if rate is None:
        return None
    # During ramps the long window under-reports by ~half a window; the fast
    # window keeps up. max() is safe: both are completion rates of the same
    # counters, so steady state agrees and dips fall back to the long window
    # (scale-down damping).
    fast = first_value(QUERY_ARRIVAL_RATE_FAST)
    if fast is not None:
        rate = max(rate, fast)
    return OptimizerMetrics(
        arrival_rate=rate * 60.0,  # req/s -> req/min (reference convention)
        ttft_seconds=first_value(QUERY_AVG_TTFT) or 0.0,
        itl_seconds=first_value(QUERY_AVG_ITL) or 0.0,
    )


@dataclass
class AcceleratorTelemetry:
    """Latency/arrival telemetry for one accelerator type's share of a
    model's fleet, rebuilt from per-pod query results. Feeds one EKF per
    accelerator so heterogeneous fleets (the BASELINE config-4 v5e-vs-v5p
    scenario) tune each performance profile against its own latencies
    instead of the model-wide mixture."""

    ttft_seconds: float = 0.0
    itl_seconds: float = 0.0
    # Mean per-pod completion rate for this accelerator's pods, req/min.
    # Already per-replica: no division by the fleet-wide replica count.
    arrival_rate_per_replica: float = 0.0
    pods: int = 0


def collect_accelerator_telemetry(
    metrics_source: MetricsSource,
    model_id: str,
    namespace: str,
    pod_accelerators: dict[str, str],
) -> dict[str, AcceleratorTelemetry]:
    """Per-accelerator TTFT/ITL/arrival from per-pod rates.

    ``pod_accelerators`` maps pod name -> accelerator type (the caller joins
    it from ReplicaMetrics, which already carries the pod->VA->accelerator
    resolution). Pods with no latency samples in the window contribute
    nothing; an accelerator is omitted unless its pods produced TTFT *and*
    ITL *and* arrival samples (the EKF needs all three), so the caller can
    fall back to model-wide telemetry or skip."""
    if not pod_accelerators:
        return {}
    params = {PARAM_MODEL_ID: model_id, PARAM_NAMESPACE: namespace}
    try:
        results = metrics_source.refresh(RefreshSpec(
            queries=[QUERY_POD_TTFT_SUM_RATE, QUERY_POD_TTFT_COUNT_RATE,
                     QUERY_POD_ITL_SUM_RATE, QUERY_POD_ITL_COUNT_RATE,
                     QUERY_POD_ARRIVAL_RATE, QUERY_POD_ARRIVAL_RATE_FAST],
            params=params))
    except Exception as e:  # noqa: BLE001
        log.debug("per-pod latency telemetry unavailable for %s: %s",
                  model_id, e)
        return {}

    def per_pod(name: str) -> dict[str, float]:
        result = results.get(name)
        if result is None or result.has_error():
            return {}
        out: dict[str, float] = {}
        for v in result.values:
            # `sum by (pod)` leaves exactly one label; an empty pod means
            # the deployment aggregated the label away (recording rules).
            pod = v.labels.get("pod", "")
            if pod and math.isfinite(v.value):
                out[pod] = float(v.value)
        return out

    ttft_sum = per_pod(QUERY_POD_TTFT_SUM_RATE)
    ttft_count = per_pod(QUERY_POD_TTFT_COUNT_RATE)
    itl_sum = per_pod(QUERY_POD_ITL_SUM_RATE)
    itl_count = per_pod(QUERY_POD_ITL_COUNT_RATE)
    arrival = per_pod(QUERY_POD_ARRIVAL_RATE)
    arrival_fast = per_pod(QUERY_POD_ARRIVAL_RATE_FAST)

    acc: dict[str, dict[str, float]] = {}
    for pod, accelerator in pod_accelerators.items():
        if not accelerator:
            continue
        a = acc.setdefault(accelerator, {
            "ttft_sum": 0.0, "ttft_count": 0.0, "itl_sum": 0.0,
            "itl_count": 0.0, "arrival": 0.0, "arrival_pods": 0.0,
            "pods": 0.0})
        a["ttft_sum"] += ttft_sum.get(pod, 0.0)
        a["ttft_count"] += ttft_count.get(pod, 0.0)
        a["itl_sum"] += itl_sum.get(pod, 0.0)
        a["itl_count"] += itl_count.get(pod, 0.0)
        # Ramp correction as in collect_optimizer_metrics: the long window
        # lags a rising rate by ~half a window, the fast one tracks it.
        pod_arrival = arrival.get(pod)
        pod_fast = arrival_fast.get(pod)
        if pod_arrival is not None or pod_fast is not None:
            a["arrival"] += max(pod_arrival or 0.0, pod_fast or 0.0)
            # Only pods that produced arrival samples enter the per-replica
            # mean — a just-started pod with no samples yet must not bias
            # lambda low while the latency means reflect the serving pods.
            a["arrival_pods"] += 1
        a["pods"] += 1

    out: dict[str, AcceleratorTelemetry] = {}
    for accelerator, a in acc.items():
        if a["ttft_count"] <= 0 or a["itl_count"] <= 0 or a["arrival_pods"] <= 0:
            continue  # no samples this window; caller decides the fallback
        out[accelerator] = AcceleratorTelemetry(
            ttft_seconds=a["ttft_sum"] / a["ttft_count"],
            itl_seconds=a["itl_sum"] / a["itl_count"],
            arrival_rate_per_replica=(a["arrival"] / a["arrival_pods"]) * 60.0,
            pods=int(a["pods"]),
        )
    return out
