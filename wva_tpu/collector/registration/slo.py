"""SLO-analyzer telemetry queries: model-level arrival rate and observed
latencies.

The reference's inferno path consumed the same shape through
``interfaces.OptimizerMetrics`` (``internal/interfaces/metrics_collector.go:
12-24``, arrival rate in req/min). Queries accept both vLLM-TPU (``vllm:*``)
and JetStream metric families, like the saturation registrations.
"""

from __future__ import annotations

import logging
import math

from wva_tpu.collector.source.query_template import QueryTemplate
from wva_tpu.collector.source.registry import PROMETHEUS_SOURCE_NAME, SourceRegistry
from wva_tpu.collector.source.source import (
    PARAM_MODEL_ID,
    PARAM_NAMESPACE,
    MetricsSource,
    RefreshSpec,
)
from wva_tpu.interfaces.allocation import OptimizerMetrics

log = logging.getLogger(__name__)

QUERY_ARRIVAL_RATE = "model_arrival_rate"

# Rate window for the arrival-rate query. During a ramp the measured rate is
# ~half a window stale, and with slices taking minutes to provision, 30s less
# telemetry lag is 30s less backlog to drain — but rate() needs >=2 samples
# in the window, so the window must stay >= 2x the Prometheus scrape
# interval. Default 1m tolerates the common 30s scrape; deployments scraping
# at 15s or faster (our chart's default) should set 30s.
ARRIVAL_RATE_WINDOW_ENV = "WVA_SLO_ARRIVAL_RATE_WINDOW"
DEFAULT_ARRIVAL_RATE_WINDOW = "1m"


def arrival_rate_window() -> str:
    import os
    import re

    raw = os.environ.get(ARRIVAL_RATE_WINDOW_ENV,
                         DEFAULT_ARRIVAL_RATE_WINDOW)
    return raw if re.fullmatch(r"\d+[smh]", raw) else DEFAULT_ARRIVAL_RATE_WINDOW


def arrival_rate_window_seconds() -> float:
    """The arrival-rate window as seconds (consumed by the demand-trend
    spin-up gate)."""
    raw = arrival_rate_window()
    return float(raw[:-1]) * {"s": 1.0, "m": 60.0, "h": 3600.0}[raw[-1]]


QUERY_AVG_TTFT = "model_avg_ttft"
QUERY_AVG_ITL = "model_avg_itl"

# Short-window companion to the arrival-rate query. During a ramp the
# long-window rate lags the true rate by ~half a window; the fast window
# tracks it closely, so the collector reports max(long, fast). With a scrape
# interval above the fast window the query simply returns no data and the
# long window stands alone (rate() needs >=2 samples) — strictly additive.
QUERY_ARRIVAL_RATE_FAST = "model_arrival_rate_fast"
FAST_ARRIVAL_RATE_WINDOW = "10s"

_NS_MODEL = '{namespace="{{.namespace}}",model_name="{{.modelID}}"}'


def register_slo_queries(source_registry: SourceRegistry) -> None:
    src = source_registry.get(PROMETHEUS_SOURCE_NAME)
    if src is None:
        log.debug("Prometheus source not registered; skipping SLO queries")
        return
    ql = src.query_list()
    window = arrival_rate_window()
    ql.register_if_absent(QueryTemplate(
        name=QUERY_ARRIVAL_RATE,
        template=(
            f"sum(rate(vllm:request_success_total{_NS_MODEL}[{window}])"
            f" or rate(jetstream_request_success_total{_NS_MODEL}[{window}]))"
        ),
        params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
        description=f"Model request arrival (completion) rate over {window}",
    ))
    ql.register_if_absent(QueryTemplate(
        name=QUERY_ARRIVAL_RATE_FAST,
        template=(
            f"sum(rate(vllm:request_success_total{_NS_MODEL}"
            f"[{FAST_ARRIVAL_RATE_WINDOW}])"
            f" or rate(jetstream_request_success_total{_NS_MODEL}"
            f"[{FAST_ARRIVAL_RATE_WINDOW}]))"
        ),
        params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
        description=("Model request completion rate over "
                     f"{FAST_ARRIVAL_RATE_WINDOW} (ramp tracking)"),
    ))
    ql.register_if_absent(QueryTemplate(
        name=QUERY_AVG_TTFT,
        template=(
            f"sum(rate(vllm:time_to_first_token_seconds_sum{_NS_MODEL}[5m])"
            f" or rate(jetstream_time_to_first_token_sum{_NS_MODEL}[5m]))"
            f" / sum(rate(vllm:time_to_first_token_seconds_count{_NS_MODEL}[5m])"
            f" or rate(jetstream_time_to_first_token_count{_NS_MODEL}[5m]))"
        ),
        params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
        description="Observed mean TTFT (s) over 5m",
    ))
    ql.register_if_absent(QueryTemplate(
        name=QUERY_AVG_ITL,
        template=(
            f"sum(rate(vllm:time_per_output_token_seconds_sum{_NS_MODEL}[5m])"
            f" or rate(jetstream_time_per_output_token_sum{_NS_MODEL}[5m]))"
            f" / sum(rate(vllm:time_per_output_token_seconds_count{_NS_MODEL}[5m])"
            f" or rate(jetstream_time_per_output_token_count{_NS_MODEL}[5m]))"
        ),
        params=[PARAM_NAMESPACE, PARAM_MODEL_ID],
        description="Observed mean inter-token latency (s) over 5m",
    ))


def collect_optimizer_metrics(
    metrics_source: MetricsSource, model_id: str, namespace: str,
) -> OptimizerMetrics | None:
    """Model-level rate/latency telemetry; None when the arrival rate is
    unavailable (latencies are optional — used only by the tuner)."""
    params = {PARAM_MODEL_ID: model_id, PARAM_NAMESPACE: namespace}
    try:
        results = metrics_source.refresh(RefreshSpec(
            queries=[QUERY_ARRIVAL_RATE, QUERY_ARRIVAL_RATE_FAST,
                     QUERY_AVG_TTFT, QUERY_AVG_ITL],
            params=params))
    except Exception as e:  # noqa: BLE001
        log.debug("optimizer metrics unavailable for %s: %s", model_id, e)
        return None

    def first_value(name: str) -> float | None:
        result = results.get(name)
        if result is None or result.has_error():
            return None
        for v in result.values:
            if math.isfinite(v.value):
                return float(v.value)
        return None

    rate = first_value(QUERY_ARRIVAL_RATE)
    if rate is None:
        return None
    # During ramps the long window under-reports by ~half a window; the fast
    # window keeps up. max() is safe: both are completion rates of the same
    # counters, so steady state agrees and dips fall back to the long window
    # (scale-down damping).
    fast = first_value(QUERY_ARRIVAL_RATE_FAST)
    if fast is not None:
        rate = max(rate, fast)
    return OptimizerMetrics(
        arrival_rate=rate * 60.0,  # req/s -> req/min (reference convention)
        ttft_seconds=first_value(QUERY_AVG_TTFT) or 0.0,
        itl_seconds=first_value(QUERY_AVG_ITL) or 0.0,
    )
