"""Scale-to-zero request counting
(reference ``internal/collector/registration/scale_to_zero.go:30-138``).

``collect_model_request_count`` errors when the count cannot be determined —
the enforcer treats that as "do not scale to zero" (fail-safe).
"""

from __future__ import annotations

import logging

from wva_tpu.collector.source.promql import format_promql_duration
from wva_tpu.collector.source.query_template import QueryTemplate
from wva_tpu.collector.source.registry import PROMETHEUS_SOURCE_NAME, SourceRegistry
from wva_tpu.collector.source.source import (
    PARAM_MODEL_ID,
    PARAM_NAMESPACE,
    MetricsSource,
    RefreshSpec,
)

log = logging.getLogger(__name__)

QUERY_MODEL_REQUEST_COUNT = "model_request_count"
PARAM_RETENTION_PERIOD = "retentionPeriod"

_NS_MODEL = '{namespace="{{.namespace}}",model_name="{{.modelID}}"}'


class RequestCountUnavailableError(RuntimeError):
    pass


def register_scale_to_zero_queries(source_registry: SourceRegistry) -> None:
    src = source_registry.get(PROMETHEUS_SOURCE_NAME)
    if src is None:
        log.debug("Prometheus source not registered; skipping scale-to-zero queries")
        return
    src.query_list().register_if_absent(QueryTemplate(
        name=QUERY_MODEL_REQUEST_COUNT,
        template=(
            f"sum(increase(vllm:request_success_total{_NS_MODEL}[{{{{.retentionPeriod}}}}])"
            f" or increase(jetstream_request_success_total{_NS_MODEL}[{{{{.retentionPeriod}}}}]))"
        ),
        params=[PARAM_NAMESPACE, PARAM_MODEL_ID, PARAM_RETENTION_PERIOD],
        description="Total successful requests for a model over the retention period",
    ))


def collect_model_request_count(
    metrics_source: MetricsSource,
    model_id: str,
    namespace: str,
    retention_seconds: float,
) -> float:
    """Total successful requests over the retention window. Raises
    RequestCountUnavailableError when the count cannot be determined — callers
    MUST treat that as "unknown", never as zero."""
    params = {
        PARAM_MODEL_ID: model_id,
        PARAM_NAMESPACE: namespace,
        PARAM_RETENTION_PERIOD: format_promql_duration(retention_seconds),
    }
    results = metrics_source.refresh(
        RefreshSpec(queries=[QUERY_MODEL_REQUEST_COUNT], params=params))
    result = results.get(QUERY_MODEL_REQUEST_COUNT)
    if result is None:
        raise RequestCountUnavailableError(
            f"no result for request count query for model {model_id}")
    if result.has_error():
        raise RequestCountUnavailableError(
            f"request count query failed for model {model_id}: {result.error}")
    if not result.values:
        raise RequestCountUnavailableError(
            f"no values in request count result for model {model_id} "
            "(metrics may not be scraped yet)")
    return result.first_value().value
