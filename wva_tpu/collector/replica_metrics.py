"""Replica metrics collection: joins per-pod query results, maps pods to VAs,
derives token capacity (reference ``internal/collector/replica_metrics.go:60-468``).

TPU capacity derivation: vLLM-TPU pods expose ``vllm:cache_config_info``
(num_gpu_blocks x block_size, as on GPU); JetStream pods expose
``jetstream_serving_config_info`` whose slot budget gives
``max_concurrent_decodes x tokens_per_slot`` (falling back to
``max_target_length`` per slot) — either way the analyzer sees one
``total_kv_capacity_tokens`` number.
"""

from __future__ import annotations

import logging
import math
from dataclasses import dataclass, field

from wva_tpu.api.v1alpha1 import DEFAULT_VARIANT_COST, VariantAutoscaling
from wva_tpu.collector.registration.saturation import (
    QUERY_AVG_INPUT_TOKENS,
    QUERY_AVG_OUTPUT_TOKENS,
    QUERY_CACHE_CONFIG_INFO,
    QUERY_GENERATE_BACKLOG,
    QUERY_KV_CACHE_USAGE,
    QUERY_PREFIX_CACHE_HIT_RATE,
    QUERY_QUEUE_LENGTH,
    QUERY_SCHEDULER_QUEUE_BYTES,
    QUERY_SCHEDULER_QUEUE_SIZE,
    QUERY_SERVING_CONFIG_INFO,
    QUERY_SLOTS_AVAILABLE,
    QUERY_SLOTS_USED,
)
from wva_tpu.collector.source.pod_va_mapper import PodVAMapper
from wva_tpu.collector.source.source import (
    PARAM_MODEL_ID,
    PARAM_NAMESPACE,
    MetricResult,
    MetricsSource,
    RefreshSpec,
)
from wva_tpu.config.types import FreshnessThresholds
from wva_tpu.constants import ACCELERATOR_NAME_LABEL_KEY
from wva_tpu.interfaces import (
    ReplicaMetrics,
    ReplicaMetricsMetadata,
    SchedulerQueueMetrics,
)
from wva_tpu.k8s.objects import Deployment, Pod
from wva_tpu.utils.clock import SYSTEM_CLOCK, Clock
from wva_tpu.utils.variant import namespaced_key

log = logging.getLogger(__name__)


class MetricsCollectionError(RuntimeError):
    pass


@dataclass
class _PodData:
    kv_usage: float = 0.0
    # Oldest sample timestamp among the load-bearing queries (0 = unknown):
    # drives the freshness classification in the emitted metadata.
    oldest_ts: float = 0.0
    has_kv: bool = False
    queue_len: int = 0
    has_queue: bool = False
    num_kv_blocks: int = 0
    block_size: int = 0
    has_cache_config: bool = False
    jetstream_capacity_tokens: int = 0
    avg_output_tokens: float = 0.0
    avg_input_tokens: float = 0.0
    prefix_cache_hit_rate: float = 0.0
    generate_backlog: int = 0
    slots_used: int = 0
    slots_available: int = 0
    has_slots: bool = False


def _freshness_metadata(collected_at: float, oldest_ts: float,
                        thresholds: FreshnessThresholds) -> ReplicaMetricsMetadata:
    """Classify the pod's sample age (0 = no timestamped samples -> FRESH,
    the in-memory-backend case where samples are synthesized at query
    time)."""
    age = max(collected_at - oldest_ts, 0.0) if oldest_ts > 0 else 0.0
    return ReplicaMetricsMetadata(
        collected_at=collected_at, age_seconds=age,
        freshness=thresholds.determine_status(age))


def _finite(v: float) -> bool:
    return not (math.isnan(v) or math.isinf(v))


def _pod_name(labels: dict[str, str]) -> str:
    return labels.get("pod") or labels.get("pod_name") or ""


class ReplicaMetricsCollector:
    def __init__(self, source: MetricsSource, pod_va_mapper: PodVAMapper | None = None,
                 clock: Clock | None = None,
                 freshness: FreshnessThresholds | None = None) -> None:
        self.source = source
        self.pod_va_mapper = pod_va_mapper
        self.clock = clock or SYSTEM_CLOCK
        # PROMETHEUS_METRICS_CACHE_{FRESH,STALE,UNAVAILABLE}_THRESHOLD:
        # classifies per-replica sample age into the emitted metadata
        # (reference source.go staleness helpers).
        self.freshness = freshness or FreshnessThresholds()

    def scoped(self, source: MetricsSource) -> "ReplicaMetricsCollector":
        """A collector bound to a different source view — the engine hands
        each tick a collector over its tick-scoped GroupedMetricsView while
        the mapper/clock/freshness config stay shared."""
        return ReplicaMetricsCollector(source, self.pod_va_mapper,
                                       clock=self.clock,
                                       freshness=self.freshness)

    def collect_replica_metrics(
        self,
        model_id: str,
        namespace: str,
        deployments: dict[str, Deployment],
        variant_autoscalings: dict[str, VariantAutoscaling],
        variant_costs: dict[str, float] | None = None,
    ) -> list[ReplicaMetrics]:
        """Per-pod metrics for saturation analysis. ``deployments`` and
        ``variant_autoscalings`` are keyed by "namespace/name"."""
        params = {PARAM_MODEL_ID: model_id, PARAM_NAMESPACE: namespace}
        queries = [
            QUERY_KV_CACHE_USAGE,
            QUERY_QUEUE_LENGTH,
            QUERY_CACHE_CONFIG_INFO,
            QUERY_SERVING_CONFIG_INFO,
            QUERY_AVG_OUTPUT_TOKENS,
            QUERY_AVG_INPUT_TOKENS,
            QUERY_PREFIX_CACHE_HIT_RATE,
            QUERY_GENERATE_BACKLOG,
            QUERY_SLOTS_USED,
            QUERY_SLOTS_AVAILABLE,
        ]
        results = self.source.refresh(RefreshSpec(queries=queries, params=params))

        pod_data: dict[str, _PodData] = {}

        def data_for(labels: dict[str, str]) -> _PodData | None:
            name = _pod_name(labels)
            if not name:
                return None
            return pod_data.setdefault(name, _PodData())

        # KV cache + queue are the load-bearing queries: their failure aborts
        # collection (reference :132-136,160-164).
        kv = results.get(QUERY_KV_CACHE_USAGE)
        if kv is not None and kv.has_error():
            raise MetricsCollectionError(f"KV cache query failed: {kv.error}")
        for v in (kv.values if kv else []):
            d = data_for(v.labels)
            if d is not None:
                d.kv_usage, d.has_kv = v.value, True
                if v.timestamp > 0:
                    d.oldest_ts = min(d.oldest_ts or v.timestamp, v.timestamp)

        queue = results.get(QUERY_QUEUE_LENGTH)
        if queue is not None and queue.has_error():
            raise MetricsCollectionError(f"queue length query failed: {queue.error}")
        for v in (queue.values if queue else []):
            d = data_for(v.labels)
            if d is not None:
                d.queue_len, d.has_queue = int(v.value), True
                if v.timestamp > 0:
                    d.oldest_ts = min(d.oldest_ts or v.timestamp, v.timestamp)

        # V2 capacity info: vLLM block config.
        for v in _ok_values(results, QUERY_CACHE_CONFIG_INFO):
            d = data_for(v.labels)
            if d is None:
                continue
            d.num_kv_blocks = _int_label(v.labels, "num_gpu_blocks", d.num_kv_blocks)
            d.block_size = _int_label(v.labels, "block_size", d.block_size)
            if d.num_kv_blocks > 0 and d.block_size > 0:
                d.has_cache_config = True

        # V2 capacity info: JetStream slot budget.
        for v in _ok_values(results, QUERY_SERVING_CONFIG_INFO):
            d = data_for(v.labels)
            if d is None:
                continue
            decodes = _int_label(v.labels, "max_concurrent_decodes", 0)
            per_slot = _int_label(v.labels, "tokens_per_slot", 0) or \
                _int_label(v.labels, "max_target_length", 0)
            if decodes > 0 and per_slot > 0:
                d.jetstream_capacity_tokens = decodes * per_slot

        for v in _ok_values(results, QUERY_AVG_OUTPUT_TOKENS):
            d = data_for(v.labels)
            if d is not None and _finite(v.value):
                d.avg_output_tokens = v.value
        for v in _ok_values(results, QUERY_AVG_INPUT_TOKENS):
            d = data_for(v.labels)
            if d is not None and _finite(v.value):
                d.avg_input_tokens = v.value
        for v in _ok_values(results, QUERY_PREFIX_CACHE_HIT_RATE):
            d = data_for(v.labels)
            if d is not None and _finite(v.value) and 0 <= v.value <= 1:
                d.prefix_cache_hit_rate = v.value

        for v in _ok_values(results, QUERY_GENERATE_BACKLOG):
            d = data_for(v.labels)
            if d is not None and _finite(v.value):
                d.generate_backlog = int(v.value)
        for v in _ok_values(results, QUERY_SLOTS_USED):
            d = data_for(v.labels)
            if d is not None and _finite(v.value):
                d.slots_used, d.has_slots = int(v.value), True
        for v in _ok_values(results, QUERY_SLOTS_AVAILABLE):
            d = data_for(v.labels)
            if d is not None and _finite(v.value):
                d.slots_available = int(v.value)
                d.has_slots = True

        # Join into ReplicaMetrics.
        collected_at = self.clock.now()
        out: list[ReplicaMetrics] = []
        for pod_name in sorted(pod_data):
            data = pod_data[pod_name]
            if not data.has_kv and not data.has_queue:
                continue

            va_name = self._find_va_for_pod(pod_name, namespace, deployments)
            if not va_name:
                log.info("Skipping pod %s: no matching deployment/VA", pod_name)
                continue
            variant_key = namespaced_key(namespace, va_name)

            accelerator = ""
            va = variant_autoscalings.get(variant_key)
            if va is not None:
                accelerator = va.metadata.labels.get(ACCELERATOR_NAME_LABEL_KEY, "")

            cost = DEFAULT_VARIANT_COST
            if variant_costs and variant_key in variant_costs:
                cost = variant_costs[variant_key]

            total_capacity = 0
            if data.has_cache_config:
                total_capacity = data.num_kv_blocks * data.block_size
            elif data.jetstream_capacity_tokens > 0:
                total_capacity = data.jetstream_capacity_tokens
            tokens_in_use = 0
            if total_capacity > 0:
                tokens_in_use = int(
                    min(max(round(data.kv_usage * total_capacity), 0), total_capacity))

            out.append(ReplicaMetrics(
                pod_name=pod_name,
                model_id=model_id,
                namespace=namespace,
                variant_name=va_name,
                accelerator_name=accelerator,
                kv_cache_usage=data.kv_usage,
                queue_length=data.queue_len,
                cost=cost,
                num_kv_blocks=data.num_kv_blocks,
                block_size=data.block_size,
                total_kv_capacity_tokens=total_capacity,
                tokens_in_use=tokens_in_use,
                avg_output_tokens=data.avg_output_tokens,
                avg_input_tokens=data.avg_input_tokens,
                prefix_cache_hit_rate=data.prefix_cache_hit_rate,
                generate_backlog=data.generate_backlog,
                slots_used=data.slots_used,
                slots_total=data.slots_used + data.slots_available if data.has_slots else 0,
                metadata=_freshness_metadata(collected_at, data.oldest_ts,
                                             self.freshness),
            ))
        log.debug("Collected %d replica metrics for %s/%s",
                  len(out), namespace, model_id)
        return out

    def _find_va_for_pod(self, pod_name: str, namespace: str,
                         deployments: dict[str, Deployment]) -> str:
        if self.pod_va_mapper is None:
            return ""
        pod = self.pod_va_mapper.client.try_get(Pod.KIND, namespace, pod_name)
        if pod is None:
            # Pod metrics can outlive the pod briefly; fall back to prefix
            # matching against the tracked deployments.
            for key in deployments:
                dep_name = key.split("/", 1)[1]
                if pod_name.startswith(dep_name + "-"):
                    return self.pod_va_mapper.va_name_for_scale_target_name(
                        dep_name, namespace) or ""
            return ""
        tracked = {key.split("/", 1)[1] for key in deployments}
        # Name-only resolution: the join consumes nothing but the VA name,
        # and the full-object lookup cost one VA GET per pod per tick.
        return self.pod_va_mapper.va_name_for_pod(
            pod, tracked_deployments=tracked) or ""

    def collect_scheduler_queue_metrics(self, model_id: str) -> SchedulerQueueMetrics | None:
        """Model-level flow-control queue; None when unavailable
        (reference :409-468)."""
        params = {PARAM_MODEL_ID: model_id}
        try:
            results = self.source.refresh(RefreshSpec(
                queries=[QUERY_SCHEDULER_QUEUE_SIZE, QUERY_SCHEDULER_QUEUE_BYTES],
                params=params))
        except Exception as e:  # noqa: BLE001
            log.debug("scheduler queue metrics unavailable for %s: %s", model_id, e)
            return None

        queue_size = queue_bytes = 0
        has_data = False
        for v in _ok_values(results, QUERY_SCHEDULER_QUEUE_SIZE):
            if _finite(v.value):
                queue_size += int(v.value)
                has_data = True
        for v in _ok_values(results, QUERY_SCHEDULER_QUEUE_BYTES):
            if _finite(v.value):
                queue_bytes += int(v.value)
                has_data = True
        if not has_data:
            return None
        return SchedulerQueueMetrics(queue_size=queue_size, queue_bytes=queue_bytes)


def _ok_values(results: dict[str, MetricResult], name: str):
    result = results.get(name)
    if result is None or result.has_error():
        return []
    return result.values


def _int_label(labels: dict[str, str], key: str, default: int) -> int:
    raw = labels.get(key, "")
    if not raw:
        return default
    try:
        return int(float(raw))
    except ValueError:
        return default
