"""Data-acquisition layer (reference ``internal/collector``)."""
