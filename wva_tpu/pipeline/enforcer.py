"""Scale-to-zero / minimum-replica enforcement
(reference ``pipeline/enforcer.go:18-183``).

Fail-safe contract: an unknown request count keeps the current targets —
scale-to-zero only happens on positive confirmation of zero traffic.
"""

from __future__ import annotations

import logging
from typing import Callable

from wva_tpu.api.v1alpha1 import DEFAULT_VARIANT_COST
from wva_tpu.config import (
    ScaleToZeroConfigData,
    is_scale_to_zero_enabled,
    scale_to_zero_retention_seconds,
)
from wva_tpu.interfaces import (
    ACTION_NO_CHANGE,
    ACTION_SCALE_DOWN,
    ACTION_SCALE_UP,
    VariantDecision,
    VariantSaturationAnalysis,
)

log = logging.getLogger(__name__)

# Decision-step reason stamped on scale-to-zero enforcement. Shared by the
# V1 engine path and the replay engine: record/replay equality hinges on
# both producing the identical string, so there is exactly one copy.
SCALE_TO_ZERO_REASON = "scale-to-zero: no requests within retention"

# (model_id, namespace, retention_seconds) -> request count; raises when the
# count cannot be determined. A callback that can route through a
# tick-scoped metrics view (grouped collection) additionally accepts
# ``source=`` and declares it by setting ``supports_source = True`` on
# itself — older callbacks (replay harness, tests) need no change.
RequestCountFunc = Callable[[str, str, float], float]


class Enforcer:
    def __init__(self, request_count_func: RequestCountFunc) -> None:
        self.request_count_func = request_count_func
        # Optional blackbox.FlightRecorder: when set, every enforce_policy
        # call records its request-count observation and outcome — replay
        # re-feeds the recorded count instead of querying a collector.
        self.flight_recorder = None
        # Tick-scoped metrics source override (the engine's
        # GroupedMetricsView): set for the duration of one engine tick so
        # the scale-to-zero request count rides the same fleet-wide grouped
        # query as everything else. Enforcement runs on the engine thread
        # only, so a plain attribute is race-free.
        self.metrics_source = None

    def enforce_policy(
        self,
        model_id: str,
        namespace: str,
        saturation_targets: dict[str, int],
        variant_analyses: list[VariantSaturationAnalysis],
        scale_to_zero_config: ScaleToZeroConfigData,
    ) -> tuple[dict[str, int], bool]:
        """Returns (targets, applied). When scale-to-zero is enabled for the
        model: zero requests over retention => all targets 0; query error =>
        keep targets. When disabled: guarantee >= 1 total replica, restored
        on the cheapest variant."""
        trace = {"model_id": model_id, "namespace": namespace,
                 "request_count": None, "error": None, "retention": None}
        if is_scale_to_zero_enabled(scale_to_zero_config, model_id):
            targets, applied = self._apply_scale_to_zero(
                model_id, namespace, saturation_targets, scale_to_zero_config,
                trace)
        else:
            targets, applied = self._ensure_minimum_replicas(
                model_id, saturation_targets, variant_analyses)
        if self.flight_recorder is not None:
            from wva_tpu.blackbox.schema import encode_scale_to_zero_config

            trace.update(
                targets=dict(targets), scaled_to_zero=applied,
                s2z_config=encode_scale_to_zero_config(scale_to_zero_config))
            self.flight_recorder.record_stage("enforcer", trace)
        return targets, applied

    def _apply_scale_to_zero(
        self,
        model_id: str,
        namespace: str,
        targets: dict[str, int],
        scale_to_zero_config: ScaleToZeroConfigData,
        trace: dict | None = None,
    ) -> tuple[dict[str, int], bool]:
        retention = scale_to_zero_retention_seconds(scale_to_zero_config, model_id)
        if trace is not None:
            trace["retention"] = retention
        try:
            if (self.metrics_source is not None
                    and getattr(self.request_count_func,
                                "supports_source", False)):
                count = self.request_count_func(
                    model_id, namespace, retention,
                    source=self.metrics_source)
            else:
                count = self.request_count_func(model_id, namespace, retention)
        except Exception as e:  # noqa: BLE001 — fail-safe boundary
            if trace is not None:
                trace["error"] = str(e)
            log.warning("Failed to get request count for %s, keeping targets: %s",
                        model_id, e)
            return targets, False
        if trace is not None:
            trace["request_count"] = count
        if count > 0:
            return targets, False
        log.info("No requests for %s/%s in %.0fs retention, scaling to zero",
                 namespace, model_id, retention)
        for variant in targets:
            targets[variant] = 0
        return targets, True

    @staticmethod
    def _ensure_minimum_replicas(
        model_id: str,
        targets: dict[str, int],
        variant_analyses: list[VariantSaturationAnalysis],
    ) -> tuple[dict[str, int], bool]:
        if sum(targets.values()) > 0:
            return targets, False
        costs = {va.variant_name: va.cost for va in variant_analyses}
        cheapest = ""
        cheapest_cost = -1.0
        for variant in targets:
            cost = costs.get(variant, DEFAULT_VARIANT_COST)
            if cheapest_cost < 0 or cost < cheapest_cost or \
                    (cost == cheapest_cost and variant < cheapest):
                cheapest, cheapest_cost = variant, cost
        if cheapest:
            targets[cheapest] = 1
            log.info("Preserving minimum replica for %s on cheapest variant %s",
                     model_id, cheapest)
            return targets, True
        return targets, False


def bridge_enforce(
    decisions: list[VariantDecision],
    model_id: str,
    namespace: str,
    enforcer: Enforcer,
    scale_to_zero_config: ScaleToZeroConfigData,
    now: float,
    optimizer_name: str,
) -> bool:
    """Enforcer bridge for the V2/SLO optimizer flow (reference
    engine_v2.go:76-127): run policy enforcement over one model's
    optimizer-produced decisions, adjusting them in place and appending the
    enforcer's audit step. Module-level so the trace replay harness re-runs
    the exact production code path. Returns whether scale-to-zero applied."""
    targets = {d.variant_name: d.target_replicas for d in decisions
               if d.model_id == model_id and d.namespace == namespace}
    analyses = [
        VariantSaturationAnalysis(
            variant_name=d.variant_name, accelerator_name=d.accelerator_name,
            cost=d.cost, replica_count=d.current_replicas)
        for d in decisions
        if d.model_id == model_id and d.namespace == namespace
    ]
    enforced, scaled_to_zero = enforcer.enforce_policy(
        model_id, namespace, targets, analyses, scale_to_zero_config)
    for d in decisions:
        if d.model_id != model_id or d.namespace != namespace:
            continue
        target = enforced.get(d.variant_name)
        if target is not None and target != d.target_replicas:
            d.target_replicas = target
            if target > d.current_replicas:
                d.action = ACTION_SCALE_UP
            elif target < d.current_replicas:
                d.action = ACTION_SCALE_DOWN
            else:
                d.action = ACTION_NO_CHANGE
            d.reason = (f"V2 {d.action} (optimizer: "
                        f"{optimizer_name}, enforced)")
            d.add_step("enforcer",
                       (SCALE_TO_ZERO_REASON if scaled_to_zero
                        else f"min-replica floor -> {target}"),
                       was_constrained=True, now=now)
        else:
            d.add_step("enforcer", "no policy change", now=now)
    return scaled_to_zero
