"""Scale-to-zero / minimum-replica enforcement
(reference ``pipeline/enforcer.go:18-183``).

Fail-safe contract: an unknown request count keeps the current targets —
scale-to-zero only happens on positive confirmation of zero traffic.
"""

from __future__ import annotations

import logging
from typing import Callable

from wva_tpu.api.v1alpha1 import DEFAULT_VARIANT_COST
from wva_tpu.config import (
    ScaleToZeroConfigData,
    is_scale_to_zero_enabled,
    scale_to_zero_retention_seconds,
)
from wva_tpu.interfaces import VariantSaturationAnalysis

log = logging.getLogger(__name__)

# (model_id, namespace, retention_seconds) -> request count; raises when the
# count cannot be determined.
RequestCountFunc = Callable[[str, str, float], float]


class Enforcer:
    def __init__(self, request_count_func: RequestCountFunc) -> None:
        self.request_count_func = request_count_func

    def enforce_policy(
        self,
        model_id: str,
        namespace: str,
        saturation_targets: dict[str, int],
        variant_analyses: list[VariantSaturationAnalysis],
        scale_to_zero_config: ScaleToZeroConfigData,
    ) -> tuple[dict[str, int], bool]:
        """Returns (targets, applied). When scale-to-zero is enabled for the
        model: zero requests over retention => all targets 0; query error =>
        keep targets. When disabled: guarantee >= 1 total replica, restored
        on the cheapest variant."""
        if is_scale_to_zero_enabled(scale_to_zero_config, model_id):
            return self._apply_scale_to_zero(
                model_id, namespace, saturation_targets, scale_to_zero_config)
        return self._ensure_minimum_replicas(
            model_id, saturation_targets, variant_analyses)

    def _apply_scale_to_zero(
        self,
        model_id: str,
        namespace: str,
        targets: dict[str, int],
        scale_to_zero_config: ScaleToZeroConfigData,
    ) -> tuple[dict[str, int], bool]:
        retention = scale_to_zero_retention_seconds(scale_to_zero_config, model_id)
        try:
            count = self.request_count_func(model_id, namespace, retention)
        except Exception as e:  # noqa: BLE001 — fail-safe boundary
            log.warning("Failed to get request count for %s, keeping targets: %s",
                        model_id, e)
            return targets, False
        if count > 0:
            return targets, False
        log.info("No requests for %s/%s in %.0fs retention, scaling to zero",
                 namespace, model_id, retention)
        for variant in targets:
            targets[variant] = 0
        return targets, True

    @staticmethod
    def _ensure_minimum_replicas(
        model_id: str,
        targets: dict[str, int],
        variant_analyses: list[VariantSaturationAnalysis],
    ) -> tuple[dict[str, int], bool]:
        if sum(targets.values()) > 0:
            return targets, False
        costs = {va.variant_name: va.cost for va in variant_analyses}
        cheapest = ""
        cheapest_cost = -1.0
        for variant in targets:
            cost = costs.get(variant, DEFAULT_VARIANT_COST)
            if cheapest_cost < 0 or cost < cheapest_cost or \
                    (cost == cheapest_cost and variant < cheapest):
                cheapest, cheapest_cost = variant, cost
        if cheapest:
            targets[cheapest] = 1
            log.info("Preserving minimum replica for %s on cheapest variant %s",
                     model_id, cheapest)
            return targets, True
        return targets, False
