"""Decision pipeline stages (reference ``internal/engines/pipeline``)."""

from wva_tpu.pipeline.optimizer import (
    CostAwareOptimizer,
    ModelScalingRequest,
    ScalingOptimizer,
    saturation_targets_to_decisions,
)
from wva_tpu.pipeline.enforcer import (
    Enforcer,
    SCALE_TO_ZERO_REASON,
    bridge_enforce,
)
from wva_tpu.pipeline.limiter import (
    AllocationAlgorithm,
    DefaultLimiter,
    GreedyBySaturation,
    Inventory,
    Limiter,
    ResourceAllocator,
    ResourceConstraints,
    ResourcePool,
    SliceInventory,
    StaticInventory,
)

__all__ = [
    "CostAwareOptimizer",
    "ModelScalingRequest",
    "ScalingOptimizer",
    "saturation_targets_to_decisions",
    "Enforcer",
    "SCALE_TO_ZERO_REASON",
    "bridge_enforce",
    "AllocationAlgorithm",
    "DefaultLimiter",
    "GreedyBySaturation",
    "Inventory",
    "Limiter",
    "ResourceAllocator",
    "ResourceConstraints",
    "ResourcePool",
    "SliceInventory",
    "StaticInventory",
]
