"""Decision pipeline stages (reference ``internal/engines/pipeline``)."""

from wva_tpu.pipeline.optimizer import (
    CostAwareOptimizer,
    ModelScalingRequest,
    ScalingOptimizer,
)
from wva_tpu.pipeline.enforcer import Enforcer
from wva_tpu.pipeline.limiter import (
    AllocationAlgorithm,
    DefaultLimiter,
    GreedyBySaturation,
    Inventory,
    Limiter,
    ResourceAllocator,
    ResourceConstraints,
    ResourcePool,
    SliceInventory,
)

__all__ = [
    "CostAwareOptimizer",
    "ModelScalingRequest",
    "ScalingOptimizer",
    "Enforcer",
    "AllocationAlgorithm",
    "DefaultLimiter",
    "GreedyBySaturation",
    "Inventory",
    "Limiter",
    "ResourceAllocator",
    "ResourceConstraints",
    "ResourcePool",
    "SliceInventory",
]
