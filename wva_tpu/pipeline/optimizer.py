"""Scaling optimizers (reference ``pipeline/{optimizer_interfaces,
cost_aware_optimizer}.go``).

``CostAwareOptimizer`` (unlimited mode): per model, scale-up fills
required_capacity on variants sorted by cost/per-replica-capacity ascending;
scale-down removes floor(spare/per_replica) from most-expensive-first with the
cheapest protected at 1 only when it is the last variant with replicas.
"""

from __future__ import annotations

import abc
import logging
import math
from dataclasses import dataclass, field

from wva_tpu.interfaces import (
    ACTION_NO_CHANGE,
    ACTION_SCALE_DOWN,
    ACTION_SCALE_UP,
    AnalyzerResult,
    ModelSaturationAnalysis,
    VariantCapacity,
    VariantDecision,
    VariantReplicaState,
)

log = logging.getLogger(__name__)


def saturation_targets_to_decisions(
    targets: dict[str, int],
    analysis: ModelSaturationAnalysis,
    variant_states: list[VariantReplicaState],
    enforcer_note: str = "",
) -> list[VariantDecision]:
    """Convert V1 saturation targets to decisions (reference
    engine.go:586-659). Module-level (not an engine method) so the trace
    replay harness re-runs the exact production code path offline.
    ``enforcer_note`` carries the already-applied enforcement outcome into
    the decision audit trail (the V1 path enforces on raw targets before
    decisions exist)."""
    analyses = {va.variant_name: va for va in analysis.variant_analyses}
    states = {s.variant_name: s for s in variant_states}
    decisions = []
    for variant_name in sorted(targets):
        target = targets[variant_name]
        state = states.get(variant_name,
                           VariantReplicaState(variant_name=variant_name))
        va = analyses.get(variant_name)
        if target > state.current_replicas:
            action = ACTION_SCALE_UP
        elif target < state.current_replicas:
            action = ACTION_SCALE_DOWN
        else:
            action = ACTION_NO_CHANGE
        decision = VariantDecision(
            variant_name=variant_name,
            namespace=analysis.namespace,
            model_id=analysis.model_id,
            current_replicas=state.current_replicas,
            target_replicas=target,
            original_target_replicas=target,
            desired_replicas=state.desired_replicas,
            action=action,
            saturation_based=True,
            saturation_only=True,
            reason=f"saturation-only mode: {action}",
            chips_per_replica=max(state.chips_per_replica, 1),
        )
        if va is not None:
            decision.accelerator_name = va.accelerator_name
            decision.cost = va.cost
            decision.spare_capacity = va.avg_spare_kv_capacity
        ts = analysis.analyzed_at or None
        decision.add_step(
            "analyzer:v1",
            (analysis.scale_up_reason if analysis.should_scale_up
             else "no saturation trigger"
             f" (spare kv {analysis.avg_spare_kv_capacity:.2f},"
             f" spare queue {analysis.avg_spare_queue_length:.1f})"),
            now=ts)
        decision.add_step("optimizer:percentage",
                          f"saturation-only mode: {action}", now=ts)
        decision.add_step("enforcer", enforcer_note or "no policy change",
                          was_constrained=bool(enforcer_note), now=ts)
        decisions.append(decision)
    return decisions


@dataclass
class ModelScalingRequest:
    """Analyzer result + variant state for one model."""

    model_id: str = ""
    namespace: str = ""
    result: AnalyzerResult | None = None
    variant_states: list[VariantReplicaState] = field(default_factory=list)


class ScalingOptimizer(abc.ABC):
    @abc.abstractmethod
    def name(self) -> str: ...

    @abc.abstractmethod
    def optimize(self, requests: list[ModelScalingRequest],
                 constraints: list | None = None) -> list[VariantDecision]:
        """Produce decisions for all models; constraints may be None
        (unlimited mode)."""


def _cost_efficiency(vc: VariantCapacity) -> float:
    if vc.per_replica_capacity <= 0:
        return math.inf
    return vc.cost / vc.per_replica_capacity


class CostAwareOptimizer(ScalingOptimizer):
    # Optional blackbox.FlightRecorder: when set, every optimize() call
    # records per-model targets into the current engine cycle's trace.
    flight_recorder = None

    def name(self) -> str:
        return "cost-aware"

    def optimize(self, requests: list[ModelScalingRequest],
                 constraints: list | None = None) -> list[VariantDecision]:
        decisions: list[VariantDecision] = []
        for req in requests:
            if req.result is None:
                continue
            states = {s.variant_name: s for s in req.variant_states}
            capacities = {vc.variant_name: vc for vc in req.result.variant_capacities}
            targets = {s.variant_name: s.current_replicas for s in req.variant_states}

            if req.result.required_capacity > 0:
                self._scale_up(req.result, targets)
            elif req.result.spare_capacity > 0:
                self._scale_down(req.result, targets)

            if self.flight_recorder is not None:
                self.flight_recorder.record_stage("optimizer", {
                    "name": self.name(),
                    "model_id": req.model_id,
                    "namespace": req.namespace,
                    "required_capacity": req.result.required_capacity,
                    "spare_capacity": req.result.spare_capacity,
                    "targets": dict(targets),
                })
            decisions.extend(self._build_decisions(req, states, capacities, targets))
        return decisions

    @staticmethod
    def _scale_up(result: AnalyzerResult, targets: dict[str, int]) -> None:
        """Fill required capacity cheapest-efficiency-first (reference
        :77-104). Pending replicas are NOT skipped — the analyzer already
        counted their capacity into anticipated supply."""
        remaining = result.required_capacity
        for vc in sorted(result.variant_capacities, key=_cost_efficiency):
            if remaining <= 0:
                break
            if vc.per_replica_capacity <= 0:
                continue
            needed = math.ceil(remaining / vc.per_replica_capacity)
            targets[vc.variant_name] = targets.get(vc.variant_name, 0) + needed
            remaining -= needed * vc.per_replica_capacity

    @staticmethod
    def _scale_down(result: AnalyzerResult, targets: dict[str, int]) -> None:
        """Remove whole replicas most-expensive-first while spare covers them
        (reference :111-167)."""
        capacities = result.variant_capacities
        cheapest = min(capacities, key=lambda vc: vc.cost).variant_name \
            if capacities else ""
        remaining = result.spare_capacity
        for vc in sorted(capacities, key=lambda v: -v.cost):
            if remaining <= 0:
                break
            if vc.per_replica_capacity <= 0:
                continue
            current = targets.get(vc.variant_name, 0)
            min_replicas = 0
            if vc.variant_name == cheapest:
                # Protect cheapest at 1 only when no other variant has replicas
                # (prevents scale-down deadlock).
                other_has = any(t > 0 for name, t in targets.items()
                                if name != cheapest)
                if not other_has:
                    min_replicas = 1
            removable = current - min_replicas
            if removable <= 0:
                continue
            to_remove = min(int(remaining // vc.per_replica_capacity), removable)
            if to_remove <= 0:
                continue
            targets[vc.variant_name] = current - to_remove
            remaining -= to_remove * vc.per_replica_capacity

    def _build_decisions(
        self,
        req: ModelScalingRequest,
        states: dict[str, VariantReplicaState],
        capacities: dict[str, VariantCapacity],
        targets: dict[str, int],
    ) -> list[VariantDecision]:
        decisions = []
        for name in sorted(targets):
            target = targets[name]
            state = states.get(name, VariantReplicaState(variant_name=name))
            vc = capacities.get(name, VariantCapacity(variant_name=name))
            if target > state.current_replicas:
                action = ACTION_SCALE_UP
                reason = (f"V2 scale-up (optimizer: cost-aware, "
                          f"required: {req.result.required_capacity:.0f})")
            elif target < state.current_replicas:
                action = ACTION_SCALE_DOWN
                reason = (f"V2 scale-down (optimizer: cost-aware, "
                          f"spare: {req.result.spare_capacity:.0f})")
            else:
                action = ACTION_NO_CHANGE
                reason = "V2 steady state"
            decision = VariantDecision(
                variant_name=name,
                model_id=req.model_id,
                namespace=req.namespace,
                accelerator_name=vc.accelerator_name,
                cost=vc.cost,
                current_replicas=state.current_replicas,
                target_replicas=target,
                chips_per_replica=state.chips_per_replica,
                action=action,
                reason=reason,
            )
            # Decision audit trail (reference saturation_analyzer.go:109-124
            # DecisionSteps): one entry per pipeline stage. Decisions
            # materialize here, so the analyzer's contribution is recorded
            # first, from its result.
            ts = req.result.analyzed_at or None
            decision.add_step(
                f"analyzer:{req.result.analyzer_name or 'saturation'}",
                f"demand={req.result.total_demand:.2f} "
                f"supply={req.result.total_supply:.2f} "
                f"required={req.result.required_capacity:.2f} "
                f"spare={req.result.spare_capacity:.2f}",
                now=ts)
            decision.add_step(f"optimizer:{self.name()}", reason, now=ts)
            decisions.append(decision)
        return decisions
