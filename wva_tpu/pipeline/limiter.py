"""Resource limiter: slice-granular inventory x allocation algorithm
(reference ``pipeline/{limiter_interfaces,default_limiter,type_inventory,
greedy_saturation_algorithm}.go``).

TPU re-design of the reference's per-GPU-type pooling: the inventory unit is
the **whole slice**. Each variant pool (e.g. ``v5e-8``) counts chips backed by
whole schedulable slices; allocation is quantized to a replica's chip
requirement (= chips per slice for slice-spanning replicas), and a typed
allocator prevents cross-variant allocation, replacing the reference's
``normalizeAcceleratorName`` GPU-product matching (type_inventory.go:23-65)
with the canonical variant names from discovery.
"""

from __future__ import annotations

import abc
import logging
from dataclasses import dataclass, field

from wva_tpu.discovery import TPUSliceDiscovery
from wva_tpu.interfaces import VariantDecision
from wva_tpu.utils.clock import SYSTEM_CLOCK, Clock

log = logging.getLogger(__name__)


@dataclass
class ResourcePool:
    """Per-variant chip pool."""

    accelerator_type: str = ""
    limit: int = 0  # chips in whole slices
    used: int = 0

    @property
    def available(self) -> int:
        return max(self.limit - self.used, 0)


@dataclass
class ResourceConstraints:
    """Per-type availability for the V2 optimizer path."""

    provider_name: str = ""
    pools: dict[str, ResourcePool] = field(default_factory=dict)
    total_limit: int = 0
    total_used: int = 0
    total_available: int = 0


class ResourceAllocator(abc.ABC):
    """Abstracts reservation granularity for allocation algorithms."""

    @abc.abstractmethod
    def try_allocate(self, decision: VariantDecision, chips: int) -> int:
        """Reserve up to ``chips`` for the decision; returns granted count."""


class Inventory(abc.ABC):
    @abc.abstractmethod
    def refresh(self) -> None: ...

    @abc.abstractmethod
    def set_used(self, used_by_type: dict[str, int]) -> None: ...

    @abc.abstractmethod
    def create_allocator(self) -> ResourceAllocator: ...

    @abc.abstractmethod
    def pools(self) -> dict[str, ResourcePool]: ...

    def total_limit(self) -> int:
        return sum(p.limit for p in self.pools().values())

    def total_used(self) -> int:
        return sum(p.used for p in self.pools().values())

    def total_available(self) -> int:
        return sum(p.available for p in self.pools().values())


class AllocationAlgorithm(abc.ABC):
    @abc.abstractmethod
    def name(self) -> str: ...

    @abc.abstractmethod
    def allocate(self, decisions: list[VariantDecision],
                 allocator: ResourceAllocator) -> None: ...


class Limiter(abc.ABC):
    @abc.abstractmethod
    def name(self) -> str: ...

    @abc.abstractmethod
    def limit(self, decisions: list[VariantDecision]) -> None:
        """Constrain decisions in place."""


class SliceInventory(Inventory):
    """Chip pools per TPU slice variant, fed by discovery. Only chips that
    belong to whole schedulable slices count toward the limit — plus, when
    the elastic capacity plane is wired, chips of slices the provisioner
    has in flight within their credited lead window (``ready +
    provisioning-arriving-within-lead-time``): a scale-up the provisioner
    is already fulfilling must not be re-clamped to zero and re-ordered."""

    def __init__(self, discovery: TPUSliceDiscovery) -> None:
        self.discovery = discovery
        self._pools: dict[str, ResourcePool] = {}
        # Optional wva_tpu.capacity.CapacityManager; None = static
        # inventory semantics, byte-identical to pre-capacity builds.
        self.capacity = None
        # The discovery snapshot of the LAST refresh: the engine's
        # capacity pass runs in the same tick and reuses it instead of
        # listing + parsing the node fleet a second time.
        self.last_slices: dict | None = None

    def refresh(self) -> None:
        slices = self.discovery.discover_slices()
        self.last_slices = slices
        pools = {}
        for variant, cap in slices.items():
            limit = cap.total_slices * cap.chips_per_slice
            if self.capacity is not None:
                limit += self.capacity.pool_credit_chips(variant)
            pools[variant] = ResourcePool(
                accelerator_type=variant,
                limit=limit,
                used=self._pools.get(variant, ResourcePool()).used,
            )
        if self.capacity is not None:
            # A variant whose FIRST slices are still provisioning has no
            # discovered pool yet; its in-flight credit still needs a pool
            # or the limiter would clamp the pending scale-up to zero and
            # the manager would re-order every tick.
            for variant, credit in self.capacity.credit_only_pools(
                    set(pools)).items():
                pools[variant] = ResourcePool(
                    accelerator_type=variant, limit=credit,
                    used=self._pools.get(variant, ResourcePool()).used)
        self._pools = pools

    def set_used(self, used_by_type: dict[str, int]) -> None:
        for pool in self._pools.values():
            pool.used = 0
        for variant, used in used_by_type.items():
            pool = self._pools.get(variant)
            if pool is not None:
                pool.used = used

    def create_allocator(self) -> ResourceAllocator:
        return _TypedSliceAllocator(self._pools)

    def pools(self) -> dict[str, ResourcePool]:
        # Value copies: consumers (the V2 constraint path) may decrement
        # availability while planning without corrupting inventory state.
        return {k: ResourcePool(accelerator_type=p.accelerator_type,
                                limit=p.limit, used=p.used)
                for k, p in self._pools.items()}


class StaticInventory(Inventory):
    """Fixed chip pools (type -> chip limit): no discovery behind it.
    Used by the trace replay harness (pools reconstructed from a recorded
    limiter snapshot) and by tests that need a deterministic inventory."""

    def __init__(self, limits: dict[str, int]) -> None:
        self._pools = {
            t: ResourcePool(accelerator_type=t, limit=int(limit))
            for t, limit in limits.items()}

    def refresh(self) -> None:
        pass

    def set_used(self, used_by_type: dict[str, int]) -> None:
        for pool in self._pools.values():
            pool.used = 0
        for variant, used in used_by_type.items():
            pool = self._pools.get(variant)
            if pool is not None:
                pool.used = used

    def create_allocator(self) -> ResourceAllocator:
        return _TypedSliceAllocator(self._pools)

    def pools(self) -> dict[str, ResourcePool]:
        return {k: ResourcePool(accelerator_type=p.accelerator_type,
                                limit=p.limit, used=p.used)
                for k, p in self._pools.items()}


class _TypedSliceAllocator(ResourceAllocator):
    """Allocates only from the decision's own variant pool — cross-type
    allocation is impossible (reference typeAllocator :337-377)."""

    def __init__(self, pools: dict[str, ResourcePool]) -> None:
        self._pools = pools

    def try_allocate(self, decision: VariantDecision, chips: int) -> int:
        pool = self._pools.get(decision.accelerator_name)
        if pool is None or chips <= 0:
            return 0
        granted = min(chips, pool.available)
        pool.used += granted
        return granted


class GreedyBySaturation(AllocationAlgorithm):
    """Allocate to the most saturated variants first
    (reference greedy_saturation_algorithm.go:34-106).

    Two equivalent implementations of the grant pass:

    - **sequential** (default): one ``try_allocate`` round trip per
      scale-up decision — the reference shape.
    - **masked** (``vectorized = True``, set by the fused decision plane
      WVA_FUSED): per-pool clamp arithmetic over the whole sorted
      decision array at once. Greedy sequential consumption from a pool
      is exactly ``grant_i = clip(avail - cum_prev_requests_i, 0,
      req_i)`` — a cumulative sum plus masks, no per-decision branches.
      Integer math, so the two forms are equal by construction
      (property-asserted in tests/test_fused_plane.py).
    """

    # Flipped on by the fused decision plane (WVA_FUSED); default off so
    # standalone Limiter users keep the reference shape.
    vectorized = False

    def name(self) -> str:
        return "greedy-by-saturation"

    def allocate(self, decisions: list[VariantDecision],
                 allocator: ResourceAllocator) -> None:
        candidates = [d for d in decisions
                      if d.target_replicas > d.current_replicas]
        # Most saturated first (lowest spare), then cheapest.
        candidates.sort(key=lambda d: (d.spare_capacity, d.cost))
        if self.vectorized and isinstance(allocator, _TypedSliceAllocator):
            self._allocate_masked(candidates, allocator)
            return
        for d in candidates:
            self._allocate_for_decision(d, allocator)

    @staticmethod
    def _allocate_masked(candidates: list[VariantDecision],
                         allocator: "_TypedSliceAllocator") -> None:
        """The masked grant pass. For each pool, in the sorted decision
        order: every decision before the exhaustion point receives its
        full request, the decision at the exhaustion point receives the
        remainder (the pool consumes the unusable sub-replica tail, as
        the sequential allocator does), everything after receives 0."""
        import numpy as np

        if not candidates:
            return
        n = len(candidates)
        chips_per = np.array(
            [d.chips_per_replica if d.chips_per_replica > 0 else 1
             for d in candidates], dtype=np.int64)
        needed = np.array(
            [d.target_replicas - d.current_replicas for d in candidates],
            dtype=np.int64)
        requested = needed * chips_per
        grants = np.zeros(n, dtype=np.int64)
        names = [d.accelerator_name for d in candidates]
        for pool_name in dict.fromkeys(names):
            pool = allocator._pools.get(pool_name)
            if pool is None:
                continue  # unknown variant: grant stays 0
            mask = np.fromiter((nm == pool_name for nm in names),
                               dtype=bool, count=n)
            req = requested[mask]
            cum_prev = np.concatenate(([0], np.cumsum(req)[:-1]))
            granted = np.clip(pool.available - cum_prev, 0, req)
            grants[mask] = granted
            pool.used += int(granted.sum())
        replicas = grants // chips_per
        for d, r, need, cp in zip(candidates, replicas, needed, chips_per):
            d.chips_allocated = int(r * cp)
            d.target_replicas = d.current_replicas + int(r)
            if r < need:
                d.was_limited = True

    @staticmethod
    def _allocate_for_decision(d: VariantDecision,
                               allocator: ResourceAllocator) -> None:
        replicas_needed = d.target_replicas - d.current_replicas
        if replicas_needed <= 0:
            return
        chips_per_replica = d.chips_per_replica if d.chips_per_replica > 0 else 1
        requested = replicas_needed * chips_per_replica
        granted = allocator.try_allocate(d, requested)
        # Partial allocation floors to whole replicas (whole slices).
        replicas_allocated = granted // chips_per_replica
        d.chips_allocated = replicas_allocated * chips_per_replica
        d.target_replicas = d.current_replicas + replicas_allocated
        if replicas_allocated < replicas_needed:
            d.was_limited = True


class DefaultLimiter(Limiter):
    """Inventory x algorithm (reference default_limiter.go:20-121)."""

    def __init__(self, name: str, inventory: Inventory,
                 algorithm: AllocationAlgorithm,
                 clock: Clock | None = None) -> None:
        self._name = name
        self.inventory = inventory
        self.algorithm = algorithm
        # Injected clock: limiter audit steps must be stamped from the same
        # clock as every other pipeline stage or replay cannot reproduce
        # them bit-for-bit.
        self.clock = clock or SYSTEM_CLOCK
        # Optional blackbox.FlightRecorder: when set, every limit() call
        # records the refreshed inventory pools so replay can rebuild a
        # StaticInventory with identical limits.
        self.flight_recorder = None

    def name(self) -> str:
        return self._name

    def limit(self, decisions: list[VariantDecision]) -> None:
        if not decisions:
            return
        self.inventory.refresh()
        self.inventory.set_used(self._calculate_used_chips(decisions))
        if self.flight_recorder is not None:
            pools = self.inventory.pools()
            self.flight_recorder.record_stage("limiter", {
                "name": self._name,
                "pools": [{"accelerator_type": p.accelerator_type,
                           "limit": p.limit, "used": p.used}
                          for _, p in sorted(pools.items())],
            })
        allocator = self.inventory.create_allocator()
        self.algorithm.allocate(decisions, allocator)
        self._update_metadata(decisions)

    @staticmethod
    def _calculate_used_chips(decisions: list[VariantDecision]) -> dict[str, int]:
        used: dict[str, int] = {}
        for d in decisions:
            if not d.accelerator_name:
                continue
            used[d.accelerator_name] = used.get(d.accelerator_name, 0) + \
                d.current_replicas * max(d.chips_per_replica, 1)
        return used

    def _update_metadata(self, decisions: list[VariantDecision]) -> None:
        now = self.clock.now()
        for d in decisions:
            if d.was_limited:
                d.limited_by = self._name
            change = d.target_replicas - d.current_replicas
            if change <= 0:
                reason = (f"no scale-up (target={d.target_replicas}, "
                          f"current={d.current_replicas})")
            elif d.was_limited:
                reason = (f"limited: allocated {d.chips_allocated} chips "
                          f"for +{change} replicas")
            else:
                reason = f"allocated {d.chips_allocated} chips for +{change} replicas"
            d.add_step(self._name, reason, d.was_limited, now=now)

    def compute_constraints(self, current_usage: dict[str, int]) -> ResourceConstraints:
        """V2 path: expose availability instead of mutating decisions
        (reference default_limiter.go:113-135)."""
        self.inventory.refresh()
        self.inventory.set_used(current_usage)
        return ResourceConstraints(
            provider_name=self._name,
            pools=self.inventory.pools(),
            total_limit=self.inventory.total_limit(),
            total_used=self.inventory.total_used(),
            total_available=self.inventory.total_available(),
        )
