"""Fleet-wide vectorized decision stage (WVA_VEC_DECIDE, default on;
docs/design/fused-plane.md §host-vectorization).

PR 13 fused the tick's device computation into ONE dispatch; what remained
of the analyze phase was per-model host Python — finalize's supply/demand
algebra, the cost-aware optimizer's greedy fills, and the enforcer bridge's
full-decision-list rescans. This module re-expresses those stages as row
arithmetic over the ``[M]`` model axis:

- :func:`finalize_fleet` — the finalize algebra as numpy float64 column
  passes with mask columns (anticipation-horizon, ramping-slope,
  headroom, burst, zero-supply), one pass for the whole tick. The
  candidate walk (VariantCapacity materialization + left-to-right supply
  sums) and the trend observe stay scalar per row: summation order and
  estimator statefulness are exactly where vector forms stop being
  bitwise-identical, and byte-equality with the per-model path is the
  contract (same discipline as WVA_FUSED=off).
- :func:`cost_aware_fleet` — the CostAwareOptimizer's efficiency-ranked
  scale-up fill and most-expensive-first scale-down become masked
  ``[M, V]`` column passes (one iteration per variant rank, all models at
  once); decision objects and their step dicts are then materialized FROM
  the target arrays in one batch walk via the optimizer's own
  ``_build_decisions`` (byte-identical strings/steps by construction).
- :func:`enforce_fleet` — ``bridge_enforce`` semantics at O(decisions)
  total: the per-model bridge rescans the WHOLE decision list per model
  (O(models x decisions) — quadratic on one-model-per-decision fleets);
  here decisions are grouped once and each model's enforcement runs over
  its own slice, same per-model enforce_policy calls in the same order.

WVA_VEC_DECIDE=off restores the per-model loops (byte-identical statuses
AND trace cycles); WVA_VEC_ASSERT=1 runs both forms and raises on the
first diverging bit (tests/debugging only — pays both costs).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Callable

import numpy as np

from wva_tpu.analyzers.queueing.analyzer import (
    BACKLOG_DRAIN_HORIZON_SECONDS,
    SizingPlan,
    accumulate_capacities,
    finalize_algebra,
)
from wva_tpu.interfaces import (
    ACTION_NO_CHANGE,
    ACTION_SCALE_DOWN,
    ACTION_SCALE_UP,
    DEFAULT_SCALE_DOWN_BOUNDARY,
    DEFAULT_SCALE_UP_THRESHOLD,
    AnalyzerResult,
    SaturationScalingConfig,
    VariantDecision,
    VariantSaturationAnalysis,
)
from wva_tpu.pipeline.enforcer import SCALE_TO_ZERO_REASON, Enforcer
from wva_tpu.pipeline.optimizer import ModelScalingRequest

log = logging.getLogger(__name__)


def _bit_eq(a: float, b: float) -> bool:
    """Bitwise-equality for the assert mode: NaN == NaN, else ==."""
    return a == b or (a != a and b != b)


# ---------------------------------------------------------------------------
# Stage 1 — vectorized finalize
# ---------------------------------------------------------------------------


@dataclass
class _FinalizeRow:
    """One model's scalars extracted for the column pass."""

    key: str
    plan: SizingPlan
    demand: float
    trend_demand: float
    supply: float
    anticipated: float
    best_headroom: float | None
    scale_up: float
    scale_down: float
    horizon: float
    headroom_replicas: float
    burst: float
    slope: float = 0.0


def _extract_row(analyzer, key: str, plan: SizingPlan,
                 per_replica: list[float]) -> _FinalizeRow:
    input = plan.input
    cfg = input.config if isinstance(input.config, SaturationScalingConfig) \
        else SaturationScalingConfig()
    # Everything here is side-effect-free EXCEPT accumulate_capacities
    # (appends VariantCapacity to plan.result — same partial state the
    # scalar path leaves behind if finalize raises mid-walk).
    demand = analyzer._demand_per_s(input)
    trend_demand = analyzer._trend_demand_per_s(input)
    supply, anticipated, best_headroom = accumulate_capacities(
        plan.result, plan.candidates, per_replica, cfg.headroom_replicas)
    return _FinalizeRow(
        key=key, plan=plan, demand=demand, trend_demand=trend_demand,
        supply=supply, anticipated=anticipated, best_headroom=best_headroom,
        scale_up=cfg.scale_up_threshold or DEFAULT_SCALE_UP_THRESHOLD,
        scale_down=cfg.scale_down_boundary or DEFAULT_SCALE_DOWN_BOUNDARY,
        horizon=cfg.anticipation_horizon_seconds,
        headroom_replicas=cfg.headroom_replicas,
        burst=cfg.burst_slope_rps)


def _algebra_columns(rows: list[_FinalizeRow]) -> tuple:
    """:func:`finalize_algebra` over ``[M]`` float64 columns. Every
    elementwise op (+ - * / maximum minimum where-select) is the same IEEE
    double op the scalar path runs, applied under the scalar path's branch
    conditions as masks; anything order-sensitive (summation) never enters
    this function."""
    demand = np.array([r.demand for r in rows], dtype=np.float64)
    slope = np.array([r.slope for r in rows], dtype=np.float64)
    supply = np.array([r.supply for r in rows], dtype=np.float64)
    anticipated = np.array([r.anticipated for r in rows], dtype=np.float64)
    best = np.array([0.0 if r.best_headroom is None else r.best_headroom
                     for r in rows], dtype=np.float64)
    has_best = np.array([r.best_headroom is not None for r in rows],
                        dtype=bool)
    scale_up = np.array([r.scale_up for r in rows], dtype=np.float64)
    scale_down = np.array([r.scale_down for r in rows], dtype=np.float64)
    horizon = np.array([r.horizon for r in rows], dtype=np.float64)
    headroom_n = np.array([r.headroom_replicas for r in rows],
                          dtype=np.float64)
    burst = np.array([r.burst for r in rows], dtype=np.float64)

    scaling = demand.copy()
    m_h = horizon > 0
    if m_h.any():
        scaling[m_h] = demand[m_h] + np.maximum(slope[m_h], 0.0) * horizon[m_h]
    # Deficit-aware anticipation, on the (horizon > 0, slope > 0) rows only
    # (compressed so the masked-out rows never see the divisions).
    m_d = m_h & (slope > 0)
    if m_d.any():
        d, a = demand[m_d], anticipated[m_d]
        s, h = slope[m_d], horizon[m_d]
        t0 = np.where(d >= a, 0.0, np.minimum((a - d) / s, h))
        deficit = (d - a) * (h - t0) + s * (h * h - t0 * t0) / 2.0
        upd = scaling[m_d]
        pos = deficit > 0
        upd[pos] = upd[pos] + deficit[pos] / BACKLOG_DRAIN_HORIZON_SECONDS
        scaling[m_d] = upd
    headroom = np.zeros_like(demand)
    m_p = (headroom_n > 0) & has_best
    headroom[m_p] = headroom_n[m_p] * best[m_p]
    m_b = (burst > 0) & m_h
    if m_b.any():
        headroom[m_b] = np.maximum(headroom[m_b], burst[m_b] * horizon[m_b])
    util = np.where(demand > 0, 1.0, 0.0)
    m_s = supply > 0
    util[m_s] = demand[m_s] / supply[m_s]
    required = np.maximum(scaling / scale_up + headroom - anticipated, 0.0)
    spare = np.zeros_like(demand)
    spare[m_s] = np.maximum(
        supply[m_s] - demand[m_s] / scale_down[m_s] - headroom[m_s], 0.0)
    spare[m_d] = 0.0
    return scaling, headroom, util, required, spare


def finalize_fleet(
    analyzer,
    items: list[tuple[str, SizingPlan, list[float]]],
    assert_mode: bool = False,
) -> tuple[dict[str, AnalyzerResult], dict[str, Exception]]:
    """Finalize every sized plan of the tick in one fleet pass. ``items``
    MUST be in the engine's sorted merge order — the demand-trend observes
    run in exactly that order, like the per-model loop. Returns
    ``(results_by_key, errors_by_key)``; an errored model degrades alone
    (the engine applies the same invalidate + safety-net handling as a
    per-model finalize raise)."""
    rows: list[_FinalizeRow] = []
    errors: dict[str, Exception] = {}
    for key, plan, per_replica in items:
        try:
            rows.append(_extract_row(analyzer, key, plan, per_replica))
        except Exception as e:  # noqa: BLE001 — per-model isolation
            errors[key] = e
    # Trend observes AFTER each row's extraction succeeded and in item
    # order: per-key estimator state evolves exactly as under the loop.
    for r in rows:
        input = r.plan.input
        r.slope = analyzer._demand_trend.observe(
            f"{input.namespace}|{input.model_id}",
            r.plan.result.analyzed_at, r.trend_demand)
    results: dict[str, AnalyzerResult] = {}
    if not rows:
        return results, errors
    try:
        cols = _algebra_columns(rows)
    except Exception:  # noqa: BLE001 — the observes already ran, so the
        # degradation is the (pure) scalar algebra per row, never a
        # re-observe.
        log.exception("Vectorized finalize algebra failed; scalar fallback")
        cols = None
    for i, r in enumerate(rows):
        result = r.plan.result
        scalar = None
        if cols is None or assert_mode:
            scalar = finalize_algebra(
                r.demand, r.slope, r.supply, r.anticipated, r.best_headroom,
                r.scale_up, r.scale_down, r.horizon, r.headroom_replicas,
                r.burst)
        if cols is None:
            values = scalar
        else:
            values = tuple(float(c[i]) for c in cols)
            if assert_mode:
                names = ("scaling_demand", "headroom_capacity",
                         "utilization", "required_capacity",
                         "spare_capacity")
                for name, vec_v, sc_v in zip(names, values, scalar):
                    if not _bit_eq(vec_v, sc_v):
                        raise AssertionError(
                            f"WVA_VEC_ASSERT: finalize[{r.key}].{name} "
                            f"diverged: vectorized {vec_v!r} != scalar "
                            f"{sc_v!r}")
        (result.scaling_demand, result.headroom_capacity,
         result.utilization, result.required_capacity,
         result.spare_capacity) = values
        result.total_supply = r.supply
        result.total_demand = r.demand
        results[r.key] = result
    return results, errors


# ---------------------------------------------------------------------------
# Stage 2 — vectorized cost-aware optimize
# ---------------------------------------------------------------------------


def cost_aware_fleet(optimizer,
                     requests: list[ModelScalingRequest],
                     ) -> list[VariantDecision]:
    """``CostAwareOptimizer.optimize`` with the greedy fills flipped to
    masked ``[M, V]`` column passes: one pass per variant RANK (sorted by
    cost-efficiency for scale-up, cost-descending for scale-down) updates
    every model's remaining capacity and integer targets at once. Flight
    records and decision objects are then materialized per request in
    request order from the target arrays — through the optimizer's own
    ``_build_decisions``, so reasons/steps/ordering are byte-identical by
    construction. Rows whose required/spare are non-finite fall back to
    the scalar fills (so pathological inputs raise exactly what the loop
    would)."""
    live = [r for r in requests if r.result is not None]
    if not live:
        return []
    M = len(live)
    required = np.array([r.result.required_capacity for r in live],
                        dtype=np.float64)
    spare = np.array([r.result.spare_capacity for r in live],
                     dtype=np.float64)
    finite = np.isfinite(required) & np.isfinite(spare)
    up = finite & (required > 0)
    down = finite & ~up & (spare > 0)
    bad = set(np.nonzero(~finite)[0].tolist())

    # Per-request variant tables. The target-name universe is the state
    # names (dict insertion order) plus any capacity name the scale-up fill
    # first touches — exactly the keys targets.get()/targets[...] would
    # create in the loop.
    rows_idx = np.arange(M)
    slot_names: list[list[str]] = []   # universe per request, slot order
    slot_of: list[dict[str, int]] = []
    cap_rows: list[list[float]] = []
    cost_rows: list[list[float]] = []
    eff_rows: list[list[float]] = []
    cslot_rows: list[list[int]] = []
    base_len: list[int] = []           # state-name prefix length
    for r in live:
        names: dict[str, int] = {}
        base: dict[str, int] = {}
        for s in r.variant_states:
            if s.variant_name not in names:
                names[s.variant_name] = len(names)
            # Dict-comprehension semantics: later duplicates overwrite.
            base[s.variant_name] = s.current_replicas
        base_len.append(len(names))
        caps, costs, effs, slots = [], [], [], []
        for vc in r.result.variant_capacities:
            if vc.variant_name not in names:
                names[vc.variant_name] = len(names)
            caps.append(vc.per_replica_capacity)
            costs.append(vc.cost)
            effs.append(vc.cost / vc.per_replica_capacity
                        if vc.per_replica_capacity > 0 else np.inf)
            slots.append(names[vc.variant_name])
        slot_names.append(list(names))
        slot_of.append(names)
        cap_rows.append(caps)
        cost_rows.append(costs)
        eff_rows.append(effs)
        cslot_rows.append(slots)
    U = max(len(n) for n in slot_names)
    V = max((len(c) for c in cap_rows), default=0)
    tgt = np.zeros((M, max(U, 1)), dtype=np.int64)
    present = np.zeros((M, max(U, 1)), dtype=bool)
    for i, r in enumerate(live):
        for s in r.variant_states:
            j = slot_of[i][s.variant_name]
            tgt[i, j] = s.current_replicas
            present[i, j] = True
    added_order: list[list[int]] = [[] for _ in range(M)]

    if V and (up.any() or down.any()):
        cap = np.zeros((M, V), dtype=np.float64)
        cost = np.full((M, V), -np.inf)     # padding sorts LAST cost-desc
        eff = np.full((M, V), np.inf)       # padding sorts LAST by eff
        cslot = np.zeros((M, V), dtype=np.int64)
        cvalid = np.zeros((M, V), dtype=bool)
        for i in range(M):
            n = len(cap_rows[i])
            if n:
                cap[i, :n] = cap_rows[i]
                cost[i, :n] = cost_rows[i]
                eff[i, :n] = eff_rows[i]
                cslot[i, :n] = cslot_rows[i]
                cvalid[i, :n] = True

        if up.any():
            # Scale-up: fill required capacity cheapest-efficiency-first
            # (stable sort = Python sorted's tie order). Pending replicas
            # are NOT skipped — the analyzer already counted them.
            order = np.argsort(eff, axis=1, kind="stable")
            rem = required.copy()
            act_up = up.copy()
            for i in bad:
                act_up[i] = False
            for j in range(V):
                occ = order[:, j]
                c = cap[rows_idx, occ]
                act = act_up & (rem > 0) & (c > 0) & cvalid[rows_idx, occ]
                if not act.any():
                    continue
                needed = np.ceil(rem[act] / c[act])
                slots = cslot[rows_idx, occ]
                hit_r, hit_s = rows_idx[act], slots[act]
                new = ~present[hit_r, hit_s]
                tgt[hit_r, hit_s] += needed.astype(np.int64)
                present[hit_r, hit_s] = True
                for r_i, s_i in zip(hit_r[new].tolist(), hit_s[new].tolist()):
                    added_order[r_i].append(s_i)
                rem[act] = rem[act] - needed * c[act]

        if down.any():
            # Scale-down: remove whole replicas most-expensive-first while
            # spare covers them, cheapest protected at 1 only when it is
            # the last variant with replicas.
            order = np.argsort(-cost, axis=1, kind="stable")
            cost_valid = np.where(cvalid, cost, np.inf)
            cheap_occ = np.argmin(cost_valid, axis=1)  # FIRST minimum
            cheap_slot = cslot[rows_idx, cheap_occ]
            has_caps = cvalid.any(axis=1)
            rem = spare.copy()
            act_dn = down.copy()
            for i in bad:
                act_dn[i] = False
            for j in range(V):
                occ = order[:, j]
                c = cap[rows_idx, occ]
                act = act_dn & (rem > 0) & (c > 0) & cvalid[rows_idx, occ]
                if not act.any():
                    continue
                slots = cslot[rows_idx, occ]
                current = tgt[rows_idx, slots]
                # Protect the cheapest at 1 only when no OTHER target is
                # positive — evaluated NOW, against this column's state.
                pos = (tgt > 0) & present
                pos_cnt = pos.sum(axis=1)
                cheap_pos = pos[rows_idx, cheap_slot]
                other_has = (pos_cnt - cheap_pos.astype(np.int64)) > 0
                min_rep = np.where(
                    has_caps & (slots == cheap_slot) & ~other_has, 1, 0)
                removable = current - min_rep
                can = act & (removable > 0)
                if not can.any():
                    continue
                quot = np.zeros(M, dtype=np.float64)
                np.floor_divide(rem, c, out=quot, where=can)
                to_remove = np.minimum(quot.astype(np.int64), removable)
                can &= to_remove > 0
                if not can.any():
                    continue
                tgt[rows_idx[can], slots[can]] = \
                    current[can] - to_remove[can]
                rem[can] = rem[can] - to_remove[can] * c[can]

    # Materialize: flight records + decisions per request, request order.
    flight = optimizer.flight_recorder
    decisions: list[VariantDecision] = []
    for i, req in enumerate(live):
        states = {s.variant_name: s for s in req.variant_states}
        capacities = {vc.variant_name: vc
                      for vc in req.result.variant_capacities}
        if i in bad:
            # Non-finite capacity algebra: run the loop's own fills so any
            # raise (e.g. ceil of infinity) is exactly the loop's raise.
            targets = {s.variant_name: s.current_replicas
                       for s in req.variant_states}
            if req.result.required_capacity > 0:
                optimizer._scale_up(req.result, targets)
            elif req.result.spare_capacity > 0:
                optimizer._scale_down(req.result, targets)
        else:
            names = slot_names[i]
            targets = {}
            for j in range(base_len[i]):
                targets[names[j]] = int(tgt[i, j])
            for j in added_order[i]:
                targets[names[j]] = int(tgt[i, j])
        if flight is not None:
            flight.record_stage("optimizer", {
                "name": optimizer.name(),
                "model_id": req.model_id,
                "namespace": req.namespace,
                "required_capacity": req.result.required_capacity,
                "spare_capacity": req.result.spare_capacity,
                "targets": dict(targets),
            })
        decisions.extend(
            optimizer._build_decisions(req, states, capacities, targets))
    return decisions


# ---------------------------------------------------------------------------
# Stage 3 — fleet enforcer bridge
# ---------------------------------------------------------------------------


def enforce_fleet(
    decisions: list[VariantDecision],
    model_keys: list[tuple[str, str]],
    enforcer: Enforcer,
    s2z_config_for: Callable[[str], object],
    now: float | Callable[[], float],
    optimizer_name: str,
    on_scaled_to_zero: Callable[[str, str], None] | None = None,
) -> list[tuple[str, str]]:
    """``bridge_enforce`` over every model in ``model_keys`` order at
    O(decisions) total: ONE grouping pass replaces the per-model rescans
    of the whole decision list (group order preserves list order, so the
    per-model targets/analyses/clamp walk sees exactly the subsequence the
    bridge's filters saw). Same enforce_policy calls, same in-place
    mutations, same audit steps. ``on_scaled_to_zero`` fires right after a
    model's enforcement (so caller log lines interleave exactly as the
    loop's did); a callable ``now`` is read once per model, exactly like
    the loop's per-request clock reads. Returns the scaled-to-zero keys."""
    by_key: dict[tuple[str, str], list[VariantDecision]] = {}
    for d in decisions:
        by_key.setdefault((d.model_id, d.namespace), []).append(d)
    scaled_keys: list[tuple[str, str]] = []
    for model_id, namespace in model_keys:
        now_v = now() if callable(now) else now
        group = by_key.get((model_id, namespace), [])
        targets = {d.variant_name: d.target_replicas for d in group}
        analyses = [
            VariantSaturationAnalysis(
                variant_name=d.variant_name,
                accelerator_name=d.accelerator_name,
                cost=d.cost, replica_count=d.current_replicas)
            for d in group
        ]
        enforced, scaled_to_zero = enforcer.enforce_policy(
            model_id, namespace, targets, analyses,
            s2z_config_for(namespace))
        for d in group:
            target = enforced.get(d.variant_name)
            if target is not None and target != d.target_replicas:
                d.target_replicas = target
                if target > d.current_replicas:
                    d.action = ACTION_SCALE_UP
                elif target < d.current_replicas:
                    d.action = ACTION_SCALE_DOWN
                else:
                    d.action = ACTION_NO_CHANGE
                d.reason = (f"V2 {d.action} (optimizer: "
                            f"{optimizer_name}, enforced)")
                d.add_step("enforcer",
                           (SCALE_TO_ZERO_REASON if scaled_to_zero
                            else f"min-replica floor -> {target}"),
                           was_constrained=True, now=now_v)
            else:
                d.add_step("enforcer", "no policy change", now=now_v)
        if scaled_to_zero:
            scaled_keys.append((model_id, namespace))
            if on_scaled_to_zero is not None:
                on_scaled_to_zero(model_id, namespace)
    return scaled_keys


# ---------------------------------------------------------------------------
# WVA_VEC_ASSERT helpers
# ---------------------------------------------------------------------------


def assert_equal_decisions(vec: list[VariantDecision],
                           loop: list[VariantDecision],
                           stage: str) -> None:
    """Raise on the first divergence between the vectorized and per-model
    decision lists (dataclass equality covers every field including the
    audit steps and their timestamps)."""
    if len(vec) != len(loop):
        raise AssertionError(
            f"WVA_VEC_ASSERT: {stage} produced {len(vec)} decisions "
            f"vectorized vs {len(loop)} scalar")
    for i, (a, b) in enumerate(zip(vec, loop)):
        if a != b:
            raise AssertionError(
                f"WVA_VEC_ASSERT: {stage} decision {i} "
                f"({a.model_id}/{a.variant_name}) diverged:\n"
                f"  vectorized: {a!r}\n  scalar:     {b!r}")
