"""``python -m wva_tpu`` — the controller process entry point.

Mirrors the reference's flag surface and startup order
(``cmd/main.go:83-520``): flags > env > config file > defaults through the
unified loader; fail-fast on invalid config and unreachable Prometheus;
REST client against the API server (kubeconfig or in-cluster); ConfigMap
bootstrap before readiness; engines leader-gated; ``/metrics`` +
``/healthz`` + ``/readyz`` served over HTTP(S).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import ssl
import sys
import threading

log = logging.getLogger("wva_tpu")

# Reference verbosity convention (internal/logging/logger.go:13-37):
# -v 2 DEFAULT / 3 VERBOSE / 4 DEBUG / 5 TRACE.
_VERBOSITY_LEVELS = {0: logging.WARNING, 1: logging.INFO, 2: logging.INFO,
                     3: logging.INFO, 4: logging.DEBUG, 5: logging.DEBUG}


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="wva-tpu",
        description="TPU-native workload variant autoscaler controller")
    p.add_argument("--config", default="", metavar="PATH",
                   help="optional YAML config file (lowest precedence "
                        "after flags and env)")
    p.add_argument("--metrics-bind-address", default=None,
                   help='metrics endpoint bind address (":8443", "0" to '
                        "disable)")
    p.add_argument("--health-probe-bind-address", default=None,
                   help='health probe bind address (":8081")')
    p.add_argument("--leader-elect", action="store_true", default=None,
                   help="enable leader election for controller manager")
    p.add_argument("--metrics-secure", dest="metrics_secure",
                   action="store_true", default=None,
                   help="serve metrics over TLS (requires cert path)")
    p.add_argument("--metrics-cert-path", default=None,
                   help="directory containing the metrics TLS certificate")
    p.add_argument("--metrics-cert-name", default=None,
                   help="metrics TLS certificate file name (tls.crt)")
    p.add_argument("--metrics-cert-key", default=None,
                   help="metrics TLS key file name (tls.key)")
    p.add_argument("--kubeconfig", default="",
                   help="path to kubeconfig (default: in-cluster, then "
                        "~/.kube/config)")
    p.add_argument("--context", default="", help="kubeconfig context")
    p.add_argument("--namespace", default=None,
                   help="restrict watches to one namespace")
    p.add_argument("--skip-prometheus-validation", action="store_true",
                   help="do not fail startup when Prometheus is unreachable")
    p.add_argument("-v", "--verbosity", type=int, default=None,
                   help="log verbosity (2 default, 3 verbose, 4 debug, "
                        "5 trace)")
    return p


def setup_logging(verbosity: int, log_format: str = "") -> None:
    logging.basicConfig(
        level=_VERBOSITY_LEVELS.get(max(0, min(verbosity, 5)), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
        stream=sys.stderr)
    # Structured logging (WVA_LOG_FORMAT=json): the existing loggers run
    # through a JSON formatter carrying tick/model/shard context fields.
    # Plain stays the default — byte-identical to pre-change logs.
    fmt = (log_format or os.environ.get("WVA_LOG_FORMAT", "")).lower()
    if fmt == "json":
        from wva_tpu.obs.logjson import install

        install()


def flags_from_args(args: argparse.Namespace) -> dict:
    """argparse values -> the loader's env-style keys (None = not set)."""
    return {
        "METRICS_BIND_ADDRESS": args.metrics_bind_address,
        "HEALTH_PROBE_BIND_ADDRESS": args.health_probe_bind_address,
        "LEADER_ELECT": args.leader_elect,
        "METRICS_SECURE": args.metrics_secure,
        "METRICS_CERT_PATH": args.metrics_cert_path,
        "METRICS_CERT_NAME": args.metrics_cert_name,
        "METRICS_CERT_KEY": args.metrics_cert_key,
        "WATCH_NAMESPACE": args.namespace,
        "V": args.verbosity,
    }


def validate_prometheus(cfg, fatal: bool) -> None:
    """Connectivity check, fatal like the reference (cmd/main.go:371-374)."""
    from wva_tpu.collector.source import HTTPPromAPI

    url = cfg.prometheus_base_url()
    if not url:
        if fatal:
            log.error("PROMETHEUS_BASE_URL is required")
            raise SystemExit(1)
        return
    try:
        api = HTTPPromAPI.from_config(cfg.prometheus())
    except (OSError, ssl.SSLError) as e:
        # Unreadable/invalid CA or client-cert files are configuration
        # errors: fail fast regardless of connectivity fatality.
        log.error("Prometheus TLS configuration invalid: %s", e)
        raise SystemExit(1) from None
    try:
        api.query("vector(1)")
        log.info("Prometheus API validated at %s", url)
    except Exception as e:  # noqa: BLE001 — connectivity failure
        if fatal:
            log.error("Prometheus unreachable at %s: %s", url, e)
            raise SystemExit(1) from None
        log.warning("Prometheus unreachable at %s: %s (continuing)", url, e)


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "replay":
        # Offline decision-trace replay (wva_tpu.blackbox): re-runs a
        # recorded trace through the real pipeline and diffs decisions.
        # No cluster, no Prometheus — dispatch before controller arg
        # parsing so the flag surfaces stay independent.
        from wva_tpu.blackbox.replay import replay_cli

        return replay_cli(argv[1:])
    if argv and argv[0] == "forecast":
        # Offline forecaster backtest over a recorded decision trace
        # (wva_tpu.forecast.backtest): MAPE + under/over-provision cost
        # per candidate forecaster. Same no-cluster dispatch as replay.
        from wva_tpu.forecast.backtest import forecast_cli

        return forecast_cli(argv[1:])
    if argv and argv[0] == "explain":
        # Decision provenance (wva_tpu.obs.explain): walk the newest
        # trace cycle that decided a model and print the causal chain of
        # its final desired number through every pipeline stage. Same
        # no-cluster dispatch as replay.
        from wva_tpu.obs.explain import explain_cli

        return explain_cli(argv[1:])
    if argv and argv[0] == "sweep":
        # Offline vectorized policy search (wva_tpu.sweep): thousands of
        # (seed x knob) emulated worlds per device dispatch, trust-gated
        # knob recommendations JSON out. Same no-cluster dispatch as
        # replay.
        from wva_tpu.sweep.cli import sweep_cli

        return sweep_cli(argv[1:])
    args = build_arg_parser().parse_args(argv)
    setup_logging(args.verbosity if args.verbosity is not None else 2)

    from wva_tpu.config import load
    from wva_tpu.k8s.kubeconfig import CredentialError, resolve_credentials
    from wva_tpu.k8s.rest import RestKubeClient
    from wva_tpu.main import build_manager
    from wva_tpu.serving import HTTPEndpoints

    try:
        cfg = load(flags=flags_from_args(args), config_file_path=args.config)
    except Exception as e:  # noqa: BLE001 — fail fast like the reference
        log.error("configuration invalid: %s", e)
        return 1
    if args.verbosity is None:
        setup_logging(cfg.logger_verbosity())
    if cfg.obs_config().log_format == "json":
        # Config-file/env route to structured logs (flags won the
        # verbosity; the format is orthogonal).
        from wva_tpu.obs.logjson import install

        install()

    try:
        creds = resolve_credentials(args.kubeconfig or None,
                                    args.context or None)
    except CredentialError as e:
        log.error("no API server credentials: %s", e)
        return 1
    # Namespace-scoped mode: watch streams hit /namespaces/<ns>/... so RBAC
    # can be a Role and other namespaces' objects are never seen.
    client = RestKubeClient(creds,
                            timeout=cfg.rest_timeout(),
                            watch_namespace=cfg.watch_namespace() or "")
    try:
        client.list("Namespace")
    except Exception as e:  # noqa: BLE001 — fail fast
        log.error("API server unreachable at %s: %s", creds.server, e)
        return 1
    log.info("Connected to API server %s", creds.server)

    validate_prometheus(cfg, fatal=not args.skip_prometheus_validation)

    mgr = build_manager(client, cfg)
    mgr.setup()

    tls_cert = tls_key = ""
    with cfg._mu:
        infra, tls = cfg.infrastructure, cfg.tls
    if infra.secure_metrics and tls.metrics_cert_path:
        tls_cert = f"{tls.metrics_cert_path}/{tls.metrics_cert_name or 'tls.crt'}"
        tls_key = f"{tls.metrics_cert_path}/{tls.metrics_cert_key or 'tls.key'}"
    metrics_auth = None
    if cfg.metrics_auth_enabled():
        # Kubernetes-delegated scrape auth: TokenReview + SAR against the
        # API server (reference cmd/main.go:213-219).
        from wva_tpu.k8s.authz import TokenReviewAuthenticator

        metrics_auth = TokenReviewAuthenticator(client).allowed
        log.info("Metrics endpoint protected by TokenReview/"
                 "SubjectAccessReview")
    endpoints = HTTPEndpoints(
        render_metrics=mgr.registry.render_text,
        healthz=mgr.healthz, readyz=mgr.readyz,
        metrics_addr=cfg.metrics_addr() or ":8443",
        health_addr=cfg.probe_addr() or ":8081",
        tls_cert_file=tls_cert, tls_key_file=tls_key,
        metrics_auth=metrics_auth,
    ).start()
    metrics_port, health_port = endpoints.ports()
    log.info("Serving /metrics on :%d and /healthz /readyz on :%d",
             metrics_port, health_port)

    stop = threading.Event()

    def _signal_handler(signum, frame):  # noqa: ARG001
        log.info("Received signal %d; shutting down", signum)
        stop.set()

    signal.signal(signal.SIGTERM, _signal_handler)
    signal.signal(signal.SIGINT, _signal_handler)

    mgr.start(stop)
    try:
        while not stop.wait(1.0):
            pass
    finally:
        mgr.shutdown()  # voluntary leader step-down (ReleaseOnCancel)
        client.stop()
        endpoints.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
