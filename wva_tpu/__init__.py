"""wva_tpu — TPU-native workload-variant autoscaler framework.

A from-scratch re-design of llm-d/llm-d-workload-variant-autoscaler (studied in
SURVEY.md at the repo root) for TPU-backed LLM inference: it watches
``VariantAutoscaling`` resources, scrapes JetStream / vLLM-TPU serving metrics,
runs saturation- and token-capacity analysis per model, chooses the cheapest TPU
slice variant, and emits ``wva_*`` desired-replica metrics for HPA/KEDA — plus
direct 0->1 scale-from-zero when requests queue for an inactive model.

Layer map (mirrors reference SURVEY.md section 1):
  L0  api/ interfaces/ config/ constants/ utils/
  L2  collector/ discovery/ datastore/
  L3  analyzers/ pipeline/
  L4  engines/
  L5  controller/
  L1  actuator/ metrics/
  aux k8s/ (client abstraction + in-memory fake cluster), emulator/ (fake-TPU
      nodes + JetStream emulator), models/ ops/ parallel/ (JAX serving path used
      by the emulator and the queueing solver).
"""

__version__ = "0.1.0"
