"""Lease-based leader election gating the engines
(reference ``cmd/main.go:257-287``: LeaseDuration 60s / RenewDeadline 50s /
RetryPeriod 10s, LeaderElectionReleaseOnCancel=true for ~1-2s voluntary
failover instead of a full lease timeout).

Implements the coordination.k8s.io Lease acquire/renew protocol directly on
the KubeClient abstraction (the reference delegates to controller-runtime's
leaderelection package): a candidate acquires the lease when it is absent,
expired, or already its own; renews on every tick; and steps down by clearing
the holder on release. Conflict-safe through the client's optimistic
concurrency (ConflictError on stale resourceVersion => another candidate won
the race; re-observe next tick).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass

from wva_tpu.constants.leases import DEFAULT_LEADER_ELECTION_LEASE
from wva_tpu.k8s.client import ConflictError, KubeClient, NotFoundError
from wva_tpu.k8s.objects import Lease, ObjectMeta, clone
from wva_tpu.utils.clock import SYSTEM_CLOCK, Clock

log = logging.getLogger(__name__)

DEFAULT_LEASE_DURATION = 60.0
DEFAULT_RENEW_DEADLINE = 50.0
DEFAULT_RETRY_PERIOD = 10.0


@dataclass
class LeaderElectorConfig:
    lease_name: str = DEFAULT_LEADER_ELECTION_LEASE
    # "" resolves to the controller's namespace (POD_NAMESPACE-aware) at
    # elector construction, matching every other component's scoping.
    namespace: str = ""
    lease_duration: float = DEFAULT_LEASE_DURATION
    renew_deadline: float = DEFAULT_RENEW_DEADLINE
    retry_period: float = DEFAULT_RETRY_PERIOD
    release_on_exit: bool = True  # LeaderElectionReleaseOnCancel


class LeaderElector:
    """Tick-driven elector: call :meth:`tick` every retry_period (the manager
    loop owns scheduling so fake-clock tests stay deterministic)."""

    def __init__(self, client: KubeClient, identity: str,
                 config: LeaderElectorConfig | None = None,
                 clock: Clock | None = None) -> None:
        self.client = client
        self.identity = identity
        self.config = config or LeaderElectorConfig()
        if not self.config.namespace:
            from wva_tpu.config.helpers import system_namespace
            self.config.namespace = system_namespace()
        self.clock = clock or SYSTEM_CLOCK
        self._mu = threading.Lock()
        self._leader = False
        self._renewed_at = -1e18
        # Local observation of the remote lease record, for skew-safe expiry
        # (client-go semantics): a lease only expires after THIS process has
        # watched it go unchanged for a full lease_duration on its own clock,
        # never by comparing another replica's renew_time to our clock.
        self._observed_record: tuple[str, float] | None = None
        self._observed_at = -1e18
        # Lease-epoch fencing token: the lease's lease_transitions value
        # at OUR acquisition. Every handover bumps it (expired-acquire
        # increments; a fresh create starts a new counter), so two
        # tenures — even of the same identity — never share an epoch. The
        # engine stamps it through the apply phase; see
        # wva_tpu/resilience (fenced failover).
        self._epoch = -1
        self.on_started_leading = None  # optional callbacks
        self.on_stopped_leading = None

    def is_leader(self) -> bool:
        """Leadership with renew-deadline self-demotion: if this process has
        not managed to renew within renew_deadline it must stop acting as
        leader even before another candidate takes the lease."""
        with self._mu:
            if not self._leader:
                return False
            if self.clock.now() - self._renewed_at > self.config.renew_deadline:
                cb = self._set_leader(False)
            else:
                return True
        self._fire(cb)
        return False

    def fencing_token(self) -> int | None:
        """Lease epoch while leading (renew-deadline aware), else None.
        Callers stamp it through their write phases: a token captured
        before a handover never matches the token after it, so a deposed
        process can be fenced even when its own clock has not yet demoted
        it."""
        if not self.is_leader():
            return None
        with self._mu:
            return self._epoch if self._epoch >= 0 else None

    def tick(self) -> bool:
        """One acquire-or-renew attempt; returns leadership after the step.

        Transient-failure discipline (apiserver storms — see
        tests/test_faults.py): a transport error neither demotes nor
        acquires — the renew-deadline self-demotion in :meth:`is_leader`
        is the ONLY way connectivity loss costs leadership, and the
        observed-lease expiry rule is the only way it is gained, so a
        storm can never produce two leaders. A ConflictError gets ONE
        immediate re-observe: the holder whose renew raced a conflicting
        write re-reads the lease and renews against the fresh
        resourceVersion instead of demoting on a transient 409; a genuine
        lost race shows another holder on re-read and demotes properly.
        """
        try:
            return self._tick_once()
        except ConflictError:
            try:
                return self._tick_once()
            except ConflictError:
                log.debug("Lease race lost by %s; retrying next period",
                          self.identity)
            except NotFoundError:
                pass
            except Exception as e:  # noqa: BLE001 — transient, see above
                log.warning("leader-election retry failed for %s: %s",
                            self.identity, e)
                return self.is_leader()
        except NotFoundError:
            pass
        except Exception as e:  # noqa: BLE001 — transient, see above
            log.warning("leader-election tick failed for %s: %s",
                        self.identity, e)
            return self.is_leader()
        with self._mu:
            cb = self._set_leader(False)
        self._fire(cb)
        return False

    def _tick_once(self) -> bool:
        """One acquire-or-renew attempt; raises on client errors (the
        caller owns retry/demotion policy) and demotes on observing
        another live holder."""
        cfg = self.config
        now = self.clock.now()
        lease = self.client.try_get(Lease.KIND, cfg.namespace, cfg.lease_name)
        if lease is None:
            self.client.create(Lease(
                metadata=ObjectMeta(name=cfg.lease_name,
                                    namespace=cfg.namespace),
                holder_identity=self.identity,
                lease_duration_seconds=int(cfg.lease_duration),
                acquire_time=now, renew_time=now, lease_transitions=0))
            self._became_leader(now, 0, "acquired (new lease)")
            return True

        record = (lease.holder_identity, lease.renew_time)
        if record != self._observed_record:
            self._observed_record = record
            self._observed_at = now
        expired = now - self._observed_at > cfg.lease_duration
        if lease.holder_identity == self.identity:
            epoch = lease.lease_transitions
            lease = clone(lease)  # reads are frozen store views
            lease.renew_time = now
            self.client.update(lease)
            with self._mu:
                self._renewed_at = now
                self._epoch = epoch
                cb = self._set_leader(True)
            self._fire(cb)
            return True
        if not lease.holder_identity or expired:
            lease = clone(lease)
            lease.holder_identity = self.identity
            lease.acquire_time = now
            lease.renew_time = now
            lease.lease_transitions += 1
            self.client.update(lease)
            self._became_leader(now, lease.lease_transitions,
                                "acquired (expired lease)")
            return True
        with self._mu:
            cb = self._set_leader(False)
        self._fire(cb)
        return False

    def release(self) -> None:
        """Voluntary step-down (ReleaseOnCancel): clears the holder so the
        next candidate acquires in ~one retry period instead of waiting out
        the lease (reference cmd/main.go:277-286)."""
        if not self.config.release_on_exit:
            return
        try:
            lease = self.client.try_get(
                Lease.KIND, self.config.namespace, self.config.lease_name)
            if lease is not None and lease.holder_identity == self.identity:
                lease = clone(lease)
                lease.holder_identity = ""
                self.client.update(lease)
        except (ConflictError, NotFoundError):
            pass
        with self._mu:
            cb = self._set_leader(False)
        self._fire(cb)

    # -- internals --

    def _became_leader(self, now: float, epoch: int, how: str) -> None:
        with self._mu:
            self._renewed_at = now
            self._epoch = epoch
            cb = self._set_leader(True)
        self._fire(cb)
        log.info("Leader election: %s %s (epoch %d)", self.identity, how,
                 epoch)

    def _set_leader(self, value: bool):
        """State flip under the lock; returns the transition callback to run
        AFTER the lock is released (callbacks may call back into the elector,
        and _mu is not reentrant)."""
        changed = self._leader != value
        self._leader = value
        if not changed:
            return None
        return self.on_started_leading if value else self.on_stopped_leading

    def _fire(self, cb) -> None:
        if cb is None:
            return
        try:
            cb()
        except Exception:  # noqa: BLE001 — callbacks never break election
            log.exception("leader-election callback failed")
