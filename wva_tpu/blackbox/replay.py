"""Deterministic offline replay of recorded decision traces.

``ReplayEngine`` re-feeds a recorded trace through the REAL pipeline code —
no collector, no emulator threads, no Kubernetes:

- **V1 path** (``path: "v1"``): the full analyzer -> optimizer -> enforcer
  chain re-runs from the recorded analyzer INPUT (replica metrics, variant
  states, saturation config): :class:`SaturationAnalyzer` is stateless given
  an injected clock, so the whole decision is recomputed from scratch.
- **V2/SLO paths**: the stateful analyzers (demand-trend history, EKF-tuned
  profiles, capacity knowledge) cannot be reconstructed from a single
  cycle, so replay starts from the recorded :class:`AnalyzerResult` and
  re-runs the real ``CostAwareOptimizer`` -> enforcer bridge -> limiter.
- **Enforcer**: the recorded request-count observation is fed back instead
  of querying Prometheus (including recorded query errors, which replay the
  fail-safe keep-targets path).
- **Limiter**: a :class:`StaticInventory` is rebuilt from the recorded pool
  snapshot and the real ``DefaultLimiter`` + ``GreedyBySaturation`` re-run.

Cycles routed through the fleet-wide global optimizer are skipped (the
solver consumes cluster-wide state the per-cycle record does not carry) and
reported as such — a skip is visible, never silent.

Replayed decisions are diffed field-by-field against the recorded ones;
zero diffs means the trace is bit-for-bit reproducible. Traces recorded
under an injected FakeClock (emulator / bench) reproduce timestamps exactly;
wall-clock traces can use ``relax_timestamps`` to ignore time fields.
"""

from __future__ import annotations

import argparse
import json
import sys
from dataclasses import dataclass, field

from wva_tpu.analyzers.saturation import SaturationAnalyzer
from wva_tpu.blackbox.schema import (
    PATH_V1,
    decode,
    decode_scale_to_zero_config,
    encode,
)
from wva_tpu.interfaces import (
    AnalyzerResult,
    ReplicaMetrics,
    SaturationScalingConfig,
    VariantReplicaState,
)
from wva_tpu.pipeline import (
    CostAwareOptimizer,
    DefaultLimiter,
    Enforcer,
    GreedyBySaturation,
    ModelScalingRequest,
    SCALE_TO_ZERO_REASON,
    StaticInventory,
    bridge_enforce,
    saturation_targets_to_decisions,
)
from wva_tpu.utils.clock import FakeClock

# Keys stripped everywhere when relax_timestamps is set (wall-clock traces).
_TIME_KEYS = {"timestamp", "last_run_time", "analyzed_at"}

SKIP_GLOBAL_OPTIMIZER = "global-optimizer"
SKIP_OUTCOME = "non-success-outcome"


def load_trace(path: str) -> list[dict]:
    """Parse a JSONL trace file into cycle records (blank lines skipped)."""
    records = []
    with open(path, "r", encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: invalid trace line: {e}") \
                    from None
    return records


@dataclass
class ReplayReport:
    cycles_total: int = 0
    cycles_replayed: int = 0
    cycles_empty: int = 0
    cycles_skipped: dict[str, int] = field(default_factory=dict)
    decisions_recorded: int = 0
    decisions_replayed: int = 0
    mismatches: list[dict] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        # Zero replayed cycles means nothing was verified: a recording
        # regression that empties every record (or stamps non-success
        # outcomes, or routes everything to the skipped global optimizer)
        # must fail the `make replay-golden` gate, not green-light it.
        return not self.mismatches and self.cycles_replayed > 0

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "cycles_total": self.cycles_total,
            "cycles_replayed": self.cycles_replayed,
            "cycles_empty": self.cycles_empty,
            "cycles_skipped": dict(sorted(self.cycles_skipped.items())),
            "decisions_recorded": self.decisions_recorded,
            "decisions_replayed": self.decisions_replayed,
            "mismatch_count": len(self.mismatches),
            "mismatches": self.mismatches,
        }


class ReplayEngine:
    def __init__(self, records: list[dict]) -> None:
        self.records = records
        self.clock = FakeClock()
        self.v1_analyzer = SaturationAnalyzer(clock=self.clock)
        self.optimizer = CostAwareOptimizer()
        # Replayed decisions per replayed cycle id (for --emit / inspection).
        self.replayed: dict[int, list] = {}

    # --- main loop ---

    def replay(self, relax_timestamps: bool = False,
               max_diffs: int = 1000) -> ReplayReport:
        report = ReplayReport()
        for rec in self.records:
            report.cycles_total += 1
            models = rec.get("models") or []
            if not models:
                report.cycles_empty += 1
                continue
            skip = self._skip_reason(rec, models)
            if skip is not None:
                report.cycles_skipped[skip] = \
                    report.cycles_skipped.get(skip, 0) + 1
                continue
            self.clock.set(float(rec.get("ts", 0.0)))
            decisions = self._replay_cycle(rec, models)
            self.replayed[rec.get("cycle", report.cycles_total)] = decisions
            report.cycles_replayed += 1
            self._diff_cycle(rec, decisions, relax_timestamps,
                             max_diffs, report)
        return report

    @staticmethod
    def _skip_reason(rec: dict, models: list[dict]) -> str | None:
        if rec.get("outcome") not in ("", "success", None):
            # Error/aborted ticks may carry partial records from failed
            # attempts — not a replay anchor.
            return SKIP_OUTCOME
        if any(m.get("optimizer") == "global" for m in models):
            return SKIP_GLOBAL_OPTIMIZER
        return None

    def _replay_cycle(self, rec: dict, models: list[dict]) -> list:
        enforcer_events = {
            (ev.get("model_id"), ev.get("namespace")): ev
            for ev in rec.get("stages", []) if ev.get("stage") == "enforcer"}
        limiter_event = next(
            (ev for ev in rec.get("stages", [])
             if ev.get("stage") == "limiter"), None)
        forecast_event = next(
            (ev for ev in rec.get("stages", [])
             if ev.get("stage") == "forecast"), None)
        health_event = next(
            (ev for ev in rec.get("stages", [])
             if ev.get("stage") == "health"), None)
        federation_event = next(
            (ev for ev in rec.get("stages", [])
             if ev.get("stage") == "federation"), None)

        decisions: list = []
        v2_requests: list[ModelScalingRequest] = []
        for m in models:
            if m.get("path") == PATH_V1:
                decisions.extend(self._replay_v1_model(m, enforcer_events))
            else:
                v2_requests.append(self._decode_request(m))
        if v2_requests:
            decisions.extend(
                self._replay_v2(v2_requests, enforcer_events))

        if forecast_event is not None:
            # Proactive floors re-applied from the RECORDED event via the
            # same code path the live engine used (the planner's learned
            # state — history rings, lead-time samples, rolling errors —
            # is not reconstructable from a single cycle).
            from wva_tpu.forecast.apply import apply_forecast_floors

            apply_forecast_floors(decisions,
                                  forecast_event.get("floors") or [],
                                  now=self.clock.now())

        if limiter_event is not None:
            limits = {p["accelerator_type"]: p["limit"]
                      for p in limiter_event.get("pools", [])}
            limiter = DefaultLimiter(
                limiter_event.get("name", "tpu-slice-limiter"),
                StaticInventory(limits), GreedyBySaturation(),
                clock=self.clock)
            limiter.limit(decisions)

        if health_event is not None:
            # Do-no-harm clamps re-applied from the RECORDED event through
            # the same shared path the live gate used (health.apply) — the
            # monitor's state (ages, hysteresis streaks, last-known-good
            # holds) is not reconstructable from one cycle. Post-limiter,
            # matching the live ordering: holds and freezes are absolute.
            from wva_tpu.health.apply import apply_health_clamps

            apply_health_clamps(decisions,
                                health_event.get("clamps") or [],
                                now=self.clock.now())

        if federation_event is not None:
            # Spill floors re-applied from the RECORDED plan slice through
            # the shared federation.apply path — the arbiter's state
            # (hysteresis books, other regions' captures) is not
            # reconstructable from one cycle. After the health gate,
            # matching the live ordering: a raise-only floor on a healthy
            # target never fights a local freeze.
            from wva_tpu.federation.apply import apply_federation_directives

            apply_federation_directives(decisions,
                                        federation_event.get("directives")
                                        or [],
                                        now=self.clock.now())
        return decisions

    # --- per-path replay ---

    def _replay_v1_model(self, m: dict, enforcer_events: dict) -> list:
        model_id, namespace = m.get("model_id", ""), m.get("namespace", "")
        inp = m.get("input", {})
        replica_metrics = [decode(ReplicaMetrics, x)
                           for x in inp.get("replica_metrics", [])]
        states = [decode(VariantReplicaState, x)
                  for x in inp.get("variant_states", [])]
        cfg = decode(SaturationScalingConfig, inp.get("config")) \
            or SaturationScalingConfig()
        recorded_ts = (m.get("analysis") or {}).get("analyzed_at")
        if recorded_ts:
            self.clock.set(float(recorded_ts))

        analysis = self.v1_analyzer.analyze_model_saturation(
            model_id, namespace, replica_metrics, cfg)
        targets = self.v1_analyzer.calculate_saturation_targets(
            analysis, states)

        ev = enforcer_events.get((model_id, namespace))
        enforcer = self._enforcer_for(ev)
        s2z = decode_scale_to_zero_config((ev or {}).get("s2z_config"))
        targets, scaled_to_zero = enforcer.enforce_policy(
            model_id, namespace, targets, analysis.variant_analyses, s2z)
        return saturation_targets_to_decisions(
            targets, analysis, states,
            enforcer_note=(SCALE_TO_ZERO_REASON
                           if scaled_to_zero else ""))

    def _decode_request(self, m: dict) -> ModelScalingRequest:
        inp = m.get("input", {})
        result = decode(AnalyzerResult, m.get("result"))
        if result is not None and result.analyzed_at:
            self.clock.set(result.analyzed_at)
        return ModelScalingRequest(
            model_id=m.get("model_id", ""),
            namespace=m.get("namespace", ""),
            result=result,
            variant_states=[decode(VariantReplicaState, x)
                            for x in inp.get("variant_states", [])])

    def _replay_v2(self, requests: list[ModelScalingRequest],
                   enforcer_events: dict) -> list:
        decisions = self.optimizer.optimize(requests, None)
        for req in requests:
            ev = enforcer_events.get((req.model_id, req.namespace))
            enforcer = self._enforcer_for(ev)
            s2z = decode_scale_to_zero_config((ev or {}).get("s2z_config"))
            bridge_enforce(decisions, req.model_id, req.namespace, enforcer,
                           s2z, now=self.clock.now(),
                           optimizer_name=self.optimizer.name())
        return decisions

    @staticmethod
    def _enforcer_for(ev: dict | None) -> Enforcer:
        """Enforcer whose request-count source is the RECORDED observation —
        including recorded query errors, which replay the fail-safe
        keep-targets branch exactly."""
        def count_func(model_id: str, namespace: str, retention: float):
            if ev is not None and ev.get("error"):
                raise RuntimeError(f"recorded query error: {ev['error']}")
            if ev is None or ev.get("request_count") is None:
                raise LookupError(
                    f"trace has no request count for {namespace}/{model_id}")
            return ev["request_count"]
        return Enforcer(count_func)

    # --- diffing ---

    def _diff_cycle(self, rec: dict, decisions: list,
                    relax_timestamps: bool, max_diffs: int,
                    report: ReplayReport) -> None:
        recorded = rec.get("decisions") or []
        # Mixed incremental cycles: models whose analysis was fingerprint-
        # skipped had their PRIOR cycle's decisions re-emitted — replay
        # cannot recompute them from this cycle's (absent) model record,
        # and they were verified the cycle they were computed. Exclude
        # them from the diff instead of failing on decision count.
        skipped = {(ev.get("model_id"), ev.get("namespace"))
                   for ev in rec.get("stages", [])
                   if ev.get("stage") == "fingerprint_skip"}
        if skipped:
            recorded = [d for d in recorded
                        if (d.get("model_id"), d.get("namespace"))
                        not in skipped]
        replayed = [encode(d) for d in decisions]
        if relax_timestamps:
            recorded = [_strip_time_keys(d) for d in recorded]
            replayed = [_strip_time_keys(d) for d in replayed]
        report.decisions_recorded += len(recorded)
        report.decisions_replayed += len(replayed)
        cycle = rec.get("cycle")
        if len(recorded) != len(replayed):
            if len(report.mismatches) < max_diffs:
                report.mismatches.append({
                    "cycle": cycle, "kind": "decision-count",
                    "recorded": len(recorded), "replayed": len(replayed)})
            return
        for i, (a, b) in enumerate(zip(recorded, replayed)):
            for path, rec_v, rep_v in _diff_value(a, b, ""):
                if len(report.mismatches) >= max_diffs:
                    return
                report.mismatches.append({
                    "cycle": cycle,
                    "variant": a.get("variant_name", f"#{i}"),
                    "namespace": a.get("namespace", ""),
                    "field": path.lstrip("."),
                    "recorded": rec_v, "replayed": rep_v})


_MISSING = "<missing>"


def _strip_time_keys(value):
    if isinstance(value, dict):
        return {k: _strip_time_keys(v) for k, v in value.items()
                if k not in _TIME_KEYS}
    if isinstance(value, list):
        return [_strip_time_keys(v) for v in value]
    return value


def _diff_value(recorded, replayed, path):
    """Yield (path, recorded, replayed) for every differing leaf."""
    if isinstance(recorded, dict) and isinstance(replayed, dict):
        for key in sorted(set(recorded) | set(replayed)):
            yield from _diff_value(recorded.get(key, _MISSING),
                                   replayed.get(key, _MISSING),
                                   f"{path}.{key}")
        return
    if isinstance(recorded, list) and isinstance(replayed, list):
        if len(recorded) != len(replayed):
            yield (f"{path}.length", len(recorded), len(replayed))
        for i, (a, b) in enumerate(zip(recorded, replayed)):
            yield from _diff_value(a, b, f"{path}[{i}]")
        return
    if isinstance(recorded, bool) != isinstance(replayed, bool) \
            or recorded != replayed:
        yield (path, recorded, replayed)


# --- CLI (python -m wva_tpu replay) ---

def replay_cli(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="wva-tpu replay",
        description="Re-run a recorded decision trace through the real "
                    "analyzer/optimizer/enforcer/limiter pipeline and diff "
                    "replayed decisions against recorded ones.")
    p.add_argument("trace", help="JSONL trace file (WVA_TRACE_PATH output)")
    p.add_argument("--json", action="store_true",
                   help="print the full machine-readable report")
    p.add_argument("--relax-timestamps", action="store_true",
                   help="ignore time fields (for wall-clock traces, whose "
                        "per-stage timestamps are not reproducible)")
    p.add_argument("--max-diffs", type=int, default=20,
                   help="cap on reported field mismatches (default 20)")
    args = p.parse_args(argv)

    try:
        records = load_trace(args.trace)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    engine = ReplayEngine(records)
    report = engine.replay(relax_timestamps=args.relax_timestamps,
                           max_diffs=args.max_diffs)
    if args.json:
        print(json.dumps(report.to_dict(), sort_keys=True, indent=1))
    else:
        d = report.to_dict()
        print(f"cycles: {d['cycles_total']} total, "
              f"{d['cycles_replayed']} replayed, "
              f"{d['cycles_empty']} empty, "
              f"skipped: {d['cycles_skipped'] or 'none'}")
        print(f"decisions: {d['decisions_recorded']} recorded, "
              f"{d['decisions_replayed']} replayed, "
              f"{d['mismatch_count']} mismatched")
        for m in report.mismatches:
            print(f"  cycle {m.get('cycle')} "
                  f"{m.get('namespace', '')}/{m.get('variant', '')} "
                  f"{m.get('field', m.get('kind'))}: "
                  f"recorded={m.get('recorded')!r} "
                  f"replayed={m.get('replayed')!r}")
        if report.ok:
            print("REPLAY OK (zero diffs)")
        elif report.cycles_replayed == 0:
            print("REPLAY FAILED (no cycles replayed — nothing verified)")
        else:
            print("REPLAY FAILED")
    return 0 if report.ok else 1
