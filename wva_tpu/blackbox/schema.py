"""DecisionTrace record schema: serialization for the flight recorder.

One JSONL record per engine cycle:

.. code-block:: json

    {"schema": 1, "cycle": 17, "ts": 1000123.0, "engine": "saturation-engine",
     "analyzer": "v1", "outcome": "success",
     "models":    [{"model_id": "...", "namespace": "...", "path": "v1",
                    "input": {"replica_metrics": [], "variant_states": [],
                              "config": {}, "scheduler_queue": null},
                    "analysis": {}, "targets": {},
                    "enforced_targets": {}, "scaled_to_zero": false}],
     "stages":    [{"stage": "enforcer", "...": "..."}],
     "decisions": [{"variant_name": "...", "decision_steps": []}],
     "post":      [{"stage": "reconcile", "...": "..."}]}

``models`` carries everything the replay engine needs to re-run the pipeline
(the analyzer INPUT for the stateless V1 path; the :class:`AnalyzerResult`
for the stateful V2/SLO analyzers, whose trend/EKF state cannot be
reconstructed from one cycle). ``stages`` carries the pipeline components'
own events (enforcer request counts, limiter inventory pools) recorded
during the cycle; ``post`` carries events attributed after the cycle ended
(reconciler status writes triggered by this cycle's decisions).

Encoding is plain :func:`dataclasses.asdict`; decoding is a small generic
type-hint-driven reconstructor, so interface dataclasses round-trip without
per-type glue. Floats round-trip exactly (JSON uses repr shortest-float).
"""

from __future__ import annotations

import dataclasses
import types
from typing import Union, get_args, get_origin, get_type_hints

TRACE_SCHEMA_VERSION = 1

# Stage-event names used by the pipeline hooks.
STAGE_ENFORCER = "enforcer"
STAGE_OPTIMIZER = "optimizer"
STAGE_LIMITER = "limiter"
# Predictive capacity planner (wva_tpu.forecast): per-model plans + the
# replica floors it applied, recorded between enforcement and the limiter.
# Replay re-applies the RECORDED floors (like the limiter replays from the
# recorded pool snapshot) — the planner's learned state is not
# reconstructable from one cycle.
STAGE_FORECAST = "forecast"
# Elastic capacity plane (wva_tpu.capacity): per-tick ledger snapshot
# (ready/provisioning/preempted slices per variant, stocked-out tiers) plus
# the provisioning requests submitted/completed/expired this cycle.
# Recorded AFTER the limiter: capacity influences decisions only through
# the inventory pools the limiter stage already records, so replay needs
# no capacity-specific logic — the stage is pure observability.
STAGE_CAPACITY = "capacity"
STAGE_ACTUATION = "actuation"
STAGE_RECONCILE = "reconcile"
# Dirty-set incremental ticks: models whose input fingerprint was unchanged
# this cycle, so prepare->analyze was skipped and the prior cycle's decision
# re-emitted. Recorded so an incremental trace still explains every model's
# outcome (replay treats skipped models exactly like no-record models: the
# re-emitted decisions were already verified the cycle they were computed).
STAGE_FINGERPRINT_SKIP = "fingerprint_skip"
# Crash-restart resilience plane (wva_tpu.resilience): recorded ONCE, on
# the first cycle after a boot that actually recovered something (warm-
# start seeds, checkpoint rehydration) or is still ramp-holding models.
# Pure observability: the boot ramp's do-no-harm clamps ride the health
# stage below (state "boot") and replay through the same shared
# health.apply path, so replay needs no boot-specific logic. A fresh
# fault-free boot records nothing — traces stay byte-identical with the
# plane off.
STAGE_BOOT = "boot"
# Sharded active-active engine (wva_tpu.shard): recorded ONLY on cycles
# where shard topology changed — a shard joined/left/crashed and the
# consistent-hash ring moved model ownership (moves + the rebalance holds
# opened). Steady-state sharded cycles record nothing, so sharded traces
# stay byte-identical to the unsharded engine's (and to each other at any
# shard count). Pure observability: the rebalance ramp's do-no-harm clamps
# ride STAGE_HEALTH (state "rebalance") and replay through the shared
# health.apply path, so replay needs no shard-specific logic.
STAGE_SHARD = "shard"
# Input-health plane (wva_tpu.health): per-model trust states this cycle
# plus the do-no-harm clamps the gate applied to final decisions. Recorded
# AFTER the limiter; replay re-applies the RECORDED clamps through the same
# shared code path (health.apply) — monitor state (ages, hysteresis
# streaks, last-known-good holds) is not reconstructable from one cycle.
# Only cycles where something was non-FRESH (or clamped) record the stage,
# so a fault-free world's trace carries no health events.
STAGE_HEALTH = "health"
# Multi-cluster federation plane (wva_tpu.federation): the arbiter plan as
# THIS region saw it — region states (with capture ages and re-admission
# hysteresis) plus the spill directives applied to this region's final
# decisions. Recorded AFTER the health gate; replay re-applies the
# RECORDED directives through the shared federation.apply path — arbiter
# state (hysteresis books, other regions' captures) is not
# reconstructable from one cycle. Only cycles with a directive or a
# non-healthy region record the stage, so a healthy fleet's traces (and
# any single-cluster deployment's) stay byte-identical to the plane off.
STAGE_FEDERATION = "federation"

# Per-model pipeline paths.
PATH_V1 = "v1"
PATH_V2 = "v2"
PATH_SLO = "slo"


def encode(obj):
    """Dataclass / list / dict / scalar -> JSON-serializable structure."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: encode(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, dict):
        return {k: encode(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [encode(v) for v in obj]
    return obj


def decode(cls, data):
    """Reconstruct dataclass ``cls`` from :func:`encode` output. Unknown keys
    are ignored (forward compatibility with newer trace schemas)."""
    if data is None:
        return None
    hints = get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name in data:
            kwargs[f.name] = _decode_value(hints.get(f.name), data[f.name])
    return cls(**kwargs)


def _decode_value(tp, value):
    if value is None or tp is None:
        return value
    origin = get_origin(tp)
    # typing.Optional[X] and PEP 604 ``X | None`` have different origins.
    if origin is Union or origin is types.UnionType:
        for arg in get_args(tp):
            if arg is type(None):
                continue
            return _decode_value(arg, value)
        return value
    if origin in (list, tuple):
        args = get_args(tp)
        elem = args[0] if args else None
        return [_decode_value(elem, v) for v in value]
    if origin is dict:
        args = get_args(tp)
        elem = args[1] if len(args) > 1 else None
        return {k: _decode_value(elem, v) for k, v in value.items()}
    if dataclasses.is_dataclass(tp):
        return decode(tp, value)
    if tp is int and isinstance(value, float):
        return int(value)
    return value


def encode_scale_to_zero_config(cfg) -> dict:
    """``ScaleToZeroConfigData`` (model -> ModelScaleToZeroConfig)."""
    return {k: encode(v) for k, v in (cfg or {}).items()}


def decode_scale_to_zero_config(data) -> dict:
    from wva_tpu.config.types import ModelScaleToZeroConfig

    return {k: decode(ModelScaleToZeroConfig, v)
            for k, v in (data or {}).items()}
