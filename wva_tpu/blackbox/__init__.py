"""Decision flight recorder + deterministic replay (the "black box").

Production autoscalers keep audit histories of every scaling decision
(Google Autopilot's decision logs; AIBrix's simulation-driven tuning loop) —
without one, a mis-sized scale-up that happened 20 minutes ago is
undebuggable, because the inputs that produced it are gone. This package
records one JSONL :data:`~wva_tpu.blackbox.schema.TRACE_SCHEMA_VERSION`
record per engine cycle (metric snapshot, analyzer inputs/outputs, optimizer
decisions, enforcer/limiter mutations, actuation outcome) into a thread-safe
ring buffer with optional spill-to-disk, and can re-feed a recorded trace
through the REAL analyzer -> optimizer -> enforcer -> limiter pipeline
offline (``python -m wva_tpu replay trace.jsonl``), diffing replayed
decisions against recorded ones bit-for-bit.
"""

from wva_tpu.blackbox.recorder import FlightRecorder
from wva_tpu.blackbox.replay import ReplayEngine, ReplayReport, load_trace
from wva_tpu.blackbox.schema import TRACE_SCHEMA_VERSION, decode, encode

__all__ = [
    "FlightRecorder",
    "ReplayEngine",
    "ReplayReport",
    "load_trace",
    "TRACE_SCHEMA_VERSION",
    "decode",
    "encode",
]
