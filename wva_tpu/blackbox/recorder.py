"""Thread-safe decision flight recorder: ring buffer + JSONL spill-to-disk.

The engine executor opens a cycle record around every tick; the engine and
the pipeline stages (optimizer / enforcer / limiter) append their inputs,
outputs, and mutations into it; the reconciler's status writes — which run
AFTER the tick that produced the decisions (drained triggers in simulation,
a separate thread in production) — attach to the just-finished cycle's
``post`` list until the next cycle opens. Committed records land in a
bounded ring (the in-memory "black box", readable via :meth:`snapshot`) and,
when a spill path is configured, are appended to a JSONL file that
``python -m wva_tpu replay`` consumes.

Recording is observability and must never bite: every hook is wrapped so a
serialization error degrades to a dropped-record counter, not a failed
engine tick.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import time
from collections import deque

from wva_tpu.blackbox.schema import TRACE_SCHEMA_VERSION, encode
from wva_tpu.utils.clock import SYSTEM_CLOCK, Clock

log = logging.getLogger(__name__)

# Writer-thread handoff bound: caps memory if the disk hangs outright.
# Deliberately independent of ring_size — a small ring must not make the
# spill file lossy under a normal burst the writer absorbs in milliseconds.
SPILL_QUEUE_SIZE = 1024

DROP_REASON_EVICTED = "ring-evicted"
DROP_REASON_WRITE_ERROR = "write-error"
DROP_REASON_WRITE_BACKLOG = "write-backlog"
DROP_REASON_NO_CYCLE = "no-open-cycle"
DROP_REASON_ENCODE_ERROR = "encode-error"


class FlightRecorder:
    """Cycle-scoped trace accumulator. All methods are thread-safe and
    exception-safe (failures count into ``dropped_total``)."""

    def __init__(self, clock: Clock | None = None, ring_size: int = 512,
                 spill_path: str | None = None, registry=None) -> None:
        self._mu = threading.Lock()
        # File I/O happens on a dedicated writer thread, never on the engine
        # tick thread: a slow or hung disk (NFS stall, error-retry) must not
        # block begin_cycle. Committed records are handed over via a bounded
        # queue — when the disk can't keep up the queue fills and records
        # drop (counted), the control loop never waits. flush() is the
        # synchronization point that drains the queue (deterministic tests,
        # shutdown). _spill_mu guards the file handle (writer vs close).
        self._spill_mu = threading.Lock()
        self._spill_queue: queue.Queue | None = None
        self.clock = clock or SYSTEM_CLOCK
        self.ring: deque = deque(maxlen=max(int(ring_size), 1))
        self.spill_path = spill_path
        # MetricsRegistry (duck-typed): observe_trace_record /
        # observe_trace_drop / observe_trace_write. None = counters only.
        self.registry = registry
        self._cycle_id = 0
        self._open: dict | None = None      # record being built (in-tick)
        self._pending: dict | None = None   # finished, accepting post events
        self._spill_file = None
        self.records_total = 0
        self.dropped_total = 0
        if self.spill_path is not None:
            self._spill_queue = queue.Queue(maxsize=SPILL_QUEUE_SIZE)
            threading.Thread(target=self._writer_loop,
                             name="trace-spill-writer", daemon=True).start()

    # --- cycle lifecycle (called by the engine executor) ---

    def begin_cycle(self, engine: str) -> None:
        with self._mu:
            spill = self._commit_pending_locked()
            self._cycle_id += 1
            self._open = {
                "schema": TRACE_SCHEMA_VERSION,
                "cycle": self._cycle_id,
                "ts": self.clock.now(),
                "engine": engine,
                "analyzer": "",
                "outcome": "",
                "models": [],
                "stages": [],
                "decisions": [],
                "post": [],
            }
        self._spill(spill)

    def end_cycle(self, outcome: str) -> None:
        """Close the open cycle. The record stays pending (accepting ``post``
        events from the reconciler) until the next ``begin_cycle`` or
        :meth:`flush` commits it to the ring + spill file."""
        with self._mu:
            if self._open is None:
                return
            self._open["outcome"] = outcome
            self._pending = self._open
            self._open = None

    def reset_cycle(self) -> None:
        """Clear the open cycle's payload (models/stages/decisions) and
        re-stamp its timestamp. The engine calls this at task entry so a
        retried tick (executor retry loop) starts a clean record instead of
        appending duplicate model entries to the failed attempt's."""
        with self._mu:
            if self._open is not None:
                self._open["models"] = []
                self._open["stages"] = []
                self._open["decisions"] = []
                self._open["ts"] = self.clock.now()

    # --- in-cycle hooks (engine + pipeline stages) ---

    def annotate(self, **fields) -> None:
        """Merge cycle-level metadata (e.g. ``analyzer="slo"``)."""
        with self._mu:
            if self._open is not None:
                self._open.update(fields)

    def record_model(self, payload: dict) -> None:
        self._append("models", payload)

    def record_stage(self, stage: str, payload: dict) -> None:
        """Pipeline-stage event. During a tick it lands in ``stages``; after
        ``end_cycle`` (reconciler territory) it lands in the pending record's
        ``post`` list — attributing post-tick effects to the cycle whose
        decisions caused them."""
        self._append("stages", {"stage": stage, **payload})

    def record_stage_if(self, expected: tuple[str, int], stage: str,
                        payload: dict) -> bool:
        """Append a stage event ONLY if the record currently accepting
        events still matches ``expected`` (engine, cycle id), atomically.
        The reconciler runs on its own thread, so a separate "compare
        cycle_info(), then record_stage()" would race the engine's
        begin_cycle and file the event under the next cycle's record.
        Returns whether the event was attached."""
        try:
            payload = encode({"stage": stage, **payload})
        except Exception:  # noqa: BLE001
            self._drop(DROP_REASON_ENCODE_ERROR)
            log.debug("trace payload encoding failed", exc_info=True)
            return False
        with self._mu:
            rec = self._open if self._open is not None else self._pending
            if rec is None or (rec["engine"], rec["cycle"]) != expected:
                return False
            rec["stages" if self._open is not None else "post"] \
                .append(payload)
            return True

    def record_decisions(self, decisions) -> None:
        try:
            encoded = [encode(d) for d in decisions]
        except Exception:  # noqa: BLE001 — observability must not bite
            self._drop(DROP_REASON_ENCODE_ERROR)
            log.debug("decision encoding failed", exc_info=True)
            return
        with self._mu:
            if self._open is None:
                self._drop_locked(DROP_REASON_NO_CYCLE)
                return
            self._open["decisions"] = encoded

    # --- internals ---

    def _append(self, key: str, payload: dict) -> None:
        try:
            payload = encode(payload)
        except Exception:  # noqa: BLE001
            self._drop(DROP_REASON_ENCODE_ERROR)
            log.debug("trace payload encoding failed", exc_info=True)
            return
        with self._mu:
            if self._open is not None:
                self._open[key].append(payload)
            elif self._pending is not None:
                self._pending["post"].append(payload)
            else:
                self._drop_locked(DROP_REASON_NO_CYCLE)

    def _drop(self, reason: str) -> None:
        with self._mu:
            self._drop_locked(reason)

    def _drop_locked(self, reason: str) -> None:
        self.dropped_total += 1
        if self.registry is not None:
            try:
                self.registry.observe_trace_drop(reason)
            except Exception:  # noqa: BLE001
                pass

    def _commit_pending_locked(self) -> dict | None:
        """Commit the pending record to the ring; returns the record to
        hand to :meth:`_spill` AFTER ``_mu`` is released (None when nothing
        to write)."""
        record = self._pending
        self._pending = None
        if record is None:
            return None
        if self.spill_path is None and len(self.ring) == self.ring.maxlen:
            # The evicted record was never persisted anywhere: that IS a
            # drop. With a spill file the ring is just a hot cache.
            self._drop_locked(DROP_REASON_EVICTED)
        self.ring.append(record)
        self.records_total += 1
        if self.registry is not None:
            try:
                self.registry.observe_trace_record(record.get("engine", ""))
            except Exception:  # noqa: BLE001
                pass
        return record if self.spill_path is not None else None

    def _spill(self, record: dict | None) -> None:
        """Hand a committed record to the writer thread, never blocking:
        with the disk stalled the queue fills and the record drops
        (counted), but the engine tick thread keeps making decisions."""
        if record is None:
            return
        try:
            self._spill_queue.put_nowait(record)
        except queue.Full:
            self._drop(DROP_REASON_WRITE_BACKLOG)
            log.warning("trace spill backlog: writer cannot keep up with "
                        "%s; record dropped from file (still in ring)",
                        self.spill_path)

    def _writer_loop(self) -> None:
        while True:
            record = self._spill_queue.get()
            try:
                self._write_record(record)
            finally:
                self._spill_queue.task_done()

    def _write_record(self, record: dict) -> None:
        start = time.perf_counter()
        failed: Exception | None = None
        with self._spill_mu:
            try:
                if self._spill_file is None:
                    self._spill_file = open(  # noqa: SIM115 — long-lived
                        self.spill_path, "a", encoding="utf-8")
                self._spill_file.write(
                    json.dumps(record, sort_keys=True, separators=(",", ":"))
                    + "\n")
                self._spill_file.flush()
            except Exception as e:  # noqa: BLE001 — recording must never
                # bite: an uncaught error (OSError, or TypeError from a
                # non-JSON-serializable payload that slipped through
                # encode()) would kill the writer thread and silently end
                # all future spills.
                failed = e
        if failed is not None:
            self._drop(DROP_REASON_WRITE_ERROR)
            log.warning("trace spill to %s failed: %s", self.spill_path,
                        failed)
        elif self.registry is not None:
            try:
                self.registry.observe_trace_write(
                    time.perf_counter() - start)
            except Exception:  # noqa: BLE001
                pass

    def cycle_info(self) -> tuple[str, int]:
        """(engine, cycle id) of the record currently accepting events (the
        open in-tick record, else the pending post-cycle one); ``("", 0)``
        when neither. The reconciler compares this against a decision's
        recorded (source, cycle) so an event only attaches to the exact
        cycle whose decision it consumed — a reconcile arriving after the
        next tick opened must not leak into that unrelated record."""
        with self._mu:
            rec = self._open if self._open is not None else self._pending
            return (rec["engine"], rec["cycle"]) if rec is not None \
                else ("", 0)

    def current_cycle(self) -> int:
        """Cycle id of the record currently accepting events (0 when none).
        The engine stamps this onto DecisionCache entries so the reconciler
        can attribute its trace events to the deciding cycle."""
        return self.cycle_info()[1]

    # --- reading / shutdown ---

    def snapshot(self) -> list[dict]:
        """Committed records currently held in the ring (oldest first)."""
        with self._mu:
            return list(self.ring)

    def flush(self) -> None:
        """Commit the pending record (if any), drain the writer queue, and
        sync the spill file. This is the synchronization point for readers
        of the spill file (harness teardown, replay tests) — unlike the
        recording hooks it WAITS for the disk."""
        with self._mu:
            spill = self._commit_pending_locked()
        self._spill(spill)
        if self._spill_queue is not None:
            self._spill_queue.join()
        with self._spill_mu:
            if self._spill_file is not None:
                try:
                    self._spill_file.flush()
                except OSError:
                    pass

    def close(self) -> None:
        self.flush()
        with self._spill_mu:
            if self._spill_file is not None:
                try:
                    self._spill_file.close()
                except OSError:
                    pass
                self._spill_file = None
