"""Actuation layer (reference ``internal/actuator/{actuator,direct_actuator}.go``).

``Actuator`` emits the ``wva_*`` gauges that external actuators (HPA/KEDA via
Prometheus Adapter) act on — the only steady-state scaling output.
``DirectActuator`` writes the scale subresource directly and is used solely by
scale-from-zero (HPA cannot act on a 0-replica target).
"""

from __future__ import annotations

import logging

from wva_tpu.api.v1alpha1 import VariantAutoscaling
from wva_tpu.k8s.client import KubeClient, NotFoundError
from wva_tpu.k8s.objects import Deployment
from wva_tpu.metrics import MetricsRegistry
from wva_tpu.utils import scale_target

log = logging.getLogger(__name__)


class Actuator:
    """Metric-emission actuator (reference actuator.go:16-87)."""

    def __init__(self, client: KubeClient, registry: MetricsRegistry) -> None:
        self.client = client
        self.registry = registry

    def emit_metrics(self, va: VariantAutoscaling,
                     client: KubeClient | None = None,
                     desired: int | None = None,
                     accelerator: str | None = None) -> None:
        """Read REAL current replicas from the target and emit
        current/desired/ratio gauges. Raises on missing target (caller logs
        but never fails the loop on emission errors). ``client`` lets the
        engine pass its tick-scoped snapshot so the per-VA emission loop
        costs zero API requests (the tick already LISTed every target).
        ``desired``/``accelerator`` override the VA's status values: the
        engine emits its JUST-COMPUTED decision from the frozen snapshot
        read, without mutating status first (the status write — and its
        copy-on-write clone — is skipped when nothing material changed)."""
        target = scale_target.scale_target_state((client or self.client).get(
            va.spec.scale_target_ref.kind or Deployment.KIND,
            va.metadata.namespace, va.spec.scale_target_ref.name))
        # OBSERVED replicas only (reference actuator.go reads
        # Status.Replicas directly): during the 0->N scale-from-zero window
        # spec.replicas is already N while zero pods exist — a spec
        # fallback would report current=N and hide the ratio=desired
        # encoding HPA relies on in exactly that window.
        current = target.status_replicas
        if desired is None:
            desired = va.status.desired_optimized_alloc.num_replicas
        if accelerator is None:
            accelerator = va.status.desired_optimized_alloc.accelerator
        self.registry.emit_replica_metrics(
            variant_name=va.metadata.name,
            namespace=va.metadata.namespace,
            accelerator=accelerator,
            current=current,
            desired=desired,
        )

    def emit_metrics_batch(self, entries) -> None:
        """Batched gauge emission for the apply phase: ``entries`` of
        ``(variant_name, namespace, accelerator, current, desired)``,
        one registry lock pass for the whole fleet."""
        self.registry.emit_replica_metrics_batch(entries)


class DirectActuator:
    """Scale-subresource actuator (reference direct_actuator.go:37-121).
    Works against any registered scalable kind (Deployment now; JobSet /
    LeaderWorkerSet adapters for multi-host slices use the same path)."""

    def __init__(self, client: KubeClient) -> None:
        self.client = client

    def scale_target_object(self, kind: str, namespace: str, name: str,
                            replicas: int, only_up: bool = False) -> bool:
        """Set spec.replicas via the scale subresource; returns True when a
        write happened (False = already at the target). ``only_up`` never
        reduces replicas (the fast-actuation path accelerates scale-up only;
        scale-down stays HPA-paced)."""
        try:
            current = self.client.get(kind, namespace, name)
        except NotFoundError:
            raise
        current_replicas = getattr(current, "replicas", None)
        if current_replicas == replicas:
            return False
        if only_up and current_replicas is not None \
                and replicas < current_replicas:
            return False
        self.client.patch_scale(kind, namespace, name, replicas)
        log.info("Scaled %s %s/%s: %s -> %d", kind, namespace, name,
                 current_replicas, replicas)
        return True
