"""Thread-safe cache of EndpointPools + namespace tracking
(reference ``internal/datastore/datastore.go:39-260``).

On ``pool_set`` a per-pool metrics source is created via an injected factory
(the wiring layer provides the EPP pod-scraping source factory) and registered
under the pool's name — dependency-inverted so the datastore doesn't import
the collector.
"""

from __future__ import annotations

import threading
from typing import Callable

from wva_tpu.utils.pool import EndpointPool, selector_is_subset

# factory(pool) -> MetricsSource-like object; registry has register/get.
SourceFactory = Callable[[EndpointPool], object]


class PoolNotFoundError(KeyError):
    pass


class Datastore:
    def __init__(
        self,
        source_registry=None,
        source_factory: SourceFactory | None = None,
    ) -> None:
        self._mu = threading.RLock()
        self._pools: dict[str, EndpointPool] = {}
        self._registry = source_registry
        self._source_factory = source_factory
        # namespace -> resourceType -> set of resource names
        self._namespaces: dict[str, dict[str, set[str]]] = {}

    # --- pools ---

    def pool_set(self, pool: EndpointPool) -> None:
        if pool is None:
            raise ValueError("pool is null")
        if self._registry is not None and self._source_factory is not None:
            self._registry.register_if_absent(
                pool.name, lambda: self._source_factory(pool))
        with self._mu:
            self._pools[pool.name] = pool

    def pool_get(self, name: str) -> EndpointPool:
        with self._mu:
            pool = self._pools.get(name)
        if pool is None:
            raise PoolNotFoundError(f"pool {name} not found")
        return pool

    def pool_get_metrics_source(self, name: str):
        if self._registry is None:
            return None
        return self._registry.get(name)

    def pool_list(self) -> list[EndpointPool]:
        with self._mu:
            return list(self._pools.values())

    def pool_get_from_labels(self, labels: dict[str, str]) -> EndpointPool:
        """First pool whose selector is a subset of the given pod-template
        labels (scale-from-zero target matching; reference :133-152)."""
        with self._mu:
            pools = list(self._pools.values())
        for pool in pools:
            if pool.selector and selector_is_subset(pool.selector, labels):
                return pool
        raise PoolNotFoundError(f"no pool matches labels {labels}")

    def pool_delete(self, name: str) -> None:
        with self._mu:
            self._pools.pop(name, None)
        if self._registry is not None:
            self._registry.unregister(name)

    def clear(self) -> None:
        with self._mu:
            self._pools.clear()

    # --- namespace tracking (feeds the ConfigMap watch filter) ---

    def namespace_track(self, resource_type: str, resource_name: str, namespace: str) -> None:
        if not namespace:
            return
        with self._mu:
            self._namespaces.setdefault(namespace, {}).setdefault(
                resource_type, set()).add(resource_name)

    def namespace_untrack(self, resource_type: str, resource_name: str, namespace: str) -> None:
        with self._mu:
            ns = self._namespaces.get(namespace)
            if not ns:
                return
            names = ns.get(resource_type)
            if names:
                names.discard(resource_name)
                if not names:
                    del ns[resource_type]
            if not ns:
                del self._namespaces[namespace]

    def is_namespace_tracked(self, namespace: str) -> bool:
        with self._mu:
            return namespace in self._namespaces

    def list_tracked_namespaces(self) -> list[str]:
        with self._mu:
            return sorted(self._namespaces)
