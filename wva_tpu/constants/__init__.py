"""Centralized constants (reference ``internal/constants/{labels,metrics}.go``)."""

from wva_tpu.constants.labels import (
    CONTROLLER_INSTANCE_LABEL_KEY,
    NAMESPACE_CONFIG_ENABLED_LABEL_KEY,
    NAMESPACE_EXCLUDE_ANNOTATION_KEY,
    ACCELERATOR_NAME_LABEL_KEY,
    GKE_NODEPOOL_NODE_LABEL,
    GKE_TPU_ACCELERATOR_NODE_LABEL,
    GKE_TPU_TOPOLOGY_NODE_LABEL,
    TPU_RESOURCE_NAME,
)
from wva_tpu.constants.leases import (
    DEFAULT_LEADER_ELECTION_LEASE,
    FLEET_SHARD_ID,
    SHARD_LEASE_PREFIX,
    shard_lease_name,
    shard_lease_names,
)
from wva_tpu.constants.metrics import *  # noqa: F401,F403
from wva_tpu.constants.metrics import __all__ as _metrics_all

__all__ = [
    "DEFAULT_LEADER_ELECTION_LEASE",
    "FLEET_SHARD_ID",
    "SHARD_LEASE_PREFIX",
    "shard_lease_name",
    "shard_lease_names",
    "CONTROLLER_INSTANCE_LABEL_KEY",
    "NAMESPACE_CONFIG_ENABLED_LABEL_KEY",
    "NAMESPACE_EXCLUDE_ANNOTATION_KEY",
    "ACCELERATOR_NAME_LABEL_KEY",
    "GKE_NODEPOOL_NODE_LABEL",
    "GKE_TPU_ACCELERATOR_NODE_LABEL",
    "GKE_TPU_TOPOLOGY_NODE_LABEL",
    "TPU_RESOURCE_NAME",
] + list(_metrics_all)
