"""Metric-name constants (reference ``internal/constants/metrics.go:8-121``).

Three input families:
- ``vllm:*`` — vLLM-TPU emits the same engine-agnostic names as CUDA vLLM, so
  the reference's queries transfer unchanged (SURVEY.md section 7 stage 2).
- ``jetstream_*`` — JetStream / MaxText serving gauges (prefill/generate
  backlogs, decode slots, HBM KV utilization).
- ``inference_extension_*`` — llm-d inference-scheduler flow-control metrics
  (model-scoped, engine-agnostic).

Output family ``wva_*`` is byte-identical to the reference so the HPA /
KEDA / Prometheus-Adapter glue transfers verbatim.
"""

# --- vLLM(-TPU) input metrics ---
VLLM_NUM_REQUESTS_RUNNING = "vllm:num_requests_running"
VLLM_REQUEST_SUCCESS_TOTAL = "vllm:request_success_total"
VLLM_REQUEST_PROMPT_TOKENS_SUM = "vllm:request_prompt_tokens_sum"
VLLM_REQUEST_PROMPT_TOKENS_COUNT = "vllm:request_prompt_tokens_count"
VLLM_REQUEST_GENERATION_TOKENS_SUM = "vllm:request_generation_tokens_sum"
VLLM_REQUEST_GENERATION_TOKENS_COUNT = "vllm:request_generation_tokens_count"
VLLM_TTFT_SECONDS_SUM = "vllm:time_to_first_token_seconds_sum"
VLLM_TTFT_SECONDS_COUNT = "vllm:time_to_first_token_seconds_count"
VLLM_TPOT_SECONDS_SUM = "vllm:time_per_output_token_seconds_sum"
VLLM_TPOT_SECONDS_COUNT = "vllm:time_per_output_token_seconds_count"
VLLM_KV_CACHE_USAGE_PERC = "vllm:kv_cache_usage_perc"
VLLM_NUM_REQUESTS_WAITING = "vllm:num_requests_waiting"
VLLM_CACHE_CONFIG_INFO = "vllm:cache_config_info"
VLLM_PREFIX_CACHE_HITS = "vllm:prefix_cache_hits"
VLLM_PREFIX_CACHE_QUERIES = "vllm:prefix_cache_queries"

# --- JetStream input metrics ---
# Requests accepted but not yet prefilled (the saturation "queue length").
JETSTREAM_PREFILL_BACKLOG_SIZE = "jetstream_prefill_backlog_size"
# Prefilled requests waiting for a free decode slot.
JETSTREAM_GENERATE_BACKLOG_SIZE = "jetstream_generate_backlog_size"
# Concurrent decode slots currently in use / configured maximum.
JETSTREAM_SLOTS_USED = "jetstream_slots_used"
JETSTREAM_SLOTS_AVAILABLE = "jetstream_slots_available"
# HBM KV-cache utilization of the slice, 0.0-1.0 (the "kv_cache_usage" analogue).
JETSTREAM_KV_CACHE_UTILIZATION = "jetstream_kv_cache_utilization"
# Latency/token histograms (sum/count pairs, same shape as the vllm ones).
JETSTREAM_TTFT_SECONDS_SUM = "jetstream_time_to_first_token_seconds_sum"
JETSTREAM_TTFT_SECONDS_COUNT = "jetstream_time_to_first_token_seconds_count"
JETSTREAM_TPOT_SECONDS_SUM = "jetstream_time_per_output_token_seconds_sum"
JETSTREAM_TPOT_SECONDS_COUNT = "jetstream_time_per_output_token_seconds_count"
JETSTREAM_REQUEST_SUCCESS_TOTAL = "jetstream_request_success_total"
JETSTREAM_PROMPT_TOKENS_SUM = "jetstream_request_input_length_sum"
JETSTREAM_PROMPT_TOKENS_COUNT = "jetstream_request_input_length_count"
JETSTREAM_GENERATION_TOKENS_SUM = "jetstream_request_output_length_sum"
JETSTREAM_GENERATION_TOKENS_COUNT = "jetstream_request_output_length_count"
# Info-style gauge exposing serving config as labels (max_concurrent_decodes,
# max_target_length, tokens_per_slot, tpu_topology) — value always 1.0; the V2
# analyzer's capacity analogue of vllm:cache_config_info.
JETSTREAM_SERVING_CONFIG_INFO = "jetstream_serving_config_info"

# --- Inference-scheduler flow-control metrics (model-scoped, no namespace label) ---
SCHEDULER_FLOW_CONTROL_QUEUE_SIZE = "inference_extension_flow_control_queue_size"
SCHEDULER_FLOW_CONTROL_QUEUE_BYTES = "inference_extension_flow_control_queue_bytes"

# --- WVA output metrics (identical to reference for HPA/KEDA glue) ---
WVA_REPLICA_SCALING_TOTAL = "wva_replica_scaling_total"
WVA_DESIRED_REPLICAS = "wva_desired_replicas"
WVA_CURRENT_REPLICAS = "wva_current_replicas"
WVA_DESIRED_RATIO = "wva_desired_ratio"

# --- Controller self-observability (TPU-build addition; the reference gets
# the equivalent from controller-runtime's reconcile metrics) ---
WVA_ENGINE_TICK_DURATION_SECONDS = "wva_engine_tick_duration_seconds"
WVA_ENGINE_TICKS_TOTAL = "wva_engine_ticks_total"
# Ticks whose wall-clock duration exceeded the engine's poll interval: the
# loop is falling behind its own cadence (apiserver latency injection,
# metrics-backend timeouts, or genuine fleet growth). Alert on rate > 0.
WVA_TICK_OVERRUNS_TOTAL = "wva_tick_overruns_total"

# --- Input-health plane (wva_tpu.health) ---
# Per-model trust ladder: one series per (model, namespace, state) with
# value 1 for the current state and 0 otherwise (state is
# fresh | degraded | blackout). Alert on degraded/blackout == 1.
WVA_INPUT_HEALTH = "wva_input_health"

# --- Crash-restart resilience plane (wva_tpu.resilience) ---
# Models still held by the do-no-harm boot ramp this tick (DEGRADED-
# equivalent: scale-up allowed, scale-down forbidden, until their inputs
# prove fresh). Non-zero long after a restart means the metrics plane
# never proved fresh — investigate the inputs, not the autoscaler.
WVA_BOOT_RAMP_MODELS_HELD = "wva_boot_ramp_models_held"
# Items recovered by the boot warm start, one series per
# source = held | orders | stockouts | trust | leadtime | health_books.
# All-zero after a restart means the checkpoint was missing/unreadable
# and VA statuses were empty — the boot ramp alone carried recovery.
WVA_BOOT_RECOVERED_ITEMS = "wva_boot_recovered_items"
# The lease epoch (Lease.leaseTransitions at acquisition) this process is
# acting under; emitted only while leading. Two processes exporting the
# same epoch simultaneously would indicate broken fencing — alert on it.
WVA_LEADER_EPOCH = "wva_leader_epoch"
# Resilience-checkpoint writes since process start, and the world time of
# the newest one. A flat-lining writes counter with the plane enabled
# means checkpoint persistence is failing (RBAC, conflicts, fencing).
WVA_CHECKPOINT_WRITES = "wva_checkpoint_writes"
WVA_CHECKPOINT_LAST_SAVE_TIMESTAMP = "wva_checkpoint_last_save_timestamp"

# --- Decision flight recorder health (wva_tpu.blackbox) ---
WVA_TRACE_RECORDS_TOTAL = "wva_trace_records_total"
WVA_TRACE_DROPPED_TOTAL = "wva_trace_dropped_total"
WVA_TRACE_WRITE_SECONDS = "wva_trace_write_seconds"

# --- Predictive capacity planner (wva_tpu.forecast) ---
# The provisioning horizon the planner is ACTUALLY using per model: the
# measured actuation->ready latency quantile (or the configured default
# until samples exist).
WVA_FORECAST_LEAD_TIME_SECONDS = "wva_forecast_lead_time_seconds"
# Chosen forecaster's demand forecast at (now + lead time).
WVA_FORECAST_DEMAND = "wva_forecast_demand"
# Rolling symmetric-MAPE per (model, forecaster) from matured backtests.
WVA_FORECAST_ERROR = "wva_forecast_error"
# 1 when the model is demoted to reactive (rolling error over threshold).
WVA_FORECAST_DEMOTED = "wva_forecast_demoted"

# --- Elastic capacity plane (wva_tpu.capacity) ---
# Whole slices per (variant, state): state is ready / provisioning /
# preempted (watch-observed losses discovery has not re-confirmed yet).
WVA_CAPACITY_SLICES = "wva_capacity_slices"
# Chips the planner may allocate for the variant right now: ready plus
# provisioning-arriving-within-lead-time.
WVA_CAPACITY_CHIPS_EFFECTIVE = "wva_capacity_chips_effective"
# 1 while the (variant, tier) is pinned stocked-out by the quota circuit
# breaker (re-probe pending).
WVA_CAPACITY_STOCKED_OUT = "wva_capacity_stocked_out"
# Provisioning requests submitted, by (variant, tier, outcome).
WVA_CAPACITY_PROVISION_TOTAL = "wva_capacity_provision_requests_total"
# Spot slices lost to preemption (cumulative).
WVA_CAPACITY_PREEMPTED_TOTAL = "wva_capacity_preempted_slices_total"
# Measured slice provisioning lead (submission -> discovered ready) per
# (variant, tier) — the actuation->scheduled phase of the lead-time split.
WVA_CAPACITY_PROVISION_LEAD_SECONDS = "wva_capacity_provision_lead_seconds"

# --- DemandTrend estimator health (analyzers/trend.py stats() hook) ---
WVA_TREND_SERIES_SAMPLES = "wva_trend_series_samples"
WVA_TREND_SERIES_STALENESS_SECONDS = "wva_trend_series_staleness_seconds"

# --- Watch-backed informer cache (k8s/informer.py) ---
# Seconds since the kind's store was last confirmed fresh (watch event or
# list); alert on this growing past the resync interval.
WVA_INFORMER_AGE_SECONDS = "wva_informer_age_seconds"
# 1 when the kind's initial LIST completed and the watch is registered.
WVA_INFORMER_SYNCED = "wva_informer_synced"
# --- Dirty-set incremental ticks (engines/saturation) ---
# Models whose input fingerprint was unchanged this tick (analysis skipped,
# prior decision re-emitted as a heartbeat).
WVA_TICK_MODELS_SKIPPED = "wva_tick_models_skipped"
# Models analyzed (dirty or resync) this tick.
WVA_TICK_MODELS_ANALYZED = "wva_tick_models_analyzed"
# Wall-clock seconds the last engine tick spent per phase
# (phase="prepare" | "fingerprint" | "analyze" | "apply"): the next hot
# path must be visible from metrics, not only from `make bench-profile`.
WVA_TICK_PHASE_SECONDS = "wva_tick_phase_seconds"
# --- Immutable object plane (docs/design/object-plane.md) ---
# K8s object copies (objects.clone / thaw) taken during the last engine
# tick. ~0 on steady-state ticks: reads are zero-copy frozen views, and a
# copy happens only at a write site (copy-on-write builder).
WVA_TICK_OBJECT_COPIES = "wva_tick_object_copies"
# --- Sharded active-active engine (wva_tpu/shard; docs/design/sharding.md) ---
# 1 when this process's shard-lease manager holds the shard's lease
# (shard="0".."N-1" | "fleet"); one-hot per shard.
WVA_SHARD_OWNER = "wva_shard_owner"
# Models the consistent-hash ring assigns to each shard this tick.
WVA_SHARD_MODELS_OWNED = "wva_shard_models_owned"
# Ownership moves (model reassigned to a different shard) since process
# start: shard join/leave/crash rebalances.
WVA_SHARD_REBALANCE_TOTAL = "wva_shard_rebalance_total"
# Age of the newest summary the fleet solve consumed from each shard. In
# the in-process plane this is ~0; process-per-shard deployments alert on
# it (a wedged shard worker stops publishing).
WVA_SHARD_SUMMARY_AGE_SECONDS = "wva_shard_summary_age_seconds"

# --- Fleet-tick tracing plane (wva_tpu/obs; docs/design/observability.md) ---
# Tick span trees committed by the span recorder (one per engine tick
# while WVA_SPANS is on).
WVA_SPANS_TICKS_TOTAL = "wva_spans_ticks_total"
# Spans or tick trees dropped, by reason (ring eviction without spill,
# spill write error/backlog, encode error, span outside a tick).
WVA_SPANS_DROPPED_TOTAL = "wva_spans_dropped_total"
# Slow-tick flight-recorder dumps written, by reason (overrun — the tick
# ran longer than its poll interval — or slow-tick — it crossed
# WVA_TRACE_SLOW_TICK_MS). Each dump is the full span tree of the slow
# tick; the log line carries the path.
WVA_SLOW_TICK_DUMPS_TOTAL = "wva_slow_tick_dumps_total"
# OTLP/HTTP span exports, by outcome (success | error | dropped). Only
# emitted when WVA_OTLP_ENDPOINT is set.
WVA_OTLP_EXPORTS_TOTAL = "wva_otlp_exports_total"

# --- Federation plane (wva_tpu/federation; docs/design/federation.md) ---
# Replicas the arbiter's current plan spills into each target region
# (region=target, source=source region(s), per spilled model); 0-swept
# when a directive retires.
WVA_FEDERATION_SPILL_REPLICAS = "wva_federation_spill_replicas"
# Arbiter classification per region (state="healthy" | "degraded" |
# "blackout"); one-hot, from the last published plan.
WVA_FEDERATION_REGION_STATE = "wva_federation_region_state"
# Age of each region's newest ClusterCapture as the arbiter last saw it.
# A capture older than WVA_FEDERATION_CAPTURE_STALE classifies the region
# BLACKOUT — alert before that.
WVA_FEDERATION_CAPTURE_AGE_SECONDS = "wva_federation_capture_age_seconds"

# --- Common metric label names ---
LABEL_KIND = "kind"
LABEL_MODEL_NAME = "model_name"
LABEL_TARGET_MODEL_NAME = "target_model_name"
LABEL_NAMESPACE = "namespace"
LABEL_VARIANT_NAME = "variant_name"
LABEL_DIRECTION = "direction"
LABEL_REASON = "reason"
LABEL_ACCELERATOR_TYPE = "accelerator_type"
LABEL_CONTROLLER_INSTANCE = "controller_instance"
LABEL_POD = "pod"
LABEL_METRIC_NAME = "__name__"
LABEL_ENGINE = "engine"
LABEL_OUTCOME = "outcome"
LABEL_FORECASTER = "forecaster"
LABEL_STATE = "state"
LABEL_TIER = "tier"
LABEL_PHASE = "phase"
LABEL_SOURCE = "source"
LABEL_SHARD = "shard"
LABEL_REGION = "region"

__all__ = [n for n in dir() if n.isupper()]
