"""Kubernetes label/annotation keys (reference ``internal/constants/labels.go:7-28``,
``internal/utils/variant.go`` accelerator label) plus the GKE TPU node-label
schema the discovery layer consumes."""

# Associates VAs with a specific controller instance (multi-controller isolation).
CONTROLLER_INSTANCE_LABEL_KEY = "wva.tpu.llmd.ai/controller-instance"

# Namespace opt-in for namespace-local ConfigMap overrides.
NAMESPACE_CONFIG_ENABLED_LABEL_KEY = "wva.tpu.llmd.ai/config-enabled"

# Namespace exclusion annotation — set "true" to exclude from WVA management.
NAMESPACE_EXCLUDE_ANNOTATION_KEY = "wva.tpu.llmd.ai/exclude"

# VA label naming the TPU slice variant served by this VA's target
# (reference uses `inference.optimization/acceleratorName` for the GPU type;
# internal/utils/variant.go:GetAcceleratorType). Values like "v5e-8", "v5p-16".
ACCELERATOR_NAME_LABEL_KEY = "inference.optimization/acceleratorName"

# --- GKE TPU node-pool labels (discovery layer; SURVEY.md section 7 stage 3) ---

# TPU generation/class, e.g. "tpu-v5-lite-podslice" (v5e), "tpu-v5p-slice".
GKE_TPU_ACCELERATOR_NODE_LABEL = "cloud.google.com/gke-tpu-accelerator"

# Physical slice topology, e.g. "2x4" (8 chips, 1 host) or "4x4" (16 chips, 2 hosts).
GKE_TPU_TOPOLOGY_NODE_LABEL = "cloud.google.com/gke-tpu-topology"

# Extended resource advertised by the TPU device plugin on each node.
TPU_RESOURCE_NAME = "google.com/tpu"

# Node label for the GKE node pool name (slice grouping: all hosts of one
# multi-host slice live in one node pool and carry the same topology).
GKE_NODEPOOL_NODE_LABEL = "cloud.google.com/gke-nodepool"
