"""Coordination lease names (coordination.k8s.io Leases).

Every Lease the controller acquires is named here — the chart's RBAC
(`charts/wva-tpu/templates/rbac/leader-election-role.yaml`) enumerates the
same names per release, so a name drift between code and chart fails the
chart goldens instead of failing at runtime with a Forbidden.

The sharded active-active engine (``wva_tpu/shard``;
docs/design/sharding.md) generalizes the single leader-election Lease into
a lease-per-shard family: shards ``0..N-1`` each have their own Lease
(``wva-tpu-shard-<i>``), and the distinguished **fleet** shard — the one
that runs the fleet-level solve and the apply phase — rides the existing
leader-election Lease, so unsharded deployments keep exactly one Lease and
sharded ones add N.
"""

from __future__ import annotations

# The controller-manager leader-election Lease (reference cmd/main.go
# LeaderElectionID). In sharded mode this IS the `fleet` shard's lease:
# holding it entitles a process to consume shard summaries, run the
# fleet-level solve, and apply decisions.
DEFAULT_LEADER_ELECTION_LEASE = "72dd1cf1.wva.tpu.llmd.ai"

# Shard lease family: one Lease per consistent-hash shard. A worker may
# hold several (the in-process plane holds all of them); each is acquired,
# renewed, and fenced with the same discipline as the leader lease
# (lease_transitions epoch = the shard's fencing token).
SHARD_LEASE_PREFIX = "wva-tpu-shard"

# The distinguished fleet shard's id in metrics/labels ("shard" label).
FLEET_SHARD_ID = "fleet"


def shard_lease_name(shard: int) -> str:
    """Lease name for consistent-hash shard ``shard`` (0-based)."""
    return f"{SHARD_LEASE_PREFIX}-{int(shard)}"


def shard_lease_names(shards: int) -> list[str]:
    """Every shard Lease a ``shards``-way deployment acquires (the fleet
    shard's lease — the leader-election Lease — is configured separately)."""
    return [shard_lease_name(i) for i in range(int(shards))]
