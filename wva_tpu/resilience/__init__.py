"""Crash-restart resilience plane (docs/design/resilience.md).

PR 10 made the engine do-no-harm while its *inputs* fail; this package makes
the controller itself survivable. Three cooperating pieces:

- **Warm-start recovery** (:func:`warm_start`): on boot, re-seed the
  input-health plane's last-known-good desireds from durable VA status
  (``status.desiredOptimizedAlloc`` — written every tick, survives any
  crash) and rehydrate the capacity ledger, forecast trust scores, and
  measured lead-time samples from a compact rv-guarded checkpoint
  ConfigMap (:class:`CheckpointStore`). Orders submitted after the last
  checkpoint are simply absent from it — the shortfall re-orders, which is
  the safe direction (extra capacity arriving, never phantom credit).

- **Do-no-harm boot ramp** (:class:`BootRamp`): for the first
  ``WVA_STARTUP_HOLD_TICKS`` engine ticks every model is treated as
  DEGRADED-equivalent (scale-UP allowed, scale-down/scale-to-zero
  forbidden) until its inputs PROVE fresh — a real backend observation
  classified FRESH, not the health monitor's restart-bootstrap "the clock
  starts now" freshness. In a fault-free world the first tick proves every
  model fresh and the ramp releases without clamping anything, so
  decisions, statuses, and traces are byte-identical to the plane being
  off (same discipline as ``WVA_HEALTH``).

- **Fenced leader failover**: the elector exposes a lease-epoch fencing
  token (``lease_transitions`` at acquisition — bumped by every handover);
  the engine captures it at tick start and re-checks it between analyze
  and apply. A leader deposed mid-tick raises
  :class:`LeadershipLostError` instead of actuating — combined with the
  rv-guarded status writes, two processes can never both actuate inside
  one epoch.

Everything is ``WVA_RESILIENCE``-gated (default on); the durable
checkpoint alone is additionally ``WVA_CHECKPOINT``-gated so operators can
fall back to the boot ramp only (``WVA_CHECKPOINT=off``) with the same
zero-wrong-direction guarantee.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from wva_tpu.resilience.checkpoint import (
    CHECKPOINT_CONFIGMAP_NAME,
    CHECKPOINT_DATA_KEY,
    CHECKPOINT_SCHEMA_VERSION,
    CheckpointStore,
    canonical_json,
)

log = logging.getLogger(__name__)

__all__ = [
    "CHECKPOINT_CONFIGMAP_NAME",
    "CHECKPOINT_DATA_KEY",
    "CHECKPOINT_SCHEMA_VERSION",
    "BootRamp",
    "CheckpointStore",
    "LeadershipLostError",
    "SimulatedCrash",
    "WarmStartReport",
    "canonical_json",
    "warm_start",
]


class LeadershipLostError(RuntimeError):
    """Raised by the engine's fence check when leadership (or the lease
    epoch) changed between analyze and apply: a deposed leader mid-tick
    must never actuate. The executor's retry loop re-checks its leader
    gate and aborts the tick."""


class SimulatedCrash(RuntimeError):
    """Chaos-harness injection: the process 'dies' between analyze and
    apply (decisions computed, never actuated). Raised by the engine when
    its ``crash_before_apply`` hook is armed; the harness then tears the
    manager down and rebuilds it against the same world."""


class BootRamp:
    """Per-model do-no-harm hold for the first ticks after process start.

    A model is *held* (scale-down forbidden, scale-up allowed) until its
    inputs prove fresh — the health monitor classified it FRESH from a
    REAL backend observation this tick — or the ramp expires after
    ``hold_ticks`` engine ticks, by which time the age-based health ladder
    has taken over (its restart-bootstrap grace is ``degraded_after``
    seconds; size ``hold_ticks`` to cover it at your engine interval).
    Single-threaded by design: only the engine tick touches it.
    """

    def __init__(self, hold_ticks: int) -> None:
        self.hold_ticks = max(0, int(hold_ticks))
        self._ticks = 0
        self._released: set[str] = set()

    @property
    def active(self) -> bool:
        return self._ticks < self.hold_ticks

    def holding(self, key: str) -> bool:
        return self.active and key not in self._released

    def release(self, key: str) -> None:
        """Inputs proved fresh for this model: the hold ends permanently
        (the health ladder owns any later degradation)."""
        self._released.add(key)

    def note_tick(self) -> None:
        self._ticks += 1


@dataclass
class WarmStartReport:
    """What boot recovery found — feeds the ``STAGE_BOOT`` trace stage and
    the ``wva_boot_recovered_items`` gauges."""

    held_seeded: int = 0
    orders_restored: int = 0
    stockouts_restored: int = 0
    health_books_restored: int = 0
    trust_restored: int = 0
    leadtime_rings_restored: int = 0
    checkpoint_loaded: bool = False
    checkpoint_age_seconds: float = -1.0

    def recovered_anything(self) -> bool:
        return bool(self.checkpoint_loaded or self.held_seeded)

    def to_dict(self) -> dict:
        return {
            "held_seeded": self.held_seeded,
            "orders_restored": self.orders_restored,
            "stockouts_restored": self.stockouts_restored,
            "health_books_restored": self.health_books_restored,
            "trust_restored": self.trust_restored,
            "leadtime_rings_restored": self.leadtime_rings_restored,
            "checkpoint_loaded": self.checkpoint_loaded,
            "checkpoint_age_seconds": round(self.checkpoint_age_seconds, 3),
        }


def warm_start(client, watch_namespace: str | None, now: float,
               health=None, capacity=None, forecast=None,
               store: CheckpointStore | None = None) -> WarmStartReport:
    """Boot-time state recovery. Best-effort on purpose: a storming
    apiserver at boot degrades to the boot ramp (which exists exactly for
    the nothing-recovered case), never fails process start.

    Ordering: the checkpoint restores first, then durable VA status
    OVERRIDES the health last-known-goods — the engine writes status every
    tick but checkpoints only every ``WVA_CHECKPOINT_INTERVAL`` ticks, so
    status is the fresher record of what we last asked for.
    """
    report = WarmStartReport()

    if store is not None:
        data = None
        try:
            data = store.load()
        except Exception as e:  # noqa: BLE001 — recovery is best-effort
            log.warning("resilience: checkpoint load failed: %s", e)
        if data is not None:
            # Each section restores independently: a schema-valid but
            # content-corrupt checkpoint (truncated write, hand edit) must
            # degrade that section to the boot ramp, never crash-loop the
            # process by failing every restart against the same ConfigMap.
            report.checkpoint_loaded = True
            try:
                saved_at = float(data.get("saved_at", 0.0))
            except (TypeError, ValueError):
                saved_at = 0.0
            if saved_at > 0:
                report.checkpoint_age_seconds = max(now - saved_at, 0.0)
            if capacity is not None and "capacity" in data:
                try:
                    restored = capacity.ledger.restore_state(data["capacity"])
                    report.orders_restored = restored.get("orders", 0)
                    report.stockouts_restored = restored.get("stockouts", 0)
                except Exception as e:  # noqa: BLE001 — see above
                    log.warning(
                        "resilience: capacity checkpoint corrupt, "
                        "skipping section: %s", e)
            if health is not None and "health" in data:
                try:
                    report.health_books_restored = \
                        health.restore_state(data["health"])
                except Exception as e:  # noqa: BLE001
                    log.warning(
                        "resilience: health checkpoint corrupt, "
                        "skipping section: %s", e)
            if forecast is not None and "forecast" in data:
                try:
                    report.trust_restored = \
                        forecast.restore_trust(data["forecast"])
                except Exception as e:  # noqa: BLE001
                    log.warning(
                        "resilience: forecast checkpoint corrupt, "
                        "skipping section: %s", e)
            leadtime = (forecast.leadtime if forecast is not None
                        else getattr(capacity, "leadtime", None))
            if leadtime is not None and "leadtime" in data:
                try:
                    report.leadtime_rings_restored = \
                        leadtime.restore_state(data["leadtime"])
                except Exception as e:  # noqa: BLE001
                    log.warning(
                        "resilience: leadtime checkpoint corrupt, "
                        "skipping section: %s", e)

    if health is not None:
        try:
            vas = client.list("VariantAutoscaling",
                              namespace=watch_namespace or None)
        except Exception as e:  # noqa: BLE001 — see above
            log.warning("resilience: VA warm-start listing failed: %s", e)
            vas = []
        for va in vas:
            alloc = va.status.desired_optimized_alloc
            # last_run_time == 0 means the status was never written — a
            # fresh VA has no last-known-good to seed.
            if alloc.last_run_time > 0 and alloc.num_replicas >= 0:
                health.seed_held(va.metadata.namespace, va.metadata.name,
                                 alloc.num_replicas)
                report.held_seeded += 1
    if report.recovered_anything():
        log.info("resilience: warm start recovered %s", report.to_dict())
    return report
