"""Durable controller-soft-state checkpoint (ConfigMap-backed).

The controller's decision-critical soft state — capacity in-flight orders
and stockout pins, health last-known-goods, forecast trust scores, measured
lead-time samples — dies with the process. This store serializes it to ONE
compact ConfigMap (``wva-resilience-checkpoint`` in the controller's
namespace), written at most every ``interval_ticks`` engine ticks through
the same client every other write uses (so the informer's write-through
keeps the store coherent), and rv-guarded: a conflicting write means
another process owns the checkpoint now, and this round is simply skipped.

Fencing: every checkpoint carries the writer's lease epoch. A deposed
leader (older epoch) finding a NEWER epoch in the stored checkpoint skips
its write — combined with the rv guard, a stale process can never clobber
the new leader's recovery state.

Serialization is canonical (sorted keys, fixed separators, lists instead
of tuple-keyed dicts) so ``save -> load -> save`` round-trips
byte-identically — the property test in tests/test_resilience.py holds the
plane to that.
"""

from __future__ import annotations

import json
import logging

from wva_tpu.k8s.client import ConflictError, KubeClient
from wva_tpu.k8s.objects import ConfigMap, ObjectMeta, clone
from wva_tpu.utils.clock import SYSTEM_CLOCK, Clock

log = logging.getLogger(__name__)

CHECKPOINT_CONFIGMAP_NAME = "wva-resilience-checkpoint"
CHECKPOINT_DATA_KEY = "checkpoint.json"
CHECKPOINT_SCHEMA_VERSION = 1


def canonical_json(payload: dict) -> str:
    """Deterministic encoding: byte-identical for equal state."""
    return json.dumps(payload, sort_keys=True, separators=(",", ":"))


class CheckpointStore:
    """Throttled, fenced, rv-guarded checkpoint writer/reader."""

    def __init__(self, client: KubeClient, namespace: str,
                 interval_ticks: int = 20,
                 name: str = CHECKPOINT_CONFIGMAP_NAME,
                 clock: Clock | None = None) -> None:
        self.client = client
        self.namespace = namespace
        self.name = name
        self.interval_ticks = max(1, int(interval_ticks))
        self.clock = clock or SYSTEM_CLOCK
        # Introspection for tests/bench.
        self.saves = 0
        self.skipped_fenced = 0
        self.skipped_conflict = 0
        self.last_saved_at = -1.0
        self._last_save_tick = 0

    # --- write path ---

    def maybe_save(self, tick_seq: int, epoch: int | None,
                   payload_fn) -> bool:
        """Write a checkpoint when the tick interval elapsed. ``payload_fn``
        is called only when a write will actually be attempted (gathering
        fleet state is not free). NEVER raises — a checkpoint failure must
        not fail the engine tick."""
        if tick_seq - self._last_save_tick < self.interval_ticks:
            return False
        try:
            payload = dict(payload_fn())
            payload["schema"] = CHECKPOINT_SCHEMA_VERSION
            payload["saved_at"] = self.clock.now()
            payload["epoch"] = epoch if epoch is not None else -1
            saved = self._write(payload)
        except Exception as e:  # noqa: BLE001 — never fail the tick
            log.warning("resilience: checkpoint save failed: %s", e)
            return False
        if saved:
            self._last_save_tick = tick_seq
            self.saves += 1
            self.last_saved_at = payload["saved_at"]
        return saved

    def _write(self, payload: dict) -> bool:
        body = canonical_json(payload)
        existing = self.client.try_get(ConfigMap.KIND, self.namespace,
                                       self.name)
        if existing is None:
            self.client.create(ConfigMap(
                metadata=ObjectMeta(name=self.name,
                                    namespace=self.namespace),
                data={CHECKPOINT_DATA_KEY: body}))
            return True
        # Fence: a stored checkpoint from a NEWER lease epoch means another
        # process leads now; a deposed writer must not clobber its state.
        stored_epoch = self._epoch_of(existing)
        ours = payload.get("epoch", -1)
        if stored_epoch is not None and ours >= 0 and stored_epoch > ours:
            self.skipped_fenced += 1
            log.warning(
                "resilience: checkpoint fenced (stored epoch %d > ours %d);"
                " not writing", stored_epoch, ours)
            return False
        cm = clone(existing)
        cm.data = dict(cm.data)
        cm.data[CHECKPOINT_DATA_KEY] = body
        try:
            # rv-guarded: the clone carries the read resourceVersion, so a
            # concurrent writer (new leader) wins and we skip this round.
            self.client.update(cm)
        except ConflictError:
            self.skipped_conflict += 1
            return False
        return True

    @staticmethod
    def _epoch_of(cm) -> int | None:
        try:
            data = json.loads(cm.data.get(CHECKPOINT_DATA_KEY, ""))
            epoch = int(data.get("epoch", -1))
            return epoch if epoch >= 0 else None
        except (ValueError, TypeError, AttributeError):
            return None

    # --- read path ---

    def load(self) -> dict | None:
        """The stored checkpoint payload, or None (absent / unparsable /
        future schema). Never raises for malformed content — boot recovery
        degrades to the ramp."""
        cm = self.client.try_get(ConfigMap.KIND, self.namespace, self.name)
        if cm is None:
            return None
        try:
            data = json.loads(cm.data.get(CHECKPOINT_DATA_KEY, ""))
        except (ValueError, AttributeError):
            log.warning("resilience: stored checkpoint is unparsable; "
                        "ignoring")
            return None
        if not isinstance(data, dict) \
                or data.get("schema") != CHECKPOINT_SCHEMA_VERSION:
            log.warning("resilience: stored checkpoint schema %r != %d; "
                        "ignoring", data.get("schema") if isinstance(
                            data, dict) else None,
                        CHECKPOINT_SCHEMA_VERSION)
            return None
        return data
