"""The capacity manager: the decision loop's interface to elastic slice
inventory.

Per engine tick (after analysis, around the limiter):

1. ``note_demand`` snapshots the fleet's PRE-limiter desired chips per
   variant (the limiter clamps targets to inventory, so post-limiter
   targets can never express a shortfall);
2. ``tick`` reconciles the ledger against a fresh discovery snapshot
   (retiring materialized requests and recording their measured
   provisioning lead), expires wedged orders, computes each variant's
   shortfall against ready + in-flight capacity, and submits provisioning
   requests — tier-preference ordered, deduped against outstanding orders,
   jitter-backed-off after failures, and circuit-broken per (variant,
   tier) on quota stockout;
3. the pool the limiter and the fleet solver see is extended by
   ``provisioning_chips`` (capacity arriving within its credited lead).

Everything is flight-recorded as one ``capacity`` stage event per tick.
The manager never mutates decisions: its influence on the decision path
flows exclusively through the inventory pools the limiter records, which
is what keeps capacity-enabled traces replayable from the recorded pool
snapshot alone.
"""

from __future__ import annotations

import itertools
import logging
import math
import random
import threading

from wva_tpu.capacity.ledger import CapacityLedger, InFlightRequest
from wva_tpu.capacity.provisioner import ProvisionResult, SliceProvisioner
from wva_tpu.capacity.tiers import (
    DEFAULT_TIER_COST_WEIGHTS,
    DEFAULT_TIER_PREFERENCE,
)
from wva_tpu.utils.backoff import BackoffState
from wva_tpu.utils.clock import SYSTEM_CLOCK, Clock

log = logging.getLogger(__name__)

# Ceiling on slices ordered for one variant in one tick: a demand spike must
# not translate into an unbounded cloud order (the next tick re-evaluates
# with the first batch already in flight).
MAX_SLICES_PER_REQUEST = 8

OUTCOME_ACCEPTED = "accepted"
OUTCOME_QUOTA_DENIED = "quota_denied"
OUTCOME_FAILED = "failed"
OUTCOME_DEDUPED = "deduped"


class CapacityManager:
    """Elastic capacity control plane (``WVA_CAPACITY``, default on)."""

    def __init__(self, discovery, provisioner: SliceProvisioner,
                 leadtime=None,
                 tier_preference: tuple[str, ...] = DEFAULT_TIER_PREFERENCE,
                 tier_weights: dict[str, float] | None = None,
                 stockout_reprobe_seconds: float = 300.0,
                 default_lead_seconds: float = 180.0,
                 clock: Clock | None = None,
                 seed: int = 0) -> None:
        self.discovery = discovery
        self.provisioner = provisioner
        # Shared with the forecast planner when forecasting is on: both
        # planes learn from the same measured lead times.
        self.leadtime = leadtime
        self.ledger = CapacityLedger()
        self.tier_preference = tuple(tier_preference)
        self.tier_weights = dict(tier_weights or DEFAULT_TIER_COST_WEIGHTS)
        self.stockout_reprobe_seconds = stockout_reprobe_seconds
        self.default_lead_seconds = default_lead_seconds
        self.clock = clock or SYSTEM_CLOCK
        self._mu = threading.Lock()
        self._rng = random.Random(seed)
        self._req_ids = itertools.count(1)
        # Pre-limiter demand snapshot for the current tick.
        self._tick_demand: dict[str, int] = {}
        # Per-variant jittered retry backoff for FAILED (non-quota)
        # submissions; quota denials go through the ledger's circuit
        # breaker instead.
        self._backoff: dict[str, BackoffState] = {}
        # Rolling request log for tests / the e2e's zero-repeat-requests
        # assertion: (now, variant, tier, slices, outcome).
        self.request_log: list[tuple[float, str, str, int, str]] = []
        # Per-variant chips-per-replica seen in decisions: the slice-size
        # bootstrap for variants discovery has never reported (a brand-new
        # variant's FIRST order must be sizeable before any slice exists).
        self._chip_hint: dict[str, int] = {}
        # Obs plane (WVA_SPANS): build_manager installs the engine's span
        # recorder here so provisioning orders appear in the tick tree.
        # None = off (zero cost).
        self.spans = None

    # --- watch feed (informer nudge listener registers this) ---

    def on_node_event(self, event: str, obj) -> str | None:
        """Node watch event -> ledger loss accounting. Returns the affected
        variant when a slice was lost (callers use it to nudge an immediate
        re-solve in wall-clock mode)."""
        return self.ledger.on_node_event(event, obj, self.clock.now())

    # --- engine hooks ---

    def note_demand(self, decisions) -> None:
        """Snapshot the tick's PRE-limiter desired chips per variant."""
        demand: dict[str, int] = {}
        hints: dict[str, int] = {}
        for d in decisions:
            if not d.accelerator_name:
                continue
            per_replica = max(d.chips_per_replica, 1)
            chips = per_replica * max(d.target_replicas, 0)
            demand[d.accelerator_name] = \
                demand.get(d.accelerator_name, 0) + chips
            hints[d.accelerator_name] = max(
                hints.get(d.accelerator_name, 0), per_replica)
        with self._mu:
            self._tick_demand = demand
            self._chip_hint.update(hints)

    def pool_credit_chips(self, variant: str) -> int:
        """Extra chips the inventory pool may plan against: in-flight
        provisioning inside its credited lead window."""
        return self.ledger.provisioning_chips(variant, self.clock.now())

    def tier_cost_weight(self, variant: str) -> float:
        return self.ledger.blended_tier_weight(variant, self.tier_weights)

    def provisioning_lead(self, variant: str) -> float:
        """Best measured provisioning lead across the tier walk (the
        federation capture's per-variant lead signal); falls back to the
        configured default when nothing has been measured yet."""
        return min((self._lead_estimate(variant, tier)
                    for tier in self.tier_preference),
                   default=self.default_lead_seconds)

    def credit_only_pools(self, existing: set[str]) -> dict[str, int]:
        """Variants with in-flight provisioning credit but NO discovered
        pool yet (first slices still materializing) -> credit chips, for
        the inventory to surface as pools."""
        now = self.clock.now()
        out: dict[str, int] = {}
        for variant in self.ledger.known_variants():
            if variant in existing:
                continue
            credit = self.ledger.provisioning_chips(variant, now)
            if credit > 0:
                out[variant] = credit
        return out

    def tick(self, slices: dict | None = None,
             hold_releases: frozenset[str] | bool = frozenset()) -> dict:
        """One capacity pass; returns the ``capacity`` stage event payload
        (ledger snapshot + this tick's provisioning activity). ``slices``
        is the tick's discovery snapshot when the caller already computed
        one (the limiter's inventory refresh — no point listing and
        parsing the node fleet a second time in the same tick); None runs
        a fresh discovery pass. ``hold_releases`` (the engine's input-
        health BLACKOUT signal) names the VARIANTS whose orders must not
        surrender capacity this tick: their in-flight orders are not
        expired (dropping the planning credit would shrink the pools the
        solver sees, and an order wedged during a metrics blackout often
        just means its confirmation is blind too). Per-variant on purpose
        — one model's blackout must not suppress expiry of an unrelated
        healthy variant's genuinely wedged order. ``True`` holds every
        variant (tests / blunt callers); ordering for real shortfalls
        continues either way, since frozen demand can still be
        under-supplied after a preemption."""
        now = self.clock.now()
        if slices is None:
            try:
                slices = self.discovery.discover_slices()
            except Exception as e:  # noqa: BLE001 — capacity must never
                # fail the tick; planning degrades to last-known inventory.
                log.error("capacity: slice discovery failed: %s", e)
                slices = None
        # An EMPTY snapshot is real information (every node gone) and must
        # reconcile; only a failed discovery skips it.
        completed = [] if slices is None \
            else self.ledger.observe_discovery(slices, now)
        for c in completed:
            self._record_lead(c.request.variant, c.request.tier, c.latency)
            self._backoff_for(c.request.variant).success()
        if hold_releases is True:
            hold = frozenset(self.ledger.known_variants())
        else:
            hold = frozenset(hold_releases or ())
        expired = self.ledger.expire_overdue(now, hold_variants=hold)
        for req in expired:
            # A silently-wedged order is a failure for backoff purposes:
            # the next attempt for the variant is delayed, not immediate.
            self._backoff_for(req.variant).failure(now)
            log.warning("capacity: provisioning request %s (%s x%d via %s) "
                        "never materialized; dropping its planning credit",
                        req.request_id, req.variant, req.slices, req.tier)

        requests = self._provision_shortfalls(slices or {}, now)
        return {
            "ledger": self.ledger.snapshot(now),
            "requests": requests,
            "completed": [{
                "request_id": c.request.request_id,
                "variant": c.request.variant,
                "tier": c.request.tier,
                "slices": c.request.slices,
                "latency_seconds": round(c.latency, 3),
            } for c in completed],
            "expired": [{
                "request_id": r.request_id, "variant": r.variant,
                "tier": r.tier, "slices": r.slices,
            } for r in expired],
        }

    # --- internals ---

    def _backoff_for(self, variant: str) -> BackoffState:
        with self._mu:
            st = self._backoff.get(variant)
            if st is None:
                st = self._backoff[variant] = BackoffState(
                    initial=5.0, cap=300.0, rng=self._rng)
            return st

    def _next_req_id(self, variant: str) -> str:
        # The counter restarts at 1 in every process, but the ledger may
        # hold checkpoint-restored in-flight orders from a previous
        # incarnation under the same scheme — reusing such an id would
        # silently overwrite the restored record in note_request and drop
        # its planning credit. Skip taken ids (deterministic, so seeded
        # worlds replay).
        while True:
            rid = f"req-{variant}-{next(self._req_ids)}"
            if not self.ledger.has_inflight_id(variant, rid):
                return rid

    def _record_lead(self, variant: str, tier: str, latency: float) -> None:
        if self.leadtime is not None and latency > 0:
            self.leadtime.record_provisioning(variant, tier, latency)

    def _lead_estimate(self, variant: str, tier: str) -> float:
        if self.leadtime is not None:
            lead, measured = self.leadtime.provisioning_estimate(variant,
                                                                 tier)
            if measured:
                return lead
        return self.default_lead_seconds

    def _provision_shortfalls(self, slices: dict, now: float) -> list[dict]:
        with self._mu:
            demand = dict(self._tick_demand)
            hints = dict(self._chip_hint)
        requests: list[dict] = []
        for variant in sorted(demand):
            chips_needed = demand[variant]
            cap = slices.get(variant)
            # Slice size: discovery is authoritative; the ledger remembers
            # variants discovery USED to report; the decision's own
            # chips-per-replica bootstraps a variant no slice has ever
            # existed for (replicas span whole slices in this domain).
            chips_per_slice = (cap.chips_per_slice if cap is not None
                               else self.ledger.chips_per_slice(variant)
                               or hints.get(variant, 0))
            if chips_per_slice <= 0:
                continue
            supply = self.ledger.ready_chips(variant) \
                + self.ledger.provisioning_chips(variant, now)
            shortfall = chips_needed - supply
            if shortfall <= 0:
                continue
            if self.ledger.has_request(variant):
                # Dedup: one outstanding order per variant. The next tick
                # re-evaluates once it lands (or expires).
                self._log_request(now, variant, "", 0, OUTCOME_DEDUPED)
                continue
            if not self._backoff_for(variant).ready(now):
                continue
            count = min(math.ceil(shortfall / chips_per_slice),
                        MAX_SLICES_PER_REQUEST)
            if self.spans is not None:
                with self.spans.span("capacity_order", variant=variant,
                                     slices=count) as sp:
                    event = self._submit(variant, count, chips_per_slice,
                                         now)
                    if event is not None:
                        self.spans.annotate(sp, tier=event["tier"],
                                            outcome=event["outcome"])
            else:
                event = self._submit(variant, count, chips_per_slice, now)
            if event is not None:
                requests.append(event)
        return requests

    def _submit(self, variant: str, count: int, chips_per_slice: int,
                now: float) -> dict | None:
        """Walk the tier preference order, skipping circuit-broken tiers;
        the first accepted submission wins. Every quota denial pins its
        tier; a transport error falls through to the NEXT tier (the
        preference order exists precisely to provide fallbacks — one flaky
        endpoint must not stall replacement capacity) and only backs the
        variant off when EVERY tier failed; all tiers denied/broken leaves
        the variant stocked out until a re-probe window opens."""
        last_error: dict | None = None
        for tier in self.tier_preference:
            if not self.ledger.tier_open(variant, tier, now):
                continue
            try:
                result = self.provisioner.request_slices(
                    variant, tier, count, now)
            except Exception as e:  # noqa: BLE001 — transport errors get
                # backoff, never a stockout pin (they are not evidence of
                # missing stock) and never fail the tick.
                log.warning("capacity: provisioner error for %s via %s: %s",
                            variant, tier, e)
                self._log_request(now, variant, tier, count, OUTCOME_FAILED)
                last_error = {"variant": variant, "tier": tier,
                              "slices": count, "outcome": OUTCOME_FAILED,
                              "message": str(e)}
                continue
            if result.accepted:
                lead = (result.eta_seconds if result.eta_seconds > 0
                        else self._lead_estimate(variant, tier))
                rid = result.request_id or self._next_req_id(variant)
                self.ledger.note_request(InFlightRequest(
                    request_id=rid, variant=variant, tier=tier,
                    slices=count, chips_per_slice=chips_per_slice,
                    requested_at=now, eta=now + lead))
                self.ledger.clear_stockout(variant, tier)
                self._log_request(now, variant, tier, count,
                                  OUTCOME_ACCEPTED)
                return {"variant": variant, "tier": tier, "slices": count,
                        "outcome": OUTCOME_ACCEPTED, "request_id": rid,
                        "eta_seconds": round(lead, 1)}
            if result.quota_denied:
                until = self.ledger.note_stockout(
                    variant, tier, now, self.stockout_reprobe_seconds)
                self._log_request(now, variant, tier, count,
                                  OUTCOME_QUOTA_DENIED)
                log.warning("capacity: %s stocked out via %s until t=%.0f "
                            "(%s)", variant, tier, until, result.message)
                continue  # try the next tier
            # Declined without a quota signal (NullProvisioner): nothing
            # to order through this tier, try the next.
        if last_error is not None:
            # No tier accepted and at least one errored: pace the next
            # attempt for the variant.
            self._backoff_for(variant).failure(now)
        return last_error

    def _log_request(self, now: float, variant: str, tier: str, count: int,
                     outcome: str) -> None:
        with self._mu:
            self.request_log.append((now, variant, tier, count, outcome))
            if len(self.request_log) > 4096:
                del self.request_log[:2048]
