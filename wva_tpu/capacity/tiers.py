"""Capacity cost tiers for TPU slice provisioning.

GKE sells the same slice shape through three commercial channels with very
different economics and availability semantics (AIBrix makes exactly this
heterogeneous, cost-tiered pool structure a first-class autoscaling input):

- **reservation** — pre-purchased capacity: cheapest effective rate, never
  preempted, but a finite stock that can run out ("stocked out");
- **on_demand** — pay-as-you-go: always the list price, subject to regional
  quota;
- **spot** — deeply discounted, preemptible at any moment with ~30s notice.

The provisioner requests tiers in *preference order* (reservation first);
the cost *weights* scale a variant's per-slice cost in the fleet solver so
a spot-backed pool genuinely competes on price while reservation-backed
capacity stays the default choice.

This module is a leaf (no imports from the rest of the package) so
discovery can classify nodes into tiers without a dependency cycle.
"""

from __future__ import annotations

TIER_RESERVATION = "reservation"
TIER_ON_DEMAND = "on_demand"
TIER_SPOT = "spot"

# Cheapest-stable-first: reservations are sunk cost, on-demand is the
# dependable fallback, spot is last (cheap but evaporates mid-serve).
DEFAULT_TIER_PREFERENCE: tuple[str, ...] = (
    TIER_RESERVATION, TIER_ON_DEMAND, TIER_SPOT)

# Relative cost of one slice-hour per tier (on-demand = 1.0). Roughly GKE's
# committed-use / spot discount ballpark; operators override per deployment
# (WVA_CAPACITY_TIER_WEIGHTS).
DEFAULT_TIER_COST_WEIGHTS: dict[str, float] = {
    TIER_RESERVATION: 0.6,
    TIER_ON_DEMAND: 1.0,
    TIER_SPOT: 0.3,
}

# GKE node labels the tier is read from.
GKE_SPOT_NODE_LABEL = "cloud.google.com/gke-spot"
GKE_PREEMPTIBLE_NODE_LABEL = "cloud.google.com/gke-preemptible"
GKE_RESERVATION_NODE_LABEL = "cloud.google.com/reservation-name"


def tier_for_node_labels(labels: dict[str, str]) -> str:
    """Classify a node into its capacity tier from GKE labels; unlabeled
    nodes are on-demand (the GKE default)."""
    if labels.get(GKE_SPOT_NODE_LABEL) == "true" \
            or labels.get(GKE_PREEMPTIBLE_NODE_LABEL) == "true":
        return TIER_SPOT
    if labels.get(GKE_RESERVATION_NODE_LABEL):
        return TIER_RESERVATION
    return TIER_ON_DEMAND


def parse_tier_weights(raw: str) -> dict[str, float]:
    """``"reservation=0.6,on_demand=1.0,spot=0.3"`` -> weights dict, merged
    over the defaults (unknown tiers rejected so a typo cannot silently
    drop a weight)."""
    out = dict(DEFAULT_TIER_COST_WEIGHTS)
    for part in (raw or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"invalid tier weight entry {part!r}")
        tier, _, value = part.partition("=")
        tier = tier.strip()
        if tier not in out:
            raise ValueError(f"unknown capacity tier {tier!r}")
        out[tier] = float(value)
    return out


def parse_region_tier_weights(raw: str) -> dict[str, dict[str, float]]:
    """``"us-east1=spot:0.2,reservation:0.5|eu-west4=spot:0.45"`` ->
    per-region weight overrides, each region merged over the defaults.

    ``WVA_CAPACITY_TIER_WEIGHTS`` is parsed once per process, which was
    fine while one process served one region — but the federation arbiter
    prices EVERY region's candidacy, and pricing them all with the
    arbiter's local env var would let one region's spot discount distort
    another region's arbitrage. Regions absent from the override keep the
    weights their own capture shipped (wva_tpu/federation/arbiter.py)."""
    out: dict[str, dict[str, float]] = {}
    for block in (raw or "").split("|"):
        block = block.strip()
        if not block:
            continue
        if "=" not in block:
            raise ValueError(f"invalid region tier weight block {block!r}")
        region, _, spec = block.partition("=")
        region = region.strip()
        if not region:
            raise ValueError(f"empty region in tier weight block {block!r}")
        weights = dict(DEFAULT_TIER_COST_WEIGHTS)
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if ":" not in part:
                raise ValueError(
                    f"invalid tier weight entry {part!r} for {region!r}")
            tier, _, value = part.partition(":")
            tier = tier.strip()
            if tier not in weights:
                raise ValueError(f"unknown capacity tier {tier!r}")
            weights[tier] = float(value)
        out[region] = weights
    return out


def parse_tier_preference(raw: str) -> tuple[str, ...]:
    """``"reservation,spot"`` -> preference order (subset allowed: omitting
    a tier forbids provisioning through it)."""
    if not raw:
        return DEFAULT_TIER_PREFERENCE
    tiers = tuple(t.strip() for t in raw.split(",") if t.strip())
    for t in tiers:
        if t not in DEFAULT_TIER_COST_WEIGHTS:
            raise ValueError(f"unknown capacity tier {t!r}")
    if not tiers:
        return DEFAULT_TIER_PREFERENCE
    return tiers
