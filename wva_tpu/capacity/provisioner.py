"""Slice provisioner interface: how the capacity plane asks the cloud for
more TPU slices.

The control loop never blocks on provisioning — ``request_slices`` is a
cheap *submission* (GKE: a node-pool create/resize API call) and the
fulfillment is observed asynchronously through discovery (nodes appearing)
and the ledger's in-flight accounting. Quota stockouts are a first-class
outcome, not an exception: GKE rejects the request synchronously with a
quota error, and the caller's circuit breaker pins the (variant, tier) as
unavailable until a time-decayed re-probe.

Implementations:

- :class:`wva_tpu.emulator.gke_provisioner.FakeGkeProvisioner` — the
  emulation-world implementation with configurable provisioning delay,
  seeded spot preemption injection, and per-tier quota stockouts;
- :class:`NullProvisioner` — the default in live deployments until a real
  GKE client is wired: every request is declined, so the autoscaler plans
  strictly within discovered inventory (exactly the pre-capacity-plane
  behavior).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass


@dataclass
class ProvisionResult:
    """Outcome of one slice request submission."""

    accepted: bool = False
    request_id: str = ""
    # Provisioner's own delivery estimate; 0 = unknown (the ledger then
    # uses the measured per-(variant, tier) provisioning lead).
    eta_seconds: float = 0.0
    # Quota / reservation stockout: the deterministic "cannot materialize"
    # signal that trips the circuit breaker. Transient transport errors
    # must leave this False (they get retry-with-backoff, not a pin).
    quota_denied: bool = False
    message: str = ""


class SliceProvisioner(abc.ABC):
    """Asynchronous TPU slice provisioning (GKE node-pool create/resize)."""

    @abc.abstractmethod
    def request_slices(self, variant: str, tier: str, count: int,
                       now: float) -> ProvisionResult:
        """Submit a request for ``count`` whole slices of ``variant``
        through capacity ``tier``. Must be idempotent under dedup: a
        repeated submission for the same outstanding need returns the
        existing request instead of double-ordering."""

    def release_slices(self, variant: str, tier: str, count: int,
                       now: float) -> None:
        """Optional: hand back idle slices (node-pool shrink). Default
        no-op — scale-down economics are owned by the solver's cost terms,
        and slice teardown is deliberately conservative."""

    def cancel(self, request_id: str, now: float) -> bool:
        """Optional: cancel an in-flight request. Default no-op (GKE
        node-pool operations are not reliably cancelable)."""
        return False


class NullProvisioner(SliceProvisioner):
    """Declines every request: the autoscaler plans within discovered
    inventory only. The safe default until a real cloud client is wired."""

    def request_slices(self, variant: str, tier: str, count: int,
                       now: float) -> ProvisionResult:
        return ProvisionResult(
            accepted=False,
            message="no slice provisioner configured; planning within "
                    "discovered inventory")
