"""Elastic slice inventory ledger: per-variant accounting of capacity in
every lifecycle state, not just capacity-at-hand.

States per (variant) pool:

- ``ready`` — whole schedulable slices discovery can see right now;
- ``provisioning`` — slices ordered from the provisioner, carrying an ETA
  (the provisioner's own estimate or the measured per-(variant, tier)
  provisioning lead); they count toward planning capacity while their ETA
  is credible (Autopilot's insight: plan against *measured* provisioning
  behavior, not optimism);
- ``preempted`` — slices lost to spot preemption / node failure since the
  last discovery pass (the watch event arrives seconds before discovery
  re-lists, and the pool math must not double-count the corpse);
- ``stocked_out`` — a (variant, tier) the cloud refused on quota; pinned
  unavailable with a time-decayed re-probe so the solver stops planning
  capacity that cannot materialize.

The ledger is deliberately clock-free (every method takes ``now``) so
simulated worlds drive it deterministically, and lock-protected because
node watch events land from the informer's dispatch context while the
engine tick reads it.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field

from wva_tpu.capacity.tiers import TIER_SPOT, tier_for_node_labels
from wva_tpu.constants.labels import (
    GKE_TPU_ACCELERATOR_NODE_LABEL,
    GKE_TPU_TOPOLOGY_NODE_LABEL,
    TPU_RESOURCE_NAME,
)
from wva_tpu.discovery.tpu import parse_tpu_topology
from wva_tpu.k8s.objects import parse_quantity

log = logging.getLogger(__name__)

STATE_READY = "ready"
STATE_PROVISIONING = "provisioning"
STATE_PREEMPTED = "preempted"
STATE_STOCKED_OUT = "stocked_out"

# An in-flight request keeps its planning credit until this multiple of its
# ETA has elapsed: provisioning that runs 50% past its measured lead is no
# longer capacity anyone should plan against (it may be wedged), but a
# small overrun must not flap the pool.
CREDIT_GRACE_FACTOR = 1.5
# Consecutive stockouts grow the re-probe interval geometrically up to this
# multiple (time-decayed re-probe: a persistent stockout is probed ever
# less often; one success resets the streak).
MAX_REPROBE_BACKOFF = 8


@dataclass
class InFlightRequest:
    """One accepted provisioning order."""

    request_id: str = ""
    variant: str = ""
    tier: str = ""
    slices: int = 0
    chips_per_slice: int = 0
    requested_at: float = 0.0
    eta: float = 0.0  # absolute time the slices should materialize

    @property
    def chips(self) -> int:
        return self.slices * self.chips_per_slice

    def credit_expires(self) -> float:
        lead = max(self.eta - self.requested_at, 1.0)
        return self.requested_at + CREDIT_GRACE_FACTOR * lead


@dataclass
class _VariantBook:
    variant: str = ""
    chips_per_slice: int = 0
    hosts_per_slice: int = 1
    ready_slices: int = 0
    # Highest ready count seen while orders are in flight: growth only
    # counts as order FULFILLMENT beyond this high-water mark, so a
    # NotReady flap (count dips one pass, recovers the next) cannot
    # spuriously retire an order with a bogus short lead sample. Tracks
    # the current count whenever nothing is in flight.
    peak_ready: int = 0
    tier_slices: dict[str, int] = field(default_factory=dict)
    # Slices lost to node deletion / NotReady / cordon since the last
    # discovery pass (watch-observed; cleared when discovery re-confirms).
    # lost_slices derives from lost_nodes grouped by hosts_per_slice: one
    # preempted multi-host slice produces one DELETED event PER HOST, and
    # counting each as a whole slice would overstate the loss.
    lost_slices: int = 0
    lost_nodes: set[str] = field(default_factory=set)
    # Spot hosts deleted since the last discovery pass; folded into
    # preempted_total as whole slices when discovery re-confirms (the
    # NotReady -> DELETED sequence real preemptions produce must count
    # once, and per-host events of one slice must count as one slice).
    preempted_window: set[str] = field(default_factory=set)
    inflight: dict[str, InFlightRequest] = field(default_factory=dict)
    stockout_until: dict[str, float] = field(default_factory=dict)
    stockout_streak: dict[str, int] = field(default_factory=dict)
    preempted_total: int = 0


@dataclass
class CompletedRequest:
    request: InFlightRequest | None = None
    latency: float = 0.0  # request submission -> slices discovered ready


class CapacityLedger:
    """Thread-safe per-variant slice accounting."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._books: dict[str, _VariantBook] = {}

    def _book(self, variant: str) -> _VariantBook:
        book = self._books.get(variant)
        if book is None:
            book = self._books[variant] = _VariantBook(variant=variant)
        return book

    # --- discovery feed ---

    def observe_discovery(self, slices: dict, now: float
                          ) -> list[CompletedRequest]:
        """Reconcile against a discovery snapshot (``variant ->
        SliceCapacity``). Growth beyond the expected ready count retires
        the oldest in-flight requests FIFO — their submission->discovered
        latency is the measured provisioning lead the estimator and the
        ETA math feed on. Returns the requests retired this pass."""
        completed: list[CompletedRequest] = []
        with self._mu:
            for variant, cap in slices.items():
                book = self._book(variant)
                book.chips_per_slice = cap.chips_per_slice
                book.hosts_per_slice = max(cap.hosts_per_slice, 1)
                # Fulfillment = growth beyond BOTH the expected count and
                # the in-flight-era high-water mark: a dip-and-recover
                # (NotReady flap, transiently missing node) must not
                # retire an order that has not actually landed. A genuine
                # permanent shrink makes the mark conservative — the
                # affected order then expires via its credit window and
                # is re-ordered, which is the safe direction.
                expected = max(book.ready_slices - book.lost_slices, 0)
                if not book.inflight:
                    book.peak_ready = cap.total_slices
                growth = cap.total_slices - max(expected, book.peak_ready)
                book.peak_ready = max(book.peak_ready, cap.total_slices)
                if growth > 0 and book.inflight:
                    for rid in sorted(book.inflight,
                                      key=lambda r: book.inflight[r]
                                      .requested_at):
                        if growth <= 0:
                            break
                        req = book.inflight[rid]
                        if req.slices <= growth:
                            growth -= req.slices
                            del book.inflight[rid]
                            completed.append(CompletedRequest(
                                request=req,
                                latency=max(now - req.requested_at, 0.0)))
                            # A materialized request proves the tier is not
                            # stocked out.
                            book.stockout_until.pop(req.tier, None)
                            book.stockout_streak.pop(req.tier, None)
                        else:
                            req.slices -= growth
                            growth = 0
                book.ready_slices = cap.total_slices
                book.tier_slices = dict(cap.tier_slices)
                self._fold_window_locked(book)
            # Variants discovery no longer reports: every slice is gone.
            for variant, book in self._books.items():
                if variant not in slices and (book.ready_slices
                                              or book.lost_nodes
                                              or book.preempted_window):
                    book.ready_slices = 0
                    book.tier_slices = {}
                    self._fold_window_locked(book)
        return completed

    @staticmethod
    def _preempted_pending(book: _VariantBook) -> int:
        hosts = max(book.hosts_per_slice, 1)
        return -(-len(book.preempted_window) // hosts)

    def _fold_window_locked(self, book: _VariantBook) -> None:
        """Discovery re-confirmed the variant: bake the watch-observed
        losses into the cumulative preemption count (whole slices) and
        reset the per-window transients."""
        book.preempted_total += self._preempted_pending(book)
        book.preempted_window.clear()
        book.lost_slices = 0
        book.lost_nodes.clear()

    # --- node watch feed ---

    def on_node_event(self, event: str, node, now: float) -> str | None:
        """A node went away (DELETED) or stopped being schedulable
        (NotReady / cordon): mark the backing slice lost so planning
        capacity drops THIS tick, before the next discovery pass
        re-confirms. Returns the affected variant (for the re-solve
        nudge), or None when the node is not TPU-backed or the event is
        not a loss."""
        labels = node.metadata.labels or {}
        accel = labels.get(GKE_TPU_ACCELERATOR_NODE_LABEL, "")
        if not accel:
            return None
        chips = parse_quantity(
            node.status.allocatable.get(TPU_RESOURCE_NAME, "0"))
        info = parse_tpu_topology(
            accel, labels.get(GKE_TPU_TOPOLOGY_NODE_LABEL, ""),
            chips_per_host=chips)
        if info is None:
            return None
        # An ADDED node is never a loss: real GKE nodes register NotReady
        # and flip Ready later — deducting a slice that was never counted
        # as ready would shrink planned capacity exactly while it grows.
        if event == "ADDED":
            return None
        name = node.metadata.name
        lost = (event == "DELETED"
                or not getattr(node, "ready", True)
                or getattr(node, "unschedulable", False))
        if not lost:
            # A previously-lost node RECOVERED (NotReady flap resolved,
            # uncordoned): release the loss so planning capacity comes
            # back without waiting for the next discovery pass.
            with self._mu:
                book = self._books.get(info.variant)
                if book is not None and name in book.lost_nodes:
                    book.lost_nodes.discard(name)
                    hosts = max(book.hosts_per_slice, 1)
                    book.lost_slices = min(
                        -(-len(book.lost_nodes) // hosts),
                        book.ready_slices)
            return None
        spot = tier_for_node_labels(labels) == TIER_SPOT
        with self._mu:
            book = self._book(info.variant)
            if spot and event == "DELETED":
                # Preemption accounting is per DELETED host, independent
                # of the loss dedup: the realistic NotReady -> DELETED
                # sequence must still count, once. Folded into
                # preempted_total as whole slices at the next discovery
                # pass.
                book.preempted_window.add(name)
            if name in book.lost_nodes:
                return None  # NotReady then DELETED: one loss, not two
            book.lost_nodes.add(name)
            # One lost host degrades the whole slice containing it, but
            # per-host events of one multi-host slice are ONE lost slice:
            # group by the variant's hosts-per-slice (membership is not
            # tracked, so interleaved single-host losses across slices
            # under-count — conservative for planning, which discovery
            # corrects on its next pass).
            hosts = max(book.hosts_per_slice, 1)
            book.lost_slices = min(-(-len(book.lost_nodes) // hosts),
                                   book.ready_slices)
        return info.variant

    # --- provisioning feed ---

    def note_request(self, req: InFlightRequest) -> None:
        with self._mu:
            book = self._book(req.variant)
            book.inflight[req.request_id] = req
            if book.chips_per_slice <= 0:
                # Discovery has never reported this variant (first slices
                # still materializing): the order's own slice size keeps
                # snapshot()/gauges honest until discovery confirms.
                book.chips_per_slice = req.chips_per_slice

    def note_stockout(self, variant: str, tier: str, now: float,
                      reprobe_seconds: float) -> float:
        """Pin (variant, tier) stocked out; consecutive denials grow the
        re-probe interval geometrically (capped). Returns the pin expiry."""
        with self._mu:
            book = self._book(variant)
            streak = book.stockout_streak.get(tier, 0) + 1
            book.stockout_streak[tier] = streak
            mult = min(2 ** (streak - 1), MAX_REPROBE_BACKOFF)
            until = now + reprobe_seconds * mult
            book.stockout_until[tier] = until
            return until

    def tier_open(self, variant: str, tier: str, now: float) -> bool:
        """May we submit a request through this tier right now? A pinned
        tier re-opens for ONE probe once its re-probe time passes."""
        with self._mu:
            return now >= self._book(variant).stockout_until.get(tier, 0.0)

    def clear_stockout(self, variant: str, tier: str) -> None:
        """An accepted request proves the tier has stock again."""
        with self._mu:
            book = self._book(variant)
            book.stockout_until.pop(tier, None)
            book.stockout_streak.pop(tier, None)

    def expire_overdue(self, now: float,
                       hold_variants: frozenset[str] = frozenset(),
                       ) -> list[InFlightRequest]:
        """Drop in-flight requests whose credit window lapsed (wedged or
        silently failed provisioning) so the pool stops planning against
        them. The manager decides whether to re-order. ``hold_variants``
        (the input-health plane's blacked-out variants) keep their orders'
        planning credit: a confirmation that cannot be observed is not a
        wedge — while every OTHER variant's expiry proceeds on its own
        trusted evidence."""
        expired = []
        with self._mu:
            for variant, book in self._books.items():
                if variant in hold_variants:
                    continue
                for rid in [r for r, req in book.inflight.items()
                            if now > req.credit_expires()]:
                    expired.append(book.inflight.pop(rid))
        return expired

    # --- planning reads ---

    def ready_chips(self, variant: str) -> int:
        """Schedulable chips net of watch-observed losses discovery has
        not re-confirmed yet (same-tick preemption release)."""
        with self._mu:
            book = self._books.get(variant)
            if book is None:
                return 0
            return max(book.ready_slices - book.lost_slices, 0) \
                * book.chips_per_slice

    def provisioning_chips(self, variant: str, now: float) -> int:
        """Chips of in-flight requests still inside their credit window —
        the "arriving within lead time" pool extension."""
        with self._mu:
            book = self._books.get(variant)
            if book is None:
                return 0
            return sum(req.chips for req in book.inflight.values()
                       if now <= req.credit_expires())

    def inflight_slices(self, variant: str) -> int:
        with self._mu:
            book = self._books.get(variant)
            return sum(r.slices for r in book.inflight.values()) \
                if book else 0

    def has_request(self, variant: str) -> bool:
        with self._mu:
            book = self._books.get(variant)
            return bool(book and book.inflight)

    def has_inflight_id(self, variant: str, request_id: str) -> bool:
        with self._mu:
            book = self._books.get(variant)
            return bool(book and request_id in book.inflight)

    def tier_mix(self, variant: str) -> dict[str, int]:
        with self._mu:
            book = self._books.get(variant)
            return dict(book.tier_slices) if book else {}

    def known_variants(self) -> list[str]:
        with self._mu:
            return sorted(self._books)

    def chips_per_slice(self, variant: str) -> int:
        with self._mu:
            book = self._books.get(variant)
            return book.chips_per_slice if book else 0

    def blended_tier_weight(self, variant: str,
                            weights: dict[str, float]) -> float:
        """Ready-slice-weighted mean of the tier cost weights — the factor
        the fleet solver scales this variant's per-slice cost by (a
        spot-heavy pool genuinely competes on price)."""
        with self._mu:
            book = self._books.get(variant)
            if book is None or not book.tier_slices:
                return 1.0
            total = sum(book.tier_slices.values())
            if total <= 0:
                return 1.0
            return sum(weights.get(t, 1.0) * n
                       for t, n in book.tier_slices.items()) / total

    # --- crash-restart checkpoint (wva_tpu.resilience) ---

    def export_state(self) -> dict:
        """Serializable per-variant books for the resilience checkpoint.
        Watch-window transients (lost nodes, preemption window) are NOT
        exported — they describe sub-discovery-interval state the next
        discovery pass re-derives; what must survive a restart is the
        in-flight order book (planning credit + lead measurement anchors),
        the stockout circuit breakers, and the fulfillment baseline
        (ready/peak counts — without them a restored order would be
        spuriously 'retired' by the first discovery pass re-reporting the
        pre-crash fleet as growth). Sorted everywhere: equal state must
        serialize byte-identically."""
        variants = {}
        with self._mu:
            for variant in sorted(self._books):
                book = self._books[variant]
                variants[variant] = {
                    "chips_per_slice": book.chips_per_slice,
                    "hosts_per_slice": book.hosts_per_slice,
                    "ready_slices": book.ready_slices,
                    "peak_ready": book.peak_ready,
                    "preempted_total": book.preempted_total
                    + self._preempted_pending(book),
                    "inflight": [{
                        "request_id": r.request_id,
                        "tier": r.tier,
                        "slices": r.slices,
                        "chips_per_slice": r.chips_per_slice,
                        "requested_at": r.requested_at,
                        "eta": r.eta,
                    } for r in sorted(book.inflight.values(),
                                      key=lambda r: r.request_id)],
                    "stockout_until": dict(sorted(
                        book.stockout_until.items())),
                    "stockout_streak": dict(sorted(
                        book.stockout_streak.items())),
                }
        return {"variants": variants}

    def restore_state(self, state: dict) -> dict:
        """Rehydrate from :meth:`export_state` output (boot warm-start).
        Restored orders keep their ORIGINAL ETAs: one that wedged while
        the controller was down exceeds its credit window on the first
        post-boot tick and is expired-and-reordered — the safe direction
        (an unknown order may still land, in which case the fleet briefly
        over-provisions; it never plans against phantom credit). Returns
        restore counts for the warm-start report."""
        orders = stockouts = 0
        with self._mu:
            for variant in sorted(state.get("variants", {})):
                v = state["variants"][variant]
                book = self._book(variant)
                book.chips_per_slice = int(v.get("chips_per_slice", 0))
                book.hosts_per_slice = max(int(v.get("hosts_per_slice", 1)),
                                           1)
                book.ready_slices = int(v.get("ready_slices", 0))
                book.peak_ready = int(v.get("peak_ready", 0))
                book.preempted_total = int(v.get("preempted_total", 0))
                for r in v.get("inflight", []):
                    req = InFlightRequest(
                        request_id=str(r.get("request_id", "")),
                        variant=variant,
                        tier=str(r.get("tier", "")),
                        slices=int(r.get("slices", 0)),
                        chips_per_slice=int(r.get("chips_per_slice", 0)),
                        requested_at=float(r.get("requested_at", 0.0)),
                        eta=float(r.get("eta", 0.0)))
                    book.inflight[req.request_id] = req
                    orders += 1
                for tier, until in v.get("stockout_until", {}).items():
                    book.stockout_until[str(tier)] = float(until)
                    stockouts += 1
                for tier, streak in v.get("stockout_streak", {}).items():
                    book.stockout_streak[str(tier)] = int(streak)
        return {"orders": orders, "stockouts": stockouts}

    # --- observability ---

    def snapshot(self, now: float) -> list[dict]:
        """Sorted per-variant state for the trace stage + gauges."""
        out = []
        with self._mu:
            for variant in sorted(self._books):
                book = self._books[variant]
                ready = max(book.ready_slices - book.lost_slices, 0)
                provisioning = sum(
                    r.slices for r in book.inflight.values()
                    if now <= r.credit_expires())
                stocked = sorted(
                    t for t, until in book.stockout_until.items()
                    if until > now)
                out.append({
                    "variant": variant,
                    "chips_per_slice": book.chips_per_slice,
                    STATE_READY: ready,
                    STATE_PROVISIONING: provisioning,
                    STATE_PREEMPTED: book.lost_slices,
                    # Cumulative, including the not-yet-folded window so a
                    # same-tick trace/gauge read sees the loss immediately.
                    "preempted_total": book.preempted_total
                    + self._preempted_pending(book),
                    "tier_slices": dict(sorted(book.tier_slices.items())),
                    "stocked_out_tiers": stocked,
                })
        return out
