"""Elastic capacity plane: TPU slice provisioning, preemption resilience,
and reservation/spot-aware inventory (docs/design/capacity.md).

Sits between discovery and the solver: the :class:`CapacityLedger` tracks
every variant's slices through ``ready / provisioning(ETA) / preempted /
stocked_out``; the :class:`CapacityManager` turns post-analysis shortfalls
into deduped, backoff-guarded, circuit-broken provisioning requests against
a :class:`SliceProvisioner`; the limiter's pools become
``ready + provisioning-arriving-within-lead-time``.

Gated by ``WVA_CAPACITY`` (default on); off is byte-identical to the
pre-capacity decision plane.

Lazy init (PEP 562): discovery imports :mod:`wva_tpu.capacity.tiers` for
node tier classification, and an eager ledger import here would close a
cycle back through discovery.
"""

from wva_tpu.capacity.tiers import (  # noqa: F401 — leaf module, re-export
    DEFAULT_TIER_COST_WEIGHTS,
    DEFAULT_TIER_PREFERENCE,
    TIER_ON_DEMAND,
    TIER_RESERVATION,
    TIER_SPOT,
    parse_tier_preference,
    parse_tier_weights,
    tier_for_node_labels,
)

_LAZY = {
    "CapacityLedger": "wva_tpu.capacity.ledger",
    "CompletedRequest": "wva_tpu.capacity.ledger",
    "InFlightRequest": "wva_tpu.capacity.ledger",
    "STATE_PREEMPTED": "wva_tpu.capacity.ledger",
    "STATE_PROVISIONING": "wva_tpu.capacity.ledger",
    "STATE_READY": "wva_tpu.capacity.ledger",
    "STATE_STOCKED_OUT": "wva_tpu.capacity.ledger",
    "CapacityManager": "wva_tpu.capacity.manager",
    "OUTCOME_ACCEPTED": "wva_tpu.capacity.manager",
    "OUTCOME_DEDUPED": "wva_tpu.capacity.manager",
    "OUTCOME_FAILED": "wva_tpu.capacity.manager",
    "OUTCOME_QUOTA_DENIED": "wva_tpu.capacity.manager",
    "NullProvisioner": "wva_tpu.capacity.provisioner",
    "ProvisionResult": "wva_tpu.capacity.provisioner",
    "SliceProvisioner": "wva_tpu.capacity.provisioner",
}

__all__ = [
    "DEFAULT_TIER_COST_WEIGHTS",
    "DEFAULT_TIER_PREFERENCE",
    "TIER_ON_DEMAND",
    "TIER_RESERVATION",
    "TIER_SPOT",
    "parse_tier_preference",
    "parse_tier_weights",
    "tier_for_node_labels",
    *sorted(_LAZY),
]


def __getattr__(name: str):
    module = _LAZY.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)
