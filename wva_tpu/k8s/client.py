"""Kubernetes client abstraction.

``KubeClient`` is the narrow API surface the framework needs (get/list/create/
update/update-status/delete/scale + watch). Two implementations:

- :class:`FakeCluster` — in-memory, thread-safe object store with watch-event
  dispatch and a scale subresource; the analogue of controller-runtime's fake
  client + envtest used throughout the reference's test tiers (SURVEY.md §4),
  and the substrate of the kind-emulator-equivalent harness in
  ``wva_tpu.emulator``.
- a REST client against a real API server can implement the same interface
  (out-of-cluster use); engines and controllers depend only on this interface.
"""

from __future__ import annotations

import abc
import logging
import threading
import uuid
from dataclasses import dataclass
from typing import Any, Callable, Iterable

log = logging.getLogger(__name__)

from wva_tpu.api.v1alpha1 import VariantAutoscaling
from wva_tpu.k8s.objects import labels_match
from wva_tpu.utils.clock import SYSTEM_CLOCK, Clock
from wva_tpu.utils.freeze import freeze, read_view, shallow_thaw, thaw

# Watch event types.
ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

WatchHandler = Callable[[str, Any], None]


class NotFoundError(KeyError):
    def __init__(self, kind: str, namespace: str, name: str) -> None:
        self.kind, self.namespace, self.name = kind, namespace, name
        super().__init__(f"{kind} {namespace}/{name} not found")


class ConflictError(RuntimeError):
    pass


def _kind_of(obj: Any) -> str:
    kind = getattr(obj, "KIND", None) or getattr(obj, "kind", None)
    if not kind:
        raise TypeError(f"object {obj!r} has no kind")
    return kind




class KubeClient(abc.ABC):
    """The API surface engines/controllers/collectors depend on."""

    @abc.abstractmethod
    def get(self, kind: str, namespace: str, name: str) -> Any:
        """Return a READ-ONLY view (frozen shared object under the
        zero-copy plane; a deep copy with ``WVA_ZERO_COPY=off``); raises
        NotFoundError. Callers must ``objects.clone()`` before mutating."""

    @abc.abstractmethod
    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict[str, str] | None = None) -> list[Any]:
        """Read-only object views (see :meth:`get`), optionally namespace-
        and label-filtered."""

    @abc.abstractmethod
    def create(self, obj: Any) -> Any: ...

    @abc.abstractmethod
    def update(self, obj: Any) -> Any: ...

    @abc.abstractmethod
    def update_status(self, obj: Any) -> Any:
        """Write only the status subresource."""

    @abc.abstractmethod
    def delete(self, kind: str, namespace: str, name: str) -> None: ...

    @abc.abstractmethod
    def patch_scale(self, kind: str, namespace: str, name: str, replicas: int) -> None:
        """Scale-subresource write; works for any registered scalable kind."""

    @abc.abstractmethod
    def watch(self, kind: str, handler: WatchHandler) -> None:
        """Register a handler invoked on every ADDED/MODIFIED/DELETED of kind."""


@dataclass
class _Stored:
    obj: Any


class FakeCluster(KubeClient):
    """In-memory cluster. The store holds FROZEN objects and serves reads
    zero-copy: callers still can't mutate the store — a mutation attempt
    raises ``FrozenObjectError`` instead of silently diverging (stronger
    than an API server's copy semantics; docs/design/object-plane.md).
    Writers take a mutable view via ``objects.clone()`` first; store
    updates are copy-on-write with structural sharing (a status write
    shares the old spec/template subtrees)."""

    def __init__(self, clock: Clock | None = None) -> None:
        self._mu = threading.RLock()
        self._objs: dict[tuple[str, str, str], _Stored] = {}
        self._watchers: dict[str, list[WatchHandler]] = {}
        self._rv = 0
        self.clock = clock or SYSTEM_CLOCK
        # API-request accounting: (verb, kind) -> count, incremented on every
        # client call. Lets tests assert the engine's per-tick request budget
        # (O(kinds) LISTs, zero per-VA GETs) instead of trusting it.
        self._requests: dict[tuple[str, str], int] = {}

    # --- request accounting ---

    def _count(self, verb: str, kind: str) -> None:
        key = (verb, kind)
        self._requests[key] = self._requests.get(key, 0) + 1

    def request_counts(self) -> dict[tuple[str, str], int]:
        """Copy of (verb, kind) -> request count since the last reset."""
        with self._mu:
            return dict(self._requests)

    def reset_request_counts(self) -> None:
        with self._mu:
            self._requests.clear()

    # --- internals ---

    def _key(self, kind: str, namespace: str, name: str) -> tuple[str, str, str]:
        return (kind, namespace or "", name)

    def _next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    def _dispatch(self, event: str, obj: Any) -> None:
        # Handlers are isolated: one throwing must not break the writer or
        # starve later handlers (controller-runtime event-handler semantics).
        # Snapshot under the lock: unwatch() may mutate the list concurrently
        # (e.g. a fake-apiserver watch stream detaching mid-dispatch).
        with self._mu:
            handlers = list(self._watchers.get(_kind_of(obj), []))
        for handler in handlers:
            try:
                # One frozen instance shared by every handler AND the store
                # (zero copies); with WVA_ZERO_COPY=off each handler gets
                # its own mutable deep copy, the historical contract.
                handler(event, read_view(obj))
            except Exception:  # noqa: BLE001
                log.exception("watch handler failed for %s event on %s/%s",
                              event, obj.metadata.namespace, obj.metadata.name)

    # --- KubeClient ---

    def get(self, kind: str, namespace: str, name: str) -> Any:
        with self._mu:
            self._count("get", kind)
            stored = self._objs.get(self._key(kind, namespace, name))
            if stored is None:
                raise NotFoundError(kind, namespace or "", name)
            return read_view(stored.obj)

    def try_get(self, kind: str, namespace: str, name: str) -> Any | None:
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict[str, str] | None = None) -> list[Any]:
        with self._mu:
            self._count("list", kind)
            out = []
            for (k, ns, _), stored in sorted(self._objs.items()):
                if k != kind:
                    continue
                if namespace is not None and ns != (namespace or ""):
                    continue
                if not labels_match(label_selector, stored.obj.metadata.labels):
                    continue
                out.append(read_view(stored.obj))
            return out

    def create(self, obj: Any) -> Any:
        kind = _kind_of(obj)
        with self._mu:
            self._count("create", kind)
            key = self._key(kind, obj.metadata.namespace, obj.metadata.name)
            if key in self._objs:
                raise ConflictError(f"{kind} {key[1]}/{key[2]} already exists")
            stored = thaw(obj)  # detach from the caller, then freeze
            stored.metadata.uid = stored.metadata.uid or str(uuid.uuid4())
            stored.metadata.resource_version = self._next_rv()
            stored.metadata.generation = 1
            if not stored.metadata.creation_timestamp:
                stored.metadata.creation_timestamp = self.clock.now()
            freeze(stored)
            self._objs[key] = _Stored(stored)
        self._dispatch(ADDED, stored)
        return read_view(stored)

    def update(self, obj: Any) -> Any:
        kind = _kind_of(obj)
        with self._mu:
            self._count("update", kind)
            key = self._key(kind, obj.metadata.namespace, obj.metadata.name)
            cur = self._objs.get(key)
            if cur is None:
                raise NotFoundError(kind, key[1], key[2])
            # Optimistic concurrency: a caller presenting a stale
            # resourceVersion gets Conflict, as a real API server would.
            # rv "0"/"" means "not read from the store" and skips the check.
            presented_rv = obj.metadata.resource_version
            if presented_rv not in ("", "0") and presented_rv != cur.obj.metadata.resource_version:
                raise ConflictError(
                    f"{kind} {key[1]}/{key[2]}: resourceVersion {presented_rv} "
                    f"is stale (current {cur.obj.metadata.resource_version})"
                )
            stored = thaw(obj)
            stored.metadata.uid = cur.obj.metadata.uid
            stored.metadata.creation_timestamp = cur.obj.metadata.creation_timestamp
            # Status is a subresource: main-resource updates cannot touch
            # it. The stored status subtree is frozen, so the new revision
            # SHARES it (structural sharing — no copy).
            if hasattr(stored, "status"):
                stored.status = cur.obj.status
            stored.metadata.resource_version = self._next_rv()
            stored.metadata.generation = cur.obj.metadata.generation + 1
            freeze(stored)
            self._objs[key] = _Stored(stored)
        self._dispatch(MODIFIED, stored)
        return read_view(stored)

    def update_status(self, obj: Any) -> Any:
        kind = _kind_of(obj)
        with self._mu:
            self._count("update_status", kind)
            key = self._key(kind, obj.metadata.namespace, obj.metadata.name)
            cur = self._objs.get(key)
            if cur is None:
                raise NotFoundError(kind, key[1], key[2])
            # Same optimistic concurrency as update(): a status PUT carrying
            # a stale resourceVersion gets 409, as a real apiserver gives.
            # Without this, a writer working from an older read (e.g. the
            # engine's tick-start snapshot) silently clobbers status fields
            # a concurrent writer (the reconciler) set in between — and the
            # engine's conflict-refetch path could never fire in any
            # FakeCluster-backed world. rv ""/"0" skips the check, as above.
            presented_rv = obj.metadata.resource_version
            if presented_rv not in ("", "0") and \
                    presented_rv != cur.obj.metadata.resource_version:
                raise ConflictError(
                    f"{kind} {key[1]}/{key[2]}: resourceVersion "
                    f"{presented_rv} is stale (current "
                    f"{cur.obj.metadata.resource_version})"
                )
            # Copy-on-write with structural sharing: the new revision
            # swaps in the caller's status (detached) and a re-versioned
            # metadata while sharing every other frozen subtree.
            new = shallow_thaw(cur.obj)
            new.status = thaw(obj.status)
            meta = shallow_thaw(cur.obj.metadata)
            meta.resource_version = self._next_rv()
            new.metadata = meta
            cur.obj = freeze(new)
            stored = cur.obj
        self._dispatch(MODIFIED, stored)
        return read_view(stored)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        with self._mu:
            self._count("delete", kind)
            key = self._key(kind, namespace, name)
            stored = self._objs.pop(key, None)
            if stored is None:
                raise NotFoundError(kind, namespace or "", name)
        self._dispatch(DELETED, stored.obj)

    def patch_scale(self, kind: str, namespace: str, name: str, replicas: int) -> None:
        """Works against any stored kind carrying a ``replicas`` field
        (Deployment now; JobSet/LeaderWorkerSet adapters later) — mirrors the
        reference DirectActuator's unstructured scale-subresource handling
        (direct_actuator.go:54-121)."""
        with self._mu:
            self._count("patch_scale", kind)
            key = self._key(kind, namespace, name)
            cur = self._objs.get(key)
            if cur is None:
                raise NotFoundError(kind, namespace or "", name)
            if not hasattr(cur.obj, "replicas"):
                raise TypeError(f"{kind} has no scale subresource")
            if cur.obj.replicas == replicas:
                return
            new = shallow_thaw(cur.obj)
            new.replicas = replicas
            meta = shallow_thaw(cur.obj.metadata)
            meta.resource_version = self._next_rv()
            meta.generation += 1
            new.metadata = meta
            cur.obj = freeze(new)
            stored = cur.obj
        self._dispatch(MODIFIED, stored)

    def watch(self, kind: str, handler: WatchHandler) -> None:
        with self._mu:
            self._watchers.setdefault(kind, []).append(handler)

    def unwatch(self, kind: str, handler: WatchHandler) -> None:
        """Unregister a watch handler (no-op if absent) — lets transient
        consumers like the fake API server's watch streams detach."""
        with self._mu:
            handlers = self._watchers.get(kind, [])
            if handler in handlers:
                handlers.remove(handler)

    # --- conveniences for tests/emulator ---

    def apply(self, *objs: Any) -> None:
        for o in objs:
            try:
                self.create(o)
            except ConflictError:
                self.update(o)

    def variant_autoscalings(self, namespace: str | None = None) -> list[VariantAutoscaling]:
        return self.list(VariantAutoscaling.kind, namespace)


def list_all(client: KubeClient, kinds: Iterable[str]) -> dict[str, list[Any]]:
    return {k: client.list(k) for k in kinds}
