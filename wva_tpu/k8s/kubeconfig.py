"""API-server credential resolution: kubeconfig and in-cluster.

The reference delegates this to client-go's config loading
(``ctrl.GetConfigOrDie`` in ``cmd/main.go:266``); this module implements the
same two paths the controller actually uses:

- **in-cluster**: service-account token + CA from
  ``/var/run/secrets/kubernetes.io/serviceaccount`` and the
  ``KUBERNETES_SERVICE_HOST/PORT`` env (token re-read per request so
  projected-token rotation is picked up);
- **kubeconfig**: ``$KUBECONFIG`` or ``~/.kube/config`` — current-context
  cluster/user with bearer token, token file, client certs (inline base64
  ``*-data`` or file paths), cluster CA, and ``insecure-skip-tls-verify``.
"""

from __future__ import annotations

import base64
import os
import ssl
import tempfile
from dataclasses import dataclass, field

import yaml

SERVICEACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class CredentialError(RuntimeError):
    pass


@dataclass
class Credentials:
    """Resolved connection parameters for one API server."""

    server: str  # https://host:port
    token: str = ""
    token_file: str = ""  # re-read per request when set (rotation)
    ca_file: str = ""
    client_cert_file: str = ""
    client_key_file: str = ""
    insecure_skip_tls_verify: bool = False
    _tmp_files: list[str] = field(default_factory=list)

    def bearer_token(self) -> str:
        if self.token_file:
            try:
                with open(self.token_file, encoding="utf-8") as f:
                    return f.read().strip()
            except OSError:
                return self.token
        return self.token

    def cleanup(self) -> None:
        """Remove temp files holding decoded key/cert material (created by
        kubeconfig loading from inline ``*-data`` blobs). Call on shutdown —
        private keys must not linger in the temp dir."""
        for path in self._tmp_files:
            try:
                os.unlink(path)
            except OSError:
                pass
        self._tmp_files.clear()

    def ssl_context(self) -> ssl.SSLContext | None:
        if not self.server.startswith("https"):
            return None
        ctx = ssl.create_default_context()
        if self.insecure_skip_tls_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        elif self.ca_file:
            ctx.load_verify_locations(cafile=self.ca_file)
        if self.client_cert_file and self.client_key_file:
            ctx.load_cert_chain(self.client_cert_file, self.client_key_file)
        return ctx


def _materialize(data_b64: str, suffix: str, creds: Credentials) -> str:
    """Inline base64 kubeconfig blobs -> temp files (ssl needs file paths)."""
    fd, path = tempfile.mkstemp(suffix=suffix, prefix="wva-kube-")
    with os.fdopen(fd, "wb") as f:
        f.write(base64.b64decode(data_b64))
    creds._tmp_files.append(path)
    return path


def in_cluster_credentials() -> Credentials:
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    token_file = os.path.join(SERVICEACCOUNT_DIR, "token")
    ca_file = os.path.join(SERVICEACCOUNT_DIR, "ca.crt")
    if not host or not os.path.exists(token_file):
        raise CredentialError(
            "not running in-cluster (no KUBERNETES_SERVICE_HOST or "
            "serviceaccount token)")
    return Credentials(
        server=f"https://{host}:{port}",
        token_file=token_file,
        ca_file=ca_file if os.path.exists(ca_file) else "",
    )


def kubeconfig_credentials(path: str | None = None,
                           context: str | None = None) -> Credentials:
    path = path or os.environ.get("KUBECONFIG") or os.path.expanduser(
        "~/.kube/config")
    try:
        with open(path, encoding="utf-8") as f:
            cfg = yaml.safe_load(f) or {}
    except OSError as e:
        raise CredentialError(f"cannot read kubeconfig {path}: {e}") from e

    ctx_name = context or cfg.get("current-context") or ""
    ctx = next((c.get("context") or {} for c in cfg.get("contexts") or []
                if c.get("name") == ctx_name), None)
    if ctx is None:
        raise CredentialError(f"context {ctx_name!r} not found in {path}")
    cluster = next((c.get("cluster") or {} for c in cfg.get("clusters") or []
                    if c.get("name") == ctx.get("cluster")), None)
    user = next((u.get("user") or {} for u in cfg.get("users") or []
                 if u.get("name") == ctx.get("user")), {})
    if cluster is None or not cluster.get("server"):
        raise CredentialError(f"cluster for context {ctx_name!r} has no server")

    creds = Credentials(
        server=cluster["server"].rstrip("/"),
        insecure_skip_tls_verify=bool(cluster.get("insecure-skip-tls-verify")),
    )
    if cluster.get("certificate-authority"):
        creds.ca_file = cluster["certificate-authority"]
    elif cluster.get("certificate-authority-data"):
        creds.ca_file = _materialize(
            cluster["certificate-authority-data"], ".crt", creds)
    creds.token = user.get("token", "")
    if user.get("tokenFile"):
        creds.token_file = user["tokenFile"]
    if user.get("client-certificate"):
        creds.client_cert_file = user["client-certificate"]
    elif user.get("client-certificate-data"):
        creds.client_cert_file = _materialize(
            user["client-certificate-data"], ".crt", creds)
    if user.get("client-key"):
        creds.client_key_file = user["client-key"]
    elif user.get("client-key-data"):
        creds.client_key_file = _materialize(
            user["client-key-data"], ".key", creds)
    return creds


def resolve_credentials(kubeconfig: str | None = None,
                        context: str | None = None) -> Credentials:
    """client-go loading-rules order: explicit kubeconfig > $KUBECONFIG >
    in-cluster > ~/.kube/config."""
    if kubeconfig or os.environ.get("KUBECONFIG"):
        return kubeconfig_credentials(kubeconfig, context)
    try:
        return in_cluster_credentials()
    except CredentialError:
        return kubeconfig_credentials(None, context)
