"""An in-process HTTP API server speaking the Kubernetes REST subset the
framework uses, backed by a :class:`FakeCluster`.

This is the envtest analogue (reference test tier 3, SURVEY.md §4: a real
apiserver+etcd without kubelets): :class:`RestKubeClient` and the full
manager can be exercised over genuine HTTP — serialization, status/scale
subresources, optimistic-concurrency conflicts, label selectors, watch
streams — while the emulation harness still drives the world underneath
through the same FakeCluster.

Supported surface (what the controller actually calls):

- ``GET/POST`` collection paths, ``GET/PUT/DELETE`` item paths for every
  kind in :func:`wva_tpu.k8s.serde.known_kinds`, core and group APIs;
- ``?labelSelector=k=v,...`` on lists;
- ``?watch=true`` streaming (line-delimited JSON watch events, fed live
  from FakeCluster's dispatch);
- ``PUT .../status`` and ``GET/PATCH .../scale`` subresources;
- 404/409 error bodies shaped like metav1.Status;
- optional bearer-token auth.
"""

from __future__ import annotations

import json
import logging
import queue
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from wva_tpu.k8s import serde
from wva_tpu.k8s.client import ConflictError, FakeCluster, NotFoundError

log = logging.getLogger(__name__)

# Per-stream watch event buffer. When a slow consumer lets it overflow, the
# stream is CLOSED with a 410-style gap marker (see _serve_watch) — module
# constant so the slow-consumer regression test can shrink it.
WATCH_QUEUE_MAXSIZE = 1024

# Path shapes (namespaced and cluster-scoped, core and group APIs).
_PATH_RE = re.compile(
    r"^(?:/api/v1|/apis/(?P<group>[^/]+)/(?P<version>[^/]+))"
    r"(?:/namespaces/(?P<namespace>[^/]+))?"
    r"/(?P<plural>[^/]+)"
    r"(?:/(?P<name>[^/]+))?"
    r"(?:/(?P<subresource>status|scale))?$"
)


def _plural_index() -> dict[tuple[str, str], str]:
    """(group, plural) -> kind, for request routing."""
    idx: dict[tuple[str, str], str] = {}
    for kind in serde.known_kinds():
        gvr = serde.gvr_for(kind)
        idx[(gvr.group, gvr.plural)] = kind
        if kind == "InferencePool":  # both API groups route here
            idx[("inference.networking.k8s.io", "inferencepools")] = kind
            idx[("inference.networking.x-k8s.io", "inferencepools")] = kind
    return idx


class _Handler(BaseHTTPRequestHandler):
    server_version = "wva-fake-apiserver"
    protocol_version = "HTTP/1.1"

    # injected via subclassing in FakeAPIServer
    cluster: FakeCluster = None
    plurals: dict[tuple[str, str], str] = {}
    bearer_token: str = ""
    # TokenReview / SubjectAccessReview backing state.
    sa_tokens: dict[str, str] = {}  # token -> username
    metrics_readers: set = set()  # usernames allowed to GET /metrics
    # HTTP-level request accounting, shared with FakeAPIServer: (verb, kind)
    # -> count, where verb is "get"/"list"/"watch"/"put"/... — the wire-level
    # counterpart of FakeCluster's method counters, for tests asserting the
    # per-tick request budget over real sockets.
    http_requests: dict = {}
    _http_requests_mu = threading.Lock()
    # Optional emulator.faults.FaultInjector (chaos harness): consulted
    # before every verb (503/429/latency) and inside the watch stream
    # loop (unclean mid-flight drops).
    fault_injector = None

    def _count_http(self, verb: str, kind: str) -> None:
        with self._http_requests_mu:
            key = (verb, kind)
            self.http_requests[key] = self.http_requests.get(key, 0) + 1

    def _inject_fault(self, verb: str) -> bool:
        """Chaos hook: when a fault plan says this request fails, answer
        with the injected status (after any injected latency) and skip
        the real handler. Returns True when the request was consumed."""
        fi = self.fault_injector
        if fi is None:
            return False
        act = fi.api_fault(verb, self.path)
        if act is None:
            return False
        if act.latency_seconds > 0:
            time.sleep(act.latency_seconds)
        self._send_status_error(
            act.status,
            "TooManyRequests" if act.status == 429 else "ServiceUnavailable",
            "chaos fault injection")
        return True

    # --- helpers ---

    def _send_json(self, status: int, body: dict) -> None:
        payload = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _send_status_error(self, code: int, reason: str, message: str,
                           details: dict | None = None) -> None:
        body = {
            "kind": "Status", "apiVersion": "v1", "status": "Failure",
            "reason": reason, "message": message, "code": code}
        if details:
            # Real apiservers name the missing OBJECT in Status.details;
            # clients key the "object vs subresource missing" distinction on
            # it (RestKubeClient._is_object_not_found).
            body["details"] = details
        self._send_json(code, body)

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        if not length:
            return {}
        return json.loads(self.rfile.read(length))

    def _route(self):
        """Returns (kind, namespace, name, subresource, query) or None."""
        parsed = urlparse(self.path)
        m = _PATH_RE.match(parsed.path)
        if not m:
            self._send_status_error(404, "NotFound",
                                    f"unknown path {parsed.path}")
            return None
        group = m.group("group") or ""
        kind = self.plurals.get((group, m.group("plural")))
        if kind is None:
            self._send_status_error(
                404, "NotFound",
                f"resource {m.group('plural')} in group {group!r} not served")
            return None
        query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        return (kind, m.group("namespace") or "", m.group("name") or "",
                m.group("subresource") or "", query)

    def _get_resolving_scope(self, kind: str, ns: str, name: str):
        """Item lookup that tolerates the cluster-scoped path shape:
        ``/api/v1/nodes/<name>`` carries no namespace, but FakeCluster
        stores objects under whatever ``metadata.namespace`` they were
        created with (ObjectMeta defaults to "default"). Fall back to a
        by-name scan when the path gave no namespace."""
        try:
            return self.cluster.get(kind, ns, name)
        except NotFoundError:
            if ns:
                raise
            for obj in self.cluster.list(kind):
                if obj.metadata.name == name:
                    return obj
            raise

    def _authorized(self) -> bool:
        if not self.bearer_token:
            return True
        if self.headers.get("Authorization") == f"Bearer {self.bearer_token}":
            return True
        self._send_status_error(401, "Unauthorized", "invalid bearer token")
        return False

    @staticmethod
    def _label_selector(query: dict[str, str]) -> dict[str, str] | None:
        raw = query.get("labelSelector", "")
        if not raw:
            return None
        selector = {}
        for pair in raw.split(","):
            if "=" in pair:
                k, _, v = pair.partition("=")
                selector[k.lstrip("=")] = v
        return selector

    # --- verbs ---

    def do_GET(self) -> None:  # noqa: N802
        if self._inject_fault("get"):
            return
        if not self._authorized():
            return
        routed = self._route()
        if routed is None:
            return
        kind, ns, name, sub, query = routed
        self._count_http(
            "get" if name else
            ("watch" if query.get("watch") == "true" else "list"), kind)
        try:
            if name and sub == "scale":
                obj = self.cluster.get(kind, ns, name)
                replicas = getattr(obj, "replicas", 0) or 0
                self._send_json(200, {
                    "kind": "Scale", "apiVersion": "autoscaling/v1",
                    "metadata": {"name": name, "namespace": ns},
                    "spec": {"replicas": replicas},
                    "status": {"replicas": replicas}})
            elif name:
                self._send_json(200, serde.to_k8s(
                    self._get_resolving_scope(kind, ns, name)))
            elif query.get("watch") == "true":
                self._serve_watch(kind, query, ns)
            else:
                objs = self.cluster.list(kind, namespace=ns or None,
                                         label_selector=self._label_selector(query))
                gvr = serde.gvr_for(kind)
                self._send_json(200, {
                    "kind": f"{kind}List", "apiVersion": gvr.api_version,
                    "metadata": {"resourceVersion": str(self.cluster._rv)},
                    "items": [serde.to_k8s(o) for o in objs]})
        except NotFoundError as e:
            self._send_status_error(404, "NotFound", str(e),
                                    details={"name": name, "kind": kind})

    def do_POST(self) -> None:  # noqa: N802
        if self._inject_fault("post"):
            return
        if not self._authorized():
            return
        path = urlparse(self.path).path
        if path == "/apis/authentication.k8s.io/v1/tokenreviews":
            self._serve_token_review()
            return
        if path == "/apis/authorization.k8s.io/v1/subjectaccessreviews":
            self._serve_subject_access_review()
            return
        routed = self._route()
        if routed is None:
            return
        kind, ns, _, _, _ = routed
        self._count_http("post", kind)
        try:
            obj = serde.from_k8s(kind, self._read_body())
            if ns:
                obj.metadata.namespace = ns
            created = self.cluster.create(obj)
            self._send_json(201, serde.to_k8s(created))
        except ConflictError as e:
            self._send_status_error(409, "AlreadyExists", str(e))

    def do_PUT(self) -> None:  # noqa: N802
        if self._inject_fault("put"):
            return
        if not self._authorized():
            return
        routed = self._route()
        if routed is None:
            return
        kind, ns, name, sub, _ = routed
        self._count_http("put_status" if sub == "status" else "put", kind)
        try:
            obj = serde.from_k8s(kind, self._read_body())
            obj.metadata.namespace = ns or obj.metadata.namespace
            obj.metadata.name = name or obj.metadata.name
            if sub == "status":
                updated = self.cluster.update_status(obj)
            else:
                updated = self.cluster.update(obj)
            self._send_json(200, serde.to_k8s(updated))
        except NotFoundError as e:
            self._send_status_error(404, "NotFound", str(e),
                                    details={"name": name, "kind": kind})
        except ConflictError as e:
            self._send_status_error(409, "Conflict", str(e))

    def do_PATCH(self) -> None:  # noqa: N802
        if self._inject_fault("patch"):
            return
        if not self._authorized():
            return
        routed = self._route()
        if routed is None:
            return
        kind, ns, name, sub, _ = routed
        self._count_http("patch", kind)
        body = self._read_body()
        try:
            if sub == "scale":
                replicas = int((body.get("spec") or {}).get("replicas", 0))
                self.cluster.patch_scale(kind, ns, name, replicas)
                self._send_json(200, {
                    "kind": "Scale", "apiVersion": "autoscaling/v1",
                    "metadata": {"name": name, "namespace": ns},
                    "spec": {"replicas": replicas},
                    "status": {"replicas": replicas}})
            elif sub == "status" and name:
                # Merge-patch on the status subresource (kubelets PATCH
                # node status this way): overlay the patch's status onto
                # the stored object, re-decode, and write through
                # update_status so the MODIFIED watch event streams.
                current = self._get_resolving_scope(kind, ns, name)
                doc = serde.to_k8s(current)
                patched_status = {**(doc.get("status") or {}),
                                  **(body.get("status") or {})}
                doc["status"] = patched_status
                obj = serde.from_k8s(kind, doc)
                # Cluster-scoped docs carry no namespace: target the key
                # the object is actually stored under.
                obj.metadata.namespace = current.metadata.namespace
                obj.metadata.resource_version = \
                    current.metadata.resource_version
                updated = self.cluster.update_status(obj)
                self._send_json(200, serde.to_k8s(updated))
            else:
                self._send_status_error(
                    405, "MethodNotAllowed",
                    "only the scale and status subresources support "
                    "PATCH here")
        except NotFoundError as e:
            self._send_status_error(404, "NotFound", str(e),
                                    details={"name": name, "kind": kind})

    def do_DELETE(self) -> None:  # noqa: N802
        if self._inject_fault("delete"):
            return
        if not self._authorized():
            return
        routed = self._route()
        if routed is None:
            return
        kind, ns, name, _, _ = routed
        self._count_http("delete", kind)
        try:
            self.cluster.delete(kind, ns, name)
            self._send_json(200, {"kind": "Status", "apiVersion": "v1",
                                  "status": "Success"})
        except NotFoundError as e:
            self._send_status_error(404, "NotFound", str(e),
                                    details={"name": name, "kind": kind})

    # --- authn/authz review APIs (TokenReview / SubjectAccessReview) ---

    def _serve_token_review(self) -> None:
        """TokenReview: validate a ServiceAccount token against the server's
        configured token->username map (real apiservers do the same against
        their token authenticators)."""
        body = self._read_body()
        token = ((body.get("spec") or {}).get("token")) or ""
        username = self.sa_tokens.get(token)
        status = ({"authenticated": True,
                   "user": {"username": username,
                            "groups": ["system:serviceaccounts",
                                       "system:authenticated"]}}
                  if username is not None else {"authenticated": False})
        self._send_json(201, {"apiVersion": "authentication.k8s.io/v1",
                              "kind": "TokenReview", "status": status})

    def _serve_subject_access_review(self) -> None:
        """SubjectAccessReview for nonResourceURLs: allowed iff the username
        is in the server's metrics_readers set (standing in for RBAC)."""
        body = self._read_body()
        spec = body.get("spec") or {}
        user = spec.get("user", "")
        attrs = spec.get("nonResourceAttributes") or {}
        allowed = (user in self.metrics_readers
                   and attrs.get("verb") == "get"
                   and attrs.get("path") == "/metrics")
        self._send_json(201, {"apiVersion": "authorization.k8s.io/v1",
                              "kind": "SubjectAccessReview",
                              "status": {"allowed": allowed}})

    # --- watch streaming ---

    def _serve_watch(self, kind: str, query: dict[str, str],
                     namespace: str = "") -> None:
        """Stream watch events. Registers the handler FIRST, then replays
        every stored object whose resourceVersion is newer than the client's
        ``resourceVersion`` param as a synthetic ADDED — so mutations landing
        between the client's initial list and handler registration are not
        lost (deletes in that gap are still missed, like a real apiserver
        past its watch cache; delivery is at-least-once, which level-
        triggered reconcilers tolerate). Honors ``timeoutSeconds`` so each
        stream — and its thread + watcher registration — is bounded. A
        ``/namespaces/<ns>/...`` watch path only streams that namespace's
        events, like a real apiserver."""
        events: queue.Queue = queue.Queue(maxsize=WATCH_QUEUE_MAXSIZE)
        # Set when the event queue overflowed: the stream is now known to
        # have a GAP, and silently continuing would leave the client
        # confidently stale forever (its informer store would never learn
        # about the dropped mutation). Real apiservers surface exactly this
        # as 410 Gone when a watcher falls behind the watch cache; we emit
        # the same ERROR event so RestKubeClient's re-list path fires.
        overflowed = threading.Event()

        def on_event(event: str, obj) -> None:
            if namespace and (obj.metadata.namespace or "") != namespace:
                return
            try:
                events.put_nowait((event, obj))
            except queue.Full:
                overflowed.set()  # gap: the serve loop 410s the stream

        self.cluster.watch(kind, on_event)
        try:
            since_rv = int(query.get("resourceVersion") or 0)
        except ValueError:
            since_rv = 0
        try:
            timeout_s = float(query.get("timeoutSeconds") or 300)
        except ValueError:
            timeout_s = 300.0
        deadline = time.monotonic() + timeout_s
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def send_raw(payload: dict) -> None:
            line = json.dumps(payload).encode()
            chunk = f"{len(line) + 1:x}\r\n".encode() + line + b"\n\r\n"
            self.wfile.write(chunk)
            self.wfile.flush()

        def send(event: str, obj) -> None:
            send_raw({"type": event, "object": serde.to_k8s(obj)})

        def send_gone() -> None:
            # The 410-style gap marker (apiserver "too old resource
            # version" shape): clients raise ApiError(410) and re-list.
            send_raw({"type": "ERROR", "object": {
                "kind": "Status", "apiVersion": "v1", "status": "Failure",
                "reason": "Expired", "code": 410,
                "message": "watch event queue overflowed; resourceVersion "
                           "gap — re-list required"}})

        clean_end = False
        dropped = False
        try:
            if since_rv:
                for obj in self.cluster.list(kind, namespace=namespace or None):
                    try:
                        obj_rv = int(obj.metadata.resource_version)
                    except ValueError:
                        obj_rv = 0
                    if obj_rv > since_rv:
                        send("ADDED", obj)
            while time.monotonic() < deadline:
                if overflowed.is_set():
                    # Drain nothing further: events after the drop are
                    # beyond the gap anyway. Close with the gap marker so
                    # the client re-lists instead of trusting a stream
                    # with a hole in it.
                    send_gone()
                    break
                if (self.fault_injector is not None
                        and self.fault_injector.watch_drop_now()):
                    # Chaos: kill the stream UNCLEANLY (no chunked
                    # terminator) — the client must treat it as a gap and
                    # go through its re-list + backoff path, exactly like
                    # an apiserver crash mid-stream.
                    dropped = True
                    break
                try:
                    event, obj = events.get(timeout=0.2)
                except queue.Empty:
                    if getattr(self.server, "_shutting_down", False):
                        break
                    continue
                send(event, obj)
            clean_end = not dropped
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client went away
        finally:
            self.cluster.unwatch(kind, on_event)
            if clean_end:
                # Terminate the chunked stream so clients observe a clean
                # end-of-stream (and their reconnect backoff resets) instead
                # of a socket timeout.
                try:
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                except OSError:
                    pass
            self.close_connection = True

    def log_message(self, fmt: str, *args) -> None:
        log.debug("fake-apiserver: " + fmt, *args)


class FakeAPIServer:
    """Serve a FakeCluster over HTTP on 127.0.0.1:<port> (0 = ephemeral)."""

    def __init__(self, cluster: FakeCluster, port: int = 0,
                 bearer_token: str = "",
                 sa_tokens: dict[str, str] | None = None,
                 metrics_readers: set | None = None,
                 fault_injector=None) -> None:
        self.cluster = cluster
        self._http_requests: dict = {}
        handler = type("Handler", (_Handler,), {
            "cluster": cluster,
            "plurals": _plural_index(),
            "bearer_token": bearer_token,
            "sa_tokens": dict(sa_tokens or {}),
            "metrics_readers": set(metrics_readers or ()),
            "http_requests": self._http_requests,
            "_http_requests_mu": threading.Lock(),
            "fault_injector": fault_injector,
        })
        self._handler_cls = handler
        self._server = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self._server.daemon_threads = True
        self._server._shutting_down = False
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "FakeAPIServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="fake-apiserver", daemon=True)
        self._thread.start()
        return self

    def set_fault_injector(self, fi) -> None:
        """Install/replace the chaos FaultInjector live (tests toggle
        faults around specific requests)."""
        self._handler_cls.fault_injector = fi

    def request_counts(self) -> dict:
        """Copy of (verb, kind) -> HTTP request count since start/reset."""
        with self._handler_cls._http_requests_mu:
            return dict(self._http_requests)

    def reset_request_counts(self) -> None:
        with self._handler_cls._http_requests_mu:
            self._http_requests.clear()

    def shutdown(self) -> None:
        self._server._shutting_down = True
        self._server.shutdown()
        self._server.server_close()
