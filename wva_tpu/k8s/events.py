"""Event recorder (the reference uses controller-runtime's EventRecorder;
events surface operational state transitions to ``kubectl describe``).

Deduplicates like the API server's event aggregation: a repeat of the same
(object, reason, message) within the dedup window bumps ``count`` and
``lastTimestamp`` instead of creating a new Event object.
"""

from __future__ import annotations

import logging
import zlib

from wva_tpu.k8s.client import ConflictError, KubeClient, NotFoundError
from wva_tpu.k8s.objects import Event, ObjectMeta, clone
from wva_tpu.utils.clock import SYSTEM_CLOCK, Clock

log = logging.getLogger(__name__)

TYPE_NORMAL = "Normal"
TYPE_WARNING = "Warning"


DEFAULT_DEDUP_WINDOW_SECONDS = 3600.0


class EventRecorder:
    def __init__(self, client: KubeClient, component: str = "wva-tpu",
                 clock: Clock | None = None,
                 dedup_window_seconds: float = DEFAULT_DEDUP_WINDOW_SECONDS) -> None:
        self.client = client
        self.component = component
        self.clock = clock or SYSTEM_CLOCK
        self.dedup_window_seconds = dedup_window_seconds

    def event(self, obj, event_type: str, reason: str, message: str) -> None:
        """Record an event against ``obj`` (anything with KIND + metadata).
        Failures are logged, never raised — event emission must not break
        reconciliation."""
        try:
            self._record(obj, event_type, reason, message)
        except Exception as e:  # noqa: BLE001
            log.debug("event emission failed for %s/%s: %s",
                      obj.metadata.namespace, obj.metadata.name, e)

    def normal(self, obj, reason: str, message: str) -> None:
        self.event(obj, TYPE_NORMAL, reason, message)

    def warning(self, obj, reason: str, message: str) -> None:
        self.event(obj, TYPE_WARNING, reason, message)

    # Event messages are conventionally short; the apiserver rejects very
    # long ones (events.k8s.io caps note at 1 KiB for client-aggregated
    # events — BYTES, so multi-byte UTF-8 must be measured encoded).
    # Truncate rather than fail the record call.
    MAX_MESSAGE_BYTES = 1000

    def _record(self, obj, event_type: str, reason: str, message: str) -> None:
        encoded = message.encode("utf-8")
        if len(encoded) > self.MAX_MESSAGE_BYTES:
            # Cut on a codepoint boundary ("ignore" drops a trailing
            # partial sequence).
            message = encoded[:self.MAX_MESSAGE_BYTES - 3].decode(
                "utf-8", "ignore") + "..."
        now = self.clock.now()
        kind = getattr(obj, "KIND", getattr(obj, "kind", ""))
        # Distinct messages get distinct Event objects (message-hash name
        # suffix, like client-go's aggregation key): a sequence of different
        # transitions — e.g. ScalingDecision 1->2, 2->4, 4->8 — stays fully
        # visible in `kubectl describe`, while identical recurrences still
        # dedup into one event with a count.
        msg_hash = f"{zlib.crc32(message.encode('utf-8')):08x}"
        suffix = f".{self.component}.{reason.lower()}.{msg_hash}"
        # K8s object names cap at 253 chars; trim the subject's name, never
        # the disambiguating suffix (aggregation stays correct — two
        # long-named objects sharing a 200-char prefix is not a real case).
        name = obj.metadata.name[:253 - len(suffix)] + suffix
        namespace = obj.metadata.namespace
        existing: Event | None = self.client.try_get(Event.KIND, namespace, name)
        if existing is not None:
            existing = clone(existing)  # reads are frozen store views
            fresh_series = (
                existing.message != message
                or existing.type != event_type
                # Dedup window: a recurrence long after the last occurrence
                # starts a new series so firstTimestamp reflects the current
                # episode, like the API server's aggregation window.
                or now - existing.last_timestamp > self.dedup_window_seconds)
            if not fresh_series:
                existing.count += 1
                existing.last_timestamp = now
            else:
                # Same aggregation key, new content or new episode: restart.
                existing.type = event_type
                existing.message = message
                existing.count = 1
                existing.first_timestamp = now
                existing.last_timestamp = now
            try:
                self.client.update(existing)
                return
            except (ConflictError, NotFoundError):
                pass  # raced; fall through to create-or-overwrite
        fresh = Event(
            metadata=ObjectMeta(name=name, namespace=namespace),
            involved_kind=kind, involved_name=obj.metadata.name,
            involved_namespace=namespace,
            type=event_type, reason=reason, message=message,
            count=1, first_timestamp=now, last_timestamp=now)
        try:
            self.client.create(fresh)
        except ConflictError:
            cur = self.client.try_get(Event.KIND, namespace, name)
            if cur is not None:
                fresh.metadata.resource_version = cur.metadata.resource_version
                self.client.update(fresh)
