"""Watch-backed informer cache over a ``KubeClient``
(the controller-runtime informer/cache analogue; docs/design/informer.md).

The reference controller is level-triggered but informer-backed: its
manager keeps a watch-fed cache per kind, so steady-state reconciles never
LIST the apiserver. Our tick loop got to O(kinds) LISTs per tick
(``SnapshotKubeClient``), but still *paid* those LISTs every tick even when
the fleet was quiet. :class:`InformerKubeClient` removes the per-tick LIST
entirely:

- each covered kind is LISTed ONCE at start, then ADDED/MODIFIED/DELETED
  watch events keep the store fresh (FakeCluster dispatches synchronously;
  ``RestKubeClient`` feeds the same handlers from its list+watch streams
  with 410 re-list and synthetic-event gap recovery);
- ``list()`` of a covered kind is served from the store with zero API
  requests — the tick snapshot's "one LIST per kind" becomes an in-memory
  read;
- ``get()`` always delegates to the live client (targeted GETs are the
  conflict-refetch path's freshness anchor and must never be served stale)
  and WRITES THROUGH: the fresh object updates the store;
- our own mutations write through immediately (the returned object upserts
  the store), so read-your-writes holds even before the echo watch event
  arrives over a real stream;
- a periodic resync re-LISTs a kind when no list has run for
  ``resync_seconds`` — the backstop bounding staleness from any dropped
  event the transport failed to surface.

Staleness/fallback ladder (weakest to strongest):

1. watch events (zero cost, immediate);
2. own-write write-through + live-GET write-through (per mutation/GET);
3. the watch transport's own recovery — ``RestKubeClient`` re-lists on
   410 Gone / stream errors and synthesizes ADDED/DELETED events for the
   gap; the fake apiserver closes overflowed streams with a 410 gap
   marker so that path actually fires;
4. periodic full resync LIST (``resync_seconds``);
5. informer disabled: every tick LISTs, exactly the pre-informer shape.

Thread-safe. The store holds FROZEN objects (``utils.freeze``): events,
reads and snapshot fills share ONE instance with zero copies — mutation
attempts raise instead of corrupting the store, and writers detach via
``objects.clone()`` (docs/design/object-plane.md).
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable

from wva_tpu.k8s.client import (
    ADDED,
    DELETED,
    KubeClient,
    NotFoundError,
    _kind_of,
)
from wva_tpu.k8s.objects import labels_match
from wva_tpu.utils.clock import SYSTEM_CLOCK, Clock
from wva_tpu.utils.freeze import frozen_copy, read_view

log = logging.getLogger(__name__)

# Kinds the control plane reads per tick. Pod rides along for the dirty-set
# fingerprint (pod churn must dirty its model without a per-tick Pod LIST).
# Node feeds slice discovery (the limiter's per-tick inventory refresh) and
# the capacity ledger's preemption detection — a node deletion / NotReady /
# cordon flip must mark the backing slice lost and nudge a re-solve without
# waiting out the poll interval.
DEFAULT_INFORMER_KINDS = (
    "VariantAutoscaling", "Deployment", "LeaderWorkerSet", "Pod", "Node")

# Cluster-scoped kinds: their objects carry no namespace, so a
# namespace-scoped informer still covers them cluster-wide (a controller
# watching one namespace still needs the whole node inventory).
CLUSTER_SCOPED_KINDS = frozenset({"Node"})

# Re-LIST a kind when no list has run for this long — the backstop bounding
# staleness from dropped events the transport never surfaced. Same design
# point as controller-runtime's resync, tightened because our decisions act
# on replica counts. The engine drives this from its tick (no timer thread).
DEFAULT_RESYNC_SECONDS = 600.0

# (kind, event, obj) -> None; registered via add_nudge_listener and invoked
# on MATERIAL changes only (see _material_change).
NudgeListener = Callable[[str, str, Any], None]

# Kinds whose targeted GETs are ALSO served from the store (store hit;
# misses fall through live). VariantAutoscaling is deliberately excluded:
# VA GETs anchor resourceVersion-guarded status writes (conflict-refetch),
# and serving those from a store that can lag a real watch stream by
# milliseconds would turn every recovered 409 into another 409.
GET_FROM_STORE_KINDS = frozenset({"Pod", "Deployment", "LeaderWorkerSet"})


class InformerKubeClient(KubeClient):
    """Watch-backed read-through cache wrapping a live ``KubeClient``."""

    # SnapshotKubeClient/engine key on this to know per-tick LISTs are free
    # (and that the small-fleet targeted-GET economy no longer applies).
    lists_are_local = True

    def __init__(self, client: KubeClient,
                 kinds: tuple[str, ...] = DEFAULT_INFORMER_KINDS,
                 namespace: str | None = None,
                 clock: Clock | None = None,
                 resync_seconds: float = DEFAULT_RESYNC_SECONDS) -> None:
        self.client = client
        self.kinds = tuple(kinds)
        # Namespace scope of the informer LISTs (None = cluster-wide) — the
        # controller's watch namespace. Out-of-scope reads delegate.
        self.namespace = namespace or None
        self.clock = clock or SYSTEM_CLOCK
        self.resync_seconds = resync_seconds
        self._mu = threading.Lock()
        self._store: dict[str, dict[tuple[str, str], Any]] = {}
        self._synced: set[str] = set()
        self._last_list: dict[str, float] = {}
        self._last_event: dict[str, float] = {}
        # Kinds whose (re)LIST is in flight buffer their events instead of
        # applying them: a wholesale store replacement must not overwrite
        # state that changed while the LIST response was on the wire, and
        # pre-sync events (watch registers BEFORE the initial list) must
        # not be lost. Buffered events replay on top of the fresh list
        # (last-writer-wins; level-triggered consumers tolerate the
        # at-least-once ordering).
        self._buffering: set[str] = set()
        self._buffer: dict[str, list[tuple[str, Any]]] = {}
        self._nudge_listeners: list[NudgeListener] = []
        # Per-namespace pod-set epoch (versioned fingerprint plane,
        # docs/design/informer.md §versioned-fingerprints): bumped on
        # Pod ADDED/DELETED, on material MODIFIED (labels / phase /
        # readiness / IP — the shape the engine's fingerprint consumes),
        # and wholesale on a Pod (re)LIST. An unchanged epoch proves the
        # namespace's pod shapes did not move, so the engine skips its
        # per-model pod walk entirely on quiet ticks.
        self._pod_epochs: dict[str, int] = {}
        self._pod_epoch_counter = 0
        self._started = False

    # --- lifecycle ---

    def start(self) -> "InformerKubeClient":
        """Register watch handlers FIRST, then seed each kind with one LIST
        (watch-first ordering closes the created-mid-setup window; upserts
        are idempotent so double delivery is harmless)."""
        if self._started:
            return self
        self._started = True
        for kind in self.kinds:
            with self._mu:
                self._buffering.add(kind)
                self._buffer[kind] = []
            self.client.watch(kind, self._handler_for(kind))
            self._list_kind(kind)
        return self

    def _handler_for(self, kind: str):
        def on_event(event: str, obj: Any) -> None:
            self._on_event(kind, event, obj)
        return on_event

    def _list_kind(self, kind: str) -> None:
        listed = self.client.list(
            kind, namespace=None if kind in CLUSTER_SCOPED_KINDS
            else self.namespace)
        now = self.clock.now()
        with self._mu:
            store = {
                (o.metadata.namespace or "", o.metadata.name):
                    frozen_copy(o)
                for o in listed}
            # Replay events buffered while the LIST was in flight on top
            # of the fresh snapshot — dropping them would leave the store
            # stale until the NEXT resync for anything that changed
            # mid-list.
            for event, obj in self._buffer.pop(kind, []):
                key = (obj.metadata.namespace or "", obj.metadata.name)
                if event == DELETED:
                    store.pop(key, None)
                else:
                    store[key] = obj
            if kind == "Pod":
                # A wholesale replacement may have changed any namespace's
                # pod set: bump every namespace seen before OR after
                # (conservative over-dirtying; a re-LIST is rare).
                prev = self._store.get(kind, {})
                for ns in {k[0] for k in prev} | {k[0] for k in store}:
                    self._bump_pod_epoch_locked(ns)
            self._buffering.discard(kind)
            self._store[kind] = store
            self._synced.add(kind)
            self._last_list[kind] = now

    def resync_if_stale(self) -> list[str]:
        """Re-LIST kinds whose last list is older than ``resync_seconds``;
        returns the kinds refreshed. Driven from the engine tick so a
        simulated clock advances it deterministically (no timer thread).

        A FAILED re-LIST (apiserver storm) must not fail the caller's
        tick, and — crucially — must not leave the kind wedged in
        buffering mode: buffered events are replayed onto the EXISTING
        store so the watch stream keeps the informer as fresh as it can
        be while the list path is down, and the next tick retries the
        list. Without the replay, one failed resync froze the store until
        the next SUCCESSFUL list even though live events kept arriving —
        exactly the silent-staleness failure the input-health plane
        exists to classify."""
        if not self._started or self.resync_seconds <= 0:
            return []
        now = self.clock.now()
        stale = [k for k in self.kinds
                 if now - self._last_list.get(k, 0.0) > self.resync_seconds]
        refreshed = []
        for kind in stale:
            with self._mu:
                self._buffering.add(kind)
                self._buffer.setdefault(kind, [])
            try:
                self._list_kind(kind)
                refreshed.append(kind)
            except Exception as e:  # noqa: BLE001 — a storm-failed list
                # degrades to watch-fed staleness, never a failed tick.
                log.warning("informer resync LIST failed for %s "
                            "(retrying next tick): %s", kind, e)
                self._abort_buffering(kind)
        return refreshed

    def _abort_buffering(self, kind: str) -> None:
        """A (re)LIST failed: exit buffering mode by applying the held
        events to the CURRENT store (the same application path _on_event
        uses), so the watch stream keeps the store converging while the
        list path is down. Unlike successful-list replay — where the list
        itself is the freshness signal — NO other signal exists here, so
        material buffered events must still fire the nudge listeners
        (executor wake-ups, the capacity plane's Node feed)."""
        replayed: list[tuple[str, Any, Any]] = []
        with self._mu:
            self._buffering.discard(kind)
            buffered = self._buffer.pop(kind, [])
            if kind not in self._synced:
                return  # initial list never succeeded: nothing to apply to
            for event, obj in buffered:
                prev = self._apply_event_locked(kind, event, obj)
                replayed.append((event, prev, obj))
            listeners = list(self._nudge_listeners)
        if not listeners:
            return
        for event, prev, obj in replayed:
            if _material_change(kind, event, prev, obj):
                for fn in listeners:
                    try:
                        fn(kind, event, obj)
                    except Exception:  # noqa: BLE001 — listener isolation
                        log.exception("informer nudge listener failed for "
                                      "%s %s (buffered replay)", event, kind)

    # --- event ingestion ---

    def _on_event(self, kind: str, event: str, obj: Any) -> None:
        ns = obj.metadata.namespace or ""
        if self.namespace is not None and ns != self.namespace \
                and kind not in CLUSTER_SCOPED_KINDS:
            return
        # ONE frozen instance serves the buffer, the store, the nudge
        # listeners and (on FakeCluster) every other watch handler — the
        # old path deep-copied the dispatched object into the buffer AND
        # again into the store. Dispatchers already hand out frozen
        # objects under the zero-copy plane, so this is usually free;
        # an unfrozen object (REST stream with the plane off) is detached
        # once here.
        obj = frozen_copy(obj)
        key = (ns, obj.metadata.name)
        with self._mu:
            if kind in self._buffering:
                # A (re)LIST is in flight: hold the event for replay on
                # top of the fresh snapshot (no nudge — the list itself is
                # the freshness signal, and at startup no listeners exist
                # yet).
                self._buffer.setdefault(kind, []).append((event, obj))
                self._last_event[kind] = self.clock.now()
                return
            if kind not in self._synced:
                return  # not started for this kind
            prev = self._apply_event_locked(kind, event, obj)
            self._last_event[kind] = self.clock.now()
            listeners = list(self._nudge_listeners)
        if listeners and _material_change(kind, event, prev, obj):
            for fn in listeners:
                try:
                    fn(kind, event, obj)
                except Exception:  # noqa: BLE001 — listener isolation
                    log.exception("informer nudge listener failed for "
                                  "%s %s", event, kind)

    def _apply_event_locked(self, kind: str, event: str, obj: Any) -> Any:
        """Apply one watch event to the store (caller holds the lock),
        bumping the namespace's pod-set epoch on material pod changes.
        The SINGLE application path shared by live events (_on_event) and
        failed-resync buffered-event replay (_abort_buffering) — the two
        must never drift. Returns the previously stored object."""
        ns = obj.metadata.namespace or ""
        key = (ns, obj.metadata.name)
        store = self._store.setdefault(kind, {})
        prev = store.get(key)
        if event == DELETED:
            store.pop(key, None)
            if kind == "Pod" and prev is not None:
                self._bump_pod_epoch_locked(ns)
        else:
            store[key] = obj
            if kind == "Pod" and (
                    prev is None
                    or _pod_fp_shape(prev) != _pod_fp_shape(obj)):
                self._bump_pod_epoch_locked(ns)
        return prev

    def _upsert(self, obj: Any) -> None:
        kind = _kind_of(obj)
        if kind not in self.kinds:
            return
        ns = obj.metadata.namespace or ""
        if self.namespace is not None and ns != self.namespace \
                and kind not in CLUSTER_SCOPED_KINDS:
            return
        stored = frozen_copy(obj)
        with self._mu:
            if kind in self._synced:
                store = self._store.setdefault(kind, {})
                prev = store.get((ns, obj.metadata.name))
                store[(ns, obj.metadata.name)] = stored
                if kind == "Pod" and (
                        prev is None
                        or _pod_fp_shape(prev) != _pod_fp_shape(stored)):
                    self._bump_pod_epoch_locked(ns)

    def _discard(self, kind: str, namespace: str, name: str) -> None:
        with self._mu:
            store = self._store.get(kind)
            if store is not None:
                prev = store.pop((namespace or "", name), None)
                if kind == "Pod" and prev is not None:
                    self._bump_pod_epoch_locked(namespace or "")

    # --- pod-set epochs (versioned fingerprint plane) ---

    def _bump_pod_epoch_locked(self, namespace: str) -> None:
        self._pod_epoch_counter += 1
        self._pod_epochs[namespace or ""] = self._pod_epoch_counter

    def pod_epoch(self, namespace: str) -> int:
        """Monotonic epoch of the namespace's pod SET AND SHAPES (labels,
        phase, readiness, IP — exactly what the engine's fingerprint
        consumes). Equal reads bracket a window with no material pod
        change, letting the engine reuse its memoized per-model pod
        components without listing or matching anything."""
        with self._mu:
            return self._pod_epochs.get(namespace or "", 0)

    # --- nudges (event-driven wake-ups) ---

    def add_nudge_listener(self, fn: NudgeListener) -> None:
        """Invoke ``fn(kind, event, obj)`` on MATERIAL watch changes
        (spec-level edits, scale/readiness moves, creates/deletes) — the
        engines' executors hook their ``trigger()`` here so a wake no
        longer waits out the poll interval. Status-only writes (the
        engine's own heartbeats) never nudge: generation does not move."""
        with self._mu:
            self._nudge_listeners.append(fn)

    # --- KubeClient read surface ---

    def _covers(self, kind: str, namespace: str | None) -> bool:
        if kind not in self.kinds:
            return False
        with self._mu:
            if kind not in self._synced:
                return False
        if kind in CLUSTER_SCOPED_KINDS:
            # Always LISTed cluster-wide, so any scope is served.
            return True
        return self.namespace is None or namespace == self.namespace

    def get(self, kind: str, namespace: str, name: str) -> Any:
        """Store-served for scale-target/pod kinds (the scale-from-zero
        loop GETs every VA's target each 100ms poll — those reads are what
        the informer exists to absorb); LIVE for everything else, notably
        VariantAutoscaling, whose GETs anchor rv-guarded status writes.
        Live results write through to the store."""
        if kind in GET_FROM_STORE_KINDS and self._covers(kind, namespace):
            with self._mu:
                obj = self._store.get(kind, {}).get((namespace or "", name))
            if obj is not None:
                return read_view(obj)
            # Store miss falls through live: a just-created object's watch
            # event may still be in flight on a real stream.
        try:
            obj = self.client.get(kind, namespace, name)
        except NotFoundError:
            if kind in self.kinds:
                self._discard(kind, namespace, name)
            raise
        self._upsert(obj)
        return obj

    def try_get(self, kind: str, namespace: str, name: str) -> Any | None:
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict[str, str] | None = None) -> list[Any]:
        # A cluster-wide list from a namespace-scoped informer (or any
        # out-of-scope/unsynced kind) must delegate: the store only holds
        # the watch namespace.
        if not self._covers(kind, namespace):
            return self.client.list(kind, namespace=namespace,
                                    label_selector=label_selector)
        with self._mu:
            items = sorted(self._store.get(kind, {}).items())
        out = []
        for (ns, _), obj in items:
            if namespace is not None and ns != (namespace or ""):
                continue
            if not labels_match(label_selector, obj.metadata.labels):
                continue
            out.append(read_view(obj))
        return out

    def raw_snapshot(self, kind: str,
                     namespace: str | None = None
                     ) -> dict[tuple[str, str], Any] | None:
        """Zero-copy view of a covered kind's store: a shallow dict copy
        whose VALUES are the live store objects. For callers that layer
        their own copy-on-read isolation (``SnapshotKubeClient`` deep-
        copies every read out of its tick cache) — the per-object deepcopy
        ``list()`` pays would be pure waste there. Callers must NEVER
        mutate the returned objects. None when the kind/scope is not
        covered (caller falls back to ``list()``)."""
        if not self._covers(kind, namespace):
            return None
        with self._mu:
            store = self._store.get(kind, {})
            if namespace is None:
                return dict(store)
            ns = namespace or ""
            return {key: obj for key, obj in store.items() if key[0] == ns}

    # --- KubeClient write surface (delegate + write through) ---

    def create(self, obj: Any) -> Any:
        created = self.client.create(obj)
        self._upsert(created)
        return created

    def update(self, obj: Any) -> Any:
        updated = self.client.update(obj)
        self._upsert(updated)
        return updated

    def update_status(self, obj: Any) -> Any:
        updated = self.client.update_status(obj)
        self._upsert(updated)
        return updated

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self.client.delete(kind, namespace, name)
        if kind in self.kinds:
            self._discard(kind, namespace, name)

    def patch_scale(self, kind: str, namespace: str, name: str,
                    replicas: int) -> None:
        # No object comes back from a scale patch. FakeCluster's
        # synchronous MODIFIED dispatch updates the store during the call;
        # over REST the echo event lands within stream latency. EVICT the
        # entry after delegating so a read-your-write GET in that window
        # (the tick snapshot's follow-up, the 100ms scale-from-zero poll)
        # misses the store and falls through LIVE instead of being served
        # the pre-patch replica count — the live result writes back
        # through get(). (On FakeCluster the eviction is immediately
        # repaired by the next read; the synchronous event fired before
        # the evict, so nothing fresh is lost either way.)
        self.client.patch_scale(kind, namespace, name, replicas)
        if kind in self.kinds:
            self._discard(kind, namespace, name)

    def watch(self, kind: str, handler) -> None:
        self.client.watch(kind, handler)

    # --- observability ---

    def stats(self) -> dict[str, dict[str, float]]:
        """Per-kind freshness for the ``wva_informer_*`` gauges:
        ``{kind: {age_seconds, objects, synced}}`` where ``age_seconds``
        is time since the last event OR list, whichever is newer."""
        now = self.clock.now()
        out: dict[str, dict[str, float]] = {}
        with self._mu:
            for kind in self.kinds:
                freshest = max(self._last_list.get(kind, 0.0),
                               self._last_event.get(kind, 0.0))
                out[kind] = {
                    "age_seconds": (now - freshest) if freshest else -1.0,
                    "objects": float(len(self._store.get(kind, {}))),
                    "synced": 1.0 if kind in self._synced else 0.0,
                }
        return out


def _pod_fp_shape(o: Any) -> tuple:
    """The pod surface the engine's dirty-set fingerprint consumes —
    labels (selector matching) + phase/readiness/IP. Broader than the
    nudge-worthy shape in ``_material_change`` (label edits can move a
    pod in or out of a model's selector without being wake-worthy)."""
    st = getattr(o, "status", None)
    return (o.metadata.labels, getattr(st, "phase", ""),
            getattr(st, "ready", False), getattr(st, "pod_ip", ""))


def _material_change(kind: str, event: str, prev: Any, obj: Any) -> bool:
    """Is this event worth an immediate engine wake? Creates/deletes and
    spec-level edits are; the engine's own status writes are not (status
    subresource PUTs never move ``metadata.generation``), which is what
    keeps the nudge loop from re-triggering itself off its own writes."""
    if event in (ADDED, DELETED) or prev is None:
        return True
    if obj.metadata.generation != prev.metadata.generation:
        return True
    if kind == "Pod":
        ps, pp = getattr(obj, "status", None), getattr(prev, "status", None)
        if ps is not None and pp is not None:
            return (ps.phase, ps.ready, ps.pod_ip) != \
                (pp.phase, pp.ready, pp.pod_ip)
        return False
    if kind in ("Deployment", "LeaderWorkerSet"):
        def shape(o):
            st = getattr(o, "status", None)
            return (getattr(o, "replicas", None),
                    getattr(st, "replicas", None),
                    getattr(st, "ready_replicas", None))
        return shape(obj) != shape(prev)
    if kind == "Node":
        # Readiness / cordon flips change schedulable slice inventory: a
        # spot preemption (NotReady then DELETED) or a cordon must trigger
        # an immediate re-solve, not wait out the poll interval. Allocatable
        # moves (chips appearing on a provisioning node) count too.
        def node_shape(o):
            st = getattr(o, "status", None)
            return (getattr(o, "ready", None),
                    getattr(o, "unschedulable", None),
                    getattr(st, "allocatable", None))
        return node_shape(obj) != node_shape(prev)
    return False
