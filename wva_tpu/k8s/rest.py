"""REST ``KubeClient`` against a real Kubernetes API server.

The production counterpart of :class:`wva_tpu.k8s.client.FakeCluster` — the
same narrow interface the engines/controllers depend on, spoken over the
API server's REST surface the way the reference's controller-runtime client
does (``cmd/main.go:266-303``, ``internal/utils/utils.go:69-123``):

- typed CRUD via the serde codecs (GET/POST/PUT/DELETE on GVR paths);
- status subresource writes (``PUT .../status``);
- scale subresource patches (``PATCH .../scale`` with merge-patch), kind-
  agnostic like the reference DirectActuator (``direct_actuator.go:54-121``);
- optimistic concurrency: HTTP 409 -> :class:`ConflictError`, 404 ->
  :class:`NotFoundError` (the two signals the retry/backoff wrappers and the
  leader elector key on);
- list+watch streams per kind with automatic re-list on 410 Gone and
  exponential backoff reconnects, dispatching ADDED/MODIFIED/DELETED to
  registered handlers exactly like FakeCluster's in-process dispatch.

Everything is stdlib (urllib + ssl + threads): no client library to vendor.
"""

from __future__ import annotations

import json
import logging
import random
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any

from wva_tpu.k8s import serde
from wva_tpu.utils.freeze import freeze, read_view
from wva_tpu.k8s.client import (
    ADDED,
    DELETED,
    MODIFIED,
    ConflictError,
    KubeClient,
    NotFoundError,
    WatchHandler,
    _kind_of,
)
from wva_tpu.k8s.kubeconfig import Credentials

log = logging.getLogger(__name__)

DEFAULT_TIMEOUT = 10.0
WATCH_SERVER_TIMEOUT = 300  # server closes the stream; we reconnect
WATCH_SOCKET_TIMEOUT = 330.0
WATCH_BACKOFF_INITIAL = 1.0
WATCH_BACKOFF_MAX = 30.0
# A stream must have lived at least this long before its clean end resets
# the reconnect backoff: an apiserver accepting connections and instantly
# closing them cleanly (crash-looping behind a load balancer) must not be
# hammered at the initial rate forever.
WATCH_MIN_HEALTHY_STREAM_SECONDS = 1.0

# Dedicated RNG for reconnect jitter (tests can seed/patch it without
# touching the global random state).
_jitter_rng = random.Random()


def _jittered(delay: float) -> float:
    """Full jitter over [delay/2, delay]: when an apiserver restart drops
    every kind's watch stream at once, the reconnect (and re-list) herd
    must not land in the same instant — client-go's watch backoff jitters
    for the same reason."""
    return delay * (0.5 + 0.5 * _jitter_rng.random())


class ApiError(RuntimeError):
    def __init__(self, status: int, message: str) -> None:
        self.status = status
        self.detail = message
        super().__init__(f"API server returned {status}: {message}")

    def status_object(self) -> dict:
        """The parsed Kubernetes Status body, {} when not JSON."""
        try:
            d = json.loads(self.detail)
            return d if isinstance(d, dict) else {}
        except (TypeError, ValueError):
            return {}


class RestKubeClient(KubeClient):
    def __init__(self, credentials: Credentials,
                 timeout: float = DEFAULT_TIMEOUT,
                 watch_namespace: str = "") -> None:
        self.credentials = credentials
        self.timeout = timeout
        # When set, list+watch streams for namespaced kinds hit
        # /namespaces/<ns>/... paths, so a namespace-scoped install needs
        # only Role-level RBAC and never sees (or reconciles) other
        # namespaces' objects — matching the reference manager's cache
        # scoping (cmd/main.go cache options for WATCH_NAMESPACE).
        self.watch_namespace = watch_namespace
        self._ssl = credentials.ssl_context()
        self._mu = threading.Lock()
        self._watchers: dict[str, list[WatchHandler]] = {}
        self._watch_threads: dict[str, threading.Thread] = {}
        # Per-kind objects already surfaced through list/watch, keyed by
        # (namespace, name) — the diff base for synthetic events after a
        # forced re-list (410 Gone / unexpected stream error).
        self._known: dict[str, dict[tuple[str, str], Any]] = {}
        self._stop = threading.Event()

    # --- HTTP plumbing ---

    def _request(self, method: str, path: str,
                 query: dict[str, str] | None = None,
                 body: dict | None = None,
                 content_type: str = "application/json",
                 timeout: float | None = None,
                 stream: bool = False):
        url = self.credentials.server + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        token = self.credentials.bearer_token()
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        # client-go convention: a configured timeout of 0 means NO timeout
        # (urlopen's timeout=0 would mean non-blocking sockets and fail
        # every request instantly).
        effective = timeout or self.timeout
        try:
            resp = urllib.request.urlopen(
                req, timeout=effective if effective > 0 else None,
                context=self._ssl)
        except urllib.error.HTTPError as e:
            detail = ""
            try:
                detail = e.read().decode(errors="replace")[:2048]
            except Exception:  # noqa: BLE001
                pass
            raise ApiError(e.code, detail or e.reason) from None
        if stream:
            return resp
        with resp:
            payload = resp.read()
        return json.loads(payload) if payload else {}

    @staticmethod
    def _map_error(e: ApiError, kind: str, namespace: str, name: str):
        if e.status == 404:
            return NotFoundError(kind, namespace or "", name)
        if e.status == 409:
            return ConflictError(str(e))
        return e

    def _obj_path(self, kind: str, namespace: str, name: str | None = None,
                  subresource: str | None = None) -> str:
        return serde.gvr_for(kind).path(namespace, name, subresource)

    # --- KubeClient ---

    def get(self, kind: str, namespace: str, name: str) -> Any:
        try:
            d = self._request("GET", self._obj_path(kind, namespace, name))
        except ApiError as e:
            raise self._map_error(e, kind, namespace, name) from None
        return read_view(freeze(serde.from_k8s(kind, d)))

    def try_get(self, kind: str, namespace: str, name: str) -> Any | None:
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict[str, str] | None = None) -> list[Any]:
        query: dict[str, str] = {}
        if label_selector:
            query["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in sorted(label_selector.items()))
        try:
            d = self._request("GET", self._obj_path(kind, namespace or ""),
                              query=query or None)
        except ApiError as e:
            raise self._map_error(e, kind, namespace or "", "") from None
        return [read_view(freeze(serde.from_k8s(kind, item)))
                for item in d.get("items") or []]

    def create(self, obj: Any) -> Any:
        kind = _kind_of(obj)
        ns, name = obj.metadata.namespace, obj.metadata.name
        try:
            d = self._request("POST", self._obj_path(kind, ns),
                              body=serde.to_k8s(obj))
        except ApiError as e:
            raise self._map_error(e, kind, ns, name) from None
        return read_view(freeze(serde.from_k8s(kind, d)))

    def update(self, obj: Any) -> Any:
        kind = _kind_of(obj)
        ns, name = obj.metadata.namespace, obj.metadata.name
        try:
            d = self._request("PUT", self._obj_path(kind, ns, name),
                              body=serde.to_k8s(obj))
        except ApiError as e:
            raise self._map_error(e, kind, ns, name) from None
        return read_view(freeze(serde.from_k8s(kind, d)))

    def update_status(self, obj: Any) -> Any:
        kind = _kind_of(obj)
        ns, name = obj.metadata.namespace, obj.metadata.name
        try:
            d = self._request("PUT", self._obj_path(kind, ns, name, "status"),
                              body=serde.to_k8s(obj))
        except ApiError as e:
            if e.status == 404 and not self._is_object_not_found(e, name):
                # 404 without the object's name in the Status details means
                # the KIND has no registered status subresource (the object
                # itself exists): fall back to a full update (FakeCluster
                # allows status writes generically). Keyed on the structured
                # Status body, not the human-readable message, which varies
                # across API-server versions/locales.
                return self.update(obj)
            raise self._map_error(e, kind, ns, name) from None
        return read_view(freeze(serde.from_k8s(kind, d)))

    def raw_post(self, path: str, body: dict) -> dict:
        """POST an arbitrary API payload (TokenReview/SubjectAccessReview —
        ephemeral review kinds that never round-trip through serde)."""
        return self._request("POST", path, body=body)

    @staticmethod
    def _is_object_not_found(e: ApiError, name: str) -> bool:
        """True when a 404's Status body names the missing OBJECT (vs a
        missing subresource/route, whose Status carries no object name)."""
        details = e.status_object().get("details") or {}
        return details.get("name") == name

    def delete(self, kind: str, namespace: str, name: str) -> None:
        try:
            self._request("DELETE", self._obj_path(kind, namespace, name))
        except ApiError as e:
            raise self._map_error(e, kind, namespace, name) from None

    def patch_scale(self, kind: str, namespace: str, name: str,
                    replicas: int) -> None:
        """Merge-patch the scale subresource — works for any scalable kind
        (Deployment, LeaderWorkerSet, CRDs with scale), matching the
        reference's unstructured scale handling."""
        try:
            self._request(
                "PATCH", self._obj_path(kind, namespace, name, "scale"),
                body={"spec": {"replicas": int(replicas)}},
                content_type="application/merge-patch+json")
        except ApiError as e:
            raise self._map_error(e, kind, namespace, name) from None

    # --- watch ---

    def _watch_scopes(self, kind: str) -> list[str]:
        """Namespaces whose streams a kind needs. Unscoped: one cluster-wide
        stream. Scoped: the watch namespace — plus the controller (system)
        namespace for ConfigMap/ServiceMonitor, whose global config and
        scrape-contract objects live there (the reference's scoped cache
        includes the controller namespace for the same reason)."""
        if not self.watch_namespace:
            return [""]
        scopes = [self.watch_namespace]
        if kind in ("ConfigMap", "ServiceMonitor"):
            from wva_tpu.config.helpers import system_namespace

            sysns = system_namespace()
            if sysns and sysns not in scopes:
                scopes.append(sysns)
        return scopes

    def watch(self, kind: str, handler: WatchHandler) -> None:
        """Register a handler and ensure list+watch stream(s) run for kind
        (one per watch scope — see _watch_scopes). Handler semantics match
        FakeCluster: invoked on every ADDED/MODIFIED/DELETED after
        registration; exceptions are isolated."""
        with self._mu:
            self._watchers.setdefault(kind, []).append(handler)
            for ns in self._watch_scopes(kind):
                key = f"{kind}/{ns}"
                if key not in self._watch_threads:
                    t = threading.Thread(target=self._watch_loop,
                                         args=(kind, ns),
                                         name=f"watch-{key}", daemon=True)
                    self._watch_threads[key] = t
                    t.start()

    def stop(self) -> None:
        self._stop.set()
        self.credentials.cleanup()

    def _dispatch(self, kind: str, event: str, obj: Any) -> None:
        with self._mu:
            handlers = list(self._watchers.get(kind, []))
        for handler in handlers:
            try:
                handler(event, obj)
            except Exception:  # noqa: BLE001 — handler isolation
                log.exception("watch handler failed for %s %s", event, kind)

    @staticmethod
    def _obj_key(obj: Any) -> tuple[str, str]:
        return (obj.metadata.namespace or "", obj.metadata.name)

    def _watch_loop(self, kind: str, namespace: str = "") -> None:
        backoff = WATCH_BACKOFF_INITIAL
        rv = ""
        first_list = True
        while not self._stop.is_set():
            try:
                if not rv:
                    rv = self._list_for_watch(kind, namespace,
                                              synthesize=not first_list)
                    first_list = False
                stream_started = time.monotonic()
                rv = self._stream_watch(kind, namespace, rv)
                # Reset backoff only after a HEALTHY stream: one that ended
                # CLEANLY (the server's `0\r\n\r\n` chunked terminator —
                # premature closes raise and fall through to the handlers
                # below) after actually living for a while. An
                # instant-clean-close loop keeps growing backoff.
                if (time.monotonic() - stream_started
                        >= WATCH_MIN_HEALTHY_STREAM_SECONDS):
                    backoff = WATCH_BACKOFF_INITIAL
                else:
                    self._stop.wait(_jittered(backoff))
                    backoff = min(backoff * 2, WATCH_BACKOFF_MAX)
            except ApiError as e:
                if e.status == 410:  # Gone: resourceVersion too old
                    rv = ""
                    continue
                log.warning("watch %s failed (%s); retrying in %.0fs",
                            kind, e, backoff)
                self._stop.wait(_jittered(backoff))
                backoff = min(backoff * 2, WATCH_BACKOFF_MAX)
            except (OSError, socket.timeout, json.JSONDecodeError) as e:
                # Unclean stream end / server outage: reconnect with
                # jittered growing backoff — an apiserver restart drops
                # every client's streams at once, and the reconnect herd
                # must spread out (thundering herd).
                log.debug("watch %s stream ended (%s); reconnecting in %.0fs",
                          kind, e, backoff)
                self._stop.wait(_jittered(backoff))
                backoff = min(backoff * 2, WATCH_BACKOFF_MAX)
            except Exception:  # noqa: BLE001 — one bad event (e.g. a decode
                # error from a malformed object another client wrote) must
                # never permanently kill the kind's only watch thread.
                log.exception("watch %s hit an unexpected error; re-listing "
                              "in %.0fs", kind, backoff)
                rv = ""
                self._stop.wait(_jittered(backoff))
                backoff = min(backoff * 2, WATCH_BACKOFF_MAX)

    def _list_for_watch(self, kind: str, namespace: str,
                        synthesize: bool) -> str:
        """(Re)list to obtain a consistent resourceVersion.

        The INITIAL list dispatches nothing (FakeCluster watch semantics:
        only subsequent changes dispatch). A FORCED re-list (410 Gone /
        unexpected error) covers an event gap, so level-triggered handlers
        get synthetic events to converge: ADDED for every listed object and
        DELETED for known objects that vanished during the gap — without
        this, an object whose terminal mutation fell in the gap would stay
        stale forever."""
        d = self._request("GET", self._obj_path(kind, namespace))
        rv = (d.get("metadata") or {}).get("resourceVersion", "")
        objs = [freeze(serde.from_k8s(kind, item))
                for item in d.get("items") or []]
        current = {self._obj_key(o): o for o in objs}
        scope_key = f"{kind}/{namespace}"
        with self._mu:
            previous = self._known.get(scope_key, {})
            self._known[scope_key] = current
        if synthesize:
            for obj in current.values():
                self._dispatch(kind, ADDED, obj)
            for key, obj in previous.items():
                if key not in current:
                    self._dispatch(kind, DELETED, obj)
        return rv

    def _stream_watch(self, kind: str, namespace: str, rv: str) -> str:
        """One watch stream; returns the last seen resourceVersion."""
        resp = self._request(
            "GET", self._obj_path(kind, namespace),
            query={"watch": "true", "resourceVersion": rv,
                   "allowWatchBookmarks": "true",
                   "timeoutSeconds": str(WATCH_SERVER_TIMEOUT)},
            timeout=WATCH_SOCKET_TIMEOUT, stream=True)
        with resp:
            for raw in resp:
                if self._stop.is_set():
                    break
                raw = raw.strip()
                if not raw:
                    continue
                evt = json.loads(raw)
                etype, item = evt.get("type"), evt.get("object") or {}
                new_rv = (item.get("metadata") or {}).get("resourceVersion")
                if new_rv:
                    rv = new_rv
                if etype == "BOOKMARK":
                    continue
                if etype == "ERROR":
                    code = (item.get("code") or 0)
                    raise ApiError(int(code) or 500, item.get("message", ""))
                if etype in (ADDED, MODIFIED, DELETED):
                    obj = freeze(serde.from_k8s(kind, item))
                    with self._mu:
                        known = self._known.setdefault(f"{kind}/{namespace}",
                                                       {})
                        if etype == DELETED:
                            known.pop(self._obj_key(obj), None)
                        else:
                            known[self._obj_key(obj)] = obj
                    self._dispatch(kind, etype, obj)
        return rv
