"""Typed models of the Kubernetes kinds the autoscaler touches.

The reference uses client-go's generated types; this framework defines the
narrow slices it actually consumes. All types share ``ObjectMeta`` from the
CRD module and serialize to K8s-shaped dicts where needed.

All kinds are :class:`~wva_tpu.utils.freeze.Freezable`: object stores
(``FakeCluster``/``InformerKubeClient``/``SnapshotKubeClient``) freeze them
and serve reads zero-copy — read results are SHARED and immutable. Callers
that mutate must take an explicit copy first via :func:`clone` (the
copy-on-write builder step; docs/design/object-plane.md).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, TypeVar

from wva_tpu.api.v1alpha1 import ObjectMeta
from wva_tpu.utils.freeze import (  # noqa: F401 — re-exported protocol
    Freezable,
    FrozenObjectError,
    freeze,
    is_frozen,
    object_version,
    read_view,
    thaw,
)

_T = TypeVar("_T")


@dataclass
class ResourceRequirements(Freezable):
    """Container resources; values are stringly-typed K8s quantities for
    extended resources (``google.com/tpu: "8"``)."""

    requests: dict[str, str] = field(default_factory=dict)
    limits: dict[str, str] = field(default_factory=dict)


@dataclass
class Container(Freezable):
    name: str = ""
    image: str = ""
    command: list[str] = field(default_factory=list)
    args: list[str] = field(default_factory=list)
    env: dict[str, str] = field(default_factory=dict)
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    ports: dict[str, int] = field(default_factory=dict)  # name -> containerPort


@dataclass
class PodTemplateSpec(Freezable):
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    containers: list[Container] = field(default_factory=list)
    init_containers: list[Container] = field(default_factory=list)
    node_selector: dict[str, str] = field(default_factory=dict)


@dataclass
class DeploymentStatus(Freezable):
    replicas: int = 0
    ready_replicas: int = 0
    updated_replicas: int = 0


@dataclass
class Deployment(Freezable):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    replicas: int | None = 1  # spec.replicas; None = K8s default (1)
    selector: dict[str, str] = field(default_factory=dict)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    status: DeploymentStatus = field(default_factory=DeploymentStatus)

    KIND = "Deployment"
    API_VERSION = "apps/v1"

    def desired_replicas(self) -> int:
        """spec.replicas with the K8s nil-default of 1
        (reference utils/variant.go GetDesiredReplicas)."""
        return 1 if self.replicas is None else self.replicas


@dataclass
class Lease(Freezable):
    """coordination.k8s.io/v1 Lease for leader election."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder_identity: str = ""
    lease_duration_seconds: int = 60
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_transitions: int = 0

    KIND = "Lease"
    API_VERSION = "coordination.k8s.io/v1"


@dataclass
class Event(Freezable):
    """core/v1 Event (the recorder surface the reference gets from
    controller-runtime's EventRecorder)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_kind: str = ""
    involved_name: str = ""
    involved_namespace: str = ""
    type: str = "Normal"  # Normal | Warning
    reason: str = ""
    message: str = ""
    count: int = 1
    first_timestamp: float = 0.0
    last_timestamp: float = 0.0

    KIND = "Event"
    API_VERSION = "v1"


@dataclass
class LeaderWorkerSetStatus(Freezable):
    """Group-level status: a "replica" is a whole leader+workers group."""

    replicas: int = 0  # groups that exist
    ready_replicas: int = 0  # groups whose every pod is Ready


@dataclass
class LeaderWorkerSet(Freezable):
    """Multi-host slice scale target (leaderworkerset.x-k8s.io/v1).

    One replica = one group of ``size`` pods (one per slice host) that are
    scheduled and become ready together — the scale unit for multi-host TPU
    slices (SURVEY.md section 7 "hard parts" #2: a v5e-16 replica is 2 hosts x
    8 chips scaling as one). The scale subresource operates on group count,
    so the DirectActuator and HPA paths work unchanged.
    """

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    replicas: int | None = 1  # spec.replicas = number of groups
    size: int = 1  # pods (hosts) per group
    selector: dict[str, str] = field(default_factory=dict)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    status: LeaderWorkerSetStatus = field(default_factory=LeaderWorkerSetStatus)

    KIND = "LeaderWorkerSet"
    API_VERSION = "leaderworkerset.x-k8s.io/v1"

    def desired_replicas(self) -> int:
        return 1 if self.replicas is None else self.replicas


@dataclass
class PodStatus(Freezable):
    phase: str = "Pending"  # Pending | Running | Succeeded | Failed
    ready: bool = False
    pod_ip: str = ""


@dataclass
class Pod(Freezable):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    node_name: str = ""
    status: PodStatus = field(default_factory=PodStatus)

    KIND = "Pod"
    API_VERSION = "v1"

    def is_ready(self) -> bool:
        return self.status.phase == "Running" and self.status.ready


@dataclass
class NodeStatus(Freezable):
    capacity: dict[str, str] = field(default_factory=dict)
    allocatable: dict[str, str] = field(default_factory=dict)


@dataclass
class Node(Freezable):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    status: NodeStatus = field(default_factory=NodeStatus)
    ready: bool = True
    # spec.unschedulable (kubectl cordon): the node still exists and its
    # pods keep running, but nothing new schedules there — a cordoned host
    # makes its whole slice unusable for NEW replicas, so discovery must
    # not count it as schedulable capacity.
    unschedulable: bool = False

    KIND = "Node"
    API_VERSION = "v1"

    def schedulable(self) -> bool:
        return self.ready and not self.unschedulable


@dataclass
class ConfigMap(Freezable):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: dict[str, str] = field(default_factory=dict)

    KIND = "ConfigMap"
    API_VERSION = "v1"


@dataclass
class Secret(Freezable):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: dict[str, str] = field(default_factory=dict)  # values pre-decoded

    KIND = "Secret"
    API_VERSION = "v1"


@dataclass
class Service(Freezable):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: dict[str, str] = field(default_factory=dict)
    ports: dict[str, int] = field(default_factory=dict)  # name -> port

    KIND = "Service"
    API_VERSION = "v1"


@dataclass
class Namespace(Freezable):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)

    KIND = "Namespace"
    API_VERSION = "v1"


@dataclass
class ExtensionRef(Freezable):
    """InferencePool's endpoint-picker (EPP) service reference."""

    service_name: str = ""
    port_number: int = 9090


@dataclass
class InferencePool(Freezable):
    """Gateway-API inference-extension InferencePool (v1 / v1alpha2 shapes
    both converge here; reference internal/utils/pool/pool.go:54-100)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: dict[str, str] = field(default_factory=dict)
    target_port_number: int = 8000
    extension_ref: ExtensionRef = field(default_factory=ExtensionRef)

    KIND = "InferencePool"
    API_VERSION = "inference.networking.k8s.io/v1"


@dataclass
class ServiceMonitor(Freezable):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: dict[str, str] = field(default_factory=dict)

    KIND = "ServiceMonitor"
    API_VERSION = "monitoring.coreos.com/v1"


def parse_quantity(raw: str) -> int:
    """Parse a K8s integer resource quantity ("8", "8.0"); 0 on bad input.
    Single source of truth for extended-resource counts (google.com/tpu)."""
    try:
        return int(float(raw))
    except (TypeError, ValueError):
        return 0


def labels_match(selector: dict[str, str] | None, labels: dict[str, str]) -> bool:
    """K8s equality-selector semantics: every selector entry must match; an
    empty/None selector matches everything. The single source of truth for
    label matching (client listing, pool selection)."""
    if not selector:
        return True
    return all(labels.get(k) == v for k, v in selector.items())


def clone(obj: _T) -> _T:
    """The sanctioned mutable copy of a K8s object — the copy-on-write
    builder step: ``mutable = clone(frozen_read); mutate(mutable);
    client.update*(mutable)``. Works on frozen and unfrozen objects alike
    (a frozen input thaws fully: nested FrozenDict/FrozenList revert to
    dict/list). Hot-path modules are lint-forbidden from calling
    ``copy.deepcopy`` directly (tests/test_object_plane.py) so every
    K8s-object copy is visible to the ``wva_tick_object_copies`` counter.
    """
    return thaw(obj)


def deep_copy(obj):
    return clone(obj)


# kind string -> class, for generic client paths
KINDS: dict[str, Any] = {
    c.KIND: c
    for c in (
        Deployment, Pod, Node, ConfigMap, Secret, Service, Namespace,
        InferencePool, ServiceMonitor,
    )
}
