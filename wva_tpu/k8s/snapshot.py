"""Tick-scoped cluster-state snapshot.

At fleet scale one engine tick used to cost O(VariantAutoscalings) API
round trips: the active-VA filter, ``_prepare_model_data``,
``build_variant_states``, ``_apply_decisions`` and the safety net each
issued a targeted GET per VA / scale target (Autopilot sizes its control
loop the opposite way — one shared snapshot of cluster state evaluated by
every job in the pass; AIBrix batches collection across models for the
same reason). :class:`SnapshotKubeClient` makes the snapshot pattern a
drop-in: it implements the ``KubeClient`` read surface over a per-kind
cache filled by ONE LIST on the first read of that kind, so a tick costs
O(kinds touched) list requests no matter how many VAs exist.

Semantics:

- **Reads** (``get``/``list``/``try_get``) of a snapshotted kind are served
  from the cache ZERO-COPY: cached objects are frozen
  (``utils.freeze``), so callers cannot mutate the snapshot — a write
  site takes an explicit mutable copy via ``objects.clone()`` first
  (docs/design/object-plane.md). ``WVA_ZERO_COPY=off`` restores the
  historical deep-copy-on-read contract.
- **Writes** (``create``/``update``/``update_status``/``delete``/
  ``patch_scale``) delegate to the wrapped client untouched — and update or
  invalidate the cached copy so a later read within the same tick sees the
  write (read-your-writes within the tick).
- ``refresh`` issues a TARGETED GET against the wrapped client, updating
  the cache — for callers that must revalidate ONE object mid-tick
  instead of discarding the whole snapshot. (Status-write conflict
  recovery itself lives in
  ``utils.variant.update_va_status_with_conflict_refetch``, which GETs
  via the LIVE client; the engine's snapshot is read-mostly.)
- Everything else (unknown kinds, ``watch``) delegates directly.

A snapshot is built for ONE tick and discarded; it is not a cache with an
invalidation problem. Within the tick the view is frozen — exactly the
consistency the decision loop wants, since a half-tick mix of old and new
cluster state is what produces contradictory per-model decisions.

Thread-safe: the engine's per-model analysis workers read it concurrently.
"""

from __future__ import annotations

import threading
from typing import Any

from wva_tpu.k8s.client import KubeClient, NotFoundError, _kind_of
from wva_tpu.k8s.objects import labels_match
from wva_tpu.utils.freeze import frozen_copy, read_view

# Kinds the saturation tick reads per-VA; one LIST each per tick, lazily —
# a fleet with no LeaderWorkerSet targets never lists them.
DEFAULT_SNAPSHOT_KINDS = ("VariantAutoscaling", "Deployment", "LeaderWorkerSet")

# Cache sentinel for memoized NotFound in targeted-GET mode.
_NOT_FOUND = object()


class SnapshotKubeClient(KubeClient):
    """Read-through, tick-scoped snapshot over a ``KubeClient``."""

    def __init__(self, client: KubeClient,
                 namespace: str | None = None,
                 kinds: tuple[str, ...] = DEFAULT_SNAPSHOT_KINDS) -> None:
        self.client = client
        # Namespace scope of the snapshot LISTs (None = cluster-wide), the
        # engine's watch-namespace. Reads outside this scope delegate.
        self.namespace = namespace or None
        self._kinds = frozenset(kinds)
        self._mu = threading.Lock()
        # kind -> {(namespace, name): obj-or-_NOT_FOUND}. A kind in
        # _complete was fully LISTed (reads never touch the wrapped
        # client); otherwise the cache memoizes targeted GETs — including
        # misses — for kinds in targeted mode.
        self._cache: dict[str, dict[tuple[str, str], Any]] = {}
        self._complete: set[str] = set()
        # Kinds preferring memoized targeted GETs over one LIST: on a
        # shared cluster where WVA tracks a handful of VAs among thousands
        # of foreign Deployments, LISTing the whole kind each tick costs
        # more than a few targeted GETs (still memoized, so the tick's 3-5
        # reads of each target cost ONE request). The engine flips this on
        # for scale-target kinds when the fleet is small.
        self._targeted: set[str] = set()
        # Per-kind fetch locks: the snapshot LIST is a network call and must
        # not run under _mu (it would serialize every concurrent worker's
        # reads of ALL kinds behind one slow LIST); the per-kind lock still
        # guarantees exactly one LIST per kind.
        self._fetch_locks: dict[str, threading.Lock] = {}

    # --- cache internals ---

    def _covers(self, kind: str, namespace: str | None) -> bool:
        if kind not in self._kinds:
            return False
        return self.namespace is None or namespace == self.namespace

    def use_targeted_gets(self, kinds: tuple[str, ...]) -> None:
        """Switch (not-yet-LISTed) kinds to memoized targeted GETs. Small
        fleets on shared clusters call this before any target reads: a
        handful of VAs does not justify LISTing a kind whose cluster-wide
        population may be thousands of foreign objects."""
        with self._mu:
            for kind in kinds:
                if kind not in self._complete:
                    self._targeted.add(kind)

    def _kind_cache(self, kind: str) -> dict[tuple[str, str], Any]:
        """The kind's cached objects, fully LISTed once on first need. The
        LIST runs outside ``_mu`` (under a per-kind lock) so concurrent
        readers of other — or already-cached — kinds never block behind
        it."""
        with self._mu:
            if kind in self._complete:
                return self._cache[kind]
            fetch_lock = self._fetch_locks.setdefault(kind, threading.Lock())
        with fetch_lock:
            with self._mu:
                if kind in self._complete:
                    return self._cache[kind]  # raced: another worker LISTed
            # Informer-backed client: take its store view zero-copy — the
            # store's objects are frozen, so sharing them is safe by
            # construction (write-through REPLACES entries, never mutates
            # them in place).
            raw = getattr(self.client, "raw_snapshot", None)
            cached = raw(kind, self.namespace) if raw is not None else None
            if cached is None:
                listed = self.client.list(kind, namespace=self.namespace)
                cached = {
                    (o.metadata.namespace or "", o.metadata.name):
                        frozen_copy(o)
                    for o in listed
                }
            with self._mu:
                self._cache[kind] = cached
                self._complete.add(kind)
                self._targeted.discard(kind)
            return cached

    # --- KubeClient read surface ---

    def get(self, kind: str, namespace: str, name: str) -> Any:
        if not self._covers(kind, namespace):
            return self.client.get(kind, namespace, name)
        with self._mu:
            targeted = kind in self._targeted and kind not in self._complete
        if targeted:
            return self._memoized_get(kind, namespace, name)
        cached = self._kind_cache(kind)
        with self._mu:
            obj = cached.get((namespace or "", name))
        if obj is None or obj is _NOT_FOUND:
            raise NotFoundError(kind, namespace or "", name)
        return read_view(obj)

    def _memoized_get(self, kind: str, namespace: str, name: str) -> Any:
        """Targeted-GET mode: one wrapped-client GET per object per tick,
        memoized (misses too — repeated lookups of a deleted target must
        not re-GET every stage)."""
        key = (namespace or "", name)
        with self._mu:
            obj = self._cache.get(kind, {}).get(key)
        if obj is None:
            try:
                obj = self.client.get(kind, namespace, name)
            except NotFoundError:
                obj = _NOT_FOUND
            else:
                obj = frozen_copy(obj)
            with self._mu:
                self._cache.setdefault(kind, {})[key] = obj
        if obj is _NOT_FOUND:
            raise NotFoundError(kind, namespace or "", name)
        return read_view(obj)

    def try_get(self, kind: str, namespace: str, name: str) -> Any | None:
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict[str, str] | None = None) -> list[Any]:
        in_scope = kind in self._kinds and (
            self.namespace is None or namespace == self.namespace)
        if not in_scope:
            return self.client.list(kind, namespace=namespace,
                                    label_selector=label_selector)
        cached = self._kind_cache(kind)
        with self._mu:
            objs = sorted(cached.items())
        out = []
        for (ns, _), obj in objs:
            if namespace is not None and ns != (namespace or ""):
                continue
            if not labels_match(label_selector, obj.metadata.labels):
                continue
            out.append(read_view(obj))
        return out

    def refresh(self, kind: str, namespace: str, name: str) -> Any:
        """Targeted GET against the wrapped client, updating the cache:
        revalidates ONE object mid-tick without discarding the snapshot.
        Raises NotFoundError (and drops the cached copy) when the object
        is gone."""
        try:
            obj = self.client.get(kind, namespace, name)
        except NotFoundError:
            with self._mu:
                cached = self._cache.get(kind)
                if cached is not None:
                    cached.pop((namespace or "", name), None)
            raise
        self._store(kind, obj)
        return read_view(frozen_copy(obj))

    def _store(self, kind: str, obj: Any) -> None:
        if kind not in self._kinds:
            return
        stored = frozen_copy(obj)
        with self._mu:
            self._cache.setdefault(kind, {})[
                (obj.metadata.namespace or "", obj.metadata.name)] = stored

    def _evict(self, kind: str, namespace: str, name: str) -> None:
        with self._mu:
            cached = self._cache.get(kind)
            if cached is not None:
                cached.pop((namespace or "", name), None)

    # --- KubeClient write surface (delegate + keep the tick view current) ---

    def create(self, obj: Any) -> Any:
        created = self.client.create(obj)
        self._store(_kind_of(created), created)
        return created

    def update(self, obj: Any) -> Any:
        updated = self.client.update(obj)
        self._store(_kind_of(updated), updated)
        return updated

    def update_status(self, obj: Any) -> Any:
        updated = self.client.update_status(obj)
        self._store(_kind_of(updated), updated)
        return updated

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self.client.delete(kind, namespace, name)
        self._evict(kind, namespace, name)

    def patch_scale(self, kind: str, namespace: str, name: str,
                    replicas: int) -> None:
        self.client.patch_scale(kind, namespace, name, replicas)
        if kind not in self._kinds:
            return
        # Refresh the cached copy rather than evict: evicting from a fully
        # LISTed kind would make every later same-tick read of this
        # still-existing object 404 (read-your-writes contract). One
        # targeted GET per scale patch, proportional to actuations.
        try:
            self._store(kind, self.client.get(kind, namespace, name))
        except NotFoundError:
            self._evict(kind, namespace, name)

    def watch(self, kind: str, handler) -> None:
        self.client.watch(kind, handler)

    # --- observability ---

    def covers_kind(self, kind: str) -> bool:
        """Whether reads of ``kind`` are snapshot-served (the engine's
        fingerprint only hashes a pod set when the tick can read Pods for
        free — i.e. the snapshot covers them, informer-backed)."""
        return kind in self._kinds

    def kinds_listed(self) -> list[str]:
        """Kinds whose full snapshot LIST has run (for tests/metrics);
        targeted-GET-mode kinds with memoized entries are not listed."""
        with self._mu:
            return sorted(self._complete)
