"""Kubernetes wire-format codecs for the framework's typed objects.

The reference gets (de)serialization from client-go's generated types; here
each kind the framework touches has an explicit ``to_k8s``/``from_k8s`` pair
mapping the narrow dataclasses in :mod:`wva_tpu.k8s.objects` /
:mod:`wva_tpu.api.v1alpha1` to the API server's JSON shapes, plus the
group/version/resource table the REST client uses to build request paths
(the RESTMapper equivalent; reference ``internal/utils/pool/gvr.go:25``).
"""

from __future__ import annotations

import base64
import calendar
import os
import time
from dataclasses import dataclass
from typing import Any, Callable

from wva_tpu.api import v1alpha1
from wva_tpu.api.v1alpha1 import ObjectMeta, VariantAutoscaling
from wva_tpu.utils.freeze import intern_labels
from wva_tpu.k8s.objects import (
    ConfigMap,
    Container,
    Deployment,
    DeploymentStatus,
    Event,
    ExtensionRef,
    InferencePool,
    LeaderWorkerSet,
    LeaderWorkerSetStatus,
    Lease,
    Namespace,
    Node,
    NodeStatus,
    Pod,
    PodStatus,
    PodTemplateSpec,
    ResourceRequirements,
    Secret,
    Service,
    ServiceMonitor,
)


def rfc3339(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))


def rfc3339_micro(ts: float) -> str:
    """metav1.MicroTime (Lease acquire/renew times)."""
    whole = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(ts))
    return f"{whole}.{int((ts % 1) * 1e6):06d}Z"


def parse_rfc3339(s: str | None) -> float:
    if not s:
        return 0.0
    base, frac = s.rstrip("Z"), 0.0
    if "." in base:
        base, frac_s = base.split(".", 1)
        try:
            frac = float("0." + frac_s)
        except ValueError:
            frac = 0.0
    try:
        return calendar.timegm(time.strptime(base, "%Y-%m-%dT%H:%M:%S")) + frac
    except ValueError:
        return 0.0


# --- GVR table ---------------------------------------------------------------


@dataclass(frozen=True)
class GVR:
    group: str  # "" = core
    version: str
    plural: str
    namespaced: bool = True

    @property
    def api_prefix(self) -> str:
        if self.group:
            return f"/apis/{self.group}/{self.version}"
        return f"/api/{self.version}"

    def path(self, namespace: str | None = None, name: str | None = None,
             subresource: str | None = None) -> str:
        parts = [self.api_prefix]
        if self.namespaced and namespace:
            parts.append(f"namespaces/{namespace}")
        parts.append(self.plural)
        if name:
            parts.append(name)
        if subresource:
            parts.append(subresource)
        return "/".join(parts)

    @property
    def api_version(self) -> str:
        return f"{self.group}/{self.version}" if self.group else self.version


_GVRS: dict[str, GVR] = {
    "Pod": GVR("", "v1", "pods"),
    "Service": GVR("", "v1", "services"),
    "ConfigMap": GVR("", "v1", "configmaps"),
    "Secret": GVR("", "v1", "secrets"),
    "Event": GVR("", "v1", "events"),
    "Node": GVR("", "v1", "nodes", namespaced=False),
    "Namespace": GVR("", "v1", "namespaces", namespaced=False),
    "Deployment": GVR("apps", "v1", "deployments"),
    "Lease": GVR("coordination.k8s.io", "v1", "leases"),
    "ServiceMonitor": GVR("monitoring.coreos.com", "v1", "servicemonitors"),
    "LeaderWorkerSet": GVR("leaderworkerset.x-k8s.io", "v1", "leaderworkersets"),
    "VariantAutoscaling": GVR(v1alpha1.GROUP, v1alpha1.VERSION, v1alpha1.PLURAL),
}


def gvr_for(kind: str) -> GVR:
    """Resolve the request path components for a kind. InferencePool's group
    is env-switchable like the reference's POOL_GROUP (``cmd/main.go:444-449``):
    ``inference.networking.k8s.io`` (v1, default) or
    ``inference.networking.x-k8s.io`` (v1alpha2)."""
    if kind == "InferencePool":
        group = os.environ.get("POOL_GROUP", "inference.networking.k8s.io")
        version = "v1alpha2" if group.endswith("x-k8s.io") else "v1"
        return GVR(group, version, "inferencepools")
    try:
        return _GVRS[kind]
    except KeyError:
        raise TypeError(f"no GVR mapping for kind {kind!r}") from None


# --- ObjectMeta --------------------------------------------------------------


def _meta_to_k8s(meta: ObjectMeta, namespaced: bool = True) -> dict[str, Any]:
    d = meta.to_dict()
    if not namespaced:
        d.pop("namespace", None)
    # A zero resourceVersion means "never read from a server" and must be
    # omitted on the wire (the API server rejects rv "0" on update).
    if d.get("resourceVersion") in ("", "0"):
        d.pop("resourceVersion", None)
    d.pop("generation", None)  # server-managed
    return d


def _meta_from_k8s(d: dict[str, Any]) -> ObjectMeta:
    return ObjectMeta.from_dict(d or {})


# --- pod template / containers ----------------------------------------------


def _container_to_k8s(c: Container) -> dict[str, Any]:
    d: dict[str, Any] = {"name": c.name}
    if c.image:
        d["image"] = c.image
    if c.command:
        d["command"] = list(c.command)
    if c.args:
        d["args"] = list(c.args)
    if c.env:
        d["env"] = [{"name": k, "value": v} for k, v in c.env.items()]
    res: dict[str, Any] = {}
    if c.resources.requests:
        res["requests"] = dict(c.resources.requests)
    if c.resources.limits:
        res["limits"] = dict(c.resources.limits)
    if res:
        d["resources"] = res
    if c.ports:
        d["ports"] = [{"name": n, "containerPort": p} for n, p in c.ports.items()]
    return d


def _container_from_k8s(d: dict[str, Any]) -> Container:
    res = d.get("resources") or {}
    return Container(
        name=d.get("name", ""),
        image=d.get("image", ""),
        command=list(d.get("command") or []),
        args=list(d.get("args") or []),
        env={e.get("name", ""): e.get("value", "")
             for e in d.get("env") or [] if e.get("name")},
        resources=ResourceRequirements(
            requests={k: str(v) for k, v in (res.get("requests") or {}).items()},
            limits={k: str(v) for k, v in (res.get("limits") or {}).items()}),
        ports={p.get("name", ""): int(p.get("containerPort", 0))
               for p in d.get("ports") or [] if p.get("name")},
    )


def _template_to_k8s(t: PodTemplateSpec) -> dict[str, Any]:
    spec: dict[str, Any] = {
        "containers": [_container_to_k8s(c) for c in t.containers]}
    if t.init_containers:
        spec["initContainers"] = [_container_to_k8s(c) for c in t.init_containers]
    if t.node_selector:
        spec["nodeSelector"] = dict(t.node_selector)
    meta: dict[str, Any] = {}
    if t.labels:
        meta["labels"] = dict(t.labels)
    if t.annotations:
        meta["annotations"] = dict(t.annotations)
    return {"metadata": meta, "spec": spec}


def _template_from_k8s(d: dict[str, Any]) -> PodTemplateSpec:
    meta = d.get("metadata") or {}
    spec = d.get("spec") or {}
    # Interned shared label/annotation/selector dicts: every pod of a
    # variant repeats the same few dicts across fleet-sized LISTs, and
    # decoded objects feed frozen stores (docs/design/object-plane.md).
    return PodTemplateSpec(
        labels=intern_labels(meta.get("labels")),
        annotations=intern_labels(meta.get("annotations")),
        containers=[_container_from_k8s(c) for c in spec.get("containers") or []],
        init_containers=[_container_from_k8s(c)
                         for c in spec.get("initContainers") or []],
        node_selector=intern_labels(spec.get("nodeSelector")),
    )


# --- per-kind codecs ---------------------------------------------------------


def _deployment_to_k8s(o: Deployment) -> dict[str, Any]:
    spec: dict[str, Any] = {
        "selector": {"matchLabels": dict(o.selector)},
        "template": _template_to_k8s(o.template),
    }
    if o.replicas is not None:
        spec["replicas"] = o.replicas
    return {
        "apiVersion": o.API_VERSION, "kind": o.KIND,
        "metadata": _meta_to_k8s(o.metadata), "spec": spec,
        "status": {"replicas": o.status.replicas,
                   "readyReplicas": o.status.ready_replicas,
                   "updatedReplicas": o.status.updated_replicas},
    }


def _deployment_from_k8s(d: dict[str, Any]) -> Deployment:
    spec = d.get("spec") or {}
    status = d.get("status") or {}
    return Deployment(
        metadata=_meta_from_k8s(d.get("metadata")),
        replicas=spec.get("replicas"),
        selector=intern_labels((spec.get("selector") or {}).get("matchLabels")),
        template=_template_from_k8s(spec.get("template") or {}),
        status=DeploymentStatus(
            replicas=int(status.get("replicas") or 0),
            ready_replicas=int(status.get("readyReplicas") or 0),
            updated_replicas=int(status.get("updatedReplicas") or 0)),
    )


def _pod_to_k8s(o: Pod) -> dict[str, Any]:
    d = _template_to_k8s(o.spec)
    spec = d["spec"]
    if o.node_name:
        spec["nodeName"] = o.node_name
    meta = _meta_to_k8s(o.metadata)
    # Pod labels live on metadata (the template's labels ARE the pod's).
    if o.spec.labels and "labels" not in meta:
        meta["labels"] = dict(o.spec.labels)
    conditions = [{"type": "Ready",
                   "status": "True" if o.status.ready else "False"}]
    return {
        "apiVersion": "v1", "kind": "Pod", "metadata": meta, "spec": spec,
        "status": {"phase": o.status.phase, "podIP": o.status.pod_ip,
                   "conditions": conditions},
    }


def _pod_from_k8s(d: dict[str, Any]) -> Pod:
    spec = d.get("spec") or {}
    status = d.get("status") or {}
    meta = _meta_from_k8s(d.get("metadata"))
    ready = any(c.get("type") == "Ready" and c.get("status") == "True"
                for c in status.get("conditions") or [])
    template = _template_from_k8s({"metadata": {"labels": dict(meta.labels)},
                                   "spec": spec})
    return Pod(
        metadata=meta, spec=template,
        node_name=spec.get("nodeName", ""),
        status=PodStatus(phase=status.get("phase", "Pending"), ready=ready,
                         pod_ip=status.get("podIP", "")),
    )


def _node_to_k8s(o: Node) -> dict[str, Any]:
    out = {
        "apiVersion": "v1", "kind": "Node",
        "metadata": _meta_to_k8s(o.metadata, namespaced=False),
        "status": {
            "capacity": dict(o.status.capacity),
            "allocatable": dict(o.status.allocatable),
            "conditions": [{"type": "Ready",
                            "status": "True" if o.ready else "False"}],
        },
    }
    if o.unschedulable:
        out["spec"] = {"unschedulable": True}
    return out


def _node_from_k8s(d: dict[str, Any]) -> Node:
    status = d.get("status") or {}
    spec = d.get("spec") or {}
    ready = any(c.get("type") == "Ready" and c.get("status") == "True"
                for c in status.get("conditions") or [])
    return Node(
        metadata=_meta_from_k8s(d.get("metadata")),
        status=NodeStatus(
            capacity={k: str(v) for k, v in (status.get("capacity") or {}).items()},
            allocatable={k: str(v)
                         for k, v in (status.get("allocatable") or {}).items()}),
        ready=ready,
        unschedulable=bool(spec.get("unschedulable", False)),
    )


def _configmap_to_k8s(o: ConfigMap) -> dict[str, Any]:
    return {"apiVersion": "v1", "kind": "ConfigMap",
            "metadata": _meta_to_k8s(o.metadata), "data": dict(o.data)}


def _configmap_from_k8s(d: dict[str, Any]) -> ConfigMap:
    return ConfigMap(metadata=_meta_from_k8s(d.get("metadata")),
                     data={k: str(v) for k, v in (d.get("data") or {}).items()})


def _secret_to_k8s(o: Secret) -> dict[str, Any]:
    return {"apiVersion": "v1", "kind": "Secret",
            "metadata": _meta_to_k8s(o.metadata),
            "data": {k: base64.b64encode(v.encode()).decode()
                     for k, v in o.data.items()}}


def _secret_from_k8s(d: dict[str, Any]) -> Secret:
    data = {}
    for k, v in (d.get("data") or {}).items():
        try:
            data[k] = base64.b64decode(v).decode()
        except Exception:  # noqa: BLE001 — undecodable entries skipped
            continue
    for k, v in (d.get("stringData") or {}).items():
        data[k] = str(v)
    return Secret(metadata=_meta_from_k8s(d.get("metadata")), data=data)


def _service_to_k8s(o: Service) -> dict[str, Any]:
    return {
        "apiVersion": "v1", "kind": "Service",
        "metadata": _meta_to_k8s(o.metadata),
        "spec": {"selector": dict(o.selector),
                 "ports": [{"name": n, "port": p} for n, p in o.ports.items()]},
    }


def _service_from_k8s(d: dict[str, Any]) -> Service:
    spec = d.get("spec") or {}
    return Service(
        metadata=_meta_from_k8s(d.get("metadata")),
        selector=dict(spec.get("selector") or {}),
        ports={p.get("name", ""): int(p.get("port", 0))
               for p in spec.get("ports") or [] if p.get("name")},
    )


def _namespace_to_k8s(o: Namespace) -> dict[str, Any]:
    return {"apiVersion": "v1", "kind": "Namespace",
            "metadata": _meta_to_k8s(o.metadata, namespaced=False)}


def _namespace_from_k8s(d: dict[str, Any]) -> Namespace:
    return Namespace(metadata=_meta_from_k8s(d.get("metadata")))


def _lease_to_k8s(o: Lease) -> dict[str, Any]:
    spec: dict[str, Any] = {
        "holderIdentity": o.holder_identity,
        "leaseDurationSeconds": o.lease_duration_seconds,
        "leaseTransitions": o.lease_transitions,
    }
    if o.acquire_time:
        spec["acquireTime"] = rfc3339_micro(o.acquire_time)
    if o.renew_time:
        spec["renewTime"] = rfc3339_micro(o.renew_time)
    return {"apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
            "metadata": _meta_to_k8s(o.metadata), "spec": spec}


def _lease_from_k8s(d: dict[str, Any]) -> Lease:
    spec = d.get("spec") or {}
    return Lease(
        metadata=_meta_from_k8s(d.get("metadata")),
        holder_identity=spec.get("holderIdentity") or "",
        lease_duration_seconds=int(spec.get("leaseDurationSeconds") or 60),
        acquire_time=parse_rfc3339(spec.get("acquireTime")),
        renew_time=parse_rfc3339(spec.get("renewTime")),
        lease_transitions=int(spec.get("leaseTransitions") or 0),
    )


def _event_to_k8s(o: Event) -> dict[str, Any]:
    return {
        "apiVersion": "v1", "kind": "Event",
        "metadata": _meta_to_k8s(o.metadata),
        "involvedObject": {"kind": o.involved_kind, "name": o.involved_name,
                           "namespace": o.involved_namespace},
        "type": o.type, "reason": o.reason, "message": o.message,
        "count": o.count,
        "firstTimestamp": rfc3339(o.first_timestamp) if o.first_timestamp else None,
        "lastTimestamp": rfc3339(o.last_timestamp) if o.last_timestamp else None,
        "source": {"component": "workload-variant-autoscaler"},
    }


def _event_from_k8s(d: dict[str, Any]) -> Event:
    inv = d.get("involvedObject") or {}
    return Event(
        metadata=_meta_from_k8s(d.get("metadata")),
        involved_kind=inv.get("kind", ""),
        involved_name=inv.get("name", ""),
        involved_namespace=inv.get("namespace", ""),
        type=d.get("type", "Normal"),
        reason=d.get("reason", ""),
        message=d.get("message", ""),
        count=int(d.get("count") or 1),
        first_timestamp=parse_rfc3339(d.get("firstTimestamp")),
        last_timestamp=parse_rfc3339(d.get("lastTimestamp")),
    )


def _lws_to_k8s(o: LeaderWorkerSet) -> dict[str, Any]:
    spec: dict[str, Any] = {
        "leaderWorkerTemplate": {
            "size": o.size,
            "workerTemplate": _template_to_k8s(o.template),
        },
    }
    if o.replicas is not None:
        spec["replicas"] = o.replicas
    return {
        "apiVersion": o.API_VERSION, "kind": o.KIND,
        "metadata": _meta_to_k8s(o.metadata), "spec": spec,
        "status": {"replicas": o.status.replicas,
                   "readyReplicas": o.status.ready_replicas},
    }


def _lws_from_k8s(d: dict[str, Any]) -> LeaderWorkerSet:
    spec = d.get("spec") or {}
    lwt = spec.get("leaderWorkerTemplate") or {}
    status = d.get("status") or {}
    template = _template_from_k8s(lwt.get("workerTemplate") or {})
    return LeaderWorkerSet(
        metadata=_meta_from_k8s(d.get("metadata")),
        replicas=spec.get("replicas"),
        size=int(lwt.get("size") or 1),
        selector=dict(template.labels),
        template=template,
        status=LeaderWorkerSetStatus(
            replicas=int(status.get("replicas") or 0),
            ready_replicas=int(status.get("readyReplicas") or 0)),
    )


def _pool_to_k8s(o: InferencePool) -> dict[str, Any]:
    gvr = gvr_for("InferencePool")
    spec: dict[str, Any] = {
        "selector": {"matchLabels": dict(o.selector)},
        "targetPortNumber": o.target_port_number,
        "extensionRef": {"name": o.extension_ref.service_name,
                         "portNumber": o.extension_ref.port_number},
    }
    return {"apiVersion": gvr.api_version, "kind": "InferencePool",
            "metadata": _meta_to_k8s(o.metadata), "spec": spec}


def _pool_from_k8s(d: dict[str, Any]) -> InferencePool:
    """Accept both the v1 and v1alpha2 shapes (reference pool.go:54-100):
    selector as matchLabels or flat map; extensionRef or endpointPickerRef;
    targetPortNumber or targetPorts[0].number."""
    spec = d.get("spec") or {}
    selector = spec.get("selector") or {}
    if "matchLabels" in selector:
        selector = selector.get("matchLabels") or {}
    ref = spec.get("extensionRef") or spec.get("endpointPickerRef") or {}
    port = spec.get("targetPortNumber")
    if port is None:
        ports = spec.get("targetPorts") or []
        port = ports[0].get("number", 8000) if ports else 8000
    return InferencePool(
        metadata=_meta_from_k8s(d.get("metadata")),
        selector={str(k): str(v) for k, v in selector.items()},
        target_port_number=int(port),
        extension_ref=ExtensionRef(
            service_name=ref.get("name", ""),
            port_number=int(ref.get("portNumber") or ref.get("port") or 9090)),
    )


def _sm_to_k8s(o: ServiceMonitor) -> dict[str, Any]:
    return {"apiVersion": o.API_VERSION, "kind": "ServiceMonitor",
            "metadata": _meta_to_k8s(o.metadata),
            "spec": {"selector": {"matchLabels": dict(o.selector)}}}


def _sm_from_k8s(d: dict[str, Any]) -> ServiceMonitor:
    spec = d.get("spec") or {}
    return ServiceMonitor(
        metadata=_meta_from_k8s(d.get("metadata")),
        selector=dict((spec.get("selector") or {}).get("matchLabels") or {}))


def _va_to_k8s(o: VariantAutoscaling) -> dict[str, Any]:
    d = o.to_dict()
    d["metadata"] = _meta_to_k8s(o.metadata)
    return d


_CODECS: dict[str, tuple[Callable[[Any], dict], Callable[[dict], Any]]] = {
    "Deployment": (_deployment_to_k8s, _deployment_from_k8s),
    "Pod": (_pod_to_k8s, _pod_from_k8s),
    "Node": (_node_to_k8s, _node_from_k8s),
    "ConfigMap": (_configmap_to_k8s, _configmap_from_k8s),
    "Secret": (_secret_to_k8s, _secret_from_k8s),
    "Service": (_service_to_k8s, _service_from_k8s),
    "Namespace": (_namespace_to_k8s, _namespace_from_k8s),
    "Lease": (_lease_to_k8s, _lease_from_k8s),
    "Event": (_event_to_k8s, _event_from_k8s),
    "LeaderWorkerSet": (_lws_to_k8s, _lws_from_k8s),
    "InferencePool": (_pool_to_k8s, _pool_from_k8s),
    "ServiceMonitor": (_sm_to_k8s, _sm_from_k8s),
    "VariantAutoscaling": (_va_to_k8s, VariantAutoscaling.from_dict),
}


def to_k8s(obj: Any) -> dict[str, Any]:
    kind = getattr(obj, "KIND", None) or getattr(obj, "kind", None)
    try:
        encode, _ = _CODECS[kind]
    except KeyError:
        raise TypeError(f"no codec for kind {kind!r}") from None
    return encode(obj)


def from_k8s(kind: str, d: dict[str, Any]) -> Any:
    try:
        _, decode = _CODECS[kind]
    except KeyError:
        raise TypeError(f"no codec for kind {kind!r}") from None
    obj = decode(d)
    # Cluster-scoped objects must decode with namespace "" — the wire form
    # omits the field and ObjectMeta.from_dict would default it to
    # "default", making the object unreachable by get/delete (which look up
    # under namespace "").
    if not gvr_for(kind).namespaced:
        obj.metadata.namespace = ""
    return obj


def known_kinds() -> list[str]:
    return sorted(_CODECS)
