"""Kubernetes-delegated authentication/authorization for ``/metrics``.

The reference protects its metrics endpoint with controller-runtime's
``WithAuthenticationAndAuthorization`` filter (``cmd/main.go:213-219`` +
``config/rbac/metrics_auth_role.yaml``): every scrape presents a
ServiceAccount bearer token, the apiserver validates it via **TokenReview**,
and a **SubjectAccessReview** checks the caller may ``get`` the ``/metrics``
nonResourceURL. This module is that filter: stdlib-only, short-TTL decision
cache (Prometheus scrapes every few seconds; the apiserver should not see
one review pair per scrape).
"""

from __future__ import annotations

import logging
import threading

from wva_tpu.utils.clock import SYSTEM_CLOCK, Clock

log = logging.getLogger(__name__)

TOKEN_REVIEW_PATH = "/apis/authentication.k8s.io/v1/tokenreviews"
SUBJECT_ACCESS_REVIEW_PATH = "/apis/authorization.k8s.io/v1/subjectaccessreviews"
DECISION_CACHE_TTL = 60.0
DECISION_CACHE_ALLOW_TTL = 20.0
DECISION_CACHE_MAX = 256


class TokenReviewAuthenticator:
    """``allowed(authorization_header)`` gate for the metrics listener.

    Allow decisions get a SHORTER TTL than denies: a revoked token or
    removed RBAC grant stops scraping within ``allow_ttl`` (20s) instead of
    a full minute, while unauthenticated spam is still rate-limited to one
    review pair per ``cache_ttl``. Eviction is per-entry LRU — an attacker
    cycling unknown tokens evicts only the stalest entry, never the whole
    cache of legitimate scrapers."""

    def __init__(self, client, clock: Clock | None = None,
                 cache_ttl: float = DECISION_CACHE_TTL,
                 allow_ttl: float = DECISION_CACHE_ALLOW_TTL,
                 path: str = "/metrics") -> None:
        from collections import OrderedDict

        self.client = client  # RestKubeClient (raw_post)
        self.clock = clock or SYSTEM_CLOCK
        self.cache_ttl = cache_ttl
        self.allow_ttl = min(allow_ttl, cache_ttl)
        self.path = path
        self._mu = threading.Lock()
        # token -> (ok, exp), LRU-ordered (most recent use last)
        self._cache: "OrderedDict[str, tuple[bool, float]]" = OrderedDict()

    def allowed(self, authorization_header: str) -> bool:
        if not authorization_header.startswith("Bearer "):
            return False
        token = authorization_header[len("Bearer "):].strip()
        if not token:
            return False
        now = self.clock.now()
        with self._mu:
            cached = self._cache.get(token)
            if cached is not None and now < cached[1]:
                self._cache.move_to_end(token)
                return cached[0]
        ok = self._review(token)
        if ok is None:
            # Review ERRORED (apiserver blip): fail closed for this scrape
            # but cache nothing — a healthy scraper whose re-review lands
            # during a one-second outage must not be locked out for a full
            # deny TTL.
            return False
        with self._mu:
            self._cache.pop(token, None)
            while len(self._cache) >= DECISION_CACHE_MAX:
                self._cache.popitem(last=False)  # evict LRU entry only
            ttl = self.allow_ttl if ok else self.cache_ttl
            self._cache[token] = (ok, now + ttl)
        return ok

    def _review(self, token: str) -> bool | None:
        """TokenReview (authn) then SubjectAccessReview (authz). Fail
        CLOSED: any apiserver error denies the scrape — metrics must never
        leak because the authorizer was unreachable. Errors return ``None``
        (deny, but uncacheable) so a transient blip is not remembered as a
        60s RBAC denial."""
        try:
            tr = self.client.raw_post(TOKEN_REVIEW_PATH, {
                "apiVersion": "authentication.k8s.io/v1",
                "kind": "TokenReview",
                "spec": {"token": token},
            })
        except Exception as e:  # noqa: BLE001 — fail closed
            log.warning("TokenReview failed: %s", e)
            return None
        status = tr.get("status") or {}
        if not status.get("authenticated"):
            return False
        user = status.get("user") or {}
        username = user.get("username", "")
        groups = user.get("groups") or []
        try:
            sar = self.client.raw_post(SUBJECT_ACCESS_REVIEW_PATH, {
                "apiVersion": "authorization.k8s.io/v1",
                "kind": "SubjectAccessReview",
                "spec": {
                    "user": username,
                    "groups": groups,
                    "nonResourceAttributes": {"path": self.path,
                                              "verb": "get"},
                },
            })
        except Exception as e:  # noqa: BLE001 — fail closed
            log.warning("SubjectAccessReview failed: %s", e)
            return None
        allowed = bool((sar.get("status") or {}).get("allowed"))
        if not allowed:
            log.info("Metrics scrape by %s denied by RBAC", username)
        return allowed
