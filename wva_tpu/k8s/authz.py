"""Kubernetes-delegated authentication/authorization for ``/metrics``.

The reference protects its metrics endpoint with controller-runtime's
``WithAuthenticationAndAuthorization`` filter (``cmd/main.go:213-219`` +
``config/rbac/metrics_auth_role.yaml``): every scrape presents a
ServiceAccount bearer token, the apiserver validates it via **TokenReview**,
and a **SubjectAccessReview** checks the caller may ``get`` the ``/metrics``
nonResourceURL. This module is that filter: stdlib-only, short-TTL decision
cache (Prometheus scrapes every few seconds; the apiserver should not see
one review pair per scrape).
"""

from __future__ import annotations

import logging
import threading

from wva_tpu.utils.clock import SYSTEM_CLOCK, Clock

log = logging.getLogger(__name__)

TOKEN_REVIEW_PATH = "/apis/authentication.k8s.io/v1/tokenreviews"
SUBJECT_ACCESS_REVIEW_PATH = "/apis/authorization.k8s.io/v1/subjectaccessreviews"
DECISION_CACHE_TTL = 60.0
DECISION_CACHE_MAX = 256


class TokenReviewAuthenticator:
    """``allowed(authorization_header)`` gate for the metrics listener."""

    def __init__(self, client, clock: Clock | None = None,
                 cache_ttl: float = DECISION_CACHE_TTL,
                 path: str = "/metrics") -> None:
        self.client = client  # RestKubeClient (raw_post)
        self.clock = clock or SYSTEM_CLOCK
        self.cache_ttl = cache_ttl
        self.path = path
        self._mu = threading.Lock()
        self._cache: dict[str, tuple[bool, float]] = {}  # token -> (ok, exp)

    def allowed(self, authorization_header: str) -> bool:
        if not authorization_header.startswith("Bearer "):
            return False
        token = authorization_header[len("Bearer "):].strip()
        if not token:
            return False
        now = self.clock.now()
        with self._mu:
            cached = self._cache.get(token)
            if cached is not None and now < cached[1]:
                return cached[0]
        ok = self._review(token)
        with self._mu:
            if len(self._cache) >= DECISION_CACHE_MAX:
                self._cache.clear()  # bounded; refill from live reviews
            self._cache[token] = (ok, now + self.cache_ttl)
        return ok

    def _review(self, token: str) -> bool:
        """TokenReview (authn) then SubjectAccessReview (authz). Fail
        CLOSED: any apiserver error denies the scrape — metrics must never
        leak because the authorizer was unreachable."""
        try:
            tr = self.client.raw_post(TOKEN_REVIEW_PATH, {
                "apiVersion": "authentication.k8s.io/v1",
                "kind": "TokenReview",
                "spec": {"token": token},
            })
        except Exception as e:  # noqa: BLE001 — fail closed
            log.warning("TokenReview failed: %s", e)
            return False
        status = tr.get("status") or {}
        if not status.get("authenticated"):
            return False
        user = status.get("user") or {}
        username = user.get("username", "")
        groups = user.get("groups") or []
        try:
            sar = self.client.raw_post(SUBJECT_ACCESS_REVIEW_PATH, {
                "apiVersion": "authorization.k8s.io/v1",
                "kind": "SubjectAccessReview",
                "spec": {
                    "user": username,
                    "groups": groups,
                    "nonResourceAttributes": {"path": self.path,
                                              "verb": "get"},
                },
            })
        except Exception as e:  # noqa: BLE001 — fail closed
            log.warning("SubjectAccessReview failed: %s", e)
            return False
        allowed = bool((sar.get("status") or {}).get("allowed"))
        if not allowed:
            log.info("Metrics scrape by %s denied by RBAC", username)
        return allowed
