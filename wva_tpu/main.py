"""Process wiring (reference ``cmd/main.go:83-520``).

Assembles the full controller: config load (fail-fast), datastore with the
EPP pod-scraping source factory, Prometheus source + query registration,
engines as leader-gated runnables, reconcilers, health endpoints, metric
registration. ``Manager`` supports wall-clock threaded operation and
single-threaded simulated ticks (the emulation harness and bench drive
``run_once``).
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass

from wva_tpu.actuator import Actuator, DirectActuator
from wva_tpu.analyzers.saturation_v2 import CapacityKnowledgeStore
from wva_tpu.blackbox import FlightRecorder
from wva_tpu.collector.registration import (
    register_saturation_queries,
    register_scale_to_zero_queries,
    register_slo_queries,
)
from wva_tpu.collector.registration.scale_to_zero import collect_model_request_count
from wva_tpu.collector.replica_metrics import ReplicaMetricsCollector
from wva_tpu.collector.source import (
    HTTPPromAPI,
    InMemoryPromAPI,
    PodScrapingSource,
    PodVAMapper,
    PrometheusSource,
    SourceRegistry,
    TimeSeriesDB,
    http_pod_fetcher,
)
from wva_tpu.collector.source.registry import PROMETHEUS_SOURCE_NAME
from wva_tpu.config import Config
from wva_tpu.controller import (
    ConfigMapReconciler,
    InferencePoolReconciler,
    VariantAutoscalingReconciler,
)
from wva_tpu.datastore import Datastore
from wva_tpu.discovery import TPUSliceDiscovery
from wva_tpu.engines.fastpath import FastPathMonitor
from wva_tpu.engines.saturation import SaturationEngine
from wva_tpu.engines.saturation.engine import DEFAULT_ANALYSIS_WORKERS
from wva_tpu.engines.scalefromzero import ScaleFromZeroEngine
from wva_tpu.indexers import Indexer
from wva_tpu.k8s.client import KubeClient
from wva_tpu.k8s.events import EventRecorder
from wva_tpu.k8s.informer import InformerKubeClient
from wva_tpu.leaderelection import LeaderElector, LeaderElectorConfig
from wva_tpu.metrics import MetricsRegistry
from wva_tpu.pipeline import (
    DefaultLimiter,
    Enforcer,
    GreedyBySaturation,
    SliceInventory,
)
from wva_tpu.utils import freeze as frz
from wva_tpu.utils.clock import SYSTEM_CLOCK, Clock
from wva_tpu.utils.variant import get_controller_instance

log = logging.getLogger(__name__)


@dataclass
class Manager:
    """Everything wired; the process object."""

    client: KubeClient
    config: Config
    clock: Clock
    registry: MetricsRegistry
    source_registry: SourceRegistry
    datastore: Datastore
    indexer: Indexer
    engine: SaturationEngine
    scale_from_zero: ScaleFromZeroEngine
    fastpath: FastPathMonitor
    va_reconciler: VariantAutoscalingReconciler
    configmap_reconciler: ConfigMapReconciler
    pool_reconciler: InferencePoolReconciler
    capacity_store: CapacityKnowledgeStore
    # Leader election (None = disabled -> always act as leader). Engines are
    # leader-gated; reconcilers and watches run on every replica (reference
    # cmd/main.go:378-425 leader-gated Runnables).
    elector: "LeaderElector | None" = None
    # Decision flight recorder (None = tracing disabled via config).
    flight_recorder: "FlightRecorder | None" = None
    # Obs-plane span recorder (None = WVA_SPANS off).
    spans: "object | None" = None

    _threads: list[threading.Thread] = None
    _last_election_tick: float = -1e18

    # --- health endpoints (reference cmd/main.go:482-498) ---

    def healthz(self) -> bool:
        return True

    def readyz(self) -> bool:
        return self.config.configmaps_bootstrap_complete()

    # --- lifecycle ---

    def setup(self) -> "Manager":
        self.indexer.setup()
        self.configmap_reconciler.bootstrap_initial_configmaps()
        self.configmap_reconciler.setup()
        self.pool_reconciler.setup()
        self.va_reconciler.setup()
        return self

    def start(self, stop: threading.Event) -> None:
        """Wall-clock mode: engines + trigger loop in daemon threads."""
        # Event-driven wake-ups (wall-clock mode ONLY — simulation drivers
        # using run_once stay tick-deterministic): material watch events on
        # VAs/Deployments/Pods end the engines' inter-tick waits
        # immediately, so a spec edit or a scale-from-zero-relevant change
        # is acted on in watch latency instead of up to a full poll
        # interval. Triggers are idempotent; event bursts collapse into one
        # immediate tick.
        if hasattr(self.client, "add_nudge_listener"):
            def _nudge(kind: str, event: str, obj) -> None:
                self.engine.executor.trigger()
                if kind in ("VariantAutoscaling", "Deployment",
                            "LeaderWorkerSet"):
                    self.scale_from_zero.executor.trigger()
            self.client.add_nudge_listener(_nudge)
        # Background cache warmer (fetch_interval > 0): keeps the
        # Prometheus result cache hot between engine ticks.
        prom = self.source_registry.get(PROMETHEUS_SOURCE_NAME)
        if prom is not None and hasattr(prom, "start_background_fetch"):
            prom.start_background_fetch(stop)
        self._threads = [
            threading.Thread(target=self.engine.start_optimize_loop, args=(stop,),
                             name="saturation-engine", daemon=True),
            threading.Thread(target=self.scale_from_zero.start_loop, args=(stop,),
                             name="scale-from-zero", daemon=True),
            threading.Thread(target=self.fastpath.start_loop, args=(stop,),
                             name="fast-path", daemon=True),
            threading.Thread(target=self.va_reconciler.run_trigger_loop, args=(stop,),
                             name="va-trigger-loop", daemon=True),
        ]
        if self.elector is not None:
            def election_loop():
                while not stop.is_set():
                    try:
                        self.elector.tick()
                    except Exception:  # noqa: BLE001 — election must outlive
                        # transient client errors; a dead election thread
                        # would silently demote this replica forever.
                        logging.getLogger(__name__).exception(
                            "leader-election tick failed; retrying")
                    stop.wait(self.elector.config.retry_period)
                self.elector.release()
            self._threads.append(threading.Thread(
                target=election_loop, name="leader-election", daemon=True))
        for t in self._threads:
            t.start()

    def is_leader(self) -> bool:
        return self.elector is None or self.elector.is_leader()

    def election_tick(self) -> bool:
        """One leader-election acquire/renew step, throttled to the elector's
        retry_period so a fast simulation cadence doesn't multiply lease
        traffic (no-op when disabled)."""
        if self.elector is None:
            return True
        now = self.clock.now()
        if now - self._last_election_tick < self.elector.config.retry_period \
                and self._last_election_tick > -1e17:
            return self.elector.is_leader()
        self._last_election_tick = now
        return self.elector.tick()

    def run_once(self) -> None:
        """Simulation mode: one saturation tick + one scale-from-zero tick +
        drain reconcile triggers (single-threaded, deterministic)."""
        self.election_tick()
        if self.is_leader():
            self.engine.executor.tick()
            self.scale_from_zero.executor.tick()
            self.fastpath.executor.tick()
            # The engine tick above ran BEFORE the fast-path scan: a backlog
            # it just detected would otherwise wait a whole cycle, defeating
            # the fast path in combined-tick drivers.
            if self.engine.executor.consume_trigger():
                self.engine.executor.tick()
        self.va_reconciler.drain_triggers()

    def scale_from_zero_tick(self) -> None:
        if self.is_leader():
            self.scale_from_zero.executor.tick()
        self.va_reconciler.drain_triggers()

    def fast_path_tick(self) -> bool:
        """One fast-path monitoring pass; returns True when an immediate
        saturation tick was requested (simulation drivers run the engine
        tick themselves — see EmulationHarness.run)."""
        if not self.is_leader():
            return False
        self.fastpath.executor.tick()
        return self.engine.executor.consume_trigger()

    def shutdown(self) -> None:
        """Voluntary leader step-down on exit (ReleaseOnCancel semantics);
        flush the decision trace so the last cycle is never lost; release
        the persistent worker pools (engine analysis, Prometheus queries)."""
        if self.elector is not None:
            self.elector.release()
        if self.flight_recorder is not None:
            self.flight_recorder.close()
        if self.spans is not None:
            self.spans.close()
        if self.engine.shard_plane is not None:
            # Voluntary shard-lease step-down + worker pool release: a
            # clean shutdown hands every shard to a successor in ~one
            # retry period instead of a lease timeout.
            self.engine.shard_plane.shutdown()
        self.engine.close()
        prom = self.source_registry.get(PROMETHEUS_SOURCE_NAME)
        if prom is not None and hasattr(prom, "close"):
            prom.close()


def build_manager(
    client: KubeClient,
    config: Config,
    clock: Clock | None = None,
    tsdb: TimeSeriesDB | None = None,
    pod_fetcher=None,
    mirror_wva_metrics: bool = True,
    slice_provisioner=None,
    prom_api=None,
) -> Manager:
    """Wire the full controller (reference cmd/main.go).

    ``tsdb`` selects the in-memory Prometheus backend (emulation/bench);
    when None, an HTTP backend against ``config.prometheus_base_url()`` is
    used. ``pod_fetcher`` overrides EPP pod scraping (in-process harness);
    defaults to HTTP. ``slice_provisioner`` backs the elastic capacity
    plane (WVA_CAPACITY): the emulation harness injects a
    FakeGkeProvisioner; None leaves the NullProvisioner, which plans
    strictly within discovered inventory. ``prom_api`` overrides the
    metrics backend entirely (the chaos harness wraps the in-memory API
    with a fault injector); None derives it from ``tsdb``/config.
    """
    clock = clock or SYSTEM_CLOCK

    # Zero-copy object plane (WVA_ZERO_COPY, default on;
    # docs/design/object-plane.md): store reads across the stack return
    # frozen shared objects. Process-global — the lever gates read-path
    # behavior of every store built below.
    frz.set_zero_copy(config.zero_copy_enabled())

    # Watch-backed informer cache (WVA_INFORMER, default on;
    # docs/design/informer.md): every per-kind LIST the control plane makes
    # per tick is served from a watch-fed store instead — steady-state
    # ticks issue ZERO list requests against the apiserver. Everything
    # below (engines, reconcilers, indexer) reads through the same wrapped
    # client; targeted GETs and all writes still hit the live client (and
    # write through to the store).
    if config.informer_enabled():
        client = InformerKubeClient(
            client, namespace=config.watch_namespace() or None,
            clock=clock).start()

    registry = MetricsRegistry(
        controller_instance=get_controller_instance(),
        # Mirror wva_* gauges into the TSDB so the emulated HPA loop can
        # read them exactly as Prometheus Adapter would.
        mirror_tsdb=tsdb if mirror_wva_metrics else None,
    )

    if prom_api is None:
        if tsdb is not None:
            prom_api = InMemoryPromAPI(tsdb)
        else:
            prom_api = HTTPPromAPI.from_config(config.prometheus())
    source_registry = SourceRegistry()
    prom_source = PrometheusSource(prom_api, config.prometheus_cache_config(),
                                   clock=clock)
    source_registry.register(PROMETHEUS_SOURCE_NAME, prom_source)
    register_saturation_queries(source_registry)
    register_scale_to_zero_queries(source_registry)
    register_slo_queries(source_registry)

    def pod_source_factory(pool):
        fetcher = pod_fetcher or http_pod_fetcher(
            pool.endpoint_picker.metrics_port_number,
            bearer_token=config.epp_metric_reader_bearer_token())
        return PodScrapingSource(
            client, pool.endpoint_picker.service_name,
            pool.endpoint_picker.namespace, fetcher, clock=clock)

    datastore = Datastore(source_registry=source_registry,
                          source_factory=pod_source_factory)
    indexer = Indexer(client)
    mapper = PodVAMapper(client, indexer)
    cache_cfg = config.prometheus_cache_config()
    collector = ReplicaMetricsCollector(
        prom_source, mapper, clock=clock,
        freshness=cache_cfg.freshness if cache_cfg else None)

    actuator = Actuator(client, registry)
    direct_actuator = DirectActuator(client)

    def request_count(model_id, namespace, retention, source=None):
        # ``source`` is the engine's tick-scoped GroupedMetricsView when
        # grouped collection is on (one fleet-wide request-count query per
        # tick instead of one per model); the raw source otherwise.
        return collect_model_request_count(
            source or prom_source, model_id, namespace, retention)

    request_count.supports_source = True
    enforcer = Enforcer(request_count)

    discovery = TPUSliceDiscovery(client)
    inventory = SliceInventory(discovery)
    limiter = DefaultLimiter("tpu-slice-limiter", inventory,
                             GreedyBySaturation(), clock=clock)

    # Decision flight recorder (config-gated): the executor opens one cycle
    # record per engine tick and every pipeline stage appends its part.
    trace_cfg = config.trace_config()
    flight = None
    if trace_cfg.enabled:
        flight = FlightRecorder(
            clock=clock, ring_size=trace_cfg.ring_size,
            spill_path=trace_cfg.path or None, registry=registry)
        enforcer.flight_recorder = flight
        limiter.flight_recorder = flight

    capacity_store = CapacityKnowledgeStore(clock=clock)
    recorder = EventRecorder(client, clock=clock)
    # Predictive capacity planner (WVA_FORECAST, default on): demand
    # history + measured lead times -> proactive replica floors and
    # scale-from-zero pre-wakes (docs/design/forecast.md). Disabled,
    # decisions are byte-identical to pre-forecast builds.
    forecast_planner = None
    fc_cfg = config.forecast_config()
    if fc_cfg.enabled:
        from wva_tpu.forecast import CapacityPlanner

        forecast_planner = CapacityPlanner(
            seasonal_period_seconds=fc_cfg.seasonal_period_seconds,
            grid_step_seconds=fc_cfg.grid_step_seconds,
            default_lead_time_seconds=fc_cfg.default_lead_time_seconds,
            lead_time_quantile=fc_cfg.lead_time_quantile,
            target_utilization=fc_cfg.target_utilization,
            demote_error_threshold=fc_cfg.demote_error_threshold,
            min_trust_evals=fc_cfg.min_trust_evals,
            prewake_enabled=fc_cfg.prewake_enabled,
            prewake_min_demand=fc_cfg.prewake_min_demand)
    # Elastic capacity plane (WVA_CAPACITY, default on): ledger +
    # provisioner between discovery and the solver — pools become
    # ready + provisioning-arriving-within-lead-time, preemptions release
    # chips the same tick, quota stockouts circuit-break per (variant,
    # tier) (docs/design/capacity.md). Disabled, inventory is static and
    # decisions are byte-identical to pre-capacity builds.
    capacity = None
    cap_cfg = config.capacity_config()
    if cap_cfg.enabled:
        from wva_tpu.capacity import CapacityManager, NullProvisioner
        from wva_tpu.forecast.leadtime import LeadTimeEstimator

        # Share the forecast planner's lead-time estimator when
        # forecasting is on: both planes learn from the same measured
        # actuation->scheduled->ready episodes.
        leadtime = (forecast_planner.leadtime
                    if forecast_planner is not None
                    else LeadTimeEstimator(
                        default_seconds=cap_cfg
                        .default_provision_lead_seconds))
        # Per-region tier weight override (wva_tpu/federation): a
        # federated region prices its OWN pools with its region's weights
        # so one region's spot discount (the per-process
        # WVA_CAPACITY_TIER_WEIGHTS) never distorts another region's
        # arbitrage (tests/test_federation.py).
        fed_cfg = config.federation_config()
        tier_weights = cap_cfg.tier_cost_weights
        if fed_cfg.enabled and fed_cfg.region:
            tier_weights = fed_cfg.region_tier_weights.get(
                fed_cfg.region, tier_weights)
        capacity = CapacityManager(
            discovery, slice_provisioner or NullProvisioner(),
            leadtime=leadtime,
            tier_preference=cap_cfg.tier_preference,
            tier_weights=tier_weights,
            stockout_reprobe_seconds=cap_cfg.stockout_reprobe_seconds,
            default_lead_seconds=cap_cfg.default_provision_lead_seconds,
            clock=clock)
        inventory.capacity = capacity
        # Node watch -> ledger: a deleted / NotReady / cordoned host marks
        # its slice lost the instant the event lands (the informer's nudge
        # then forces the immediate re-solve in wall-clock mode). Without
        # an informer, a raw watch registration serves the same feed.
        if hasattr(client, "add_nudge_listener"):
            def _capacity_node_feed(kind: str, event: str, obj) -> None:
                if kind == "Node":
                    capacity.on_node_event(event, obj)
            client.add_nudge_listener(_capacity_node_feed)
        else:
            client.watch("Node", capacity.on_node_event)

    # Input-health plane (WVA_HEALTH, default on): per-model trust ladder
    # over collector slice ages, scrape coverage, and control-plane
    # staleness, with a do-no-harm gate on final decisions — hold
    # last-known-good under degradation, freeze under blackout, K-tick
    # hysteresis before scale-downs resume (docs/design/health.md).
    # Disabled, decisions/statuses/traces are byte-identical to pre-health
    # builds in a fault-free world.
    health = None
    health_cfg = config.health_config()
    if health_cfg.enabled:
        from wva_tpu.health import InputHealthMonitor

        health = InputHealthMonitor(
            degraded_after=health_cfg.degraded_after_seconds,
            freeze_after=health_cfg.freeze_after_seconds,
            recovery_ticks=health_cfg.recovery_ticks)

    # Crash-restart resilience plane (WVA_RESILIENCE, default on): on
    # boot, re-seed health last-known-goods from durable VA status and
    # rehydrate capacity/forecast/lead-time soft state from the
    # rv-guarded checkpoint ConfigMap (WVA_CHECKPOINT); run every model
    # through a do-no-harm boot ramp (WVA_STARTUP_HOLD_TICKS) until its
    # inputs prove fresh; fence the apply phase with the lease epoch
    # (docs/design/resilience.md). Disabled, boots are blind (pre-change
    # behavior) and decisions/statuses/traces are byte-identical in a
    # fault-free world.
    boot_ramp = checkpointer = boot_report = None
    res_cfg = config.resilience_config()
    if res_cfg.enabled:
        from wva_tpu.config.helpers import system_namespace
        from wva_tpu.resilience import BootRamp, CheckpointStore, warm_start

        if res_cfg.checkpoint_enabled:
            checkpointer = CheckpointStore(
                client, namespace=system_namespace(),
                interval_ticks=res_cfg.checkpoint_interval_ticks,
                clock=clock)
        boot_report = warm_start(
            client, config.watch_namespace() or None, clock.now(),
            health=health, capacity=capacity, forecast=forecast_planner,
            store=checkpointer)
        if health is not None:
            # The ramp rides the health gate; without the health plane it
            # has no clamp path and stays inert.
            boot_ramp = BootRamp(res_cfg.startup_hold_ticks)

    # Analysis pool width 0 = auto, resolved by the metrics backend (same
    # rule as PrometheusSource's query concurrency): per-model collection
    # against HTTP Prometheus is I/O-bound and overlaps across workers; the
    # in-memory backend is pure Python, where extra threads only pay GIL
    # tax — and simulation/bench drivers stay single-threaded-deterministic.
    workers = config.engine_analysis_workers()
    if workers == 0:
        workers = 1 if tsdb is not None else DEFAULT_ANALYSIS_WORKERS
    engine = SaturationEngine(
        client=client, config=config, collector=collector, actuator=actuator,
        enforcer=enforcer, limiter=limiter, capacity_store=capacity_store,
        clock=clock, poll_interval=min(config.optimization_interval() / 2, 30.0),
        direct_actuator=direct_actuator, recorder=recorder,
        flight_recorder=flight,
        analysis_workers=workers,
        forecast_planner=forecast_planner,
        capacity=capacity,
        health=health,
        boot_ramp=boot_ramp,
        checkpointer=checkpointer)
    engine.boot_report = boot_report
    engine.grouped_collection = config.grouped_collection_enabled()
    engine.incremental_enabled = config.incremental_enabled()
    engine.resync_ticks = config.resync_ticks()
    engine.fp_delta_enabled = config.fp_delta_enabled()
    engine.fp_assert = config.fp_assert_enabled()
    # One-jitted-program decision plane (WVA_FUSED, default on;
    # docs/design/fused-plane.md): one device dispatch per SLO tick, and
    # the limiter's grant pass flips to the equivalent masked arithmetic.
    engine.fused_enabled = config.fused_enabled()
    if hasattr(limiter, "algorithm") and hasattr(limiter.algorithm,
                                                 "vectorized"):
        limiter.algorithm.vectorized = config.fused_enabled()
    # Vectorized decision stage (WVA_VEC_DECIDE, default on;
    # docs/design/fused-plane.md §host-vectorization): finalize/optimize/
    # enforce as fleet-wide row arithmetic instead of per-model loops.
    engine.vec_decide = config.vec_decide_enabled()
    engine.vec_assert = config.vec_assert_enabled()
    engine.solve_memo = config.solve_memo_enabled()
    # Sharded active-active engine (WVA_SHARDING, default off;
    # docs/design/sharding.md): N shard workers — each the existing
    # snapshot+analysis stack scoped to a consistent-hash partition under
    # its own Lease — publish per-shard summaries; THIS engine becomes the
    # fleet role (merge, fleet-level solve, limiter/health/apply). The
    # distinguished `fleet` shard rides the leader-election lease below.
    if config.sharding_enabled():
        from wva_tpu.shard import build_shard_plane

        engine.shard_plane = build_shard_plane(
            client=client, config=config, clock=clock, collector=collector,
            actuator=actuator, prom_source=prom_source,
            forecast_planner=forecast_planner, analysis_workers=workers,
            identity=f"{os.uname().nodename}-{os.getpid()}",
            registry=registry)
    # Multi-cluster federation plane (WVA_FEDERATION, default on;
    # docs/design/federation.md): constructed only when this cluster
    # names its region — capture export + arbiter election over the
    # ConfigMap bus on the hub cluster this kubeconfig points at. The
    # single-cluster default builds nothing and stays byte-identical to
    # pre-federation builds.
    if config.federation_enabled() and config.federation_config().region:
        from wva_tpu.federation import build_federation_plane

        engine.federation = build_federation_plane(
            client, config, clock=clock, registry=registry,
            identity=f"{os.uname().nodename}-{os.getpid()}")
    if flight is not None:
        engine.optimizer.flight_recorder = flight
    scale_from_zero = ScaleFromZeroEngine(client, config, datastore,
                                          direct_actuator, clock=clock,
                                          recorder=recorder,
                                          forecast_planner=forecast_planner)
    fastpath = FastPathMonitor(
        client, config, datastore, engine.executor,
        prom_source=prom_source, slo_analyzer=engine.slo_analyzer,
        clock=clock, forecast_planner=forecast_planner)
    # Self-observability: every engine loop reports its tick duration and
    # success/error outcome on /metrics (controller-runtime reconcile
    # metrics equivalent).
    for ex in (engine.executor, scale_from_zero.executor, fastpath.executor):
        ex.on_tick = registry.observe_tick
        # A tick longer than its poll interval means the loop is falling
        # behind its own cadence — surfaced as wva_tick_overruns_total.
        ex.on_overrun = registry.observe_tick_overrun

    # Obs plane (WVA_SPANS, default on; docs/design/observability.md):
    # span-structured tick tracing with a slow-tick flight recorder and
    # optional OTLP export. Strictly out-of-band — statuses, traces, and
    # goldens are byte-identical with the lever off OR on; off builds no
    # recorder at all (the off-lever is zero-cost, asserted by
    # `make bench-spans`).
    spans = None
    obs_cfg = config.obs_config()
    if obs_cfg.spans:
        from wva_tpu.obs import SpanRecorder

        spans = SpanRecorder(
            clock=clock, ring_size=obs_cfg.spans_ring,
            spill_path=obs_cfg.spans_path or None,
            slow_tick_ms=obs_cfg.slow_tick_ms,
            slow_dump_dir=obs_cfg.slow_dump_dir,
            otlp_endpoint=obs_cfg.otlp_endpoint,
            registry=registry, engine=engine.executor.name)
        engine.spans = spans
        if capacity is not None:
            capacity.spans = spans
        # Slow-tick flight recorder rides the overrun hook: a tick that
        # outran its poll interval dumps the span tree that explains it.
        def _engine_overrun(name: str,
                            _observe=registry.observe_tick_overrun,
                            _spans=spans) -> None:
            _observe(name)
            _spans.note_overrun(name)

        engine.executor.on_overrun = _engine_overrun

    watch_ns = config.watch_namespace() or ""
    va_reconciler = VariantAutoscalingReconciler(client, datastore, indexer,
                                                 clock=clock, recorder=recorder,
                                                 watch_namespace=watch_ns,
                                                 flight_recorder=flight)
    configmap_reconciler = ConfigMapReconciler(client, config, datastore,
                                               recorder=recorder)
    pool_reconciler = InferencePoolReconciler(client, datastore,
                                              watch_namespace=watch_ns)

    elector = None
    if config.leader_election_enabled():
        elector = LeaderElector(
            client, identity=f"{os.uname().nodename}-{os.getpid()}",
            config=LeaderElectorConfig(lease_name=config.leader_election_id()),
            clock=clock)
        # Engines only act while leading (reference cmd/main.go:378-425).
        engine.executor.gate = elector.is_leader
        scale_from_zero.executor.gate = elector.is_leader
        fastpath.executor.gate = elector.is_leader
        # A demoted manager must stop EVERY write path, not just the
        # engine tick: the scale-from-zero wake re-checks leadership
        # immediately before actuating (its worker pool can outlive a
        # mid-tick demotion), and the reconciler's decision-trigger drain
        # is leader-gated (DecisionCache entries from the leadership era
        # must not be flushed by a standby). See the non-leader-never-
        # writes regression in tests/test_resilience.py.
        scale_from_zero.write_gate = elector.is_leader
        va_reconciler.gate = elector.is_leader
        if res_cfg.enabled:
            # Lease-epoch fencing through the apply phase: captured at
            # tick start, re-checked between analyze and apply — a
            # deposed leader mid-tick can never actuate
            # (docs/design/resilience.md).
            engine.fence = elector.fencing_token

    return Manager(
        client=client, config=config, clock=clock, registry=registry,
        source_registry=source_registry, datastore=datastore, indexer=indexer,
        engine=engine, scale_from_zero=scale_from_zero, fastpath=fastpath,
        va_reconciler=va_reconciler, configmap_reconciler=configmap_reconciler,
        pool_reconciler=pool_reconciler, capacity_store=capacity_store,
        elector=elector, flight_recorder=flight, spans=spans,
    )
