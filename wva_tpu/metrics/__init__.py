"""WVA output metrics registry (reference ``internal/metrics/metrics.go:37-165``).

Four custom series with byte-identical names/labels to the reference so
Prometheus-Adapter/HPA/KEDA glue transfers verbatim:

- ``wva_replica_scaling_total`` (counter: variant_name, namespace, direction,
  reason, accelerator_type)
- ``wva_desired_replicas`` / ``wva_current_replicas`` / ``wva_desired_ratio``
  (gauges: variant_name, namespace, accelerator_type)

All series optionally carry ``controller_instance``. The registry renders
Prometheus text exposition for the metrics endpoint and can mirror into a
TimeSeriesDB so the emulation harness can close the HPA loop in-process.
"""

from __future__ import annotations

import threading

from wva_tpu.constants import (
    LABEL_ACCELERATOR_TYPE,
    LABEL_CONTROLLER_INSTANCE,
    LABEL_DIRECTION,
    LABEL_ENGINE,
    LABEL_NAMESPACE,
    LABEL_OUTCOME,
    LABEL_REASON,
    LABEL_VARIANT_NAME,
    WVA_CAPACITY_CHIPS_EFFECTIVE,
    WVA_CAPACITY_PREEMPTED_TOTAL,
    WVA_CAPACITY_PROVISION_LEAD_SECONDS,
    WVA_CAPACITY_PROVISION_TOTAL,
    WVA_CAPACITY_SLICES,
    WVA_CAPACITY_STOCKED_OUT,
    WVA_CURRENT_REPLICAS,
    WVA_DESIRED_RATIO,
    WVA_DESIRED_REPLICAS,
    WVA_ENGINE_TICK_DURATION_SECONDS,
    WVA_ENGINE_TICKS_TOTAL,
    WVA_FEDERATION_CAPTURE_AGE_SECONDS,
    WVA_FEDERATION_REGION_STATE,
    WVA_FEDERATION_SPILL_REPLICAS,
    WVA_FORECAST_DEMAND,
    WVA_FORECAST_DEMOTED,
    WVA_FORECAST_ERROR,
    WVA_FORECAST_LEAD_TIME_SECONDS,
    WVA_INFORMER_AGE_SECONDS,
    WVA_INFORMER_SYNCED,
    WVA_BOOT_RAMP_MODELS_HELD,
    WVA_BOOT_RECOVERED_ITEMS,
    WVA_CHECKPOINT_LAST_SAVE_TIMESTAMP,
    WVA_CHECKPOINT_WRITES,
    WVA_INPUT_HEALTH,
    WVA_LEADER_EPOCH,
    WVA_OTLP_EXPORTS_TOTAL,
    WVA_REPLICA_SCALING_TOTAL,
    WVA_SHARD_MODELS_OWNED,
    WVA_SLOW_TICK_DUMPS_TOTAL,
    WVA_SPANS_DROPPED_TOTAL,
    WVA_SPANS_TICKS_TOTAL,
    WVA_SHARD_OWNER,
    WVA_SHARD_REBALANCE_TOTAL,
    WVA_SHARD_SUMMARY_AGE_SECONDS,
    WVA_TICK_MODELS_ANALYZED,
    WVA_TICK_MODELS_SKIPPED,
    WVA_TICK_OBJECT_COPIES,
    WVA_TICK_OVERRUNS_TOTAL,
    WVA_TICK_PHASE_SECONDS,
    WVA_TRACE_DROPPED_TOTAL,
    WVA_TRACE_RECORDS_TOTAL,
    WVA_TRACE_WRITE_SECONDS,
    WVA_TREND_SERIES_SAMPLES,
    WVA_TREND_SERIES_STALENESS_SECONDS,
)

_LabelKey = tuple[tuple[str, str], ...]


class _Series:
    def __init__(self, name: str, kind: str, help_text: str) -> None:
        self.name = name
        self.kind = kind  # "gauge" | "counter"
        self.help_text = help_text
        self.values: dict[_LabelKey, float] = {}


class MetricsRegistry:
    def __init__(self, controller_instance: str = "", mirror_tsdb=None) -> None:
        self._mu = threading.RLock()
        self.controller_instance = controller_instance
        # Optional TimeSeriesDB mirror (emulation harness / bench).
        self.mirror_tsdb = mirror_tsdb
        # (name, label key) -> (last mirrored value, at) for the
        # same-value mirror throttle (see set_gauge).
        self._mirrored: dict[tuple, tuple[float, float]] = {}
        # (name, label key) -> {label: value} exemplar (span/trace ids
        # from the obs plane). Rendered as comment lines next to the
        # series — the classic text format has no exemplar syntax, and a
        # trailing OpenMetrics exemplar would break classic parsers.
        self._exemplars: dict[tuple, dict[str, str]] = {}
        self._series: dict[str, _Series] = {}
        self._register(WVA_REPLICA_SCALING_TOTAL, "counter",
                       "Total number of replica scaling operations")
        self._register(WVA_DESIRED_REPLICAS, "gauge",
                       "Desired number of replicas per variant")
        self._register(WVA_CURRENT_REPLICAS, "gauge",
                       "Current number of replicas per variant")
        self._register(WVA_DESIRED_RATIO, "gauge",
                       "Ratio of desired to current replicas per variant")
        self._register(WVA_ENGINE_TICK_DURATION_SECONDS, "gauge",
                       "Wall-clock duration of the last engine tick")
        self._register(WVA_ENGINE_TICKS_TOTAL, "counter",
                       "Engine ticks by outcome (success|error)")
        self._register(WVA_TICK_OVERRUNS_TOTAL, "counter",
                       "Ticks whose wall-clock duration exceeded the "
                       "engine's poll interval (the loop is falling "
                       "behind its own cadence)")
        self._register(WVA_INPUT_HEALTH, "gauge",
                       "Per-model input-health ladder: 1 for the current "
                       "state (fresh | degraded | blackout), 0 otherwise")
        self._register(WVA_TRACE_RECORDS_TOTAL, "counter",
                       "Decision-trace cycle records committed by the "
                       "flight recorder")
        self._register(WVA_TRACE_DROPPED_TOTAL, "counter",
                       "Decision-trace records or events dropped, by reason")
        self._register(WVA_TRACE_WRITE_SECONDS, "gauge",
                       "Wall-clock latency of the last trace spill write")
        self._register(WVA_FORECAST_LEAD_TIME_SECONDS, "gauge",
                       "Provisioning lead time the capacity planner uses "
                       "per model (measured actuation->ready quantile)")
        self._register(WVA_FORECAST_DEMAND, "gauge",
                       "Forecast demand at (now + lead time) from the "
                       "chosen forecaster")
        self._register(WVA_FORECAST_ERROR, "gauge",
                       "Rolling symmetric-MAPE per (model, forecaster) "
                       "from matured backtests")
        self._register(WVA_FORECAST_DEMOTED, "gauge",
                       "1 when the model is demoted to reactive scaling "
                       "(forecast rolling error over threshold)")
        self._register(WVA_TREND_SERIES_SAMPLES, "gauge",
                       "DemandTrend sliding-window sample count per model "
                       "series")
        self._register(WVA_TREND_SERIES_STALENESS_SECONDS, "gauge",
                       "Age of the newest DemandTrend sample per model "
                       "series")
        self._register(WVA_INFORMER_AGE_SECONDS, "gauge",
                       "Seconds since the informer's per-kind store was "
                       "last confirmed fresh (watch event or list)")
        self._register(WVA_INFORMER_SYNCED, "gauge",
                       "1 when the kind's initial informer LIST completed")
        self._register(WVA_TICK_MODELS_ANALYZED, "gauge",
                       "Models analyzed (dirty or resync) last engine tick")
        self._register(WVA_TICK_MODELS_SKIPPED, "gauge",
                       "Models skipped by an unchanged input fingerprint "
                       "last engine tick (prior decision re-emitted)")
        self._register(WVA_TICK_OBJECT_COPIES, "gauge",
                       "K8s object copies (copy-on-write clones) taken "
                       "during the last engine tick; ~0 at steady state")
        self._register(WVA_TICK_PHASE_SECONDS, "gauge",
                       "Wall-clock seconds the last engine tick spent per "
                       "phase (prepare | fingerprint | analyze | apply)")
        self._register(WVA_CAPACITY_SLICES, "gauge",
                       "Whole TPU slices per (variant, state): ready, "
                       "provisioning (in-flight with credible ETA), "
                       "preempted (watch-observed loss pending discovery)")
        self._register(WVA_CAPACITY_CHIPS_EFFECTIVE, "gauge",
                       "Chips the planner may allocate per variant: ready "
                       "plus provisioning-arriving-within-lead-time")
        self._register(WVA_CAPACITY_STOCKED_OUT, "gauge",
                       "1 while the (variant, tier) is pinned stocked-out "
                       "by the quota circuit breaker")
        self._register(WVA_CAPACITY_PROVISION_TOTAL, "counter",
                       "Slice provisioning requests by (variant, tier, "
                       "outcome)")
        self._register(WVA_CAPACITY_PREEMPTED_TOTAL, "counter",
                       "Spot slices lost to preemption")
        self._register(WVA_CAPACITY_PROVISION_LEAD_SECONDS, "gauge",
                       "Measured slice provisioning lead (submission -> "
                       "discovered ready) per (variant, tier)")
        self._register(WVA_BOOT_RAMP_MODELS_HELD, "gauge",
                       "Models still held DEGRADED-equivalent by the "
                       "post-restart boot ramp (inputs not yet proven "
                       "fresh)")
        self._register(WVA_BOOT_RECOVERED_ITEMS, "gauge",
                       "Items recovered by boot warm start, per source "
                       "(held | orders | stockouts | health_books | "
                       "trust | leadtime)")
        self._register(WVA_LEADER_EPOCH, "gauge",
                       "Lease epoch (leaseTransitions at acquisition) "
                       "this process acts under; exported only while "
                       "leading")
        self._register(WVA_CHECKPOINT_WRITES, "gauge",
                       "Resilience-checkpoint ConfigMap writes since "
                       "process start")
        self._register(WVA_CHECKPOINT_LAST_SAVE_TIMESTAMP, "gauge",
                       "Timestamp of the newest resilience-checkpoint "
                       "write")
        self._register(WVA_SHARD_OWNER, "gauge",
                       "1 while this process's lease manager holds the "
                       "shard's Lease (shard=\"0\"..\"N-1\" | \"fleet\")")
        self._register(WVA_SHARD_MODELS_OWNED, "gauge",
                       "Models the consistent-hash ring assigns to each "
                       "shard this tick")
        self._register(WVA_SHARD_REBALANCE_TOTAL, "gauge",
                       "Model ownership moves (shard join/leave/crash "
                       "rebalances) since process start")
        self._register(WVA_SHARD_SUMMARY_AGE_SECONDS, "gauge",
                       "Age of the newest summary the fleet solve "
                       "consumed from each shard")
        self._register(WVA_SPANS_TICKS_TOTAL, "counter",
                       "Tick span trees committed by the obs-plane span "
                       "recorder")
        self._register(WVA_SPANS_DROPPED_TOTAL, "counter",
                       "Spans or tick trees dropped by the span "
                       "recorder, by reason")
        self._register(WVA_SLOW_TICK_DUMPS_TOTAL, "counter",
                       "Slow-tick flight-recorder dumps written (full "
                       "span tree of an overrunning or over-threshold "
                       "tick), by reason")
        self._register(WVA_OTLP_EXPORTS_TOTAL, "counter",
                       "OTLP/HTTP span exports, by outcome")
        self._register(WVA_FEDERATION_SPILL_REPLICAS, "gauge",
                       "Replicas the federation arbiter's current plan "
                       "spills into each target region, per model")
        self._register(WVA_FEDERATION_REGION_STATE, "gauge",
                       "Arbiter classification per region (healthy | "
                       "degraded | blackout); one-hot")
        self._register(WVA_FEDERATION_CAPTURE_AGE_SECONDS, "gauge",
                       "Age of each region's newest ClusterCapture as "
                       "the arbiter last saw it")

    def _register(self, name: str, kind: str, help_text: str) -> None:
        self._series[name] = _Series(name, kind, help_text)

    def _key(self, labels: dict[str, str]) -> _LabelKey:
        if self.controller_instance:
            labels = {**labels, LABEL_CONTROLLER_INSTANCE: self.controller_instance}
        return tuple(sorted(labels.items()))

    # Mirror throttle: a same-valued gauge re-emission refreshes the TSDB
    # mirror at most this often. Prometheus-side consumers (the emulated
    # HPA) read instant values with the 5m lookback, so a ≤60s refresh of
    # an UNCHANGED value is observationally identical — while at fleet
    # scale the per-tick re-append of every quiet gauge was a measurable
    # slice of the apply phase. Changed values always mirror immediately.
    MIRROR_REFRESH_SECONDS = 60.0

    def set_gauge(self, name: str, labels: dict[str, str], value: float) -> None:
        with self._mu:
            mirror = self._set_gauge_locked(name, self._key(labels), value)
        if mirror is not None:
            self.mirror_tsdb.add_sample(name, dict(mirror[0]), mirror[1])

    def _set_gauge_locked(self, name: str, key: _LabelKey,
                          value: float) -> "tuple[_LabelKey, float] | None":
        """Gauge update under the registry lock; returns the (key, value)
        to mirror into the TSDB AFTER the lock is released (None when the
        same-value throttle absorbs it). Throttle bookkeeping stays under
        the lock (check-then-act on shared state); the TSDB append itself
        runs outside — it has its own locks, and a racing duplicate append
        of the same value would be harmless anyway."""
        self._series[name].values[key] = value
        if self.mirror_tsdb is None:
            return None
        now = self.mirror_tsdb.clock.now()
        last = self._mirrored.get((name, key))
        if (last is None or last[0] != value
                or now - last[1] >= self.MIRROR_REFRESH_SECONDS):
            if len(self._mirrored) >= 65536:
                # Bounded against label churn (deleted variants/
                # models): a reset only costs one extra mirror
                # append per series.
                self._mirrored.clear()
            self._mirrored[(name, key)] = (value, now)
            return (key, value)
        return None

    def inc_counter(self, name: str, labels: dict[str, str], delta: float = 1.0) -> None:
        with self._mu:
            series = self._series[name]
            key = self._key(labels)
            series.values[key] = series.values.get(key, 0.0) + delta
            value = series.values[key]
        if self.mirror_tsdb is not None:
            self.mirror_tsdb.add_sample(name, dict(key), value)

    def get(self, name: str, labels: dict[str, str]) -> float | None:
        with self._mu:
            return self._series[name].values.get(self._key(labels))

    def remove(self, name: str, labels: dict[str, str]) -> bool:
        """Drop one label set from a series (a deleted model's gauges must
        not keep exporting their last value forever). The TSDB mirror is
        left alone — its retention sweep ages the series out naturally."""
        with self._mu:
            key = self._key(labels)
            self._exemplars.pop((name, key), None)
            return self._series[name].values.pop(key, None) is not None

    def set_exemplar(self, name: str, labels: dict[str, str],
                     exemplar: dict[str, str]) -> None:
        """Attach an exemplar (span/trace ids) to one series label set.
        Surfaced as a ``# exemplar:`` comment line in the text exposition
        so operators can jump from a slow ``wva_tick_phase_seconds``
        sample straight to the span that timed it."""
        with self._mu:
            self._exemplars[(name, self._key(labels))] = dict(exemplar)

    def get_exemplar(self, name: str,
                     labels: dict[str, str]) -> dict[str, str] | None:
        with self._mu:
            return self._exemplars.get((name, self._key(labels)))

    def emit_replica_metrics(self, variant_name: str, namespace: str,
                             accelerator: str, current: int, desired: int) -> None:
        """Gauges for the external actuator (reference metrics.go:137-165).
        One shared encoding with the engine's batched apply path — see
        :meth:`emit_replica_metrics_batch` for the ratio rule."""
        self.emit_replica_metrics_batch(
            [(variant_name, namespace, accelerator, current, desired)])

    def emit_replica_metrics_batch(self, entries) -> None:
        """Replica gauges for ``entries`` of ``(variant_name, namespace,
        accelerator, current, desired)`` — the single shared encoding
        (scale-from-zero: current==0 && desired>0 => ratio = desired,
        since desired/0 is undefined but HPA needs a >1 signal). The
        engine's apply phase passes the whole fleet: the per-VA loop paid
        three lock round-trips per VA (3N acquisitions per tick); the
        fleet's gauge updates ride ONE lock pass, with the TSDB mirror
        appends collected and performed outside it (same values, same
        throttle — only the locking shape changes)."""
        mirrors: list[tuple[str, _LabelKey, float]] = []
        with self._mu:
            for variant_name, namespace, accelerator, current, desired \
                    in entries:
                key = self._key({
                    LABEL_VARIANT_NAME: variant_name,
                    LABEL_NAMESPACE: namespace,
                    LABEL_ACCELERATOR_TYPE: accelerator,
                })
                ratio = desired / current if current > 0 else float(desired)
                for name, value in (
                        (WVA_DESIRED_REPLICAS, float(desired)),
                        (WVA_CURRENT_REPLICAS, float(current)),
                        (WVA_DESIRED_RATIO, ratio)):
                    mirror = self._set_gauge_locked(name, key, value)
                    if mirror is not None:
                        mirrors.append((name, mirror[0], mirror[1]))
        for name, key, value in mirrors:
            self.mirror_tsdb.add_sample(name, dict(key), value)

    def observe_tick(self, engine: str, seconds: float, ok: bool) -> None:
        """Self-observability per engine loop (the reference relies on
        controller-runtime's reconcile duration/total for this)."""
        self.set_gauge(WVA_ENGINE_TICK_DURATION_SECONDS,
                       {LABEL_ENGINE: engine}, seconds)
        self.inc_counter(WVA_ENGINE_TICKS_TOTAL, {
            LABEL_ENGINE: engine,
            LABEL_OUTCOME: "success" if ok else "error",
        })

    def observe_tick_overrun(self, engine: str) -> None:
        """A tick ran longer than the engine's poll interval: the loop is
        falling behind its cadence (latency injection, backend timeouts,
        or genuine fleet growth). Counted separately from tick outcomes —
        an overrunning loop usually still 'succeeds'."""
        self.inc_counter(WVA_TICK_OVERRUNS_TOTAL, {LABEL_ENGINE: engine})

    def observe_trace_record(self, engine: str) -> None:
        """Flight-recorder health: one committed cycle record."""
        self.inc_counter(WVA_TRACE_RECORDS_TOTAL, {LABEL_ENGINE: engine})

    def observe_trace_drop(self, reason: str) -> None:
        """Flight-recorder health: a record/event lost (ring eviction
        without spill, spill write error, encode error, no open cycle)."""
        self.inc_counter(WVA_TRACE_DROPPED_TOTAL, {LABEL_REASON: reason})

    def observe_trace_write(self, seconds: float) -> None:
        """Flight-recorder health: last spill write latency."""
        self.set_gauge(WVA_TRACE_WRITE_SECONDS, {}, seconds)

    def observe_span_tick(self, engine: str) -> None:
        """Obs plane: one committed tick span tree."""
        self.inc_counter(WVA_SPANS_TICKS_TOTAL, {LABEL_ENGINE: engine})

    def observe_span_drop(self, reason: str) -> None:
        """Obs plane: a span or tick tree lost (ring eviction without
        spill, spill error/backlog, encode error, span outside a tick)."""
        self.inc_counter(WVA_SPANS_DROPPED_TOTAL, {LABEL_REASON: reason})

    def observe_slow_tick_dump(self, reason: str) -> None:
        """Obs plane: a slow-tick flight-recorder dump was written."""
        self.inc_counter(WVA_SLOW_TICK_DUMPS_TOTAL, {LABEL_REASON: reason})

    def observe_otlp_export(self, outcome: str) -> None:
        """Obs plane: one OTLP export attempt (success|error|dropped)."""
        self.inc_counter(WVA_OTLP_EXPORTS_TOTAL, {LABEL_OUTCOME: outcome})

    def record_scaling(self, variant_name: str, namespace: str, accelerator: str,
                       direction: str, reason: str) -> None:
        self.inc_counter(WVA_REPLICA_SCALING_TOTAL, {
            LABEL_VARIANT_NAME: variant_name,
            LABEL_NAMESPACE: namespace,
            LABEL_ACCELERATOR_TYPE: accelerator,
            LABEL_DIRECTION: direction,
            LABEL_REASON: reason,
        })

    def render_text(self) -> str:
        """Prometheus text exposition format."""
        lines = []
        with self._mu:
            for name in sorted(self._series):
                series = self._series[name]
                lines.append(f"# HELP {name} {series.help_text}")
                lines.append(f"# TYPE {name} {series.kind}")
                for key in sorted(series.values):
                    label_str = ",".join(f'{k}="{_escape(v)}"' for k, v in key)
                    suffix = f"{{{label_str}}}" if label_str else ""
                    lines.append(f"{name}{suffix} {series.values[key]:g}")
                    exemplar = self._exemplars.get((name, key))
                    if exemplar:
                        ex_str = ",".join(
                            f'{k}="{_escape(str(v))}"'
                            for k, v in sorted(exemplar.items()))
                        # Comment line, not a trailing OpenMetrics
                        # exemplar: classic-format parsers must keep
                        # scraping this endpoint unchanged.
                        lines.append(f"# exemplar: {name}{suffix} "
                                     f"{{{ex_str}}}")
        return "\n".join(lines) + "\n"


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
