"""InferencePool reconciler
(reference ``internal/controller/inferencepool_reconciler.go:41-103``).

Watches InferencePools (v1 or v1alpha2, chosen by POOL_GROUP), converts them
to EndpointPools, and stores them in the datastore — which spins up the EPP
pod-scraping source for the pool.
"""

from __future__ import annotations

import logging

from wva_tpu.datastore import Datastore
from wva_tpu.k8s.client import DELETED, KubeClient
from wva_tpu.k8s.objects import InferencePool
from wva_tpu.utils.pool import endpoint_pool_from_inference_pool

log = logging.getLogger(__name__)


class InferencePoolReconciler:
    def __init__(self, client: KubeClient, datastore: Datastore,
                 watch_namespace: str = "") -> None:
        self.client = client
        self.datastore = datastore
        self.watch_namespace = watch_namespace

    def setup(self) -> None:
        self.client.watch(InferencePool.KIND, self._on_event)
        # Seed from existing pools (scoped in namespace-scoped mode).
        for pool in self.client.list(InferencePool.KIND,
                                     namespace=self.watch_namespace or None):
            self.reconcile(pool)

    def _on_event(self, event: str, pool: InferencePool) -> None:
        if self.watch_namespace \
                and pool.metadata.namespace != self.watch_namespace:
            return
        if event == DELETED:
            self.datastore.pool_delete(pool.metadata.name)
            self.datastore.namespace_untrack(
                InferencePool.KIND, pool.metadata.name, pool.metadata.namespace)
            return
        self.reconcile(pool)

    def reconcile(self, pool: InferencePool) -> None:
        endpoint_pool = endpoint_pool_from_inference_pool(pool)
        self.datastore.pool_set(endpoint_pool)
        self.datastore.namespace_track(
            InferencePool.KIND, pool.metadata.name, pool.metadata.namespace)
        log.info("Registered InferencePool %s/%s (EPP service %s)",
                 pool.metadata.namespace, pool.metadata.name,
                 endpoint_pool.endpoint_picker.service_name)
