"""ConfigMap reconciler + bootstrap
(reference ``internal/controller/configmap_{reconciler,bootstrap,helpers}.go``).

Keeps the unified Config synced to the well-known ConfigMaps (saturation
scaling, scale-to-zero), with global (system namespace) + namespace-local
override scoping. The pre-manager bootstrap read gates the readiness probe.
"""

from __future__ import annotations

import logging

import yaml

from wva_tpu.config import (
    Config,
    detect_immutable_parameter_changes,
    parse_saturation_configmap,
    parse_scale_to_zero_configmap,
    saturation_configmap_name,
    system_namespace,
)
from wva_tpu.config.scale_to_zero import DEFAULT_SCALE_TO_ZERO_CONFIGMAP_NAME
from wva_tpu.config.slo import (
    SLO_CONFIGMAP_DATA_KEY,
    SLO_CONFIGMAP_NAME,
    parse_slo_config,
)
from wva_tpu.config.validation import ImmutableParameterError
from wva_tpu.controller.predicates import configmap_event_allowed
from wva_tpu.datastore import Datastore
from wva_tpu.k8s.client import DELETED, KubeClient, NotFoundError
from wva_tpu.k8s.objects import ConfigMap

log = logging.getLogger(__name__)


class ConfigMapReconciler:
    def __init__(self, client: KubeClient, config: Config,
                 datastore: Datastore, recorder=None) -> None:
        self.client = client
        self.config = config
        self.datastore = datastore
        self.recorder = recorder  # k8s.events.EventRecorder | None

    def setup(self) -> None:
        self.client.watch(ConfigMap.KIND, self._on_event)

    def _on_event(self, event: str, cm: ConfigMap) -> None:
        if event == DELETED:
            # Namespace-local ConfigMap deleted: fall back to global.
            if cm.metadata.namespace != system_namespace() and \
                    cm.metadata.name in (saturation_configmap_name(),
                                         DEFAULT_SCALE_TO_ZERO_CONFIGMAP_NAME,
                                         SLO_CONFIGMAP_NAME):
                self.config.remove_namespace_config(cm.metadata.namespace)
            return
        if not configmap_event_allowed(self.client, self.datastore, cm):
            return
        self.reconcile(cm)

    def reconcile(self, cm: ConfigMap) -> None:
        """Classify global vs namespace-local and apply
        (reference configmap_reconciler.go:49-98)."""
        ns = cm.metadata.namespace
        scope_ns = "" if ns == system_namespace() else ns
        try:
            if cm.metadata.name == saturation_configmap_name():
                self._handle_saturation(cm, scope_ns)
            elif cm.metadata.name == DEFAULT_SCALE_TO_ZERO_CONFIGMAP_NAME:
                self._handle_scale_to_zero(cm, scope_ns)
            elif cm.metadata.name == SLO_CONFIGMAP_NAME:
                self._handle_slo(cm, scope_ns)
            self.config.mark_configmaps_bootstrap_complete()
        except ImmutableParameterError as e:
            self.config.record_configmaps_sync_error(str(e))
            log.error("Rejected ConfigMap %s/%s: %s", ns, cm.metadata.name, e)
            if self.recorder is not None:
                self.recorder.warning(cm, "ImmutableParameterChange", str(e))

    def _handle_saturation(self, cm: ConfigMap, scope_ns: str) -> None:
        detect_immutable_parameter_changes(self.config, cm.data)
        configs = parse_saturation_configmap(cm.data)
        self.config.update_saturation_config_for_namespace(scope_ns, configs)
        log.info("Applied saturation config from %s/%s (%d entries, scope=%s)",
                 cm.metadata.namespace, cm.metadata.name, len(configs),
                 scope_ns or "global")

    def _handle_scale_to_zero(self, cm: ConfigMap, scope_ns: str) -> None:
        parsed = parse_scale_to_zero_configmap(cm.data)
        self.config.update_scale_to_zero_config_for_namespace(scope_ns, parsed)
        log.info("Applied scale-to-zero config from %s/%s (%d models, scope=%s)",
                 cm.metadata.namespace, cm.metadata.name, len(parsed),
                 scope_ns or "global")

    def _handle_slo(self, cm: ConfigMap, scope_ns: str) -> None:
        text = cm.data.get(SLO_CONFIGMAP_DATA_KEY, "")
        try:
            parsed = parse_slo_config(text) if text else None
        except (ValueError, yaml.YAMLError) as e:
            # Keep the previous config; a bad edit must not crash startup or
            # drop the running SLO config (sibling parsers skip-and-log too).
            self.config.record_configmaps_sync_error(str(e))
            log.error("Rejected SLO ConfigMap %s/%s: %s",
                      cm.metadata.namespace, cm.metadata.name, e)
            if self.recorder is not None:
                self.recorder.warning(cm, "InvalidSLOConfig", str(e))
            return
        self.config.update_slo_config_for_namespace(scope_ns, parsed)
        n_classes = len(parsed.service_classes) if parsed else 0
        n_profiles = len(parsed.profiles) if parsed else 0
        log.info("Applied SLO config from %s/%s (%d classes, %d profiles, "
                 "scope=%s)", cm.metadata.namespace, cm.metadata.name,
                 n_classes, n_profiles, scope_ns or "global")

    def bootstrap_initial_configmaps(self) -> bool:
        """Pre-manager read of the global ConfigMaps; marks bootstrap state
        that gates readiness (reference configmap_bootstrap.go:16-61).
        Missing ConfigMaps are not an error (defaults apply)."""
        ns = system_namespace()
        found_any = False
        for name in (saturation_configmap_name(),
                     DEFAULT_SCALE_TO_ZERO_CONFIGMAP_NAME, SLO_CONFIGMAP_NAME):
            try:
                cm = self.client.get(ConfigMap.KIND, ns, name)
            except NotFoundError:
                log.info("Bootstrap: ConfigMap %s/%s not found, using defaults",
                         ns, name)
                continue
            self.reconcile(cm)
            found_any = True
        self.config.mark_configmaps_bootstrap_complete()
        return found_any
