"""Event filters (reference ``internal/controller/predicates.go:31-243``)."""

from __future__ import annotations

import logging

from wva_tpu.api.v1alpha1 import VariantAutoscaling
from wva_tpu.config import configmap_name, saturation_configmap_name, system_namespace
from wva_tpu.config.scale_to_zero import DEFAULT_SCALE_TO_ZERO_CONFIGMAP_NAME
from wva_tpu.config.slo import SLO_CONFIGMAP_NAME
from wva_tpu.constants import (
    CONTROLLER_INSTANCE_LABEL_KEY,
    NAMESPACE_CONFIG_ENABLED_LABEL_KEY,
    NAMESPACE_EXCLUDE_ANNOTATION_KEY,
)
from wva_tpu.k8s.client import ADDED, DELETED, KubeClient, NotFoundError
from wva_tpu.k8s.objects import ConfigMap, Namespace
from wva_tpu.utils.variant import get_controller_instance

log = logging.getLogger(__name__)


def namespace_excluded(client: KubeClient, namespace: str) -> bool:
    """Namespace opted out via the exclude annotation
    (reference configmap_helpers.go isNamespaceExcluded)."""
    if not namespace:
        return False
    try:
        ns: Namespace = client.get(Namespace.KIND, "", namespace)
    except NotFoundError:
        return False
    return ns.metadata.annotations.get(NAMESPACE_EXCLUDE_ANNOTATION_KEY) == "true"


def namespace_config_enabled(client: KubeClient, namespace: str) -> bool:
    """Namespace opted IN for namespace-local ConfigMaps via label."""
    if not namespace:
        return False
    try:
        ns: Namespace = client.get(Namespace.KIND, "", namespace)
    except NotFoundError:
        return False
    return ns.metadata.labels.get(NAMESPACE_CONFIG_ENABLED_LABEL_KEY) == "true"


def va_event_allowed(client: KubeClient, event: str, va: VariantAutoscaling) -> bool:
    """VA predicate (reference predicates.go:101+): only CREATE events pass
    (the periodic loop covers update/delete); excluded namespaces and foreign
    controller instances are filtered."""
    if event != ADDED:
        return False
    if namespace_excluded(client, va.metadata.namespace):
        return False
    instance = get_controller_instance()
    if instance and va.metadata.labels.get(CONTROLLER_INSTANCE_LABEL_KEY) != instance:
        return False
    return True


def deployment_event_allowed(event: str) -> bool:
    """Only create/delete Deployment events matter — spec changes flow
    through the periodic loop (reference predicates.go deployment filter)."""
    return event in (ADDED, DELETED)


def well_known_configmap_names() -> set[str]:
    return {
        configmap_name(),
        saturation_configmap_name(),
        DEFAULT_SCALE_TO_ZERO_CONFIGMAP_NAME,
        SLO_CONFIGMAP_NAME,
    }


def configmap_event_allowed(client: KubeClient, datastore, cm: ConfigMap) -> bool:
    """ConfigMap filter: well-known names, in the system namespace or a
    tracked/opted-in namespace (reference predicates.go:31-99)."""
    if cm.metadata.name not in well_known_configmap_names():
        return False
    ns = cm.metadata.namespace
    if ns == system_namespace():
        return True
    if namespace_excluded(client, ns):
        return False
    if datastore is not None and datastore.is_namespace_tracked(ns):
        return True
    return namespace_config_enabled(client, ns)
