"""K8s reconcilers (reference ``internal/controller``)."""

from wva_tpu.controller.va_reconciler import VariantAutoscalingReconciler
from wva_tpu.controller.configmap_reconciler import ConfigMapReconciler
from wva_tpu.controller.inferencepool_reconciler import InferencePoolReconciler
from wva_tpu.controller.predicates import (
    configmap_event_allowed,
    deployment_event_allowed,
    namespace_excluded,
    va_event_allowed,
)

__all__ = [
    "VariantAutoscalingReconciler",
    "ConfigMapReconciler",
    "InferencePoolReconciler",
    "configmap_event_allowed",
    "deployment_event_allowed",
    "namespace_excluded",
    "va_event_allowed",
]
