"""VariantAutoscaling reconciler
(reference ``internal/controller/variantautoscaling_controller.go:90-367``).

Event-driven status writer: resolves the scale target (TargetResolved
condition), consumes the engine's DecisionCache into
``status.desiredOptimizedAlloc`` + MetricsAvailable condition, and tracks the
namespace for ConfigMap watching. Triggered by VA creates, Deployment
create/delete (mapped through the scale-target index), and DecisionTrigger
events from the engines.
"""

from __future__ import annotations

import logging
import queue
import threading

from wva_tpu.api.v1alpha1 import (
    CrossVersionObjectReference,
    REASON_TARGET_FOUND,
    REASON_TARGET_NOT_FOUND,
    TYPE_METRICS_AVAILABLE,
    TYPE_TARGET_RESOLVED,
    VariantAutoscaling,
)
from wva_tpu.datastore import Datastore
from wva_tpu.engines import common
from wva_tpu.indexers import Indexer
from wva_tpu.k8s.client import (
    ADDED,
    DELETED,
    ConflictError,
    KubeClient,
    NotFoundError,
)
from wva_tpu.k8s.objects import Deployment, LeaderWorkerSet, ServiceMonitor, clone
from wva_tpu.utils.clock import SYSTEM_CLOCK, Clock
from wva_tpu.utils.variant import (
    update_va_status_with_backoff,
    va_status_material,
)
from wva_tpu.controller.predicates import deployment_event_allowed, va_event_allowed

log = logging.getLogger(__name__)


class VariantAutoscalingReconciler:
    def __init__(self, client: KubeClient, datastore: Datastore,
                 indexer: Indexer, clock: Clock | None = None,
                 recorder=None, watch_namespace: str = "",
                 flight_recorder=None) -> None:
        self.client = client
        self.datastore = datastore
        self.indexer = indexer
        self.clock = clock or SYSTEM_CLOCK
        self.recorder = recorder  # k8s.events.EventRecorder | None
        # Optional blackbox.FlightRecorder: status writes that consume an
        # engine decision are appended to the deciding cycle's trace record
        # (its ``post`` list) — the actuation tail of the audit trail.
        self.flight_recorder = flight_recorder
        # Namespace-scoped mode: besides the client's scoped watch streams
        # (RestKubeClient), events are filtered here too so the behavior is
        # identical under any KubeClient (FakeCluster dispatches
        # cluster-wide) and two scoped installs never fight over VAs.
        self.watch_namespace = watch_namespace
        # Leader gate for the decision-trigger drain (None = always;
        # build_manager wires the elector's is_leader when election is
        # on). DecisionCache is populated only while this process leads —
        # but entries (and queued triggers) from a leadership era must not
        # be flushed AFTER demotion: the new leader recomputes, and a
        # standby replaying stale decisions would be a second writer.
        # Spec/ConfigMap watch reconciliation is not gated — only the
        # decision-consuming trigger drain.
        self.gate = None

    # --- wiring (reference SetupWithManager :291-319) ---

    # The controller's own metric-scrape contract: losing this ServiceMonitor
    # silently starves HPA/KEDA of wva_* gauges (reference
    # variantautoscaling_controller.go:330-367 — deletion alerting only).
    # The chart names its ServiceMonitor "<release>-controller-metrics" and
    # sets WVA_SERVICEMONITOR_NAME to match (templates/manager/
    # deployment.yaml); the default covers kustomize installs.
    @property
    def servicemonitor_name(self) -> str:
        import os

        return os.environ.get("WVA_SERVICEMONITOR_NAME",
                              "wva-tpu-controller-manager-metrics")

    def _in_scope(self, namespace: str) -> bool:
        return not self.watch_namespace or namespace == self.watch_namespace

    def setup(self) -> None:
        self.client.watch(VariantAutoscaling.kind, self._on_va_event)
        self.client.watch(Deployment.KIND, self._on_deployment_event)
        self.client.watch(LeaderWorkerSet.KIND, self._on_deployment_event)
        self.client.watch(ServiceMonitor.KIND, self._on_servicemonitor_event)

    def _on_servicemonitor_event(self, event: str, sm) -> None:
        if event != DELETED or sm.metadata.name != self.servicemonitor_name:
            return
        log.warning(
            "ServiceMonitor %s/%s deleted: wva_* metrics will stop being "
            "scraped and HPA/KEDA actuation will starve",
            sm.metadata.namespace, sm.metadata.name)
        if self.recorder is not None:
            self.recorder.warning(
                sm, "ServiceMonitorDeleted",
                "Controller metrics ServiceMonitor deleted; external "
                "actuation (HPA/KEDA) will lose the wva_desired_replicas "
                "signal")

    def _on_va_event(self, event: str, va: VariantAutoscaling) -> None:
        if not self._in_scope(va.metadata.namespace):
            return
        if event == DELETED:
            self.datastore.namespace_untrack(
                VariantAutoscaling.kind, va.metadata.name, va.metadata.namespace)
            common.DecisionCache.delete(va.metadata.name, va.metadata.namespace)
            return
        if not va_event_allowed(self.client, event, va):
            return
        self.reconcile(va.metadata.name, va.metadata.namespace)

    def _on_deployment_event(self, event: str, target) -> None:
        """Map scale-target create/delete (Deployment or LeaderWorkerSet) to
        the owning VA via the index — keyed by the event object's own
        kind/apiVersion (reference handleDeploymentEvent :258-288)."""
        if not deployment_event_allowed(event):
            return
        if not self._in_scope(target.metadata.namespace):
            return
        try:
            va = self.indexer.find_va_for_scale_target(
                CrossVersionObjectReference(
                    kind=target.KIND, name=target.metadata.name,
                    api_version=target.API_VERSION),
                target.metadata.namespace)
        except Exception as e:  # noqa: BLE001
            log.debug("scale-target->VA mapping failed: %s", e)
            return
        if va is not None:
            self.reconcile(va.metadata.name, va.metadata.namespace)

    def drain_triggers(self, max_events: int = 1000) -> int:
        """Consume pending DecisionTrigger events (the channel-watch analogue;
        reference SetupWithManager :313). Returns processed count."""
        processed = 0
        if self.gate is not None and not self.gate():
            return 0  # demoted: triggers stay queued for the leader
        while processed < max_events:
            try:
                ev = common.DecisionTrigger.get_nowait()
            except queue.Empty:
                break
            try:
                self.reconcile(ev.name, ev.namespace)
            except Exception as e:  # noqa: BLE001 — same isolation as
                # run_trigger_loop: one VA's transient apiserver failure
                # (storm-injected 503s) must not abort the whole drain and
                # strand every later trigger in the queue.
                log.error("reconcile %s/%s failed: %s",
                          ev.namespace, ev.name, e)
            processed += 1
        return processed

    def run_trigger_loop(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                ev = common.DecisionTrigger.get(timeout=0.2)
            except queue.Empty:
                continue
            if self.gate is not None and not self.gate():
                continue  # demoted mid-wait: drop the stale trigger
            try:
                self.reconcile(ev.name, ev.namespace)
            except Exception as e:  # noqa: BLE001
                log.error("reconcile %s/%s failed: %s", ev.namespace, ev.name, e)

    # --- reconcile (reference :90-235) ---

    def reconcile(self, name: str, namespace: str) -> None:
        try:
            # Live reads are frozen shared views; the reconciler mutates
            # conditions in place, so take the copy-on-write clone up
            # front (reconciles run per trigger, not per VA per tick).
            va = clone(self.client.get(VariantAutoscaling.kind, namespace,
                                       name))
        except NotFoundError:
            self.datastore.namespace_untrack(VariantAutoscaling.kind, name, namespace)
            common.DecisionCache.delete(name, namespace)
            return
        if va.metadata.deletion_timestamp is not None:
            self.datastore.namespace_untrack(VariantAutoscaling.kind, name, namespace)
            return

        self.datastore.namespace_track(VariantAutoscaling.kind, name, namespace)
        now = self.clock.now()
        prev_material = va_status_material(va)

        # Resolve the scale target (any supported kind) -> TargetResolved.
        try:
            kind = va.spec.scale_target_ref.kind or Deployment.KIND
            self.client.get(kind, namespace, va.spec.scale_target_ref.name)
            va.set_condition(TYPE_TARGET_RESOLVED, "True", REASON_TARGET_FOUND,
                             f"Scale target {va.spec.scale_target_ref.name} found",
                             now=now)
        except NotFoundError:
            va.set_condition(TYPE_TARGET_RESOLVED, "False", REASON_TARGET_NOT_FOUND,
                             f"Scale target {va.spec.scale_target_ref.name} not found",
                             now=now)
            if self.recorder is not None:
                self.recorder.warning(
                    va, REASON_TARGET_NOT_FOUND,
                    f"Scale target {va.spec.scale_target_ref.kind} "
                    f"{va.spec.scale_target_ref.name} not found")
            if va_status_material(va) != prev_material:
                try:
                    update_va_status_with_backoff(self.client, va)
                except ConflictError:
                    # Lost a write race (engine/scale-from-zero status PUT
                    # since our read). Level-triggered: the next trigger or
                    # poll re-reconciles from a fresh read.
                    log.debug("reconcile %s/%s: status write conflicted; "
                              "deferring to the next trigger", namespace, name)
            return

        # Consume the engine's decision.
        decision, decision_source, decision_cycle = \
            common.DecisionCache.get_entry(name, namespace)
        if decision is not None:
            if decision.accelerator_name or decision.target_replicas:
                # ScalingDecision Events are emitted by the deciding engine
                # (saturation / scale-from-zero), which sees the real
                # old->new transition; by the time this reconciler runs the
                # status already matches the cache, so emitting here would
                # only double-report in a stale-trigger race.
                va.status.desired_optimized_alloc = \
                    common.decision_to_optimized_alloc(decision)
            va.set_condition(
                TYPE_METRICS_AVAILABLE,
                "True" if decision.metrics_available else "False",
                decision.metrics_reason or "MetricsMissing",
                decision.metrics_message, now=now)
        # Write-on-change only: the engine triggers a reconcile every tick
        # per VA, and a no-op PUT per trigger doubles the apiserver write
        # load for nothing (the reference's event-driven reconciler has the
        # same property implicitly — patches only carry diffs).
        wrote = va_status_material(va) != prev_material
        if wrote:
            try:
                update_va_status_with_backoff(self.client, va)
            except ConflictError:
                # Lost a write race; re-reconcile on the next trigger/poll
                # from a fresh read (the trace event below records honestly
                # that no status write landed this pass).
                log.debug("reconcile %s/%s: status write conflicted; "
                          "deferring to the next trigger", namespace, name)
                wrote = False
        # Attribute the trace event only when the consumed decision came
        # from the exact cycle currently accepting events: DecisionCache is
        # also written by the (untraced) scale-from-zero engine, and in
        # production this reconciler runs on its own thread, so a reconcile
        # consuming cycle N's decision can arrive after cycle N+1 opened.
        # Either way the event must not land in an unrelated cycle's audit
        # record with a contradicting desired value. The compare-and-append
        # is atomic inside the recorder — checking cycle_info() here and
        # then appending would race the engine's begin_cycle.
        if self.flight_recorder is not None and decision is not None:
            self.flight_recorder.record_stage_if(
                (decision_source, decision_cycle), "reconcile", {
                    "variant": name, "namespace": namespace,
                    "source": decision_source,
                    "desired": decision.target_replicas,
                    "accelerator": decision.accelerator_name,
                    "metrics_available": decision.metrics_available,
                    "wrote_status": wrote,
                })
