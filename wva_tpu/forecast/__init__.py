"""Predictive capacity planner (docs/design/forecast.md).

The reactive engine sizes capacity for demand as observed NOW; on TPU a
replica decided now becomes ready one provisioning horizon later (2-7 min,
BASELINE.md), so a reactive decision is sized for stale demand by
construction. This package upgrades the single-slope ``DemandTrend``
anticipation into a real forecasting plane:

- :mod:`wva_tpu.forecast.history` — per-model demand history store (the
  ring-buffer column layout from ``collector/source/promql.py``);
- :mod:`wva_tpu.forecast.forecasters` — the forecaster registry (seasonal
  naive, Holt double / Holt-Winters triple exponential smoothing, linear
  trend floor), all models fitted in ONE padded jitted JAX call per tick;
- :mod:`wva_tpu.forecast.leadtime` — measured actuation->ready lead times,
  per (accelerator, model) quantile, replacing the static provisioning-
  horizon constant;
- :mod:`wva_tpu.forecast.planner` — forecast-at-(now + lead time) turned
  into a proactive replica floor + scale-from-zero pre-wake, with
  auto-demotion to reactive when the rolling backtest error exceeds the
  configured threshold;
- :mod:`wva_tpu.forecast.backtest` — offline backtest CLI
  (``python -m wva_tpu forecast backtest <trace.jsonl>``) scoring recorded
  decision traces against every candidate forecaster (MAPE + under/over-
  provision cost), gated by ``make backtest-golden``.
"""

from wva_tpu.forecast.apply import apply_forecast_floors
from wva_tpu.forecast.history import DemandHistoryStore
from wva_tpu.forecast.leadtime import LeadTimeEstimator

__all__ = [
    "CapacityPlanner",
    "DemandHistoryStore",
    "ForecastPlan",
    "LeadTimeEstimator",
    "apply_forecast_floors",
]


def __getattr__(name):
    # The planner pulls in the JAX-backed forecaster registry; loading it
    # lazily keeps the package importable without paying (or requiring)
    # JAX — the offline replay CLI applies recorded floors with
    # ``apply_forecast_floors`` alone, which is pure-Python dict math.
    if name in ("CapacityPlanner", "ForecastPlan"):
        from wva_tpu.forecast import planner

        return getattr(planner, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
