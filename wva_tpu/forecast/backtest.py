"""Offline forecaster backtest over recorded decision traces.

``python -m wva_tpu forecast backtest <trace.jsonl>`` replays the per-model
demand series out of a flight-recorder trace (``wva_tpu.blackbox``) through
every candidate forecaster — exactly the walk-forward loop the live
planner's trust gate runs, but offline and over the whole trace at once —
and scores each forecaster's MAPE plus under/over-provision cost. This is
how an operator picks ``WVA_FORECAST_*`` knobs against their OWN production
trace instead of trusting defaults (the AIBrix move: tune proactive scaling
by simulation over recorded traces), and how CI gates forecaster
regressions (``make backtest-golden`` against the committed golden report).

Scoring:

- **mape** — symmetric MAPE of forecast-at-(t + lead) vs realized demand
  at t + lead, in [0, 2].
- **under_provision_cost** — sum of demand the forecast would have left
  unserved (realized - forecast, clipped at 0), normalized by total
  realized demand. Under-provision is backlog and SLO misses — the
  expensive direction on slow-provisioning TPUs.
- **over_provision_cost** — sum of forecast excess over realized demand,
  normalized; the chip-seconds the floor would have wasted.

Only V2/SLO cycles carry an ``AnalyzerResult.total_demand``; V1 cycles are
counted and skipped (the percentage analyzer has no demand quantity).
"""

from __future__ import annotations

import argparse
import json
import sys

from wva_tpu.blackbox.replay import load_trace
from wva_tpu.forecast import forecasters as fc
from wva_tpu.forecast.history import DemandHistoryStore

# Score a matured forecast only when a realized sample exists within this
# fraction of the lead time of the target instant.
MATCH_TOLERANCE_FRACTION = 0.5


def extract_series(records: list[dict]) -> tuple[dict[str, list], int]:
    """Per-model (t, demand) series from trace records; returns
    (series-by-key, v1 model records skipped)."""
    series: dict[str, list[tuple[float, float]]] = {}
    skipped = 0
    for rec in records:
        if rec.get("outcome") not in ("", "success", None):
            continue
        ts = float(rec.get("ts", 0.0))
        for m in rec.get("models") or []:
            result = m.get("result")
            if result is None or "total_demand" not in result:
                skipped += 1
                continue
            key = f"{m.get('namespace', '')}|{m.get('model_id', '')}"
            t = float(result.get("analyzed_at") or ts)
            series.setdefault(key, []).append(
                (t, float(result["total_demand"])))
    for vals in series.values():
        vals.sort()
    return series, skipped


def backtest_series(points: list[tuple[float, float]], lead: float,
                    period: float, grid_step: float,
                    min_history: float) -> dict[str, dict]:
    """Walk-forward backtest of one model's series; returns per-forecaster
    scores."""
    long_step = period / fc.SEASON_STEPS
    store = DemandHistoryStore(
        window_seconds=long_step * fc.N_GRID,
        fine_window_seconds=grid_step * fc.N_GRID,
        long_gap_seconds=long_step / 2.0)
    pending: list[tuple[float, dict[str, float]]] = []
    scored: dict[str, list[tuple[float, float]]] = {
        name: [] for name in fc.FORECASTERS}
    tol = max(lead * MATCH_TOLERANCE_FRACTION, grid_step)
    t0 = points[0][0]
    for t, d in points:
        # Score matured forecasts against this realized sample.
        still = []
        for due, preds in pending:
            if due > t:
                still.append((due, preds))
            elif abs(t - due) <= tol:
                for name, p in preds.items():
                    scored[name].append((p, d))
        pending = still
        store.observe("k", t, max(d, 0.0))
        if t - t0 < min_history:
            continue
        windows = store.windows("k")
        fine, nf = fc.resample(windows[0], t, grid_step)
        longg, nl = fc.resample(windows[1], t, long_step)
        fit = fc.fit_batch([fc.SeriesGrids(
            fine=fine, fine_valid=nf, long=longg, long_valid=nl,
            h_fine_steps=lead / grid_step, h_long_steps=lead / long_step,
            season_steps=fc.SEASON_STEPS)])[0]
        pending.append((t + lead, fit))

    out = {}
    for name, pairs in scored.items():
        if not pairs:
            out[name] = {"n": 0}
            continue
        total_real = sum(r for _, r in pairs)
        mape = sum(abs(p - r) / max((abs(p) + abs(r)) / 2.0, 1e-6)
                   for p, r in pairs) / len(pairs)
        under = sum(max(r - p, 0.0) for p, r in pairs)
        over = sum(max(p - r, 0.0) for p, r in pairs)
        norm = max(total_real, 1e-9)
        out[name] = {
            "n": len(pairs),
            "mape": round(min(mape, 2.0), 6),
            "under_provision_cost": round(under / norm, 6),
            "over_provision_cost": round(over / norm, 6),
        }
    return out


def run_backtest(trace_path: str, lead: float, period: float,
                 grid_step: float, min_history: float) -> dict:
    records = load_trace(trace_path)
    series, v1_skipped = extract_series(records)
    models = {}
    for key in sorted(series):
        if len(series[key]) >= 3:
            models[key] = backtest_series(series[key], lead, period,
                                          grid_step, min_history)
    agg: dict[str, dict] = {}
    for per_model in models.values():
        for name, s in per_model.items():
            if not s.get("n"):
                continue
            a = agg.setdefault(name, {"n": 0, "mape": 0.0,
                                      "under_provision_cost": 0.0,
                                      "over_provision_cost": 0.0})
            w, n = a["n"], s["n"]
            for field in ("mape", "under_provision_cost",
                          "over_provision_cost"):
                a[field] = (a[field] * w + s[field] * n) / (w + n)
            a["n"] = w + n
    for a in agg.values():
        for field in ("mape", "under_provision_cost", "over_provision_cost"):
            a[field] = round(a[field], 6)
    ranking = sorted(agg, key=lambda n: (agg[n]["mape"], n))
    return {
        "trace": trace_path.rsplit("/", 1)[-1],
        "cycles": len(records),
        "models": models,
        "v1_model_records_skipped": v1_skipped,
        "lead_time_seconds": lead,
        "seasonal_period_seconds": period,
        "aggregate": agg,
        "ranking": ranking,
        "best": ranking[0] if ranking else "",
        "seasonal_beats_linear": bool(
            agg.get("linear") and any(
                agg.get(n, {}).get("mape", float("inf"))
                < agg["linear"]["mape"] for n in fc.SEASONAL_FORECASTERS)),
    }


def compare_to_golden(report: dict, golden: dict,
                      rel_tol: float = 1e-4) -> list[str]:
    """Regression gate: ranking must match exactly, aggregate scores within
    tolerance, and the seasonal-beats-linear acceptance bit must hold."""
    problems = []
    if report.get("ranking") != golden.get("ranking"):
        problems.append(f"ranking changed: {golden.get('ranking')} -> "
                        f"{report.get('ranking')}")
    if golden.get("seasonal_beats_linear") \
            and not report.get("seasonal_beats_linear"):
        problems.append("seasonal forecaster no longer beats the "
                        "linear-trend baseline")
    for name, g in (golden.get("aggregate") or {}).items():
        r = (report.get("aggregate") or {}).get(name)
        if r is None:
            problems.append(f"forecaster {name} missing from report")
            continue
        for field in ("mape", "under_provision_cost",
                      "over_provision_cost", "n"):
            gv, rv = g.get(field), r.get(field)
            if gv is None or rv is None:
                continue
            if abs(rv - gv) > rel_tol * max(abs(gv), 1.0):
                problems.append(
                    f"{name}.{field}: golden={gv} got={rv}")
    return problems


def backtest_cli(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="wva-tpu forecast backtest",
        description="Replay a recorded decision trace's demand series "
                    "through every candidate forecaster and score MAPE + "
                    "under/over-provision cost.")
    p.add_argument("trace", help="JSONL decision trace (WVA_TRACE_PATH "
                                 "output)")
    p.add_argument("--lead", type=float, default=150.0,
                   help="forecast horizon in seconds (default 150 — the "
                        "provisioning lead-time design point)")
    p.add_argument("--period", type=float, default=86400.0,
                   help="seasonal period in seconds (default 1 day; match "
                        "the trace's seasonality)")
    p.add_argument("--grid-step", type=float, default=None,
                   help="fine-grid resolution in seconds (default 15, or "
                        "the --knobs recommendation's "
                        "WVA_FORECAST_GRID_STEP)")
    p.add_argument("--knobs", default="",
                   help="sweep recommendations JSON (python -m wva_tpu "
                        "sweep --out): apply its WVA_FORECAST_GRID_STEP "
                        "and report whether this trace's best forecaster "
                        "validates its recommendation")
    p.add_argument("--knobs-model", default="",
                   help="model key inside --knobs (default: its only "
                        "model)")
    p.add_argument("--min-history", type=float, default=None,
                   help="warm-up seconds before the first scored forecast "
                        "(default: one lead time; 0 scores from the first "
                        "sample)")
    p.add_argument("--json", action="store_true",
                   help="print the full machine-readable report")
    p.add_argument("--golden", default="",
                   help="compare against a committed golden report; "
                        "non-zero exit on regression")
    p.add_argument("--update-golden", action="store_true",
                   help="rewrite the --golden file from this run")
    args = p.parse_args(argv)

    # Tuned-knob application (the sweep plane's artifact): the
    # recommendation's observation window maps onto the backtest's fine
    # grid; its forecaster pick is validated against this trace's
    # ranking. Explicit --grid-step still wins.
    knob_info = None
    if args.knobs:
        try:
            with open(args.knobs, "r", encoding="utf-8") as f:
                recs = json.load(f)["recommendations"]
            model = args.knobs_model or sorted(recs)[0]
            applied = recs[model]["applied_knobs"]
        except (OSError, ValueError, KeyError) as e:
            print(f"error: unusable --knobs {args.knobs}: {e}",
                  file=sys.stderr)
            return 2
        knob_info = {"path": args.knobs, "model": model,
                     "recommended_forecaster": applied.get("forecaster"),
                     "trusted": bool(recs[model]["trust"]["trusted"])}
        if args.grid_step is None:
            step = applied.get("WVA_FORECAST_GRID_STEP")
            if step is not None:
                args.grid_step = float(step)
    if args.grid_step is None:
        args.grid_step = 15.0

    try:
        report = run_backtest(args.trace, args.lead, args.period,
                              args.grid_step,
                              args.lead if args.min_history is None
                              else args.min_history)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if knob_info is not None:
        knob_info["backtest_best"] = report["best"]
        knob_info["backtest_validates"] = bool(
            report["best"] == knob_info["recommended_forecaster"])
        report["knobs"] = knob_info

    if args.json:
        print(json.dumps(report, sort_keys=True, indent=1))
    else:
        print(f"trace: {report['trace']} ({report['cycles']} cycles, "
              f"{len(report['models'])} models, lead {args.lead:.0f}s, "
              f"period {args.period:.0f}s)")
        for name in report["ranking"]:
            a = report["aggregate"][name]
            print(f"  {name:15s} mape={a['mape']:.4f} "
                  f"under={a['under_provision_cost']:.4f} "
                  f"over={a['over_provision_cost']:.4f} n={a['n']}")
        print(f"best: {report['best'] or 'n/a'}; seasonal beats linear: "
              f"{report['seasonal_beats_linear']}")
        if knob_info is not None:
            print(f"knobs: {knob_info['path']} recommends "
                  f"{knob_info['recommended_forecaster']} "
                  f"(trusted={knob_info['trusted']}); backtest "
                  f"{'validates' if knob_info['backtest_validates'] else 'disagrees'}"
                  f" (best={knob_info['backtest_best'] or 'n/a'})")

    if args.golden:
        if args.update_golden:
            slim = {k: v for k, v in report.items()
                    if k not in ("models", "knobs")}
            with open(args.golden, "w", encoding="utf-8") as f:
                json.dump(slim, f, sort_keys=True, indent=1)
                f.write("\n")
            print(f"wrote {args.golden}")
            return 0
        try:
            with open(args.golden, "r", encoding="utf-8") as f:
                golden = json.load(f)
        except (OSError, ValueError) as e:
            print(f"error: unreadable golden {args.golden}: {e}",
                  file=sys.stderr)
            return 2
        problems = compare_to_golden(report, golden)
        for prob in problems:
            print(f"GOLDEN MISMATCH: {prob}")
        print("BACKTEST GOLDEN OK" if not problems
              else "BACKTEST GOLDEN FAILED")
        return 0 if not problems else 1
    return 0


def forecast_cli(argv: list[str] | None = None) -> int:
    """``python -m wva_tpu forecast <subcommand>`` dispatcher."""
    argv = argv or []
    if argv and argv[0] == "backtest":
        return backtest_cli(argv[1:])
    print("usage: python -m wva_tpu forecast backtest <trace.jsonl> [...]",
          file=sys.stderr)
    return 2
