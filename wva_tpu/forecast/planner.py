"""The capacity planner: forecast-at-(now + measured lead time) -> proactive
replica floor + scale-from-zero pre-wake.

Per engine tick (on the engine thread, in sorted model order — decisions
stay byte-deterministic at any analysis-pool width):

1. every model's observed demand lands in the history store (the fast-path
   monitor adds between-tick samples through the same entry point);
2. every model's variant states feed the lead-time estimator;
3. matured backtest entries (forecasts whose target time has arrived) are
   scored against realized demand — a rolling symmetric-MAPE per
   (model, forecaster) is the selection signal (Autopilot-style: choose by
   replayed error, not by faith);
4. all models' forecasters are fitted in ONE padded jitted JAX call;
5. per model, the best TRUSTED forecaster's forecast at (now + lead time)
   becomes a proactive replica floor on the variant the decisions favor.

Guardrails (the planner must never be worse than reactive):

- **No trust, no floor.** A forecaster must survive ``min_trust_evals``
  matured backtests with rolling error <= ``demote_error_threshold``
  before its forecast moves a single replica.
- **Auto-demotion.** When the BEST forecaster's rolling error exceeds the
  threshold, the model demotes to reactive (floor withdrawn) until the
  error decays back under it — a forecast miss decays the floor by
  construction, since the miss raises the rolling error that gates it.
- **Growth only.** Floors only ever RAISE a decision's target; scale-down
  stays reactive (mirrors ``DemandTrend``'s max(slope, 0)).
- **Limiter last.** Floors apply before the slice limiter, so whole-slice
  inventory caps always bind (a floor can never allocate chips that do not
  exist).
"""

from __future__ import annotations

import logging
import math
import threading
from bisect import bisect_left
from collections import deque
from dataclasses import dataclass, field

from wva_tpu.forecast import forecasters as fc
from wva_tpu.forecast.history import DemandHistoryStore
from wva_tpu.forecast.leadtime import LeadTimeEstimator

log = logging.getLogger(__name__)

# Bound on remembered not-yet-matured forecasts per model. Entries are
# appended once per engine tick and popped unconditionally once their due
# time passes, so the steady-state depth is (lead time / tick interval).
# The bound must exceed that for the LONGEST credible lead or forecasts
# would be evicted before maturation and trust could never be earned: at a
# 15s tick, 1024 entries cover a 4.2h lead — beyond the lead-time
# estimator's own episode timeout (1h), so the cap is a runaway backstop,
# never a scoring ceiling. Memory is trivial (4 floats per entry).
MAX_PENDING = 1024
# A matured forecast scores only when a realized-demand sample exists
# within this many fine-grid steps of the target time.
REALIZED_TOLERANCE_STEPS = 4.0


@dataclass
class ForecastPlan:
    """One model's planning record for a tick (flight-recorded under the
    ``forecast`` stage; round-trips through the blackbox schema)."""

    model_id: str = ""
    namespace: str = ""
    demand: float = 0.0
    lead_time_seconds: float = 0.0
    lead_time_measured: bool = False
    forecaster: str = ""
    forecast_demand: float = 0.0
    forecasts: dict[str, float] = field(default_factory=dict)
    errors: dict[str, float] = field(default_factory=dict)
    evals: dict[str, int] = field(default_factory=dict)
    trusted: bool = False
    demoted: bool = False
    floor_replicas: int = 0
    variant_name: str = ""
    reason: str = ""


@dataclass
class _Pending:
    due: float
    horizon: float
    forecasts: dict[str, float]


@dataclass
class PreparedTick:
    """The planning pass split at the device boundary (the fused decision
    plane, docs/design/fused-plane.md): everything :meth:`plan` does
    BEFORE the forecaster fit — demand/variant observation, idle
    eviction, grid resampling, backtest scoring, trust selection — done
    up front so the fit itself can ride the tick's ONE fused dispatch.
    The engine fills ``fits``/``chosen`` from the fused result and hands
    the whole object back to :meth:`plan`, which then runs the same
    per-model planning loop the staged path runs.

    ``trust_idx``/``trusted`` are the model axis's mask columns: the
    selected forecaster's registry index (UNTRUSTED = no forecaster past
    the gate; the program gathers the linear floor) and whether its
    rolling error clears the demotion threshold.
    """

    now: float = 0.0
    keys: list[str] = field(default_factory=list)
    grids: list = field(default_factory=list)
    horizons: list[tuple[float, bool]] = field(default_factory=list)
    trust_idx: list[int] = field(default_factory=list)
    trusted: list[bool] = field(default_factory=list)
    fits: list[dict[str, float]] | None = None
    chosen: list[float] | None = None
    # The global-routed mask column as the engine's no-floor partition:
    # keys of models the fleet-wide solver owns (a per-model floor would
    # fight its deliberate starvation/migration sequencing).
    global_no_floor: frozenset = frozenset()


class CapacityPlanner:
    """Thread-safe predictive planner; one instance per engine."""

    def __init__(self, seasonal_period_seconds: float = 86400.0,
                 grid_step_seconds: float = 15.0,
                 default_lead_time_seconds: float = 150.0,
                 lead_time_quantile: float = 0.9,
                 target_utilization: float = 0.85,
                 demote_error_threshold: float = 0.35,
                 min_trust_evals: int = 3,
                 growth_min_ratio: float = 1.05,
                 error_ewma_alpha: float = 0.3,
                 prewake_enabled: bool = True,
                 prewake_min_demand: float = 1.0,
                 prewake_check_interval: float = 30.0,
                 batched: bool = True) -> None:
        self.period = max(seasonal_period_seconds, 1.0)
        self.grid_step = max(grid_step_seconds, 1.0)
        # Long grid: SEASON_STEPS cells per period -> N_GRID/SEASON_STEPS
        # (2.5) periods of context.
        self.long_step = self.period / fc.SEASON_STEPS
        self.target_utilization = min(max(target_utilization, 0.05), 1.0)
        self.demote_error_threshold = demote_error_threshold
        self.min_trust_evals = max(min_trust_evals, 1)
        self.growth_min_ratio = growth_min_ratio
        self.error_ewma_alpha = error_ewma_alpha
        self.prewake_enabled = prewake_enabled
        self.prewake_min_demand = prewake_min_demand
        self.prewake_check_interval = prewake_check_interval
        self.batched = batched
        self.history = DemandHistoryStore(
            window_seconds=self.long_step * fc.N_GRID,
            fine_window_seconds=self.grid_step * fc.N_GRID,
            long_gap_seconds=self.long_step / 2.0)
        self.leadtime = LeadTimeEstimator(
            quantile=lead_time_quantile,
            default_seconds=default_lead_time_seconds)
        self._mu = threading.Lock()
        # key -> pending (not yet matured) forecast evaluations.
        self._pending: dict[str, deque[_Pending]] = {}
        # (key, forecaster) -> (ewma error, eval count).
        self._errors: dict[tuple[str, str], tuple[float, int]] = {}
        self._last_plan: dict[str, ForecastPlan] = {}
        self._last_prewake_check: dict[str, float] = {}
        # key -> EWMA of realized demand: the error denominator is floored
        # at a fraction of the model's own demand scale, so a forecast off
        # by 0.01 req/s against a realized 0 during a quiet phase does not
        # score as a 200% miss and demote a good seasonal forecaster
        # (symmetric MAPE is unstable at zero; units vary per analyzer, so
        # the floor must be scale-relative, never a constant).
        self._demand_scale: dict[str, float] = {}
        # key -> the accelerator serving most of the model's replicas, so
        # lead-time estimates for a model with no samples of its own can
        # fall back to the fleet's measured latencies for that accelerator.
        self._accel_by_key: dict[str, str] = {}

    # -- feeds --

    @staticmethod
    def key_for(namespace: str, model_id: str) -> str:
        return f"{namespace}|{model_id}"

    def observe_demand(self, namespace: str, model_id: str, now: float,
                       demand: float) -> None:
        """Record one demand sample (engine tick or fast-path feed)."""
        self.history.observe(self.key_for(namespace, model_id), now,
                             max(demand, 0.0))

    def observe_variants(self, namespace: str, model_id: str,
                         variant_states, now: float) -> None:
        key = self.key_for(namespace, model_id)
        best = None
        for vs in variant_states:
            self.leadtime.observe(key, vs.variant_name, vs.accelerator_name,
                                  vs.desired_replicas, vs.ready_replicas, now)
            if vs.accelerator_name and (
                    best is None or vs.ready_replicas > best[0]):
                best = (vs.ready_replicas, vs.accelerator_name)
        if best is not None:
            with self._mu:
                self._accel_by_key[key] = best[1]

    def _estimate_lead(self, key: str) -> tuple[float, bool]:
        """Lead time for a model: own samples, else the fleet's measured
        latencies for the accelerator it runs on, else the default."""
        with self._mu:
            accel = self._accel_by_key.get(key, "")
        return self.leadtime.estimate(key, accel)

    # -- planning --

    def prepare_tick(self, entries, now: float) -> PreparedTick:
        """Everything :meth:`plan` does before the forecaster fit, for
        the fused decision plane. ``entries`` are ``(namespace,
        model_id, demand, variant_states)`` tuples for the models that
        will produce scaling requests this tick; they are processed in
        the exact (namespace, model_id) order ``plan`` sorts requests
        into, so the planner's learned state (history rings, lead-time
        samples — including the shared per-accelerator fallback rings —
        idle eviction, backtest scores) evolves byte-identically to the
        staged pass.

        Backtest scoring and trust selection run here too: scoring
        depends only on history + pending entries (all pre-fit state),
        nothing matures between this call and the per-model planning
        loop within one tick, and ``_plan_model``'s own scoring call is
        then a no-op — which is what makes the trust-index column the
        device gather reads agree with the host's trust rule.

        Caveat: if a model observed here never reaches :meth:`plan`
        (a downstream per-model failure), its demand sample and scores
        stay — one extra history point on an abnormal path."""
        ordered = sorted(entries, key=lambda e: (e[0], e[1]))
        prep = PreparedTick(now=now)
        for ns, model, demand, variant_states in ordered:
            key = self.key_for(ns, model)
            self.observe_demand(ns, model, now, demand)
            self.observe_variants(ns, model, variant_states, now)
            prep.keys.append(key)
        self._evict_dead_keys(now)
        for key in prep.keys:
            lead, measured = self._estimate_lead(key)
            prep.grids.append(self._grids_for(key, now, lead))
            prep.horizons.append((lead, measured))
            with self._mu:
                self._score_matured(key, now)
                best, best_err, _ = self._best_trusted_locked(key)
            if best is None:
                prep.trust_idx.append(-1)
                prep.trusted.append(False)
            else:
                prep.trust_idx.append(fc.FORECASTERS.index(best))
                prep.trusted.append(
                    best_err <= self.demote_error_threshold)
        return prep

    def plan(self, requests, now: float,
             no_floor_keys: frozenset[str] = frozenset(),
             prepared: PreparedTick | None = None,
             ) -> tuple[list[ForecastPlan], list[dict]]:
        """One planning pass over this tick's models. ``requests`` are the
        engine's :class:`ModelScalingRequest`s (result + variant states).
        Returns (plans, floors); apply floors with
        :func:`~wva_tpu.forecast.apply.apply_forecast_floors`.

        ``no_floor_keys`` — models whose placement another authority owns
        (the fleet-wide global optimizer deliberately starves low-priority
        models on constrained pools; a per-model floor would fight that
        assignment). They still get the full learning pass (history,
        lead times, backtest scoring) — only the floor is withheld.

        ``prepared`` — a :class:`PreparedTick` from :meth:`prepare_tick`.
        The learning pass (observation, eviction, scoring) already ran,
        so it must NOT run again: requests are matched to prepared rows
        by key (a downstream per-model failure may have dropped some —
        the surviving subset reuses its rows; row-independent fits make
        the subset bitwise what a fresh fit would produce). When the
        fused dispatch failed, ``prepared.fits`` is None and the fit
        runs here as its own (staged) dispatch over the prepared grids —
        the degradation path stays byte-identical to WVA_FUSED=off.
        Only a request whose key was never prepared (should not happen)
        forces the full staged pass, which re-observes — a benign
        duplicate on an already-abnormal path."""
        reqs = sorted(requests, key=lambda r: (r.namespace, r.model_id))
        live_reqs = [r for r in reqs if r.result is not None]
        if prepared is not None:
            req_keys = [self.key_for(r.namespace, r.model_id)
                        for r in live_reqs]
            if not set(req_keys) <= set(prepared.keys):
                prepared = None
        chosen: list[float] | None = None
        if prepared is not None:
            rows = {k: i for i, k in enumerate(prepared.keys)}
            idx = [rows[k] for k in req_keys]
            keyed = list(zip(req_keys, live_reqs))
            grids = [prepared.grids[i] for i in idx]
            horizons = [prepared.horizons[i] for i in idx]
            if prepared.fits is not None:
                fits = [prepared.fits[i] for i in idx]
                chosen = ([prepared.chosen[i] for i in idx]
                          if prepared.chosen is not None else None)
            else:
                fits = (fc.fit_batch(grids) if self.batched
                        else fc.fit_serial(grids))
        else:
            keyed = []
            for req in reqs:
                if req.result is None:
                    continue
                key = self.key_for(req.namespace, req.model_id)
                self.observe_demand(req.namespace, req.model_id, now,
                                    req.result.total_demand)
                self.observe_variants(req.namespace, req.model_id,
                                      req.variant_states, now)
                keyed.append((key, req))
            self._evict_dead_keys(now)

            grids, horizons = [], []
            for key, req in keyed:
                lead, measured = self._estimate_lead(key)
                grids.append(self._grids_for(key, now, lead))
                horizons.append((lead, measured))
            fits = (fc.fit_batch([g for g in grids]) if self.batched
                    else fc.fit_serial([g for g in grids]))

        plans: list[ForecastPlan] = []
        floors: list[dict] = []
        for i, ((key, req), grid, fit, (lead, measured)) in enumerate(zip(
                keyed, grids, fits, horizons)):
            plan = self._plan_model(key, req, fit, lead, measured, now,
                                    floor_allowed=key not in no_floor_keys,
                                    forecast_value=(
                                        chosen[i] if chosen is not None
                                        else None))
            plans.append(plan)
            if plan.floor_replicas > 0 and plan.variant_name:
                floors.append({
                    "namespace": plan.namespace,
                    "model_id": plan.model_id,
                    "variant_name": plan.variant_name,
                    "floor_replicas": plan.floor_replicas,
                    "reason": plan.reason,
                })
        return plans, floors

    def _plan_model(self, key: str, req, fit: dict[str, float],
                    lead: float, measured: bool, now: float,
                    floor_allowed: bool = True,
                    forecast_value: float | None = None) -> ForecastPlan:
        demand = max(req.result.total_demand, 0.0)
        plan = ForecastPlan(
            model_id=req.model_id, namespace=req.namespace, demand=demand,
            lead_time_seconds=round(lead, 1), lead_time_measured=measured,
            forecasts={name: fit[name] for name in fc.FORECASTERS})
        with self._mu:
            self._score_matured(key, now)
            pend = self._pending.setdefault(key, deque(maxlen=MAX_PENDING))
            pend.append(_Pending(due=now + lead, horizon=lead,
                                 forecasts=dict(fit)))
            for name in fc.FORECASTERS:
                err, evals = self._errors.get((key, name), (0.0, 0))
                plan.errors[name] = round(err, 6)
                plan.evals[name] = evals
            best, best_err, best_evals = self._best_trusted_locked(key)
        # The fused plane's device gather already selected this model's
        # forecast through the trust-index column; the gathered value is
        # bitwise the registry array element the staged reads below pick
        # (same device array), so either source yields the same plan.
        if forecast_value is None:
            forecast_value = fit[best if best is not None else "linear"]
        if best is None:
            plan.forecaster = "linear"  # floor of the registry, untrusted
            plan.forecast_demand = forecast_value
            plan.reason = (f"forecast untrusted ({self.min_trust_evals} "
                           "scored backtests required); reactive")
        elif best_err > self.demote_error_threshold:
            plan.forecaster = best
            plan.forecast_demand = forecast_value
            plan.demoted = True
            plan.reason = (f"forecast demoted: best rolling error "
                           f"{best_err:.2f} > "
                           f"{self.demote_error_threshold:.2f}; reactive")
        else:
            plan.trusted = True
            plan.forecaster = best
            plan.forecast_demand = forecast_value
            if floor_allowed:
                self._maybe_floor(plan, req, best_evals)
            else:
                plan.reason = ("fleet (global) optimizer owns this model's "
                               "placement; forecast floor withheld")
        with self._mu:
            self._last_plan[key] = plan
        return plan

    def _maybe_floor(self, plan: ForecastPlan, req, evals: int) -> None:
        """Proactive floor: replicas to serve the forecast at landing time,
        on the variant the current decisions favor. Growth-gated so a
        steady or falling forecast never perturbs reactive behavior."""
        if plan.forecast_demand < self.prewake_min_demand:
            # Noise gate, same threshold as the pre-wake: at zero observed
            # demand the growth ratio passes for ANY epsilon forecast
            # (seasonal residue of 0.01), and a floor of 1 replica would
            # override the enforcer's scale-to-zero every tick — demand
            # below the act-on-it threshold stays reactive.
            plan.reason = (f"forecast {plan.forecast_demand:.2f} below "
                           f"minimum actionable demand "
                           f"{self.prewake_min_demand:.2f}; reactive")
            return
        if plan.forecast_demand <= max(plan.demand, 1e-9) \
                * self.growth_min_ratio:
            plan.reason = (f"forecast {plan.forecast_demand:.2f} within "
                           f"{self.growth_min_ratio:.2f}x of demand "
                           f"{plan.demand:.2f}; reactive")
            return
        best_vc = None
        for vc in req.result.variant_capacities:
            if vc.per_replica_capacity <= 0:
                continue
            rank = (-vc.replica_count, vc.cost, vc.variant_name)
            if best_vc is None or rank < best_vc[0]:
                best_vc = (rank, vc)
        if best_vc is None:
            plan.reason = "no variant with known per-replica capacity"
            return
        vc = best_vc[1]
        floor = math.ceil(plan.forecast_demand
                          / (vc.per_replica_capacity
                             * self.target_utilization))
        plan.floor_replicas = int(floor)
        plan.variant_name = vc.variant_name
        plan.reason = (
            f"forecast[{plan.forecaster}] {plan.forecast_demand:.2f} at "
            f"now+{plan.lead_time_seconds:.0f}s "
            f"({'measured' if plan.lead_time_measured else 'default'} "
            f"lead time, {evals} backtests) -> floor {floor} replicas")

    def _evict_dead_keys(self, now: float) -> None:
        """Per-tick hygiene: the history store's time-based idle eviction
        is the source of truth for which models still matter (a
        scaled-to-zero model stays live as long as its rings do, so
        pre-wake keeps working); every other per-key state — pending
        backtests, rolling errors, plans, throttles, lead-time samples —
        follows it. Without this, a long-lived controller with model churn
        accumulates dead entries forever (the same leak class the
        DemandTrend idle sweep fixes)."""
        if not self.history.evict_idle(now):
            return
        live = set(self.history.keys())
        with self._mu:
            for d in (self._pending, self._last_plan,
                      self._last_prewake_check, self._accel_by_key,
                      self._demand_scale):
                for k in [k for k in d if k not in live]:
                    del d[k]
            for k in [k for k in self._errors if k[0] not in live]:
                del self._errors[k]
        self.leadtime.evict_missing(live)

    def _best_trusted_locked(self, key: str) -> tuple[str | None, float, int]:
        """(forecaster, rolling error, evals) with the lowest rolling error
        among those past the trust gate, or (None, inf, 0). THE trust rule
        — the floor path and the pre-wake path must never disagree on which
        forecaster is trusted. Caller holds the lock."""
        best, best_err, best_evals = None, float("inf"), 0
        for name in fc.FORECASTERS:
            err, evals = self._errors.get((key, name), (0.0, 0))
            if evals >= self.min_trust_evals and err < best_err:
                best, best_err, best_evals = name, err, evals
        return best, best_err, best_evals

    # -- rolling backtest scoring --

    def _score_matured(self, key: str, now: float) -> None:
        """Score pending forecasts whose target time has arrived against
        realized demand (symmetric MAPE, EWMA-smoothed). Caller holds
        the lock."""
        pend = self._pending.get(key)
        if not pend:
            return
        while pend and pend[0].due <= now:
            entry = pend.popleft()
            realized = self._realized_at(key, entry.due)
            if realized is None:
                continue
            scale = self._demand_scale.get(key, abs(realized))
            scale += 0.1 * (abs(realized) - scale)
            self._demand_scale[key] = scale
            denom_floor = max(0.05 * scale, 1e-6)
            for name, predicted in entry.forecasts.items():
                err = (abs(predicted - realized)
                       / max((abs(predicted) + abs(realized)) / 2.0,
                             denom_floor))
                err = min(err, 2.0)
                old, n = self._errors.get((key, name), (0.0, 0))
                a = self.error_ewma_alpha if n else 1.0
                self._errors[(key, name)] = (old + a * (err - old), n + 1)

    def _realized_at(self, key: str, t: float) -> float | None:
        """Observed demand nearest ``t`` (within tolerance), from the fine
        ring."""
        windows = self.history.windows(key)
        if windows is None:
            return None
        w = windows[0]
        if len(w) == 0:
            return None
        tol = REALIZED_TOLERANCE_STEPS * self.grid_step
        i = bisect_left(w.ts, t, w.lo, w.hi)
        best = None
        for j in (i - 1, i):
            if w.lo <= j < w.hi:
                dt = abs(w.ts[j] - t)
                if dt <= tol and (best is None or dt < best[0]):
                    best = (dt, w.vals[j])
        return best[1] if best else None

    def _grids_for(self, key: str, now: float, lead: float) -> fc.SeriesGrids:
        windows = self.history.windows(key)
        if windows is None:
            fine, nf = [0.0] * fc.N_GRID, 0
            longg, nl = [0.0] * fc.N_GRID, 0
        else:
            fine, nf = fc.resample(windows[0], now, self.grid_step)
            longg, nl = fc.resample(windows[1], now, self.long_step)
        return fc.SeriesGrids(
            fine=fine, fine_valid=nf, long=longg, long_valid=nl,
            h_fine_steps=lead / self.grid_step,
            h_long_steps=lead / self.long_step,
            season_steps=fc.SEASON_STEPS)

    # -- consumers --

    def lead_time_for(self, namespace: str,
                      model_id: str) -> tuple[float, bool]:
        return self._estimate_lead(self.key_for(namespace, model_id))

    def last_plan(self, namespace: str, model_id: str) -> ForecastPlan | None:
        with self._mu:
            return self._last_plan.get(self.key_for(namespace, model_id))

    def should_prewake(self, namespace: str, model_id: str,
                       now: float) -> tuple[bool, str]:
        """Scale-from-zero pre-wake: wake a scaled-to-zero model when a
        TRUSTED forecaster predicts demand >= ``prewake_min_demand`` at
        (now + lead time). Called from the scale-from-zero engine's 100ms
        loop — throttled per model, and it records the observed zero-demand
        samples so the seasonal fit keeps learning through the quiet phase."""
        if not self.prewake_enabled:
            return False, ""
        key = self.key_for(namespace, model_id)
        with self._mu:
            last = self._last_prewake_check.get(key, float("-inf"))
            if now - last < self.prewake_check_interval:
                return False, ""
            self._last_prewake_check[key] = now
        # A scaled-to-zero model serves zero demand — record it BEFORE any
        # trust gating, so the seasonal grids see the quiet phase instead
        # of LOCF'ing the last active sample forward (an untrusted model
        # must keep learning its real pattern through the idle phase, or
        # it would re-earn trust later against fabricated demand).
        self.history.observe(key, now, 0.0)
        with self._mu:
            self._score_matured(key, now)
            best, best_err, _ = self._best_trusted_locked(key)
        if best is None or best_err > self.demote_error_threshold:
            return False, ""
        lead, measured = self._estimate_lead(key)
        fit = fc.fit_batch([self._grids_for(key, now, lead)])[0]
        forecast = fit[best]
        if forecast < self.prewake_min_demand:
            return False, ""
        return True, (
            f"forecast pre-wake: {best} predicts demand {forecast:.2f} >= "
            f"{self.prewake_min_demand:.2f} at now+{lead:.0f}s "
            f"({'measured' if measured else 'default'} lead time)")

    def stats(self, now: float):
        """History-store stats keyed by model key (for trend/forecast
        gauges)."""
        return self.history.stats(now)

    # -- crash-restart checkpoint (wva_tpu.resilience) --

    def export_trust(self) -> dict:
        """Serializable trust state for the resilience checkpoint: rolling
        backtest errors (the trust gate's entire evidence base — weeks of
        matured evaluations a restart would otherwise discard), the
        per-model demand scale the error denominator floors on, and the
        dominant-accelerator map lead-time fallbacks key on. Pending
        (not-yet-matured) forecasts are NOT exported — they score against
        the in-memory demand history, which does not survive either.
        Sorted everywhere: equal state serializes byte-identically."""
        with self._mu:
            return {
                "errors": [[key, name, err, evals]
                           for (key, name), (err, evals)
                           in sorted(self._errors.items())],
                "demand_scale": [[k, v] for k, v
                                 in sorted(self._demand_scale.items())],
                "accel": [[k, v] for k, v
                          in sorted(self._accel_by_key.items())],
            }

    def restore_trust(self, state: dict) -> int:
        """Rehydrate from :meth:`export_trust` output (boot warm-start).
        A restored model whose best forecaster already passed the trust
        gate resumes proactive floors as soon as fresh demand history
        rebuilds — instead of re-earning ``min_trust_evals`` matured
        backtests from scratch after every restart. Returns how many
        (model, forecaster) error entries were restored."""
        restored = 0
        with self._mu:
            for key, name, err, evals in state.get("errors", []):
                self._errors[(str(key), str(name))] = \
                    (float(err), int(evals))
                restored += 1
            for key, value in state.get("demand_scale", []):
                self._demand_scale[str(key)] = float(value)
            for key, accel in state.get("accel", []):
                self._accel_by_key[str(key)] = str(accel)
        return restored
