"""Forecast floor application — shared by the live engine and trace replay.

Kept free of JAX imports: the replay CLI applies RECORDED floors (the
``forecast`` stage event in the decision trace) without re-running the
planner, exactly like the limiter replay rebuilds from the recorded pool
snapshot — so a trace recorded with forecasting on replays to zero diffs.
"""

from __future__ import annotations

from wva_tpu.interfaces import ACTION_SCALE_UP, VariantDecision

FORECAST_STEP_NAME = "forecast"


def apply_forecast_floors(decisions: list[VariantDecision],
                          floors: list[dict], now: float) -> int:
    """Raise each floored variant's target to its proactive floor (never
    lowers — the planner only ever ADDS capacity ahead of forecast demand;
    scale-down stays reactive). Runs BEFORE the limiter so inventory caps
    still bind. Returns how many decisions were raised."""
    if not floors:
        return 0
    by_variant = {(d.namespace, d.variant_name): d for d in decisions}
    raised = 0
    for f in floors:
        d = by_variant.get((f.get("namespace", ""), f.get("variant_name", "")))
        floor = int(f.get("floor_replicas", 0))
        if d is None or floor <= d.target_replicas:
            continue
        d.target_replicas = floor
        if floor > d.current_replicas:
            d.action = ACTION_SCALE_UP
        d.reason = f.get("reason", "") or d.reason
        d.add_step(FORECAST_STEP_NAME, f.get("reason", ""), now=now)
        raised += 1
    return raised
