"""Per-model demand history store.

Same storage discipline as the TSDB's series columns
(``collector/source/promql.py`` ``_Series``): parallel ``array('d')``
timestamp/value columns with a live-region start offset — appends are O(1)
amortized, retention trims advance the offset instead of ``pop(0)``-ing
objects, and reads hand out zero-copy :class:`SeriesWindow` views.

Two tiers per key, because the forecaster registry reads two grids:

- **fine** — every sample (engine ticks + fast-path feed, seconds apart),
  bounded by ``fine_window_seconds``; feeds the recent-trend forecasters
  (linear, Holt).
- **long** — decimated to ``long_gap_seconds`` between samples, bounded by
  ``window_seconds`` (>= 2 seasonal periods); feeds the seasonal
  forecasters (seasonal-naive, Holt-Winters), which need days of context a
  dense ring could not hold at bounded memory.
"""

from __future__ import annotations

import threading
from array import array
from dataclasses import dataclass

from wva_tpu.collector.source.promql import SeriesWindow


class RingColumns:
    """One series' column store: parallel timestamp/value arrays with a
    live-region start offset — the same layout and trim/compaction
    discipline as the TSDB's ``promql._Series``, deliberately a SEPARATE
    implementation rather than an extraction: the TSDB trims against a
    store-wide retention under striped locks on its ingest hot path, while
    this ring owns a per-ring window and trims inline on append. If you
    change the compaction heuristic here, check
    ``collector/source/promql.py`` ``_trim_locked`` for the twin."""

    __slots__ = ("ts", "vals", "start", "last_ts", "window_seconds")

    COMPACT_MIN_DEAD = 256

    def __init__(self, window_seconds: float) -> None:
        self.ts = array("d")
        self.vals = array("d")
        self.start = 0
        self.last_ts = float("-inf")
        self.window_seconds = window_seconds

    def append(self, ts: float, value: float) -> None:
        # Monotonic guard: the store is fed by several cadences (engine tick,
        # fast path); an out-of-order stamp would break the bisect reads.
        if ts < self.last_ts:
            return
        self.ts.append(ts)
        self.vals.append(value)
        self.last_ts = ts
        cutoff = ts - self.window_seconds
        start, n = self.start, len(self.ts)
        while start < n and self.ts[start] < cutoff:
            start += 1
        self.start = start
        if start >= self.COMPACT_MIN_DEAD and start * 2 >= n:
            self.ts = self.ts[start:]
            self.vals = self.vals[start:]
            self.start = 0

    def __len__(self) -> int:
        return len(self.ts) - self.start

    def window(self) -> SeriesWindow:
        """Zero-copy view of the live region (immutable snapshot: appends
        only extend past ``hi``; compaction replaces the arrays)."""
        return SeriesWindow(self.ts, self.vals, self.start, len(self.ts))


@dataclass
class _KeyHistory:
    fine: RingColumns
    long: RingColumns


@dataclass
class HistoryKeyStats:
    samples_fine: int
    samples_long: int
    span_seconds: float
    staleness_seconds: float


class DemandHistoryStore:
    """Thread-safe per-key (``"ns|model"``) demand history, two-tier rings."""

    def __init__(self, window_seconds: float = 2 * 86400.0,
                 fine_window_seconds: float = 1800.0,
                 long_gap_seconds: float = 0.0) -> None:
        self.window_seconds = window_seconds
        self.fine_window_seconds = min(fine_window_seconds, window_seconds)
        # Decimation gap for the long ring: default sized so the long ring
        # holds the whole window in ~1k samples regardless of feed cadence.
        self.long_gap_seconds = long_gap_seconds or max(
            window_seconds / 1024.0, 1.0)
        self._mu = threading.Lock()
        self._keys: dict[str, _KeyHistory] = {}

    def observe(self, key: str, now: float, demand: float) -> None:
        with self._mu:
            h = self._keys.get(key)
            if h is None:
                h = _KeyHistory(fine=RingColumns(self.fine_window_seconds),
                                long=RingColumns(self.window_seconds))
                self._keys[key] = h
            h.fine.append(now, demand)
            if now - h.long.last_ts >= self.long_gap_seconds:
                h.long.append(now, demand)

    def windows(self, key: str) -> tuple[SeriesWindow, SeriesWindow] | None:
        """(fine, long) zero-copy views, or None for an unknown key."""
        with self._mu:
            h = self._keys.get(key)
            if h is None:
                return None
            return h.fine.window(), h.long.window()

    def keys(self) -> list[str]:
        with self._mu:
            return sorted(self._keys)

    def evict_idle(self, now: float) -> int:
        """Drop keys whose newest sample fell out of the window (deleted /
        renamed models must not pin rings forever); returns count dropped.
        Deliberately time-based, NOT active-set-based: a model scaled to
        zero keeps its history so the pre-wake forecast can still see its
        seasonal pattern."""
        with self._mu:
            stale = [k for k, h in self._keys.items()
                     if now - h.long.last_ts > self.window_seconds]
            for k in stale:
                del self._keys[k]
            return len(stale)

    def stats(self, now: float) -> dict[str, HistoryKeyStats]:
        with self._mu:
            out = {}
            for k, h in self._keys.items():
                w = h.long.window()
                span = (w.ts[w.hi - 1] - w.ts[w.lo]) if len(w) >= 2 else 0.0
                out[k] = HistoryKeyStats(
                    samples_fine=len(h.fine),
                    samples_long=len(h.long),
                    span_seconds=span,
                    staleness_seconds=(now - h.long.last_ts
                                       if len(h.long) else float("inf")),
                )
            return out
