"""Forecaster registry: batched, jitted demand forecasts.

Four candidate forecasters, Autopilot-style (Rzadca et al., EuroSys 2020 —
fit several recommenders over sliding windows, select by replayed error):

- ``linear``          — least-squares trend over the fine grid (the
                        existing ``DemandTrend`` slope as a forecaster; the
                        registry floor).
- ``holt``            — double exponential smoothing (level + trend) over
                        the fine grid; tracks ramps with less lag than the
                        window fit.
- ``seasonal_naive``  — demand one season ago (+ the forecast horizon) from
                        the long grid; the classic strong baseline for
                        diurnal serving traffic.
- ``holt_winters``    — additive triple exponential smoothing (level +
                        trend + per-phase seasonal terms) over the long
                        grid.

Batching discipline matches the SLO solver (``queue_model.size_batch``):
every model's series is resampled onto fixed-width grids (``N_GRID``
columns, LOCF), the model axis is padded to a power-of-two bucket, and ONE
jitted call computes every forecaster for every model — a 48-model tick
costs one dispatch, not 48. All per-model math is row-independent
(elementwise ops, per-row reductions, per-row scan state), so batched and
serial fits are byte-identical at any batch width — asserted by
``tests/test_forecast.py`` and the ``test_tick_scale.py`` determinism
suite.

Two grids per model, because no single resolution serves both families:
the **fine** grid (``grid_step_seconds``) covers the recent window for the
trend forecasters; the **long** grid spans >= 2 seasonal periods at
``period / (N_GRID/2)`` resolution for the seasonal ones.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

FORECASTERS = ("linear", "holt", "seasonal_naive", "holt_winters")
SEASONAL_FORECASTERS = ("seasonal_naive", "holt_winters")

# Static grid width. 160 columns cover 40min of 15s fine steps and 2+
# seasonal periods on the long grid (step = period / 64).
N_GRID = 160
# Long-grid resolution: season length in steps (<= N_GRID / 2 so at least
# two full seasons fit the grid and the seasonal state can be learned).
SEASON_STEPS = 64
# A fit needs this many real samples before any forecaster output is
# trusted; below it every forecaster degrades to last-value persistence.
MIN_VALID = 4

# Smoothing constants (fixed, not per-model-tuned: the registry selects
# between FORMS by replayed error; tuning constants per model would need
# its own backtest loop for marginal gain).
HOLT_ALPHA = 0.5
HOLT_BETA = 0.2
HW_ALPHA = 0.35
HW_BETA = 0.1
HW_GAMMA = 0.35


@dataclass
class SeriesGrids:
    """One model's resampled inputs for the batched fit."""

    fine: list[float]  # N_GRID values, newest at index N_GRID-1
    fine_valid: int  # trailing valid count (0 = no data)
    long: list[float]
    long_valid: int
    h_fine_steps: float  # forecast horizon in fine steps
    h_long_steps: float  # forecast horizon in long steps
    season_steps: int  # seasonal period in long steps


def resample(window, now: float, step: float) -> tuple[list[float], int]:
    """Sample-and-hold a SeriesWindow onto ``N_GRID`` points ending at
    ``now`` (newest at the last index). Returns (values, valid_count):
    points before the first sample are invalid (zero-filled)."""
    vals = [0.0] * N_GRID
    n = len(window)
    if n == 0:
        return vals, 0
    ts0 = window.ts[window.lo]
    j = window.hi - 1  # walk newest -> oldest
    valid = 0
    for i in range(N_GRID - 1, -1, -1):
        t = now - (N_GRID - 1 - i) * step
        if t < ts0:
            break
        while j > window.lo and window.ts[j] > t:
            j -= 1
        if window.ts[j] > t:
            break
        vals[i] = window.vals[j]
        valid += 1
    return vals, valid


@partial(jax.jit, static_argnames=("m",))
def _fit_grid(fine, fine_valid, long_vals, long_valid,
              h_fine, h_long, season, m: int):
    """All four forecasters over ``m`` models at once. Shapes: grids
    ``[m, N_GRID]`` float32, everything else ``[m]``. Returns
    ``{name: [m]}`` forecasts at each model's horizon, clamped >= 0."""
    idx = jnp.arange(N_GRID, dtype=jnp.float32)  # [N]
    rows = jnp.arange(m)

    def mask_of(valid):
        return (idx[None, :] >= (N_GRID - valid)[:, None]).astype(jnp.float32)

    def last_value(vals):
        return vals[:, -1]

    fine_m = mask_of(fine_valid)
    long_m = mask_of(long_valid)

    # -- linear: masked least-squares over the fine grid index axis --
    n = jnp.sum(fine_m, axis=1)
    sx = jnp.sum(fine_m * idx[None, :], axis=1)
    sy = jnp.sum(fine_m * fine, axis=1)
    sxx = jnp.sum(fine_m * idx[None, :] * idx[None, :], axis=1)
    sxy = jnp.sum(fine_m * idx[None, :] * fine, axis=1)
    denom = n * sxx - sx * sx
    slope = jnp.where(denom > 0, (n * sxy - sx * sy)
                      / jnp.where(denom > 0, denom, 1.0), 0.0)
    intercept = jnp.where(n > 0, (sy - slope * sx)
                          / jnp.where(n > 0, n, 1.0), 0.0)
    linear = intercept + slope * (N_GRID - 1 + h_fine)

    # -- holt: double exponential smoothing over the fine grid --
    def holt_step(carry, xm):
        level, trend, started = carry
        x, valid = xm  # [m] each
        new_level = HOLT_ALPHA * x + (1 - HOLT_ALPHA) * (level + trend)
        new_trend = HOLT_BETA * (new_level - level) + (1 - HOLT_BETA) * trend
        # First valid sample initializes the level; invalid steps carry.
        level2 = jnp.where(started > 0, new_level, x)
        trend2 = jnp.where(started > 0, new_trend, 0.0)
        level = jnp.where(valid > 0, level2, level)
        trend = jnp.where(valid > 0, trend2, trend)
        started = jnp.maximum(started, valid)
        return (level, trend, started), None

    zeros = jnp.zeros((m,), jnp.float32)
    (h_level, h_trend, _), _ = jax.lax.scan(
        holt_step, (zeros, zeros, zeros), (fine.T, fine_m.T))
    holt = h_level + h_trend * h_fine

    # -- seasonal_naive: long-grid value one season before the target --
    j = jnp.round(N_GRID - 1 + h_long - season.astype(jnp.float32))
    j_int = jnp.clip(j.astype(jnp.int32), 0, N_GRID - 1)
    picked = long_vals[rows, j_int]
    j_valid = (j >= (N_GRID - long_valid).astype(jnp.float32)) \
        & (j <= N_GRID - 1)
    seasonal_naive = jnp.where(j_valid, picked, last_value(long_vals))

    # -- holt_winters: additive triple smoothing over the long grid --
    def hw_step(carry, xim):
        level, trend, seas, started = carry
        x, i, valid = xim
        phase = jnp.mod(i.astype(jnp.int32), season)  # [m]
        s = seas[rows, phase]
        new_level = HW_ALPHA * (x - s) + (1 - HW_ALPHA) * (level + trend)
        new_trend = HW_BETA * (new_level - level) + (1 - HW_BETA) * trend
        new_s = HW_GAMMA * (x - new_level) + (1 - HW_GAMMA) * s
        level2 = jnp.where(started > 0, new_level, x)
        trend2 = jnp.where(started > 0, new_trend, 0.0)
        s2 = jnp.where(started > 0, new_s, s)
        apply = valid > 0
        level = jnp.where(apply, level2, level)
        trend = jnp.where(apply, trend2, trend)
        seas = seas.at[rows, phase].set(jnp.where(apply, s2, s))
        started = jnp.maximum(started, valid)
        return (level, trend, seas, started), None

    steps = jnp.arange(N_GRID, dtype=jnp.float32)
    steps_b = jnp.broadcast_to(steps[:, None], (N_GRID, m))
    (w_level, w_trend, w_seas, _), _ = jax.lax.scan(
        hw_step,
        (zeros, zeros, jnp.zeros((m, N_GRID), jnp.float32), zeros),
        (long_vals.T, steps_b, long_m.T))
    f_phase = jnp.mod(
        jnp.round(N_GRID - 1 + h_long).astype(jnp.int32), season)
    holt_winters = w_level + w_trend * h_long + w_seas[rows, f_phase]

    # Insufficient history (either grid): persistence, the only honest
    # answer; clamp everything at zero (demand is non-negative).
    fallback_fine = last_value(fine)
    fallback_long = last_value(long_vals)
    enough_fine = fine_valid >= MIN_VALID
    enough_long = long_valid >= MIN_VALID
    return {
        "linear": jnp.maximum(
            jnp.where(enough_fine, linear, fallback_fine), 0.0),
        "holt": jnp.maximum(
            jnp.where(enough_fine, holt, fallback_fine), 0.0),
        "seasonal_naive": jnp.maximum(
            jnp.where(enough_long, seasonal_naive, fallback_long), 0.0),
        "holt_winters": jnp.maximum(
            jnp.where(enough_long, holt_winters, fallback_long), 0.0),
    }


def _bucket(m: int) -> int:
    b = 1
    while b < m:
        b *= 2
    return b


def fit_batch(grids: list[SeriesGrids]) -> list[dict[str, float]]:
    """ONE padded jitted fit across every model; returns one
    ``{forecaster: forecast}`` dict per input, in order. Padding rows are
    fully invalid and sliced off — per-model results are independent of
    batch composition (asserted batched == serial by the test suite)."""
    if not grids:
        return []
    from wva_tpu.utils import dispatch

    dispatch.note()
    m = _bucket(len(grids))

    def pad(vals, fill=0.0):
        return vals + [fill] * (m - len(grids))

    out = _fit_grid(
        jnp.asarray(pad([g.fine for g in grids], [0.0] * N_GRID),
                    jnp.float32),
        jnp.asarray(pad([g.fine_valid for g in grids], 0), jnp.float32),
        jnp.asarray(pad([g.long for g in grids], [0.0] * N_GRID),
                    jnp.float32),
        jnp.asarray(pad([g.long_valid for g in grids], 0), jnp.float32),
        jnp.asarray(pad([g.h_fine_steps for g in grids], 0.0), jnp.float32),
        jnp.asarray(pad([g.h_long_steps for g in grids], 0.0), jnp.float32),
        jnp.asarray(pad([max(1, min(g.season_steps, N_GRID))
                         for g in grids], 1), jnp.int32),
        m=m,
    )
    host = {k: [float(x) for x in v] for k, v in out.items()}
    return [{k: host[k][i] for k in FORECASTERS}
            for i in range(len(grids))]


def fit_serial(grids: list[SeriesGrids]) -> list[dict[str, float]]:
    """One fit call per model (the bench comparison lever and the
    byte-equality oracle for :func:`fit_batch`)."""
    return [fit_batch([g])[0] for g in grids]
