"""Measured provisioning lead times.

Replaces the static provisioning-horizon constant
(``anticipationHorizonSeconds``) with the per-(accelerator, model) quantile
of OBSERVED actuation->ready latencies. The engine feeds each model's
variant states every tick; the estimator opens an episode when a variant's
desired replicas exceed its ready replicas (a scale-up is in flight), and
closes it when ready catches up — the elapsed time is one lead-time sample
covering the whole real chain: HPA/actuator reaction, slice provisioning,
multi-host group assembly, model load, readiness. In the emulation harness
those transitions are driven by ``emulator/kubelet.py``'s ``ready_at``
physics; in live mode by pod readiness as reflected in scale-target status.

Samples are kept in small per-(accelerator, model) rings; the estimate is a
configurable quantile (default p90 — sizing for the common-case lead time
under-provisions whenever provisioning lands slow, and slow is exactly when
backlog hurts most). Fallback order: (accelerator, model) -> accelerator ->
configured default.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from dataclasses import dataclass

MAX_SAMPLES = 64
# An episode that outlives this is abandoned (deleted variant, wedged
# provisioning the operator resolved by other means): recording it would
# poison the quantile with an unbounded outlier.
EPISODE_TIMEOUT_SECONDS = 3600.0


@dataclass
class _Episode:
    started: float
    goal: int
    accelerator: str
    # Phase split: the instant the goal count of replicas became SCHEDULED
    # (pods bound — the slice exists; what remains is model load +
    # readiness). 0 = not reached yet. An episode that never reaches
    # scheduled (provisioning stockout) times out and records NOTHING:
    # a wedged order must not pollute either phase's p90.
    scheduled_at: float = 0.0
    tier: str = ""


class LeadTimeEstimator:
    """Thread-safe actuation->ready latency tracker, split into an
    actuation->scheduled phase (slice provisioning, measured per
    (variant, tier)) and a scheduled->ready phase (model load/readiness,
    per variant). The full-chain quantile remains the planner's horizon;
    the provisioning phase feeds the capacity ledger's ETA math."""

    def __init__(self, quantile: float = 0.9,
                 default_seconds: float = 150.0) -> None:
        self.quantile = min(max(quantile, 0.0), 1.0)
        self.default_seconds = default_seconds
        self._mu = threading.Lock()
        # (model_key, accelerator) -> ring of observed latencies (seconds).
        self._samples: dict[tuple[str, str], deque[float]] = {}
        self._by_accel: dict[str, deque[float]] = {}
        # Provisioning phase (actuation->scheduled), keyed per
        # (slice variant, capacity tier) — the scarce, tier-dependent part
        # of the chain — with a per-tier fleet-wide fallback ring that
        # mirrors ``_by_accel``.
        self._prov: dict[tuple[str, str], deque[float]] = {}
        self._prov_by_tier: dict[str, deque[float]] = {}
        # Serving phase (scheduled->ready) per variant.
        self._serve: dict[str, deque[float]] = {}
        # "model_key|variant" -> open scale-up episode.
        self._episodes: dict[str, _Episode] = {}

    def observe(self, model_key: str, variant_name: str, accelerator: str,
                desired: int, ready: int, now: float,
                scheduled: int | None = None, tier: str = "") -> None:
        """One variant's (desired, ready) observation for this tick.
        ``scheduled`` (pods bound to provisioned hosts), when known, stamps
        the episode's phase boundary so provisioning and serving latencies
        are recorded separately; callers without that signal keep the
        single-phase behavior unchanged."""
        ekey = f"{model_key}|{variant_name}"
        with self._mu:
            ep = self._episodes.get(ekey)
            if ep is not None and (now - ep.started > EPISODE_TIMEOUT_SECONDS
                                   or desired < ep.goal):
                # Abandoned or retargeted down: elapsed time no longer
                # measures one provisioning round trip. Nothing recorded —
                # a stockout that never scheduled must expire silently.
                del self._episodes[ekey]
                ep = None
            if ep is None:
                if desired > ready:
                    self._episodes[ekey] = _Episode(
                        started=now, goal=desired, accelerator=accelerator,
                        tier=tier)
                return
            if desired > ep.goal:
                # Retarget up mid-flight: measure to the new goal (the
                # planner cares when the full order lands).
                ep.goal = desired
                if scheduled is not None and scheduled < ep.goal:
                    ep.scheduled_at = 0.0  # new goal: not yet provisioned
            if tier:
                ep.tier = tier
            if (scheduled is not None and ep.scheduled_at == 0.0
                    and scheduled >= ep.goal):
                ep.scheduled_at = now
                self._record_provisioning_locked(
                    ep.accelerator, ep.tier, now - ep.started)
            if ready >= ep.goal:
                self._record(model_key, ep.accelerator, now - ep.started)
                if ep.scheduled_at > 0.0:
                    self._ring(self._serve, ep.accelerator).append(
                        max(now - ep.scheduled_at, 0.0))
                del self._episodes[ekey]

    def _record(self, model_key: str, accelerator: str,
                latency: float) -> None:
        if latency <= 0:
            return
        ring = self._samples.setdefault(
            (model_key, accelerator), deque(maxlen=MAX_SAMPLES))
        ring.append(latency)
        self._by_accel.setdefault(
            accelerator, deque(maxlen=MAX_SAMPLES)).append(latency)

    @staticmethod
    def _ring(store: dict, key) -> deque:
        ring = store.get(key)
        if ring is None:
            ring = store[key] = deque(maxlen=MAX_SAMPLES)
        return ring

    def _record_provisioning_locked(self, variant: str, tier: str,
                                    latency: float) -> None:
        if latency <= 0:
            return
        self._ring(self._prov, (variant, tier)).append(latency)
        if tier:
            self._ring(self._prov_by_tier, tier).append(latency)

    def record_provisioning(self, variant: str, tier: str,
                            latency: float) -> None:
        """Direct provisioning-lead sample from the capacity ledger: a
        slice order's submission->discovered-ready latency, measured per
        (variant, tier)."""
        with self._mu:
            self._record_provisioning_locked(variant, tier, latency)

    def provisioning_estimate(self, variant: str,
                              tier: str = "") -> tuple[float, bool]:
        """(provisioning lead seconds, measured?). Fallback chain mirrors
        :meth:`estimate`'s per-accelerator ladder: the (variant, tier)
        samples -> the variant's best-covered tier -> the fleet's samples
        for ``tier`` (a variant never provisioned through this tier
        inherits the tier's measured behavior) -> the configured default
        (measured=False)."""
        with self._mu:
            ring = self._prov.get((variant, tier))
            if not ring:
                rings = [r for (v, _), r in self._prov.items()
                         if v == variant and r]
                if rings:
                    ring = max(rings, key=len)
            if ring:
                return self._quantile(list(ring), self.quantile), True
            tier_ring = self._prov_by_tier.get(tier)
            if tier_ring:
                return self._quantile(list(tier_ring), self.quantile), True
            return self.default_seconds, False

    @staticmethod
    def _quantile(samples: list[float], q: float) -> float:
        xs = sorted(samples)
        pos = q * (len(xs) - 1)
        lo = int(math.floor(pos))
        hi = min(lo + 1, len(xs) - 1)
        return xs[lo] + (xs[hi] - xs[lo]) * (pos - lo)

    def estimate(self, model_key: str,
                 accelerator: str = "") -> tuple[float, bool]:
        """(lead-time seconds, measured?). Fallback chain: the model's own
        samples on ``accelerator`` -> the model's best-covered accelerator
        -> the fleet's samples for ``accelerator`` (a NEW model inherits
        its accelerator's measured latencies) -> the configured default
        (measured=False)."""
        with self._mu:
            ring = self._samples.get((model_key, accelerator))
            if not ring:
                # Best-covered accelerator for the model (covers both the
                # model-level ask and a variant that moved accelerators).
                rings = [r for (mk, _), r in self._samples.items()
                         if mk == model_key and r]
                if rings:
                    ring = max(rings, key=len)
            if ring:
                return self._quantile(list(ring), self.quantile), True
            accel_ring = self._by_accel.get(accelerator)
            if accel_ring:
                return self._quantile(list(accel_ring), self.quantile), True
            # Phase composition: no full-chain sample yet, but the capacity
            # plane measured slice provisioning (per variant/tier) and a
            # serving phase exists for the accelerator — their sum is a
            # measured horizon where the single-phase ladder has nothing.
            prov_rings = [r for (v, _), r in self._prov.items()
                          if v == accelerator and r]
            serve_ring = self._serve.get(accelerator)
            if prov_rings and serve_ring:
                prov = max(prov_rings, key=len)
                return (self._quantile(list(prov), self.quantile)
                        + self._quantile(list(serve_ring), self.quantile),
                        True)
            return self.default_seconds, False

    def sample_count(self, model_key: str) -> int:
        with self._mu:
            return sum(len(r) for (mk, _), r in self._samples.items()
                       if mk == model_key)

    # --- crash-restart checkpoint (wva_tpu.resilience) ---

    @staticmethod
    def _export_rings(store: dict, split_key: bool) -> list:
        if split_key:
            return [[k[0], k[1], list(ring)]
                    for k, ring in sorted(store.items()) if ring]
        return [[k, list(ring)] for k, ring in sorted(store.items())
                if ring]

    def export_state(self) -> dict:
        """Serializable sample rings for the resilience checkpoint — the
        measured actuation->ready and provisioning latencies every horizon
        decision keys on (losing them re-opens the default-constant
        under-provisioning window after every restart). Open episodes are
        NOT exported: their (desired, ready) anchors do not survive the
        restart gap, and a re-opened episode mid-scale-up would record a
        bogus short sample."""
        with self._mu:
            return {
                "samples": self._export_rings(self._samples, True),
                "by_accel": self._export_rings(self._by_accel, False),
                "prov": self._export_rings(self._prov, True),
                "prov_by_tier": self._export_rings(self._prov_by_tier,
                                                   False),
                "serve": self._export_rings(self._serve, False),
            }

    def restore_state(self, state: dict) -> int:
        """Rehydrate from :meth:`export_state` output (boot warm-start).
        Returns how many rings were restored."""
        restored = 0
        with self._mu:
            for model_key, accel, values in state.get("samples", []):
                ring = self._ring(self._samples, (str(model_key),
                                                  str(accel)))
                ring.extend(float(v) for v in values)
                restored += 1
            for variant, tier, values in state.get("prov", []):
                ring = self._ring(self._prov, (str(variant), str(tier)))
                ring.extend(float(v) for v in values)
                restored += 1
            for store_name, store in (("by_accel", self._by_accel),
                                      ("prov_by_tier", self._prov_by_tier),
                                      ("serve", self._serve)):
                for key, values in state.get(store_name, []):
                    ring = self._ring(store, str(key))
                    ring.extend(float(v) for v in values)
                    restored += 1
        return restored

    def evict_missing(self, live_keys: set[str]) -> None:
        """Drop episodes + samples for models that no longer exist."""
        with self._mu:
            for k in [k for k in self._episodes
                      if k.rsplit("|", 1)[0] not in live_keys]:
                del self._episodes[k]
            for k in [k for k in self._samples if k[0] not in live_keys]:
                del self._samples[k]
