"""Multi-cluster capacity federation (docs/design/federation.md).

N per-cluster engines each export a compact :class:`ClusterCapture`; one
elected **capacity arbiter** merges them and emits raise-only spill
directives — cross-cluster spill on stockout, reservation/spot arbitrage
with per-region cost weights, and blackout-aware failover with
re-admission hysteresis. ``WVA_FEDERATION`` is the lever (default on);
off — or simply leaving ``WVA_FEDERATION_REGION`` unset — is
byte-identical in statuses and trace cycles to the unfederated engine.
"""

from wva_tpu.federation.apply import (
    FEDERATION_STEP_NAME,
    apply_federation_directives,
)
from wva_tpu.federation.arbiter import (
    REGION_BLACKOUT,
    REGION_DEGRADED,
    REGION_HEALTHY,
    CapacityArbiter,
    classify_capture,
)
from wva_tpu.federation.capture import (
    ClusterCapture,
    ConfigMapCaptureBus,
    InProcessCaptureBus,
    ModelDemand,
    RegionModelHealth,
    VariantCapacity,
    capture_to_payload,
    demand_key,
    payload_to_capture,
)
from wva_tpu.federation.plane import FederationPlane

__all__ = [
    "FEDERATION_STEP_NAME",
    "apply_federation_directives",
    "REGION_BLACKOUT",
    "REGION_DEGRADED",
    "REGION_HEALTHY",
    "CapacityArbiter",
    "classify_capture",
    "ClusterCapture",
    "ConfigMapCaptureBus",
    "InProcessCaptureBus",
    "ModelDemand",
    "RegionModelHealth",
    "VariantCapacity",
    "capture_to_payload",
    "demand_key",
    "payload_to_capture",
    "FederationPlane",
    "build_federation_plane",
]


def build_federation_plane(client, config, clock, registry=None,
                           identity: str = "wva"):
    """Production wiring: ConfigMap capture bus + arbiter lease on the hub
    cluster this controller's kubeconfig points at (``client``). Returns
    None when federation is off or no region name is configured — the
    engine then never constructs the plane, keeping the single-cluster
    default byte-identical to pre-federation builds."""
    fed = config.federation_config()
    if not fed.enabled or not fed.region:
        return None
    from wva_tpu.config.helpers import system_namespace
    from wva_tpu.leaderelection import LeaderElector, LeaderElectorConfig

    bus = ConfigMapCaptureBus(client, namespace=system_namespace(),
                              regions=fed.regions or (fed.region,))
    elector = LeaderElector(
        client, f"{identity}-{fed.region}",
        config=LeaderElectorConfig(lease_name=fed.arbiter_lease),
        clock=clock)
    arbiter = CapacityArbiter(
        tier_preference=config.capacity_config().tier_preference,
        region_tier_weights=fed.region_tier_weights,
        capture_stale_seconds=fed.capture_stale_seconds,
        spill_max_replicas=fed.spill_max_replicas,
        readmit_ticks=fed.readmit_ticks,
        blackout_shed=fed.blackout_shed)
    return FederationPlane(
        region=fed.region, bus=bus, elector=elector, arbiter=arbiter,
        clock=clock, registry=registry,
        plan_stale_seconds=fed.capture_stale_seconds)
