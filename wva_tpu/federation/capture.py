"""Per-cluster captures: the only thing a region ships to the global
capacity arbiter (docs/design/federation.md §capture-schema).

Each engine tick produces a :class:`ClusterCapture` — compact per-model
demand entries (post-health-gate targets), the capacity ledger's per-variant
snapshot with measured provisioning leads, the input-health plane's raw
per-model signals, and the region's effective tier cost weights — never
object graphs: no K8s objects, no analyzer state, no collector views cross
the region boundary. The arbiter merges captures in sorted region order,
which is what makes its decisions byte-identical across capture arrival
orders (tests/test_federation.py).

Two transports, mirroring the shard summary bus:

- **In-process** (emulator / bench / multi-cluster harness): captures and
  the arbiter's published plan pass by reference through
  :class:`InProcessCaptureBus`.
- **ConfigMap** (one hub cluster shared by every region's controller):
  :class:`ConfigMapCaptureBus` publishes each capture as canonical JSON in
  ``wva-federation-capture-<region>`` and the arbiter's plan in
  ``wva-federation-plan`` (rv-guarded writes, the checkpoint ConfigMap
  discipline) — ``wva_federation_capture_age_seconds`` is the alert.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field

log = logging.getLogger(__name__)

CAPTURE_CONFIGMAP_PREFIX = "wva-federation-capture"
CAPTURE_DATA_KEY = "capture"
PLAN_CONFIGMAP_NAME = "wva-federation-plan"
PLAN_DATA_KEY = "plan"
CAPTURE_SCHEMA_VERSION = 1


@dataclass
class ModelDemand:
    """One variant's demand/position in its home region: the post-health-
    gate target (what the region would run if it could) next to what it
    actually runs — the gap is what the arbiter may spill elsewhere."""

    variant_name: str = ""
    namespace: str = ""
    model_id: str = ""
    accelerator_name: str = ""
    current_replicas: int = 0
    target_replicas: int = 0
    chips_per_replica: int = 1


@dataclass
class RegionModelHealth:
    """One model's input-health classification as shipped to the arbiter
    (the region's own ladder runs locally; the arbiter only consumes the
    classification — same split as the shard plane's HealthSignals)."""

    state: str = "fresh"
    age_seconds: float = 0.0
    allow_scale_down: bool = True
    reason: str = ""


@dataclass
class VariantCapacity:
    """One variant's ledger row + measured provisioning lead. ``ready``/
    ``provisioning``/``preempted`` are slices; ``tier_slices`` is the
    per-tier ready inventory the arbitrage ranking walks."""

    variant: str = ""
    chips_per_slice: int = 1
    ready: int = 0
    provisioning: int = 0
    preempted: int = 0
    tier_slices: dict[str, int] = field(default_factory=dict)
    stocked_out_tiers: list[str] = field(default_factory=list)
    lead_seconds: float = 0.0


@dataclass
class ClusterCapture:
    """One region's full federation export for one engine tick."""

    region: str = ""
    epoch: int = -1                 # region lease fencing token at capture
    tick_seq: int = 0
    published_at: float = 0.0
    demand: dict[str, ModelDemand] = field(default_factory=dict)
    health: dict[str, RegionModelHealth] = field(default_factory=dict)
    capacity: dict[str, VariantCapacity] = field(default_factory=dict)
    # The region's effective tier cost weights (after any per-region
    # override) — the arbitrage ranking input.
    tier_weights: dict[str, float] = field(default_factory=dict)


def demand_key(namespace: str, variant_name: str) -> str:
    return f"{namespace}|{variant_name}"


def capture_to_payload(cap: ClusterCapture) -> dict:
    """Canonical JSON-able form for the ConfigMap transport; the
    in-process bus skips this entirely (references cross no process
    boundary there)."""
    return {
        "schema": CAPTURE_SCHEMA_VERSION,
        "region": cap.region,
        "epoch": cap.epoch,
        "tick_seq": cap.tick_seq,
        "published_at": cap.published_at,
        "demand": {
            k: {"variant_name": d.variant_name, "namespace": d.namespace,
                "model_id": d.model_id,
                "accelerator_name": d.accelerator_name,
                "current_replicas": d.current_replicas,
                "target_replicas": d.target_replicas,
                "chips_per_replica": d.chips_per_replica}
            for k, d in sorted(cap.demand.items())},
        "health": {
            k: {"state": h.state, "age_seconds": h.age_seconds,
                "allow_scale_down": h.allow_scale_down, "reason": h.reason}
            for k, h in sorted(cap.health.items())},
        "capacity": {
            k: {"variant": c.variant, "chips_per_slice": c.chips_per_slice,
                "ready": c.ready, "provisioning": c.provisioning,
                "preempted": c.preempted,
                "tier_slices": dict(sorted(c.tier_slices.items())),
                "stocked_out_tiers": sorted(c.stocked_out_tiers),
                "lead_seconds": c.lead_seconds}
            for k, c in sorted(cap.capacity.items())},
        "tier_weights": dict(sorted(cap.tier_weights.items())),
    }


def payload_to_capture(data: dict) -> ClusterCapture:
    """Inverse of :func:`capture_to_payload`."""
    cap = ClusterCapture(
        region=str(data.get("region", "")),
        epoch=int(data.get("epoch", -1)),
        tick_seq=int(data.get("tick_seq", 0)),
        published_at=float(data.get("published_at", 0.0)),
        tier_weights={k: float(v)
                      for k, v in (data.get("tier_weights") or {}).items()},
    )
    for k, d in (data.get("demand") or {}).items():
        cap.demand[k] = ModelDemand(
            variant_name=d.get("variant_name", ""),
            namespace=d.get("namespace", ""),
            model_id=d.get("model_id", ""),
            accelerator_name=d.get("accelerator_name", ""),
            current_replicas=int(d.get("current_replicas", 0)),
            target_replicas=int(d.get("target_replicas", 0)),
            chips_per_replica=int(d.get("chips_per_replica", 1)))
    for k, h in (data.get("health") or {}).items():
        cap.health[k] = RegionModelHealth(
            state=h.get("state", "fresh"),
            age_seconds=float(h.get("age_seconds", 0.0)),
            allow_scale_down=bool(h.get("allow_scale_down", True)),
            reason=h.get("reason", ""))
    for k, c in (data.get("capacity") or {}).items():
        cap.capacity[k] = VariantCapacity(
            variant=c.get("variant", k),
            chips_per_slice=int(c.get("chips_per_slice", 1)),
            ready=int(c.get("ready", 0)),
            provisioning=int(c.get("provisioning", 0)),
            preempted=int(c.get("preempted", 0)),
            tier_slices={t: int(n)
                         for t, n in (c.get("tier_slices") or {}).items()},
            stocked_out_tiers=list(c.get("stocked_out_tiers") or []),
            lead_seconds=float(c.get("lead_seconds", 0.0)))
    return cap


class InProcessCaptureBus:
    """Reference-passing bus for the multi-cluster harness (one capture
    slot per region + one global plan slot, overwritten per tick)."""

    def __init__(self) -> None:
        self._captures: dict[str, ClusterCapture] = {}
        self._plan: dict | None = None

    def publish(self, cap: ClusterCapture) -> None:
        self._captures[cap.region] = cap

    def read_all(self) -> dict[str, ClusterCapture]:
        return dict(self._captures)

    def publish_plan(self, plan: dict) -> None:
        self._plan = plan

    def read_plan(self) -> dict | None:
        return self._plan


class ConfigMapCaptureBus:
    """ConfigMap transport against a shared hub cluster: rv-guarded
    publish (a deposed arbiter's stale plan write 409s harmlessly), reads
    that treat corrupt or missing payloads as absent — an absent capture
    ages into BLACKOUT classification on the arbiter side, which is the
    safe direction."""

    def __init__(self, client, namespace: str,
                 regions: tuple[str, ...] = ()) -> None:
        self.client = client
        self.namespace = namespace
        self.regions = tuple(regions)

    def _capture_name(self, region: str) -> str:
        return f"{CAPTURE_CONFIGMAP_PREFIX}-{region}"

    def _put(self, name: str, key: str, payload: str) -> None:
        from wva_tpu.k8s.client import ConflictError
        from wva_tpu.k8s.objects import ConfigMap, ObjectMeta, clone

        try:
            existing = self.client.try_get(ConfigMap.KIND, self.namespace,
                                           name)
            if existing is None:
                self.client.create(ConfigMap(
                    metadata=ObjectMeta(name=name, namespace=self.namespace),
                    data={key: payload}))
            else:
                cm = clone(existing)
                cm.data = {key: payload}
                self.client.update(cm)
        except ConflictError:
            # Another writer holds a newer view — exactly the fencing
            # outcome we want; next tick re-publishes.
            log.debug("federation publish conflicted for %s", name)
        except Exception as e:  # noqa: BLE001 — publishing must never fail
            log.warning("federation publish failed for %s: %s", name, e)

    def _get(self, name: str, key: str) -> dict | None:
        from wva_tpu.k8s.objects import ConfigMap

        try:
            cm = self.client.try_get(ConfigMap.KIND, self.namespace, name)
        except Exception as e:  # noqa: BLE001 — a storming hub reads
            log.warning("federation read failed for %s: %s", name, e)
            return None                             # as absent
        if cm is None or not cm.data.get(key):
            return None
        try:
            return json.loads(cm.data[key])
        except (ValueError, TypeError) as e:
            log.warning("federation payload %s corrupt: %s", name, e)
            return None

    def publish(self, cap: ClusterCapture) -> None:
        self._put(self._capture_name(cap.region), CAPTURE_DATA_KEY,
                  json.dumps(capture_to_payload(cap), sort_keys=True,
                             separators=(",", ":")))

    def read_all(self) -> dict[str, ClusterCapture]:
        out: dict[str, ClusterCapture] = {}
        for region in self.regions:
            data = self._get(self._capture_name(region), CAPTURE_DATA_KEY)
            if data is None:
                continue
            try:
                out[region] = payload_to_capture(data)
            except (ValueError, TypeError, KeyError) as e:
                log.warning("federation capture %s corrupt: %s", region, e)
        return out

    def publish_plan(self, plan: dict) -> None:
        self._put(PLAN_CONFIGMAP_NAME, PLAN_DATA_KEY,
                  json.dumps(plan, sort_keys=True, separators=(",", ":")))

    def read_plan(self) -> dict | None:
        return self._get(PLAN_CONFIGMAP_NAME, PLAN_DATA_KEY)
