"""The global capacity arbiter (docs/design/federation.md §arbiter).

One elected controller (the existing fenced-lease discipline, lease
``wva-tpu-federation-arbiter``) merges every region's
:class:`~wva_tpu.federation.capture.ClusterCapture` and emits a fleet
plan: per-region spill directives plus the region-state ledger. Three
behaviours, all raise-only in the target region:

- **Cross-cluster spill** — a model whose home region is stocked out
  across its whole tier-preference walk (or dark, below) gets its
  unserved growth routed to the candidate region with the most ready
  reservation slices, then the shortest measured provisioning lead, then
  the cheapest per-region blended tier cost, then region name.
- **Reservation/spot arbitrage** — that ranking prices each candidate
  with ITS OWN region's tier cost weights (per-region overridable via
  federation config), so one region's spot discount never distorts
  another's ranking.
- **Blackout-aware failover** — a region whose input-health plane is
  BLACKOUT (or whose capture has gone stale) sheds a bounded standby of
  its frozen footprint to healthy regions instead of freezing the fleet;
  re-admission takes ``readmit_ticks`` consecutive healthy arbiter ticks
  (boot-ramp-style hysteresis), so a flapping region cannot thrash spill
  capacity.

Everything here is pure and deterministic: captures are processed in
sorted region order, demand in sorted key order — byte-identical plans
across capture arrival orders (tests/test_federation.py).
"""

from __future__ import annotations

from dataclasses import dataclass

from wva_tpu.capacity.tiers import (
    DEFAULT_TIER_COST_WEIGHTS,
    DEFAULT_TIER_PREFERENCE,
    TIER_RESERVATION,
)
from wva_tpu.federation.capture import ClusterCapture

PLAN_SCHEMA_VERSION = 1

# Region classifications (the plan's ``region_states`` values).
REGION_HEALTHY = "healthy"
REGION_DEGRADED = "degraded"
REGION_BLACKOUT = "blackout"

# Input-health ladder states as they appear in capture health signals
# (mirrors wva_tpu/health constants; string-matched so this module stays
# import-light for tests that build captures by hand).
_FRESH = "fresh"
_BLACKOUT = "blackout"


@dataclass
class _RegionBook:
    """Arbiter-side hysteresis state for one region."""

    shedding: bool = False
    readmit_in: int = 0


def classify_capture(cap: ClusterCapture | None, age: float,
                     stale_seconds: float) -> str:
    """Pure classification of one region from its capture + age. A
    missing or stale capture is BLACKOUT — the arbiter cannot tell a dead
    hub link from a dead region, and shedding standby capacity is the
    safe direction for both. A region where at least half the models
    report input-health BLACKOUT is dark; any non-fresh model degrades."""
    if cap is None or age > stale_seconds:
        return REGION_BLACKOUT
    total = len(cap.health)
    if total:
        dark = sum(1 for h in cap.health.values() if h.state == _BLACKOUT)
        if dark * 2 >= total and dark > 0:
            return REGION_BLACKOUT
        if any(h.state != _FRESH for h in cap.health.values()):
            return REGION_DEGRADED
    return REGION_HEALTHY


class CapacityArbiter:
    """Deterministic fleet merge: captures in → plan out. State is the
    per-region hysteresis book only; a leadership move restarts it cold,
    which (like a process restart) errs toward keeping spill standby a
    few extra ticks — the do-no-harm direction."""

    def __init__(self,
                 tier_preference: tuple[str, ...] = DEFAULT_TIER_PREFERENCE,
                 region_tier_weights: dict[str, dict[str, float]] | None = None,
                 capture_stale_seconds: float = 90.0,
                 spill_max_replicas: int = 4,
                 readmit_ticks: int = 3,
                 blackout_shed: bool = True) -> None:
        self.tier_preference = tuple(tier_preference)
        self.region_tier_weights = {
            r: dict(w) for r, w in (region_tier_weights or {}).items()}
        self.capture_stale_seconds = capture_stale_seconds
        self.spill_max_replicas = spill_max_replicas
        self.readmit_ticks = readmit_ticks
        self.blackout_shed = blackout_shed
        self._books: dict[str, _RegionBook] = {}
        self._tick = 0

    # --- per-region pricing ---------------------------------------------

    def _weights_for(self, region: str, cap: ClusterCapture | None
                     ) -> dict[str, float]:
        """A region is priced with its own weights: the federation-config
        override wins, then the weights the region shipped in its capture,
        then the process defaults. Keyed per region so one region's spot
        discount cannot leak into another's ranking (the tiers.py env var
        is per-process and would otherwise apply fleet-wide)."""
        override = self.region_tier_weights.get(region)
        if override:
            return override
        if cap is not None and cap.tier_weights:
            return cap.tier_weights
        return DEFAULT_TIER_COST_WEIGHTS

    def _cheapest_open_tier_weight(self, region: str, cap: ClusterCapture,
                                   accelerator: str) -> float:
        weights = self._weights_for(region, cap)
        vc = cap.capacity.get(accelerator)
        stocked = set(vc.stocked_out_tiers) if vc is not None else set()
        open_weights = [weights.get(t, 1.0) for t in self.tier_preference
                        if t not in stocked]
        return min(open_weights) if open_weights else max(
            weights.values(), default=1.0)

    # --- candidate ranking ----------------------------------------------

    def _rank_targets(self, source: str, key: str, model_id: str,
                      accelerator: str, captures: dict[str, ClusterCapture],
                      states: dict[str, dict]) -> list[str]:
        """Healthy, non-shedding regions serving the same (demand key,
        model) ranked: ready reservation slices desc, measured lead asc,
        own-region blended cost asc, region name asc."""
        ranked = []
        for region in sorted(captures):
            if region == source:
                continue
            st = states[region]
            if st["state"] != REGION_HEALTHY or st["shedding"]:
                continue
            cap = captures[region]
            entry = cap.demand.get(key)
            if entry is None or entry.model_id != model_id:
                continue
            vc = cap.capacity.get(accelerator)
            reservation_ready = (
                vc.tier_slices.get(TIER_RESERVATION, 0) if vc else 0)
            lead = vc.lead_seconds if vc else float("inf")
            cost = self._cheapest_open_tier_weight(region, cap, accelerator)
            ranked.append(((-reservation_ready, lead, cost, region), region))
        ranked.sort(key=lambda t: t[0])
        return [region for _, region in ranked]

    # --- spill sizing ----------------------------------------------------

    @staticmethod
    def _provisioning_replicas(cap: ClusterCapture, accelerator: str,
                               chips_per_replica: int) -> int:
        vc = cap.capacity.get(accelerator)
        if vc is None:
            return 0
        chips = vc.provisioning * vc.chips_per_slice
        return chips // max(chips_per_replica, 1)

    def _stockout_unserved(self, cap: ClusterCapture, entry) -> int:
        """Growth a healthy region cannot place: wants more replicas than
        it runs + has provisioning, with every preferred tier stockout-
        pinned for that accelerator."""
        vc = cap.capacity.get(entry.accelerator_name)
        if vc is None or not set(self.tier_preference) <= set(
                vc.stocked_out_tiers):
            return 0
        inflight = self._provisioning_replicas(
            cap, entry.accelerator_name, entry.chips_per_replica)
        return max(entry.target_replicas - entry.current_replicas - inflight,
                   0)

    # --- the merge -------------------------------------------------------

    def tick(self, captures: dict[str, ClusterCapture], now: float,
             epoch: int = -1) -> dict:
        """One arbiter pass: classify every region (with re-admission
        hysteresis), then walk demand in sorted order emitting raise-only
        spill directives keyed by TARGET region."""
        self._tick += 1
        regions = sorted(set(captures) | set(self._books))
        states: dict[str, dict] = {}
        for region in regions:
            cap = captures.get(region)
            age = max(now - cap.published_at, 0.0) if cap is not None else 0.0
            raw = classify_capture(cap, age, self.capture_stale_seconds)
            book = self._books.setdefault(region, _RegionBook())
            if raw == REGION_BLACKOUT:
                book.shedding = True
                book.readmit_in = self.readmit_ticks
            elif book.shedding:
                if raw == REGION_HEALTHY:
                    book.readmit_in -= 1
                    if book.readmit_in <= 0:
                        book.shedding = False
                        book.readmit_in = 0
                else:
                    # Degraded ticks do not count toward re-admission —
                    # the region must PROVE healthy for the full window.
                    book.readmit_in = self.readmit_ticks
            states[region] = {
                "state": raw,
                "capture_age": round(age, 3),
                "shedding": book.shedding,
                "readmit_in": book.readmit_in if book.shedding else 0,
            }
        # Drop books for regions that vanished from the fleet.
        for region in list(self._books):
            if region not in captures:
                del self._books[region]

        directives: dict[str, list[dict]] = {}
        # floors accumulate per (target region, demand key) so two sources
        # spilling the same model stack instead of overwriting.
        floors: dict[tuple[str, str], dict] = {}
        for source in sorted(captures):
            cap = captures[source]
            st = states[source]
            dark = st["state"] == REGION_BLACKOUT or st["shedding"]
            for key in sorted(cap.demand):
                entry = cap.demand[key]
                if dark:
                    if not self.blackout_shed:
                        continue
                    spill = min(
                        max(entry.target_replicas, entry.current_replicas),
                        self.spill_max_replicas)
                    why = ("input-health blackout"
                           if st["state"] == REGION_BLACKOUT
                           else "re-admission hysteresis")
                else:
                    spill = min(self._stockout_unserved(cap, entry),
                                self.spill_max_replicas)
                    why = "tier stockout"
                if spill <= 0:
                    continue
                targets = self._rank_targets(
                    source, key, entry.model_id, entry.accelerator_name,
                    captures, states)
                if not targets:
                    continue
                target = targets[0]
                slot = floors.get((target, key))
                if slot is None:
                    base = captures[target].demand[key].target_replicas
                    slot = {
                        "variant_name": entry.variant_name,
                        "namespace": entry.namespace,
                        "model_id": entry.model_id,
                        "floor_replicas": base,
                        "spill_replicas": 0,
                        "source_region": source,
                        "target_region": target,
                    }
                    floors[(target, key)] = slot
                    directives.setdefault(target, []).append(slot)
                else:
                    # Multiple sources: keep them all in the provenance.
                    sources = set(slot["source_region"].split("+"))
                    sources.add(source)
                    slot["source_region"] = "+".join(sorted(sources))
                slot["floor_replicas"] += spill
                slot["spill_replicas"] += spill
                slot["reason"] = (
                    f"federation spill: +{slot['spill_replicas']} replicas "
                    f"from {slot['source_region']} ({why}) -> {target}")
        return {
            "schema": PLAN_SCHEMA_VERSION,
            "tick": self._tick,
            "epoch": epoch,
            "published_at": now,
            "region_states": states,
            "directives": {r: directives[r] for r in sorted(directives)},
        }
