"""Per-engine federation plane (docs/design/federation.md §plane).

One :class:`FederationPlane` rides each region's engine tick, AFTER the
health gate and BEFORE decisions are flight-recorded:

1. export this region's :class:`ClusterCapture` (post-health-gate targets,
   ledger snapshot + measured leads, raw health signals, effective tier
   weights) to the capture bus;
2. if this controller holds the arbiter lease (the existing fenced-lease
   discipline), merge every region's capture through
   :class:`~wva_tpu.federation.arbiter.CapacityArbiter` and publish the
   fleet plan;
3. read the current plan back and hand THIS region's spill directives to
   the engine, which applies them via the shared
   :func:`~wva_tpu.federation.apply.apply_federation_directives` path and
   records STAGE_FEDERATION — only when the plan is non-trivial, so a
   healthy single-region fleet's traces stay byte-identical to the plane
   being off.

The plane is attached only when a region name is configured
(``WVA_FEDERATION_REGION``); the default single-cluster deployment never
constructs it, which is what makes ``WVA_FEDERATION=off`` trivially
byte-identical — and the explicit off-lever is regression-tested anyway.
"""

from __future__ import annotations

import logging

from wva_tpu.constants import (
    LABEL_MODEL_NAME,
    LABEL_NAMESPACE,
    LABEL_REGION,
    LABEL_SOURCE,
    LABEL_STATE,
    WVA_FEDERATION_CAPTURE_AGE_SECONDS,
    WVA_FEDERATION_REGION_STATE,
    WVA_FEDERATION_SPILL_REPLICAS,
)
from wva_tpu.federation.arbiter import (
    REGION_BLACKOUT,
    REGION_DEGRADED,
    REGION_HEALTHY,
    CapacityArbiter,
)
from wva_tpu.federation.capture import (
    ClusterCapture,
    ModelDemand,
    RegionModelHealth,
    VariantCapacity,
    demand_key,
)

log = logging.getLogger(__name__)

REGION_STATES = (REGION_HEALTHY, REGION_DEGRADED, REGION_BLACKOUT)


class FederationPlane:
    """One region's capture/arbiter/directive loop. ``bus`` is either the
    in-process bus (harness) or the ConfigMap bus against the hub
    cluster; ``elector`` is a :class:`~wva_tpu.leaderelection.LeaderElector`
    on the shared arbiter lease (None = always arbitrate, for tests and
    single-binary fleets)."""

    def __init__(self, region: str, bus, elector=None,
                 arbiter: CapacityArbiter | None = None,
                 clock=None, registry=None,
                 plan_stale_seconds: float = 90.0) -> None:
        self.region = region
        self.bus = bus
        self.elector = elector
        self.arbiter = arbiter
        self.clock = clock
        self.registry = registry
        self.plan_stale_seconds = plan_stale_seconds
        self._tick_seq = 0
        self._spill_gauge_keys: set[tuple] = set()
        self._region_gauge_keys: set[str] = set()

    # --- capture export --------------------------------------------------

    def build_capture(self, decisions, tick_health, capacity,
                      now: float, epoch: int = -1) -> ClusterCapture:
        """Compact export of this region's tick: demand from the final
        (post-health-gate) decisions, capacity from the ledger snapshot
        plus the lead-time estimator, health from the tick's raw signals."""
        cap = ClusterCapture(region=self.region, epoch=epoch,
                             tick_seq=self._tick_seq, published_at=now)
        for d in decisions:
            cap.demand[demand_key(d.namespace, d.variant_name)] = ModelDemand(
                variant_name=d.variant_name, namespace=d.namespace,
                model_id=d.model_id, accelerator_name=d.accelerator_name,
                current_replicas=d.current_replicas,
                target_replicas=d.target_replicas,
                chips_per_replica=d.chips_per_replica)
        for key in sorted(tick_health or {}):
            h = tick_health[key]
            cap.health[key] = RegionModelHealth(
                state=h.state, age_seconds=round(h.age_seconds, 3),
                allow_scale_down=h.allow_scale_down, reason=h.reason)
        if capacity is not None:
            cap.tier_weights = dict(capacity.tier_weights)
            for row in capacity.ledger.snapshot(now):
                variant = row["variant"]
                cap.capacity[variant] = VariantCapacity(
                    variant=variant,
                    chips_per_slice=row["chips_per_slice"],
                    ready=row["ready"],
                    provisioning=row["provisioning"],
                    preempted=row["preempted"],
                    tier_slices=dict(row["tier_slices"]),
                    stocked_out_tiers=list(row["stocked_out_tiers"]),
                    lead_seconds=round(
                        capacity.provisioning_lead(variant), 1))
        return cap

    # --- the per-tick loop -----------------------------------------------

    def tick(self, decisions, tick_health, capacity, now: float,
             epoch: int = -1) -> tuple[list[dict], dict | None]:
        """Publish capture, arbitrate if leading, return (this region's
        spill directives, the STAGE_FEDERATION payload or None)."""
        self._tick_seq += 1
        try:
            self.bus.publish(self.build_capture(
                decisions, tick_health, capacity, now, epoch=epoch))
        except Exception:  # noqa: BLE001 — export must never fail a tick
            log.warning("federation capture publish failed", exc_info=True)
        leading = (self.elector.tick() if self.elector is not None
                   else self.arbiter is not None)
        if leading and self.arbiter is not None:
            fence = (self.elector.fencing_token()
                     if self.elector is not None else epoch)
            try:
                plan = self.arbiter.tick(self.bus.read_all(), now,
                                         epoch=fence if fence is not None
                                         else -1)
                self.bus.publish_plan(plan)
            except Exception:  # noqa: BLE001
                log.warning("federation arbiter tick failed", exc_info=True)
        plan = self.bus.read_plan()
        if plan is not None and (now - float(plan.get("published_at", now))
                                 > self.plan_stale_seconds):
            # A dead arbiter's last plan ages out instead of pinning spill
            # floors forever; the next elected arbiter republishes.
            plan = None
        directives = list((plan or {}).get(
            "directives", {}).get(self.region, []))
        states = (plan or {}).get("region_states", {})
        self._emit_metrics(states, directives)
        stage = None
        nontrivial = bool(directives) or any(
            s.get("state") != REGION_HEALTHY or s.get("shedding")
            for s in states.values())
        if nontrivial:
            stage = {
                "region": self.region,
                "plan_tick": int((plan or {}).get("tick", 0)),
                "states": [{"region": r, **states[r]}
                           for r in sorted(states)],
                "directives": directives,
            }
        return directives, stage

    # --- gauges ----------------------------------------------------------

    def _emit_metrics(self, states: dict, directives: list[dict]) -> None:
        registry = self.registry
        if registry is None:
            return
        emitted_regions: set[str] = set()
        for region in sorted(states):
            st = states[region]
            emitted_regions.add(region)
            for state in REGION_STATES:
                registry.set_gauge(
                    WVA_FEDERATION_REGION_STATE,
                    {LABEL_REGION: region, LABEL_STATE: state},
                    1.0 if state == st.get("state") else 0.0)
            registry.set_gauge(WVA_FEDERATION_CAPTURE_AGE_SECONDS,
                               {LABEL_REGION: region},
                               float(st.get("capture_age", 0.0)))
        for region in self._region_gauge_keys - emitted_regions:
            for state in REGION_STATES:
                registry.remove(WVA_FEDERATION_REGION_STATE,
                                {LABEL_REGION: region, LABEL_STATE: state})
            registry.remove(WVA_FEDERATION_CAPTURE_AGE_SECONDS,
                            {LABEL_REGION: region})
        self._region_gauge_keys = emitted_regions
        emitted_spills: set[tuple] = set()
        for d in directives:
            labels = {LABEL_MODEL_NAME: d.get("model_id", ""),
                      LABEL_NAMESPACE: d.get("namespace", ""),
                      LABEL_SOURCE: d.get("source_region", ""),
                      LABEL_REGION: d.get("target_region", "")}
            emitted_spills.add(tuple(sorted(labels.items())))
            registry.set_gauge(WVA_FEDERATION_SPILL_REPLICAS, labels,
                               float(d.get("spill_replicas", 0)))
        for key in self._spill_gauge_keys - emitted_spills:
            registry.remove(WVA_FEDERATION_SPILL_REPLICAS, dict(key))
        self._spill_gauge_keys = emitted_spills
