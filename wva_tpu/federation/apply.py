"""Federation directive application — shared by the live engine and trace
replay (docs/design/federation.md §spill-semantics).

Kept free of JAX and federation-plane imports: the replay CLI re-applies
RECORDED spill directives (the ``federation`` stage event in the decision
trace) without re-running the arbiter, exactly like the health replay
re-applies recorded clamps — so a trace recorded with federation on
replays to zero diffs.
"""

from __future__ import annotations

from wva_tpu.interfaces import ACTION_SCALE_UP, VariantDecision

FEDERATION_STEP_NAME = "federation"


def apply_federation_directives(decisions: list[VariantDecision],
                                directives: list[dict], now: float) -> int:
    """Raise each targeted variant's desired to its spill floor (never
    lowers — the arbiter only ever ADDS capacity in the TARGET region for
    growth its source region cannot serve; scale-down stays local and
    reactive, so a bad arbiter can at worst over-provision, never starve).
    Runs AFTER the health gate: targets are healthy regions by
    construction, and a raise-only floor cannot fight a local freeze.
    Returns how many decisions were raised."""
    if not directives:
        return 0
    by_variant = {(d.namespace, d.variant_name): d for d in decisions}
    raised = 0
    for f in directives:
        d = by_variant.get((f.get("namespace", ""), f.get("variant_name", "")))
        floor = int(f.get("floor_replicas", 0))
        if d is None or floor <= d.target_replicas:
            continue
        d.target_replicas = floor
        if floor > d.current_replicas:
            d.action = ACTION_SCALE_UP
        reason = f.get("reason", "")
        d.reason = reason or d.reason
        d.add_step(FEDERATION_STEP_NAME, reason, now=now)
        raised += 1
    return raised
