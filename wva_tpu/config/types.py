"""Config value types: cache/freshness + scale-to-zero per-model config
(reference ``internal/config/prometheus.go:26-62``, ``scale_to_zero.go:16-56``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from wva_tpu.interfaces.replica_metrics import FRESH, STALE, UNAVAILABLE
from wva_tpu.utils.freeze import Freezable

# Default retention after the last request before scaling to zero.
DEFAULT_SCALE_TO_ZERO_RETENTION_SECONDS = 10 * 60.0

# Key in per-model ConfigMaps used for global defaults.
GLOBAL_DEFAULTS_KEY = "default"


@dataclass
class FreshnessThresholds(Freezable):
    """Age thresholds classifying metric freshness.

    Each knob has a distinct job: ``fresh_threshold`` bounds FRESH,
    ``stale_threshold`` bounds STALE (older classifies UNAVAILABLE), and
    ``unavailable_threshold`` is the serve-stale-on-error cutoff — cached
    results older than it are never served even as a Prometheus-outage
    fallback (see PrometheusSource.refresh)."""

    fresh_threshold: float = 60.0
    stale_threshold: float = 120.0
    unavailable_threshold: float = 300.0

    def determine_status(self, age_seconds: float) -> str:
        if age_seconds < self.fresh_threshold:
            return FRESH
        if age_seconds < self.stale_threshold:
            return STALE
        return UNAVAILABLE


@dataclass
class CacheConfig(Freezable):
    """Metrics-cache configuration shared by all collector sources."""

    ttl: float = 30.0
    cleanup_interval: float = 60.0
    # 0 disables background fetching.
    fetch_interval: float = 30.0
    freshness: FreshnessThresholds = field(default_factory=FreshnessThresholds)


@dataclass
class ModelScaleToZeroConfig:
    """Scale-to-zero config for one model. ``enable_scale_to_zero`` is
    tri-state (None = inherit) to support partial overrides."""

    model_id: str = ""
    namespace: str = ""
    enable_scale_to_zero: bool | None = None
    retention_period: str = ""  # Go duration string; "" = inherit


# model ID (or GLOBAL_DEFAULTS_KEY) -> config
ScaleToZeroConfigData = dict[str, ModelScaleToZeroConfig]
