"""Config loading with precedence flags > env > config file > defaults
(reference ``internal/config/loader.go:40-219``; viper semantics re-created
with a small resolver).

Keys are the same env-style names the reference uses (``PROMETHEUS_BASE_URL``,
``GLOBAL_OPT_INTERVAL``, ...) so deployment manifests transfer unchanged.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Mapping

import yaml

from wva_tpu.config.config import (
    CapacityConfig,
    Config,
    EPPConfig,
    FeatureFlagsConfig,
    FederationConfig,
    ForecastConfig,
    HealthConfig,
    InfrastructureConfig,
    ObsConfig,
    PrometheusConfig,
    ResilienceConfig,
    ShardingConfig,
    TLSConfig,
    TraceConfig,
)
from wva_tpu.config.types import CacheConfig, FreshnessThresholds
from wva_tpu.constants.leases import DEFAULT_LEADER_ELECTION_LEASE
from wva_tpu.config.validation import validate
from wva_tpu.utils.durations import parse_duration, parse_duration_or_default

log = logging.getLogger(__name__)

DEFAULTS: dict[str, Any] = {
    "METRICS_BIND_ADDRESS": "0",
    "HEALTH_PROBE_BIND_ADDRESS": ":8081",
    "LEADER_ELECT": False,
    "LEADER_ELECTION_ID": DEFAULT_LEADER_ELECTION_LEASE,
    "LEADER_ELECTION_LEASE_DURATION": "60s",
    "LEADER_ELECTION_RENEW_DEADLINE": "50s",
    "LEADER_ELECTION_RETRY_PERIOD": "10s",
    "REST_CLIENT_TIMEOUT": "60s",
    "METRICS_SECURE": True,
    "METRICS_AUTH": False,
    "ENABLE_HTTP2": False,
    "WATCH_NAMESPACE": "",
    "V": 0,
    "WEBHOOK_CERT_PATH": "",
    "WEBHOOK_CERT_NAME": "tls.crt",
    "WEBHOOK_CERT_KEY": "tls.key",
    "METRICS_CERT_PATH": "",
    "METRICS_CERT_NAME": "tls.crt",
    "METRICS_CERT_KEY": "tls.key",
    "WVA_SCALE_TO_ZERO": False,
    "WVA_LIMITED_MODE": False,
    "WVA_TRACE_ENABLED": False,
    "WVA_TRACE_PATH": "",
    "WVA_TRACE_RING_SIZE": 512,
    # Predictive capacity planner (wva_tpu.forecast; docs/design/forecast.md).
    # Default on; "off"/"false"/"0" disables (decisions then byte-identical
    # to pre-forecast builds).
    "WVA_FORECAST": True,
    "WVA_FORECAST_PERIOD": "24h",
    "WVA_FORECAST_GRID_STEP": "15s",
    "WVA_FORECAST_DEFAULT_LEAD_TIME": "150s",
    "WVA_FORECAST_LEAD_TIME_QUANTILE": 0.9,
    "WVA_FORECAST_TARGET_UTILIZATION": 0.85,
    "WVA_FORECAST_DEMOTE_ERROR": 0.35,
    "WVA_FORECAST_MIN_TRUST_EVALS": 3,
    "WVA_FORECAST_PREWAKE": True,
    "WVA_FORECAST_PREWAKE_MIN_DEMAND": 1.0,
    # Input-health plane (wva_tpu.health; docs/design/health.md).
    # Default on; "off"/"false"/"0" disables (decisions, statuses, and
    # traces then byte-identical to pre-health builds in a fault-free
    # world).
    "WVA_HEALTH": True,
    # Input age past which a model is DEGRADED (hold last-known-good,
    # allow scale-up, forbid scale-down).
    "WVA_HEALTH_DEGRADED_AFTER": "120s",
    # Input age past which a model is BLACKOUT (freeze desired,
    # hard-forbid scale-to-zero, withhold forecast floors and capacity
    # releases).
    "WVA_HEALTH_FREEZE_AFTER": "300s",
    # Consecutive fresh ticks before scale-downs resume after a
    # degradation.
    "WVA_HEALTH_RECOVERY_TICKS": 3,
    # Crash-restart resilience plane (wva_tpu.resilience;
    # docs/design/resilience.md). Default on; "off"/"false"/"0" disables
    # warm-start recovery, the boot ramp, lease-epoch fencing, and the
    # checkpoint (decisions/statuses/traces then byte-identical to
    # pre-resilience builds in a fault-free world).
    "WVA_RESILIENCE": True,
    # Durable soft-state checkpoint ConfigMap (off = boot-ramp-only
    # recovery, same zero-wrong-direction guarantee).
    "WVA_CHECKPOINT": True,
    # Engine ticks between checkpoint writes.
    "WVA_CHECKPOINT_INTERVAL": 20,
    # Engine ticks every model stays DEGRADED-equivalent after boot unless
    # its inputs prove fresh earlier (scale-up allowed, scale-down/zero
    # forbidden). Size to cover WVA_HEALTH_DEGRADED_AFTER at the engine
    # interval.
    "WVA_STARTUP_HOLD_TICKS": 10,
    # Sharded active-active engine (wva_tpu.shard; docs/design/sharding.md).
    # Default OFF (a topology change is opt-in); on, the engine splits into
    # N consistent-hash shard workers (one Lease each) publishing per-shard
    # summaries to the fleet solve — byte-identical decisions at any shard
    # count, WVA_SHARDING=off byte-identical to the unsharded engine.
    "WVA_SHARDING": False,
    # Consistent-hash shards (and Leases wva-tpu-shard-0..N-1).
    "WVA_SHARD_COUNT": 4,
    # Worker processes for process-per-shard deployments (the in-process
    # plane holds every shard lease in one process regardless).
    "WVA_SHARD_WORKERS": 1,
    # Fleet ticks a rebalanced model stays under the rebalance ramp unless
    # its inputs prove fresh earlier.
    "WVA_SHARD_REBALANCE_HOLD": 5,
    # Summaries older than this cover nothing (their models hold previous
    # desired).
    "WVA_SHARD_SUMMARY_STALE": "90s",
    # Multi-cluster capacity federation (wva_tpu.federation;
    # docs/design/federation.md). Default ON, but the plane only exists
    # once WVA_FEDERATION_REGION names this cluster's region — the
    # single-cluster default (and "off") is byte-identical to the
    # unfederated engine in statuses and trace cycles.
    "WVA_FEDERATION": True,
    # This cluster's region name ("" = not federated).
    "WVA_FEDERATION_REGION": "",
    # Comma-separated fleet region list for the ConfigMap capture bus.
    "WVA_FEDERATION_REGIONS": "",
    # Arbiter election Lease on the hub cluster.
    "WVA_FEDERATION_ARBITER_LEASE": "wva-tpu-federation-arbiter",
    # Captures/plans older than this are absent (region -> BLACKOUT; a
    # dead arbiter's spill floors age out).
    "WVA_FEDERATION_CAPTURE_STALE": "90s",
    # Max replicas one directive may spill into a target region per model.
    "WVA_FEDERATION_SPILL_MAX": 4,
    # Consecutive healthy arbiter ticks before a shedding region is
    # re-admitted (boot-ramp-style hysteresis).
    "WVA_FEDERATION_READMIT_TICKS": 3,
    # Blackout-aware failover: shed a dark region's bounded standby to
    # healthy regions instead of freezing the fleet.
    "WVA_FEDERATION_BLACKOUT_SHED": True,
    # Per-region tier cost weight overrides for the arbitrage ranking,
    # e.g. "us-east1=spot:0.2,reservation:0.5|eu-west4=spot:0.45".
    "WVA_FEDERATION_REGION_TIER_WEIGHTS": "",
    # Observability plane (wva_tpu.obs; docs/design/observability.md).
    # Span-structured tick tracing, default on; strictly out-of-band —
    # statuses, traces, and goldens are byte-identical either way, and
    # "off" builds no recorder at all (zero cost).
    "WVA_SPANS": True,
    # Completed tick span trees kept in the in-memory ring.
    "WVA_SPANS_RING": 64,
    # JSONL spill path for tick trees ("" = ring only).
    "WVA_SPANS_PATH": "",
    # Slow-tick flight recorder: a tick slower than this many
    # milliseconds auto-dumps its full span tree (0 = threshold off;
    # executor overruns always dump).
    "WVA_TRACE_SLOW_TICK_MS": 0.0,
    # Directory for slow-tick dumps ("" = <tmpdir>/wva-slow-ticks).
    "WVA_SLOW_TICK_DIR": "",
    # OTLP/HTTP JSON traces endpoint ("" disables export; stdlib HTTP,
    # no OpenTelemetry dependency).
    "WVA_OTLP_ENDPOINT": "",
    # Log output format: "plain" (byte-identical to pre-change logs) or
    # "json" (one object per line with tick/model/shard context fields).
    "WVA_LOG_FORMAT": "plain",
    # Elastic capacity plane (wva_tpu.capacity; docs/design/capacity.md).
    # Default on; "off"/"false"/"0" disables (decisions then byte-identical
    # to pre-capacity builds).
    "WVA_CAPACITY": True,
    # Tier preference order ("reservation,on_demand,spot"; omitting a tier
    # forbids provisioning through it).
    "WVA_CAPACITY_TIER_PREFERENCE": "",
    # Per-tier cost weights, e.g. "reservation=0.6,on_demand=1.0,spot=0.3".
    "WVA_CAPACITY_TIER_WEIGHTS": "",
    # Base quota-stockout re-probe interval (grows geometrically on
    # consecutive stockouts, capped at 8x).
    "WVA_CAPACITY_STOCKOUT_REPROBE": "300s",
    # Provisioning-lead fallback until (variant, tier) leads are measured.
    "WVA_CAPACITY_DEFAULT_PROVISION_LEAD": "180s",
    "SCALE_FROM_ZERO_ENGINE_MAX_CONCURRENCY": 10,
    "EPP_METRIC_READER_BEARER_TOKEN": "",
    "GLOBAL_OPT_INTERVAL": "60s",
    "ENGINE_ANALYSIS_WORKERS": 0,  # 0 = auto (pooled for HTTP, serial in-mem)
    # One fleet-wide query per template per tick (vs per-model fan-out).
    "WVA_GROUPED_COLLECTION": True,
    # Watch-backed informer cache: steady-state ticks LIST nothing
    # (docs/design/informer.md). Off = one LIST per kind per tick.
    "WVA_INFORMER": True,
    # Dirty-set incremental ticks: unchanged models skip prepare->analyze
    # and re-emit the prior decision. Off = always-analyze (byte-identical).
    "WVA_INCREMENTAL": True,
    # Full re-analysis every Nth tick regardless of fingerprints (0 = off).
    "WVA_RESYNC_TICKS": 12,
    # Versioned fingerprint plane: delta-maintained dirty-set fingerprints
    # (slice versions + object-version memos + pod-set epochs). Off
    # restores per-tick recomputation (byte-identical outputs).
    "WVA_FP_DELTA": True,
    # Cross-check versioned vs recomputed fingerprints every tick (tests/
    # debugging only — pays both costs).
    "WVA_FP_ASSERT": False,
    # Zero-copy object plane (docs/design/object-plane.md): store reads
    # return frozen shared objects. Off restores deep-copy-on-read
    # (byte-identical decisions; emergency lever).
    "WVA_ZERO_COPY": True,
    # One-jitted-program decision plane (docs/design/fused-plane.md): the
    # SLO path's sizing + forecast fits + trusted-forecast selection run
    # as ONE device dispatch per tick. Off restores the staged per-stage
    # dispatches (byte-identical statuses and traces).
    "WVA_FUSED": True,
    # Vectorized decision stage (docs/design/fused-plane.md
    # §host-vectorization): the SLO path's post-dispatch host pipeline
    # (finalize algebra, cost-aware fills, enforcer bridge) runs as
    # fleet-wide row arithmetic over the model axis. Off restores the
    # per-model loops (byte-identical statuses and traces).
    "WVA_VEC_DECIDE": True,
    # Cross-check vectorized vs per-model decision stages every tick
    # (tests/debugging only — pays both costs).
    "WVA_VEC_ASSERT": False,
    # Delta-sizing solve memo (docs/design/fused-plane.md
    # §host-vectorization): candidate rows with unchanged solve keys
    # reuse the memoized sized rate; zero-change ticks dispatch only the
    # forecast fits. Off = full re-solve every tick (byte-identical).
    "WVA_SOLVE_MEMO": True,
    # GET /api/v1/query instead of POST (read-only proxies).
    "PROMETHEUS_USE_GET_QUERIES": False,
}


class _Resolver:
    """Layered key resolver: flags > env > file > defaults."""

    def __init__(
        self,
        flags: Mapping[str, Any] | None,
        env: Mapping[str, str],
        file_values: Mapping[str, Any],
    ) -> None:
        self.flags = flags or {}
        self.env = env
        self.file_values = file_values

    def get(self, key: str, default: Any = None) -> Any:
        if key in self.flags and self.flags[key] is not None:
            return self.flags[key]
        if key in self.env:
            return self.env[key]
        if key in self.file_values and self.file_values[key] is not None:
            return self.file_values[key]
        return DEFAULTS.get(key, default)

    def get_str(self, key: str) -> str:
        v = self.get(key)
        return "" if v is None else str(v)

    def get_bool(self, key: str) -> bool:
        v = self.get(key)
        if isinstance(v, bool):
            return v
        if isinstance(v, str):
            return v.strip().lower() in ("true", "1", "yes", "on")
        return bool(v)

    def get_int(self, key: str) -> int:
        v = self.get(key)
        try:
            return int(v)
        except (TypeError, ValueError):
            return int(DEFAULTS.get(key, 0))

    def get_float(self, key: str) -> float:
        v = self.get(key)
        try:
            return float(v)
        except (TypeError, ValueError):
            return float(DEFAULTS.get(key, 0.0))

    def get_duration(self, key: str) -> float:
        v = self.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
        try:
            return parse_duration(str(v))
        except ValueError:
            d = DEFAULTS.get(key, "0s")
            return parse_duration(str(d)) if isinstance(d, str) else float(d)


def load(flags: Mapping[str, Any] | None = None,
         config_file_path: str = "",
         env: Mapping[str, str] | None = None) -> Config:
    """Load + validate the unified configuration (fail-fast).

    ``flags`` is a mapping of env-style keys to explicitly-set flag values
    (None values are treated as not-set). Raises on unreadable config file or
    failed validation.
    """
    file_values: dict[str, Any] = {}
    if config_file_path:
        with open(config_file_path, "r", encoding="utf-8") as f:
            loaded = yaml.safe_load(f) or {}
        if not isinstance(loaded, dict):
            raise ValueError(f"config file {config_file_path} is not a mapping")
        file_values = loaded
        log.info("Loaded config from file %s", config_file_path)

    r = _Resolver(flags, env if env is not None else os.environ, file_values)

    cfg = Config()
    cfg.infrastructure = InfrastructureConfig(
        metrics_addr=r.get_str("METRICS_BIND_ADDRESS"),
        probe_addr=r.get_str("HEALTH_PROBE_BIND_ADDRESS"),
        enable_leader_election=r.get_bool("LEADER_ELECT"),
        leader_election_id=r.get_str("LEADER_ELECTION_ID"),
        lease_duration=r.get_duration("LEADER_ELECTION_LEASE_DURATION"),
        renew_deadline=r.get_duration("LEADER_ELECTION_RENEW_DEADLINE"),
        retry_period=r.get_duration("LEADER_ELECTION_RETRY_PERIOD"),
        rest_timeout=r.get_duration("REST_CLIENT_TIMEOUT"),
        secure_metrics=r.get_bool("METRICS_SECURE"),
        metrics_auth=r.get_bool("METRICS_AUTH"),
        enable_http2=r.get_bool("ENABLE_HTTP2"),
        watch_namespace=r.get_str("WATCH_NAMESPACE"),
        logger_verbosity=r.get_int("V"),
        optimization_interval=r.get_duration("GLOBAL_OPT_INTERVAL"),
        engine_analysis_workers=max(0, r.get_int("ENGINE_ANALYSIS_WORKERS")),
        grouped_collection=r.get_bool("WVA_GROUPED_COLLECTION"),
        informer=r.get_bool("WVA_INFORMER"),
        incremental=r.get_bool("WVA_INCREMENTAL"),
        resync_ticks=max(0, r.get_int("WVA_RESYNC_TICKS")),
        fp_delta=r.get_bool("WVA_FP_DELTA"),
        fp_assert=r.get_bool("WVA_FP_ASSERT"),
        zero_copy=r.get_bool("WVA_ZERO_COPY"),
        fused=r.get_bool("WVA_FUSED"),
        vec_decide=r.get_bool("WVA_VEC_DECIDE"),
        vec_assert=r.get_bool("WVA_VEC_ASSERT"),
        solve_memo=r.get_bool("WVA_SOLVE_MEMO"),
    )
    cfg.tls = TLSConfig(
        webhook_cert_path=r.get_str("WEBHOOK_CERT_PATH"),
        webhook_cert_name=r.get_str("WEBHOOK_CERT_NAME"),
        webhook_cert_key=r.get_str("WEBHOOK_CERT_KEY"),
        metrics_cert_path=r.get_str("METRICS_CERT_PATH"),
        metrics_cert_name=r.get_str("METRICS_CERT_NAME"),
        metrics_cert_key=r.get_str("METRICS_CERT_KEY"),
    )
    cfg.set_features(FeatureFlagsConfig(
        scale_to_zero_enabled=r.get_bool("WVA_SCALE_TO_ZERO"),
        limited_mode_enabled=r.get_bool("WVA_LIMITED_MODE"),
        scale_from_zero_max_concurrency=r.get_int("SCALE_FROM_ZERO_ENGINE_MAX_CONCURRENCY"),
    ))
    cfg.set_epp(EPPConfig(
        metric_reader_bearer_token=r.get_str("EPP_METRIC_READER_BEARER_TOKEN"),
    ))
    cfg.set_trace(TraceConfig(
        enabled=r.get_bool("WVA_TRACE_ENABLED"),
        path=r.get_str("WVA_TRACE_PATH"),
        ring_size=r.get_int("WVA_TRACE_RING_SIZE"),
    ))
    cfg.set_forecast(ForecastConfig(
        enabled=r.get_bool("WVA_FORECAST"),
        seasonal_period_seconds=r.get_duration("WVA_FORECAST_PERIOD"),
        grid_step_seconds=r.get_duration("WVA_FORECAST_GRID_STEP"),
        default_lead_time_seconds=r.get_duration(
            "WVA_FORECAST_DEFAULT_LEAD_TIME"),
        lead_time_quantile=r.get_float("WVA_FORECAST_LEAD_TIME_QUANTILE"),
        target_utilization=r.get_float("WVA_FORECAST_TARGET_UTILIZATION"),
        demote_error_threshold=r.get_float("WVA_FORECAST_DEMOTE_ERROR"),
        min_trust_evals=r.get_int("WVA_FORECAST_MIN_TRUST_EVALS"),
        prewake_enabled=r.get_bool("WVA_FORECAST_PREWAKE"),
        prewake_min_demand=r.get_float("WVA_FORECAST_PREWAKE_MIN_DEMAND"),
    ))

    cfg.set_health(HealthConfig(
        enabled=r.get_bool("WVA_HEALTH"),
        degraded_after_seconds=r.get_duration("WVA_HEALTH_DEGRADED_AFTER"),
        freeze_after_seconds=r.get_duration("WVA_HEALTH_FREEZE_AFTER"),
        recovery_ticks=r.get_int("WVA_HEALTH_RECOVERY_TICKS"),
    ))

    cfg.set_resilience(ResilienceConfig(
        enabled=r.get_bool("WVA_RESILIENCE"),
        checkpoint_enabled=r.get_bool("WVA_CHECKPOINT"),
        checkpoint_interval_ticks=max(1, r.get_int("WVA_CHECKPOINT_INTERVAL")),
        startup_hold_ticks=max(0, r.get_int("WVA_STARTUP_HOLD_TICKS")),
    ))

    cfg.set_sharding(ShardingConfig(
        enabled=r.get_bool("WVA_SHARDING"),
        shards=max(1, r.get_int("WVA_SHARD_COUNT")),
        workers=max(1, r.get_int("WVA_SHARD_WORKERS")),
        rebalance_hold_ticks=max(0, r.get_int("WVA_SHARD_REBALANCE_HOLD")),
        summary_stale_seconds=r.get_duration("WVA_SHARD_SUMMARY_STALE"),
    ))

    from wva_tpu.capacity.tiers import parse_region_tier_weights

    cfg.set_federation(FederationConfig(
        enabled=r.get_bool("WVA_FEDERATION"),
        region=r.get_str("WVA_FEDERATION_REGION").strip(),
        regions=tuple(
            s.strip() for s in
            r.get_str("WVA_FEDERATION_REGIONS").split(",") if s.strip()),
        arbiter_lease=(r.get_str("WVA_FEDERATION_ARBITER_LEASE")
                       or "wva-tpu-federation-arbiter"),
        capture_stale_seconds=r.get_duration("WVA_FEDERATION_CAPTURE_STALE"),
        spill_max_replicas=max(0, r.get_int("WVA_FEDERATION_SPILL_MAX")),
        readmit_ticks=max(0, r.get_int("WVA_FEDERATION_READMIT_TICKS")),
        blackout_shed=r.get_bool("WVA_FEDERATION_BLACKOUT_SHED"),
        region_tier_weights=parse_region_tier_weights(
            r.get_str("WVA_FEDERATION_REGION_TIER_WEIGHTS")),
    ))

    cfg.set_obs(ObsConfig(
        spans=r.get_bool("WVA_SPANS"),
        spans_ring=max(1, r.get_int("WVA_SPANS_RING")),
        spans_path=r.get_str("WVA_SPANS_PATH"),
        slow_tick_ms=max(0.0, r.get_float("WVA_TRACE_SLOW_TICK_MS")),
        slow_dump_dir=r.get_str("WVA_SLOW_TICK_DIR"),
        otlp_endpoint=r.get_str("WVA_OTLP_ENDPOINT"),
        log_format=(r.get_str("WVA_LOG_FORMAT") or "plain").lower(),
    ))

    from wva_tpu.capacity.tiers import (
        parse_tier_preference,
        parse_tier_weights,
    )

    cfg.set_capacity(CapacityConfig(
        enabled=r.get_bool("WVA_CAPACITY"),
        tier_preference=parse_tier_preference(
            r.get_str("WVA_CAPACITY_TIER_PREFERENCE")),
        tier_cost_weights=parse_tier_weights(
            r.get_str("WVA_CAPACITY_TIER_WEIGHTS")),
        stockout_reprobe_seconds=r.get_duration(
            "WVA_CAPACITY_STOCKOUT_REPROBE"),
        default_provision_lead_seconds=r.get_duration(
            "WVA_CAPACITY_DEFAULT_PROVISION_LEAD"),
    ))

    prom = PrometheusConfig(
        base_url=r.get_str("PROMETHEUS_BASE_URL"),
        bearer_token=r.get_str("PROMETHEUS_BEARER_TOKEN"),
        token_path=r.get_str("PROMETHEUS_TOKEN_PATH"),
        insecure_skip_verify=r.get_bool("PROMETHEUS_TLS_INSECURE_SKIP_VERIFY"),
        ca_cert_path=r.get_str("PROMETHEUS_CA_CERT_PATH"),
        client_cert_path=r.get_str("PROMETHEUS_CLIENT_CERT_PATH"),
        client_key_path=r.get_str("PROMETHEUS_CLIENT_KEY_PATH"),
        server_name=r.get_str("PROMETHEUS_SERVER_NAME"),
        use_get_queries=r.get_bool("PROMETHEUS_USE_GET_QUERIES"),
        cache=_parse_cache_config(r),
    )
    cfg.set_prometheus(prom)

    validate(cfg)
    log.info("Configuration loaded successfully")
    return cfg


def _parse_cache_config(r: _Resolver) -> CacheConfig:
    """Prometheus cache config (reference loader.go:176-219)."""
    d = CacheConfig()
    cache = CacheConfig(
        ttl=parse_duration_or_default(r.get_str("PROMETHEUS_METRICS_CACHE_TTL"), d.ttl),
        cleanup_interval=parse_duration_or_default(
            r.get_str("PROMETHEUS_METRICS_CACHE_CLEANUP_INTERVAL"), d.cleanup_interval),
        fetch_interval=parse_duration_or_default(
            r.get_str("PROMETHEUS_METRICS_CACHE_FETCH_INTERVAL"), d.fetch_interval),
        freshness=FreshnessThresholds(),
    )
    f = cache.freshness
    f.fresh_threshold = parse_duration_or_default(
        r.get_str("PROMETHEUS_METRICS_CACHE_FRESH_THRESHOLD"), f.fresh_threshold)
    f.stale_threshold = parse_duration_or_default(
        r.get_str("PROMETHEUS_METRICS_CACHE_STALE_THRESHOLD"), f.stale_threshold)
    f.unavailable_threshold = parse_duration_or_default(
        r.get_str("PROMETHEUS_METRICS_CACHE_UNAVAILABLE_THRESHOLD"), f.unavailable_threshold)
    return cache
